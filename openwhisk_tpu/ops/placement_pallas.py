"""Pallas TPU kernel for batched placement.

The XLA path (ops/placement.py) lowers the per-request reduction through
`lax.scan`; this kernel instead runs the whole micro-batch inside ONE
pallas_call with the fleet state resident in VMEM across all B iterations —
no per-iteration HBM round-trips for the capacity books, and the request
columns live in SMEM as scalars.

Layout notes (TPU tiling wants the fleet on the 128-lane axis):
  free    int32[1, N]   free memory permits
  health  int32[1, N]   usable mask (0/1)
  conc_t  int32[A, N]   spare concurrency permits, TRANSPOSED vs the XLA
                        kernel's [N, A] so a request's action-slot row is a
                        contiguous [1, N] vector.
  reqs    int32[B, 10]  (offset, size, home, step_inv, need, slot, max_conc,
                        rand, valid, slot_in_range) per request, in SMEM.

Semantics are identical to ops/placement.py::schedule_batch (asserted by
tests in interpret mode AND by bench.py's on-device parity stage on real
TPU hardware): same probe-rank argmin, same forced placement, same
NestedSemaphore capacity updates, same sequential intra-batch resolution.
VMEM budget caps the fleet at roughly N*A*4 bytes ~ a few MB; `fits_vmem`
reports whether a configuration qualifies (larger fleets use the
XLA/sharded path).

Hardware verdict (round 4, `bench.py --sweep` on a tunneled v5e chip):
neither kernel consistently wins — each takes ~half the (N in 128..4096,
A in 64..256) grid and every gap is within the tunnel's ±25% run-to-run
variance. XLA therefore stays the default (`TpuBalancer(kernel="xla")`);
this kernel remains a parity-verified alternative whose relative value
should be re-measured on non-tunneled hardware, where dispatch overhead
(which the single-pallas_call design minimizes) is a larger fraction of
the step.
"""
from __future__ import annotations

import os
from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from .placement import (PlacementState, RequestBatch, _mulmod,
                        pairwise_prims, repair_commit_masks)

# Import guard (CI satellite): environments whose jax predates
# jax.experimental.pallas (or ships it broken) must not explode at import
# time — the balancer probes `HAS_PALLAS` / `fits_vmem` (False) and keeps
# the XLA path, and the pytest `pallas` marker skips with
# `PALLAS_IMPORT_ERROR` as the logged reason.
try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    HAS_PALLAS = True
    PALLAS_IMPORT_ERROR: Optional[str] = None
except Exception as _e:  # noqa: BLE001 — any import failure means "no pallas"
    pl = pltpu = None  # type: ignore[assignment]
    HAS_PALLAS = False
    PALLAS_IMPORT_ERROR = repr(_e)

# VMEM fallback budget when the runtime reports no limit: cores ship
# ~16 MB; leave room for double-buffering and the runtime
_VMEM_FALLBACK_BYTES = 8 * 1024 * 1024
_vmem_budget_cache: Optional[int] = None


def vmem_budget_bytes() -> int:
    """The VMEM byte budget `fits_vmem` checks against: the ACTUAL device
    limit when the runtime reports one, else the conservative 8 MB
    fallback. Probe order (cached after the first call):

      1. `OPENWHISK_TPU_VMEM_BYTES` env override (operator escape hatch,
         also what the regression tests pin);
      2. a guarded `memory_stats()` / device-attribute probe — PJRT TPU
         runtimes that expose a vmem size report it there;
      3. the hard-coded fallback.

    Whatever the source, half is held back for double-buffering and the
    Mosaic runtime, matching the historical 8-of-16 split."""
    global _vmem_budget_cache
    if _vmem_budget_cache is not None:
        return _vmem_budget_cache
    budget = None
    env = os.environ.get("OPENWHISK_TPU_VMEM_BYTES")
    if env:
        try:
            budget = int(env) // 2
        except ValueError:
            budget = None
    if budget is None:
        try:
            d = jax.local_devices()[0]
            stats = {}
            try:
                stats = d.memory_stats() or {}
            except Exception:  # noqa: BLE001 — CPU/older PJRT: no stats
                stats = {}
            raw = next((int(v) for k, v in stats.items()
                        if "vmem" in k and isinstance(v, int) and v > 0),
                       None)
            if raw is None:
                attr = getattr(d, "vmem_size_bytes", None)
                raw = int(attr) if isinstance(attr, int) and attr > 0 else None
            if raw is not None:
                budget = raw // 2
        except Exception:  # noqa: BLE001 — introspection must never raise
            budget = None
    _vmem_budget_cache = budget if budget is not None else _VMEM_FALLBACK_BYTES
    return _vmem_budget_cache


def _reset_vmem_budget_cache() -> None:
    """Test seam: re-probe the budget (env overrides are read once)."""
    global _vmem_budget_cache
    _vmem_budget_cache = None


def fits_vmem(n_pad: int, action_slots: int) -> bool:
    """Does the VMEM-resident scan kernel's state fit? (conc [A, N] + free/
    health rows). Always False when pallas itself is unimportable."""
    if not HAS_PALLAS:
        return False
    return (action_slots + 2) * n_pad * 4 <= vmem_budget_bytes()


#: [B, N] buffers the repair kernel keeps live across the residue loop
#: (probe-rank geometry + the gathered conc rows) plus the per-round
#: materialized temporaries (Mosaic fuses the elementwise chains, so the
#: eligibility/key/selection masks share, not stack), and the [B, B]
#: pairwise conflict matrices
_REPAIR_BN_BUFFERS = 4
_REPAIR_BB_BUFFERS = 3


def fits_vmem_repair(n_pad: int, action_slots: int, batch: int) -> bool:
    """`fits_vmem` for the speculate-and-repair kernel: on top of the
    resident state it budgets the residue loop's [B, N] scratch/temporaries
    and the [B, B] pairwise conflict matrices (see repair kernel layout)."""
    if not HAS_PALLAS:
        return False
    elems = ((action_slots + 2) * n_pad
             + _REPAIR_BN_BUFFERS * batch * n_pad
             + _REPAIR_BB_BUFFERS * batch * batch)
    return elems * 4 <= vmem_budget_bytes()


def to_transposed(state: PlacementState) -> PlacementState:
    """Standard [N, A] state <-> kernel layout ([A, N] conc). Involution."""
    return PlacementState(state.free_mb, state.conc_free.T,
                          state.health)


def _kernel_body(reqs_ref, health_ref, free_ref, conc_ref, chosen_ref,
                 forced_ref, free_out, conc_out, pen_ref=None):
    n = free_out.shape[1]
    b = chosen_ref.shape[1]
    # the penalized rank can exceed n + 2 (one probe-ring lap per penalty
    # level), so the penalized variant needs the larger sentinel — same
    # rule as ops.placement._schedule_one
    big = jnp.int32(n + 2) if pen_ref is None else jnp.int32(1 << 30)
    idx = jax.lax.broadcasted_iota(jnp.int32, (1, n), 1)
    bidx = jax.lax.broadcasted_iota(jnp.int32, (1, b), 1)

    # state starts in the aliased output buffers
    free_out[:] = free_ref[:]
    conc_out[:] = conc_ref[:]
    chosen_ref[:] = jnp.full((1, b), -1, jnp.int32)
    forced_ref[:] = jnp.zeros((1, b), jnp.int32)

    def body(i, _):
        offset = reqs_ref[i, 0]
        size = reqs_ref[i, 1]
        home = reqs_ref[i, 2]
        step_inv = reqs_ref[i, 3]
        need = reqs_ref[i, 4]
        slot = reqs_ref[i, 5]
        max_conc = reqs_ref[i, 6]
        rand = reqs_ref[i, 7]
        valid = reqs_ref[i, 8] > 0
        slot_ok = reqs_ref[i, 9] > 0

        local = idx - offset
        in_part = (local >= 0) & (local < size)
        m = jnp.maximum(size, 1)
        rank = _mulmod(local - home, step_inv, m)
        if pen_ref is not None:
            rank = rank + pen_ref[:] * m

        healthy = health_ref[:] > 0
        conc_row = conc_out[pl.ds(slot, 1), :]
        eligible = in_part & healthy & ((conc_row > 0) | (free_out[:] >= need))
        key = jnp.where(eligible, rank, big)
        kmin = jnp.min(key)
        sel = jnp.min(jnp.where(key == kmin, idx, big))
        found = kmin < big

        usable = in_part & healthy
        fkey = jnp.where(usable, jnp.mod(local - rand, m), big)
        fmin = jnp.min(fkey)
        fsel = jnp.min(jnp.where(fkey == fmin, idx, big))
        have_usable = fmin < big

        chosen = jnp.where(found, sel, fsel)
        placed = valid & (found | have_usable)
        forced = valid & jnp.logical_not(found) & have_usable

        is_sel = idx == chosen
        conc_at = jnp.sum(jnp.where(is_sel, conc_row, 0))
        use_conc = placed & (conc_at > 0)
        take_mem = placed & jnp.logical_not(use_conc)

        free_out[:] = free_out[:] - jnp.where(
            is_sel & take_mem, need, 0).astype(jnp.int32)
        conc_delta = jnp.where(
            use_conc, -1,
            jnp.where(take_mem & (max_conc > 1), max_conc - 1, 0))
        # an out-of-range slot reads the clamped column (like XLA's
        # dynamic_index_in_dim) but its write is DROPPED (like XLA scatter)
        conc_out[pl.ds(slot, 1), :] = conc_row + jnp.where(
            is_sel & slot_ok, conc_delta, 0).astype(jnp.int32)

        at_i = bidx == i
        chosen_ref[:] = jnp.where(at_i & placed, chosen, chosen_ref[:])
        forced_ref[:] = jnp.where(at_i & forced, 1, forced_ref[:])
        return 0

    jax.lax.fori_loop(0, b, body, 0)


def _kernel(reqs_ref, health_ref, free_ref, conc_ref, chosen_ref, forced_ref,
            free_out, conc_out):
    _kernel_body(reqs_ref, health_ref, free_ref, conc_ref, chosen_ref,
                 forced_ref, free_out, conc_out)


def _kernel_penalized(reqs_ref, health_ref, free_ref, conc_ref, pen_ref,
                      chosen_ref, forced_ref, free_out, conc_out):
    _kernel_body(reqs_ref, health_ref, free_ref, conc_ref, chosen_ref,
                 forced_ref, free_out, conc_out, pen_ref=pen_ref)


@partial(jax.jit, static_argnames=("interpret",))
def schedule_batch_pallas(state: PlacementState, batch: RequestBatch,
                          interpret: bool = False, penalty=None
                          ) -> Tuple[PlacementState, jax.Array, jax.Array]:
    """Drop-in for schedule_batch, state in transposed ([A, N]) layout.
    `penalty=None` traces the original kernel unchanged; a penalty vector
    appends one [1, N] VMEM input AFTER the aliased state buffers, so the
    input_output_aliases indices are identical in both variants."""
    n = state.free_mb.shape[0]
    a = state.conc_free.shape[0]
    b = batch.offset.shape[0]
    # pl.ds needs an in-range start: clamp the read column (XLA's
    # dynamic_index_in_dim does the same) and flag OOB slots so their
    # writes are dropped (XLA scatter semantics)
    slot_ok = (batch.conc_slot >= 0) & (batch.conc_slot < a)
    slot = jnp.clip(batch.conc_slot, 0, a - 1)
    reqs = jnp.stack(
        [batch.offset, batch.size, batch.home, batch.step_inv, batch.need_mb,
         slot, batch.max_conc, batch.rand,
         batch.valid.astype(jnp.int32), slot_ok.astype(jnp.int32)], axis=1)
    free2 = state.free_mb.reshape(1, n)
    health2 = state.health.astype(jnp.int32).reshape(1, n)

    out_shape = (jax.ShapeDtypeStruct((1, b), jnp.int32),
                 jax.ShapeDtypeStruct((1, b), jnp.int32),
                 jax.ShapeDtypeStruct((1, n), jnp.int32),
                 jax.ShapeDtypeStruct((a, n), jnp.int32))
    out_specs = (pl.BlockSpec(memory_space=pltpu.VMEM),
                 pl.BlockSpec(memory_space=pltpu.VMEM),
                 pl.BlockSpec(memory_space=pltpu.VMEM),
                 pl.BlockSpec(memory_space=pltpu.VMEM))
    in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM)]
    if penalty is None:
        chosen, forced, free_o, conc_o = pl.pallas_call(
            _kernel, out_shape=out_shape, in_specs=in_specs,
            out_specs=out_specs, input_output_aliases={2: 2, 3: 3},
            interpret=interpret,
        )(reqs, health2, free2, state.conc_free)
    else:
        chosen, forced, free_o, conc_o = pl.pallas_call(
            _kernel_penalized, out_shape=out_shape,
            in_specs=in_specs + [pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=out_specs, input_output_aliases={2: 2, 3: 3},
            interpret=interpret,
        )(reqs, health2, free2, state.conc_free,
          penalty.astype(jnp.int32).reshape(1, n))

    new_state = PlacementState(free_o.reshape(n), conc_o, state.health)
    return new_state, chosen.reshape(b), forced.reshape(b) > 0


def _repair_kernel_body(reqs_ref, reqs_v_ref, health_ref, free_ref, conc_ref,
                        chosen_ref, forced_ref, rounds_ref, free_out,
                        conc_out, conc_bn_ref, pen_ref=None):
    """Speculate-and-repair in ONE kernel: full-batch probe, the shared
    conflict rules (ops.placement.repair_commit_masks with the pairwise
    prims), scatter-commit, and the residue loop — all with the fleet
    state resident in VMEM, so repair rounds cost vector passes instead of
    the multi-dispatch round trips the XLA while_loop pays per round.

    Orientation: per-request vectors are COLUMNS ([B, 1], request on the
    sublane axis) so [B, N] probe math and [B, B] pairwise conflict math
    broadcast without transposes; the same request matrix arrives twice —
    `reqs_ref` in SMEM (scalar reads for the dynamic-slice loops) and
    `reqs_v_ref` in VMEM (column vectors for the batch math)."""
    n = free_out.shape[1]
    b = chosen_ref.shape[1]
    # penalized ranks can exceed n + 2: larger sentinel, same rule as the
    # XLA _probe_geometry
    big = jnp.int32(n + 2) if pen_ref is None else jnp.int32(1 << 30)
    idx_bn = jax.lax.broadcasted_iota(jnp.int32, (b, n), 1)
    bidx_col = jax.lax.broadcasted_iota(jnp.int32, (b, 1), 0)
    eye_bb = (jax.lax.broadcasted_iota(jnp.int32, (b, b), 0)
              == jax.lax.broadcasted_iota(jnp.int32, (b, b), 1))
    prims = pairwise_prims(b)

    # per-request columns [B, 1]
    offset = reqs_v_ref[:, 0:1]
    size = reqs_v_ref[:, 1:2]
    home = reqs_v_ref[:, 2:3]
    step_inv = reqs_v_ref[:, 3:4]
    need = reqs_v_ref[:, 4:5]
    slot_col = reqs_v_ref[:, 5:6]
    maxc = reqs_v_ref[:, 6:7]
    rand = reqs_v_ref[:, 7:8]
    valid = reqs_v_ref[:, 8:9] > 0
    slot_ok = reqs_v_ref[:, 9:10] > 0
    simple = maxc <= 1

    # state starts in the aliased output buffers
    free_out[:] = free_ref[:]
    conc_out[:] = conc_ref[:]

    # loop-invariant geometry (health never changes inside a batch): probe
    # ranks masked to the usable partition, and the whole forced path —
    # forced placement ignores capacity, so fchoice/have_usable are fixed
    local = idx_bn - offset
    in_part = (local >= 0) & (local < size)
    m = jnp.maximum(size, 1)
    healthy = health_ref[:] > 0                      # [1, N]
    usable = in_part & healthy
    geom_rank = _mulmod(local - home, step_inv, m)
    if pen_ref is not None:
        geom_rank = geom_rank + pen_ref[:] * m
    geom_key = jnp.where(usable, geom_rank, big)
    fkey = jnp.where(usable, jnp.mod(local - rand, m), big)
    fmin = jnp.min(fkey, axis=1, keepdims=True)
    fchoice = jnp.min(jnp.where(fkey == fmin, idx_bn, big), axis=1,
                      keepdims=True)
    have_usable = fmin < big
    col_conc_geom = usable  # permit visibility is masked to the partition

    def cond(carry):
        pending, _, _, rounds = carry
        return jnp.any(pending) & (rounds <= b)

    def body(carry):
        pending, chosen, forced_acc, rounds = carry
        # per-round speculation: gather each request's conc column row
        # (the only dynamically-indexed read; slots pre-clamped host-side)
        def gather(i, _):
            conc_bn_ref[pl.ds(i, 1), :] = conc_out[pl.ds(reqs_ref[i, 5], 1), :]
            return 0

        jax.lax.fori_loop(0, b, gather, 0)
        conc_bn = conc_bn_ref[:]
        has_conc = conc_bn > 0
        free_row = free_out[:]                       # [1, N]
        eligible = has_conc | (free_row >= need)
        key = jnp.where(eligible, geom_key, big)
        kmin = jnp.min(key, axis=1, keepdims=True)
        choice = jnp.min(jnp.where(key == kmin, idx_bn, big), axis=1,
                         keepdims=True)
        found = kmin < big
        sel = jnp.where(found, choice, fchoice)      # [B, 1]
        placed = valid & (found | have_usable)
        forced = valid & jnp.logical_not(found) & have_usable
        is_sel = idx_bn == sel                       # [B, N]
        conc_at_sel = jnp.sum(jnp.where(is_sel, conc_bn, 0), axis=1,
                              keepdims=True)
        use_conc = placed & (conc_at_sel > 0)
        take_mem = placed & jnp.logical_not(use_conc)
        col_conc = jnp.any(col_conc_geom & has_conc, axis=1, keepdims=True)
        free_at_sel = jnp.sum(jnp.where(is_sel, free_row, 0), axis=1,
                              keepdims=True)

        safe, commit = repair_commit_masks(
            prims, pending=pending, placed=placed, forced=forced, sel=sel,
            take_mem=take_mem, use_conc=use_conc, simple=simple,
            need_mb=need, conc_slot=slot_col, free_at_sel=free_at_sel,
            col_conc=col_conc, n=n, a_slots=conc_out.shape[0],
            slot_ok=slot_ok)

        # commit: memory deltas collapse to one [B, N] -> [1, N] reduction
        # (cascade writers on one invoker sum exactly); conc deltas are the
        # rare class — scatter them row by row, predicated off for the
        # (typical) zero-delta rows
        dmem = jnp.sum(jnp.where(is_sel & commit & take_mem, need, 0),
                       axis=0, keepdims=True)
        free_out[:] = free_row - dmem.astype(jnp.int32)
        conc_delta = jnp.where(
            commit & use_conc, -1,
            jnp.where(commit & take_mem & jnp.logical_not(simple),
                      maxc - 1, 0))
        # an out-of-range slot reads the clamped column but its write is
        # DROPPED (XLA scatter semantics, like the scan kernel)
        conc_delta = jnp.where(slot_ok, conc_delta, 0)

        def put(i, _):
            d = jnp.sum(jnp.where(bidx_col == i, conc_delta, 0))

            @pl.when(d != 0)
            def _():
                sel_i = jnp.sum(jnp.where(bidx_col == i, sel, 0))
                s = reqs_ref[i, 5]
                row = conc_out[pl.ds(s, 1), :]
                lane = jax.lax.broadcasted_iota(jnp.int32, (1, n), 1)
                conc_out[pl.ds(s, 1), :] = row + jnp.where(
                    lane == sel_i, d, 0).astype(jnp.int32)

            return 0

        jax.lax.fori_loop(0, b, put, 0)
        chosen = jnp.where(safe, jnp.where(placed, sel, jnp.int32(-1)),
                           chosen)
        forced_acc = forced_acc | (safe & forced)
        return (pending & jnp.logical_not(safe), chosen, forced_acc,
                rounds + 1)

    _, chosen, forced_acc, rounds = jax.lax.while_loop(
        cond, body, (valid, jnp.full((b, 1), -1, jnp.int32),
                     jnp.zeros((b, 1), bool), jnp.int32(0)))

    # [B, 1] -> [1, B] result rows via the diagonal-mask transpose
    chosen_ref[:] = jnp.sum(jnp.where(eye_bb, chosen, 0), axis=0,
                            keepdims=True)
    forced_ref[:] = jnp.sum(jnp.where(eye_bb, forced_acc.astype(jnp.int32),
                                      0), axis=0, keepdims=True)
    rounds_ref[0, 0] = rounds


def _repair_kernel(reqs_ref, reqs_v_ref, health_ref, free_ref, conc_ref,
                   chosen_ref, forced_ref, rounds_ref, free_out, conc_out,
                   conc_bn_ref):
    _repair_kernel_body(reqs_ref, reqs_v_ref, health_ref, free_ref, conc_ref,
                        chosen_ref, forced_ref, rounds_ref, free_out,
                        conc_out, conc_bn_ref)


def _repair_kernel_penalized(reqs_ref, reqs_v_ref, health_ref, free_ref,
                             conc_ref, pen_ref, chosen_ref, forced_ref,
                             rounds_ref, free_out, conc_out, conc_bn_ref):
    _repair_kernel_body(reqs_ref, reqs_v_ref, health_ref, free_ref, conc_ref,
                        chosen_ref, forced_ref, rounds_ref, free_out,
                        conc_out, conc_bn_ref, pen_ref=pen_ref)


@partial(jax.jit, static_argnames=("interpret",))
def schedule_batch_repair_pallas(state: PlacementState, batch: RequestBatch,
                                 interpret: bool = False, penalty=None
                                 ) -> Tuple[PlacementState, jax.Array,
                                            jax.Array, jax.Array]:
    """Drop-in for ops.placement.schedule_batch_repair (state in the
    kernel's transposed [A, N] layout): same (state, chosen, forced,
    rounds) contract, bit-exact with the XLA repair kernel — the conflict
    rules are literally the same function (`repair_commit_masks`), only
    the index primitives differ (pairwise vs scatter/sort; their
    equivalence is fuzz-asserted). One pallas_call runs probe + conflict
    detection + commit + the residue loop with the fleet books resident in
    VMEM — no per-round dispatch round trips."""
    n = state.free_mb.shape[0]
    a = state.conc_free.shape[0]
    b = batch.offset.shape[0]
    # pl.ds needs an in-range start: clamp the gathered column (XLA's
    # fancy-index gather does the same) and flag OOB slots so their writes
    # — and their slot-keyed conflict marks — drop like XLA scatters
    slot_ok = (batch.conc_slot >= 0) & (batch.conc_slot < a)
    slot = jnp.clip(batch.conc_slot, 0, a - 1)
    reqs = jnp.stack(
        [batch.offset, batch.size, batch.home, batch.step_inv, batch.need_mb,
         slot, batch.max_conc, batch.rand,
         batch.valid.astype(jnp.int32), slot_ok.astype(jnp.int32)], axis=1)
    free2 = state.free_mb.reshape(1, n)
    health2 = state.health.astype(jnp.int32).reshape(1, n)

    out_shape = (jax.ShapeDtypeStruct((1, b), jnp.int32),
                 jax.ShapeDtypeStruct((1, b), jnp.int32),
                 jax.ShapeDtypeStruct((1, 1), jnp.int32),
                 jax.ShapeDtypeStruct((1, n), jnp.int32),
                 jax.ShapeDtypeStruct((a, n), jnp.int32))
    in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM)]
    out_specs = (pl.BlockSpec(memory_space=pltpu.VMEM),
                 pl.BlockSpec(memory_space=pltpu.VMEM),
                 pl.BlockSpec(memory_space=pltpu.SMEM),
                 pl.BlockSpec(memory_space=pltpu.VMEM),
                 pl.BlockSpec(memory_space=pltpu.VMEM))
    if penalty is None:
        chosen, forced, rounds, free_o, conc_o = pl.pallas_call(
            _repair_kernel, out_shape=out_shape, in_specs=in_specs,
            out_specs=out_specs,
            scratch_shapes=[pltpu.VMEM((b, n), jnp.int32)],
            input_output_aliases={3: 3, 4: 4},
            interpret=interpret,
        )(reqs, reqs, health2, free2, state.conc_free)
    else:
        chosen, forced, rounds, free_o, conc_o = pl.pallas_call(
            _repair_kernel_penalized, out_shape=out_shape,
            in_specs=in_specs + [pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=out_specs,
            scratch_shapes=[pltpu.VMEM((b, n), jnp.int32)],
            input_output_aliases={3: 3, 4: 4},
            interpret=interpret,
        )(reqs, reqs, health2, free2, state.conc_free,
          penalty.astype(jnp.int32).reshape(1, n))

    new_state = PlacementState(free_o.reshape(n), conc_o, state.health)
    return (new_state, chosen.reshape(b), forced.reshape(b) > 0,
            rounds.reshape(()))

"""Placement flight recorder: the last N placement decisions, explained.

The aggregate `loadbalancer_tpu_*` histograms say *how fast* the balancer
places; they cannot answer "why did activation X land on invoker Y?" or
"what did the fleet look like at that device step?". The flight recorder
keeps the last N micro-batch records in a pre-sized ring
(utils.ring_buffer.SeqRingBuffer) — per batch: an input digest (kernel,
healthy-invoker count, queue depth, oldest-request age, free-slot histogram
of the packed books), the per-request decision rows (activation id, action,
chosen invoker, forced/throttled flags, requested slot-MB), and the phase
timings (assembly/dispatch/readback/fanout) — plus an activation-id index so
`explain(activation_id)` answers with the exact batch record and decision
row, or None once the ring has wrapped past it.

Every balancer reports through the same recorder (the base-class hook in
loadbalancer/base.py): the TPU balancer records whole micro-batches with a
device digest, the CPU balancers (sharding, lean) record one-decision
batches with a `kernel: "cpu"` digest — so the introspection plane
(`/admin/placement/*` on the controller) is backend-agnostic.

Hot-path budget: one BatchRecord and one decisions list per micro-batch,
appended into the pre-sized ring — no per-request dict churn, no growth.
Switch it off with `CONFIG_whisk_loadBalancer_flightRecorder_enabled=false`
(size via `..._flightRecorder_size`).
"""
from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from ...utils.config import load_config
from ...utils.ring_buffer import SeqRingBuffer

#: decision-row tuple layout (kept a tuple, not a dict, on the hot path)
D_AID, D_ACTION, D_CHOSEN, D_INVOKER, D_FORCED, D_THROTTLED, D_SLOT_MB = \
    range(7)

DecisionRow = Tuple[str, str, int, Optional[str], bool, bool, int]


@dataclass(frozen=True)
class FlightRecorderConfig:
    """`CONFIG_whisk_loadBalancer_flightRecorder_*` env overrides."""
    enabled: bool = True
    size: int = 256


class BatchRecord:
    """One recorded placement step (a micro-batch for the TPU balancer, a
    single decision for the CPU balancers)."""

    __slots__ = ("seq", "ts", "digest", "decisions", "timings")

    def __init__(self, digest: dict,
                 decisions: Optional[List[DecisionRow]] = None,
                 timings: Optional[dict] = None):
        self.seq = -1          # assigned by FlightRecorder.record
        self.ts = time.time()
        #: input digest: kernel, healthy_invokers, queue_depth,
        #: oldest_age_ms, free_slot_hist, occupancy (keys vary by backend)
        self.digest = digest
        self.decisions: List[DecisionRow] = decisions if decisions is not None else []
        self.timings = timings or {}

    @staticmethod
    def decision_json(row: DecisionRow) -> dict:
        return {
            "activation_id": row[D_AID],
            "action": row[D_ACTION],
            "invoker_index": row[D_CHOSEN],
            "invoker": row[D_INVOKER],
            "forced": row[D_FORCED],
            "throttled": row[D_THROTTLED],
            "slot_mb": row[D_SLOT_MB],
        }

    def to_json(self, with_decisions: bool = True) -> dict:
        out = {
            "seq": self.seq,
            "ts": self.ts,
            "digest": self.digest,
            "timings": self.timings,
            "batch_size": len(self.decisions),
        }
        if with_decisions:
            out["decisions"] = [self.decision_json(r) for r in self.decisions]
        return out


class FlightRecorder:
    """Ring of BatchRecords + an activation-id -> seq index.

    The index is bounded by construction: entries are removed when their
    batch record is evicted from the ring, so it never outgrows
    size * max_batch activation ids.
    """

    def __init__(self, size: int = 256, enabled: bool = True):
        self.enabled = enabled
        self._ring: SeqRingBuffer[BatchRecord] = SeqRingBuffer(max(1, size))
        self._index: Dict[str, int] = {}

    @property
    def size(self) -> int:
        return self._ring.size

    @property
    def dropped(self) -> int:
        """Batch records the ring has wrapped past."""
        return self._ring.evicted

    def __len__(self) -> int:
        return len(self._ring)

    def record(self, rec: BatchRecord) -> int:
        """Append one batch record; index its decisions by activation id."""
        seq, evicted = self._ring.append(rec)
        rec.seq = seq
        if evicted is not None:
            old_seq = evicted.seq
            for row in evicted.decisions:
                if self._index.get(row[D_AID]) == old_seq:
                    del self._index[row[D_AID]]
        for row in rec.decisions:
            self._index[row[D_AID]] = seq
        return seq

    def explain(self, activation_id: str) -> Optional[dict]:
        """The batch record + decision row for one activation, or None if it
        was never recorded here or the ring has wrapped past it."""
        seq = self._index.get(activation_id)
        if seq is None:
            return None
        rec = self._ring.get(seq)
        if rec is None:  # wrapped between index cleanup and lookup
            self._index.pop(activation_id, None)
            return None
        for row in rec.decisions:
            if row[D_AID] == activation_id:
                return {"decision": BatchRecord.decision_json(row),
                        "batch": rec.to_json()}
        return None

    def recent(self, n: int = 20, with_decisions: bool = True) -> List[dict]:
        """The last min(n, size) batch records, oldest first."""
        return [r.to_json(with_decisions=with_decisions)
                for r in self._ring.last(n)]

    @classmethod
    def from_config(cls) -> "FlightRecorder":
        cfg = load_config(FlightRecorderConfig,
                          env_path="load_balancer.flight_recorder")
        return cls(size=cfg.size, enabled=cfg.enabled)


def occupancy_json(kernel: Optional[str], rows) -> dict:
    """Assemble the `/admin/placement/occupancy` payload from per-invoker
    (name, healthy, capacity_mb, free_mb, used_mb) tuples — ONE place for
    the documented shape, shared by all balancers. `used` may exceed `cap`
    (forced over-commit): the ratio then deliberately exceeds 1."""
    invokers = []
    cap_total = used_total = 0
    for name, healthy, cap, free, used in rows:
        invokers.append({
            "invoker": name,
            "healthy": bool(healthy),
            "capacity_mb": cap,
            "free_mb": free,
            "used_mb": used,
            "occupancy": round(used / cap, 4) if cap else 0.0,
        })
        cap_total += cap
        used_total += used
    return {
        "kernel": kernel,
        "invokers": invokers,
        "fleet": {
            "capacity_mb": cap_total,
            "used_mb": used_total,
            "occupancy": (round(used_total / cap_total, 4)
                          if cap_total else 0.0),
        },
    }


#: free_slot_histogram bucket upper bounds, in action slots: 0, 1-2, 3-4,
#: 5-8, 9-16, 17-32, 33-64, >64
_HIST_EDGES = None


def free_slot_histogram(free_mb: Sequence[int], slot_mb: int = 128
                        ) -> List[int]:
    """Compact fleet-shape digest: count of invokers whose free capacity is
    0, 1-2, 3-4, 5-8, 9-16, 17-32, 33-64, or >64 action slots of `slot_mb`
    MB each. Eight ints regardless of fleet size."""
    import numpy as np
    global _HIST_EDGES
    if _HIST_EDGES is None:
        _HIST_EDGES = np.asarray([1, 3, 5, 9, 17, 33, 65], np.int64)
    slots = np.asarray(free_mb, np.int64) // max(1, int(slot_mb))
    idx = np.searchsorted(_HIST_EDGES, slots, side="right")
    return np.bincount(idx, minlength=8).tolist()

"""Entity naming: names, paths, fully-qualified names.

Refs: EntityName/EntityPath in common/scala/.../core/entity/EntityPath.scala,
FullyQualifiedEntityName.scala. A path is /namespace[/package]; the default
namespace placeholder is "_" and resolves to the subject's own namespace.
"""
from __future__ import annotations

import re
from dataclasses import dataclass
from functools import lru_cache
from typing import Optional

ENTITY_NAME_RX = re.compile(r"^[a-zA-Z0-9][a-zA-Z0-9@ _\-.]*$")
DEFAULT_NAMESPACE = "_"
MAX_NAME_LENGTH = 256


@lru_cache(maxsize=8192)
def _name_ok(name: str) -> bool:
    """Validation verdict per distinct string: entity names repeat heavily
    on the hot path (every message parse re-validates the same few action/
    namespace names), so the regex runs once per distinct name."""
    return bool(name) and len(name) <= MAX_NAME_LENGTH \
        and ENTITY_NAME_RX.match(name) is not None


@lru_cache(maxsize=8192)
def _path_segments(path: str) -> tuple:
    """Split + validate a path once per distinct string (raises on invalid,
    so the cache only ever holds valid splits). Segments are regex-checked
    only — EntityPath has never enforced MAX_NAME_LENGTH per segment, and
    stored documents may rely on that."""
    segs = tuple(s for s in path.strip("/").split("/") if s != "")
    if not segs:
        raise ValueError(f"path {path!r} is not a valid entity path")
    for s in segs:
        if s != DEFAULT_NAMESPACE and not ENTITY_NAME_RX.match(s):
            raise ValueError(f"path segment {s!r} is not valid")
    return segs


@dataclass(frozen=True)
class EntityName:
    name: str

    def __post_init__(self):
        if not _name_ok(self.name):
            raise ValueError(f"name {self.name!r} is not a valid entity name")

    def to_path(self) -> "EntityPath":
        return EntityPath(self.name)

    def to_json(self):
        return self.name

    def __str__(self):
        return self.name


@dataclass(frozen=True)
class EntityPath:
    """Slash-separated path: "namespace" or "namespace/package"."""
    path: str

    def __post_init__(self):
        _path_segments(self.path)  # raises on invalid

    @property
    def segments(self):
        return list(_path_segments(self.path))

    @property
    def root(self) -> EntityName:
        seg = self.segments[0]
        return EntityName(seg) if seg != DEFAULT_NAMESPACE else EntityName("_default_")

    @property
    def root_str(self) -> str:
        return self.segments[0]

    @property
    def default_package(self) -> bool:
        return len(self.segments) == 1

    @property
    def is_default_namespace(self) -> bool:
        return self.segments[0] == DEFAULT_NAMESPACE

    def resolve_namespace(self, namespace: str) -> "EntityPath":
        """Replace a leading "_" with the subject's namespace
        (ref EntityPath.resolveNamespace)."""
        segs = self.segments
        if segs[0] == DEFAULT_NAMESPACE:
            return EntityPath("/".join([namespace] + segs[1:]))
        return self

    def add(self, name) -> "EntityPath":
        return EntityPath(self.path.strip("/") + "/" + str(name))

    @property
    def rel_path(self) -> Optional["EntityPath"]:
        """Path without the root namespace, if any."""
        segs = self.segments
        return EntityPath("/".join(segs[1:])) if len(segs) > 1 else None

    def to_fqn(self) -> "FullyQualifiedEntityName":
        segs = self.segments
        return FullyQualifiedEntityName(EntityPath("/".join(segs[:-1])), EntityName(segs[-1]))

    def to_json(self):
        return "/".join(self.segments)

    def __str__(self):
        return "/".join(self.segments)


@dataclass(frozen=True)
class FullyQualifiedEntityName:
    """path + name, e.g. namespace/package + action."""
    path: EntityPath
    name: EntityName
    version: Optional[object] = None

    @classmethod
    def parse(cls, fqn: str) -> "FullyQualifiedEntityName":
        segs = [s for s in fqn.strip("/").split("/") if s]
        if len(segs) < 2:
            raise ValueError(f"{fqn!r} is not a fully qualified entity name")
        return cls(EntityPath("/".join(segs[:-1])), EntityName(segs[-1]))

    @property
    def fully_qualified_name(self) -> str:
        return f"{self.path}/{self.name}"

    @property
    def namespace(self) -> str:
        return self.path.root_str

    def resolve(self, namespace: str) -> "FullyQualifiedEntityName":
        return FullyQualifiedEntityName(self.path.resolve_namespace(namespace), self.name, self.version)

    def add(self, name) -> "FullyQualifiedEntityName":
        return FullyQualifiedEntityName(self.path.add(self.name), EntityName(str(name)))

    def to_doc_id(self) -> str:
        return self.fully_qualified_name

    def to_json(self):
        return {"path": self.path.to_json(), "name": self.name.to_json()}

    @classmethod
    def from_json(cls, j) -> "FullyQualifiedEntityName":
        if isinstance(j, str):
            return cls.parse(j)
        return cls(EntityPath(j["path"]), EntityName(j["name"]))

    def __str__(self):
        return self.fully_qualified_name

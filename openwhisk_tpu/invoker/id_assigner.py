"""Stable invoker-id assignment without Zookeeper.

Rebuild of core/invoker/.../InstanceIdAssigner.scala — the reference CASes a
Curator SharedCount at /invokers/idAssignment to give each `uniqueName` a
stable id across restarts. Here the same CAS loop runs against the
ArtifactStore's revisioned document semantics: the assignment map lives in
one document; concurrent assigners conflict on the revision and retry.
"""
from __future__ import annotations

import asyncio

from ..database import ArtifactStore, DocumentConflict, NoDocumentException

DOC_ID = "system/invokerIdAssignment"


class InstanceIdAssigner:
    def __init__(self, store: ArtifactStore):
        self.store = store

    async def assign(self, unique_name: str, overwrite_id: int = None) -> int:
        """Return the stable id for unique_name, allocating the next free id
        on first sight (CAS retry loop on conflicting writers)."""
        for _ in range(50):
            try:
                doc = await self.store.get(DOC_ID)
                rev = doc.get("_rev")
            except NoDocumentException:
                doc = {"entityType": "system", "namespace": "system",
                       "name": "invokerIdAssignment", "updated": 0,
                       "assignments": {}, "next": 0}
                rev = None
            assignments = doc.get("assignments", {})
            if overwrite_id is not None:
                assigned = overwrite_id
                if assignments.get(unique_name) == assigned:
                    return assigned
                assignments[unique_name] = assigned
                doc["next"] = max(doc.get("next", 0), assigned + 1)
            elif unique_name in assignments:
                return assignments[unique_name]
            else:
                assigned = doc.get("next", 0)
                assignments[unique_name] = assigned
                doc["next"] = assigned + 1
            doc["assignments"] = assignments
            doc.pop("_rev", None)
            doc.pop("_id", None)
            try:
                await self.store.put(DOC_ID, doc, rev)
                return assigned
            except DocumentConflict:
                await asyncio.sleep(0.01)  # lost the race: re-read and retry
        raise RuntimeError("could not assign an invoker id (CAS contention)")

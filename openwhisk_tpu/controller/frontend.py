"""Sharded front end: N admission worker loops partitioned by namespace.

ISSUE 12 tentpole (3): the API layer stops being one Python loop. The
controller's front door — entitlement throttles, admission batching — ran
entirely on the single controller event loop: every arrival paid its
admission Python there, serialized with the balancer's dispatch/readback
work. This plane spreads the ADMISSION state over N worker event loops
(one thread each), partitioned by namespace hash:

  * each shard OWNS its namespace slice's throttle state — its own
    rolling-minute `RateThrottler` deques and its own `AdmissionPlane`
    micro-batcher (the PR 7 vectorized admission, unchanged) — so there
    is no cross-shard locking and no shared mutable admission state;
  * a namespace's every request lands on the same shard (crc32 hash), so
    per-namespace decisions are EXACTLY the single-loop decisions: the
    rolling window, the override replay rule and the intra-batch
    concurrency accounting all see the same per-namespace arrival order
    the serial path would (only unrelated namespaces decide in
    parallel, and they never shared state to begin with);
  * admitted requests return to the caller's loop and feed the single
    device balancer through the existing coalescers — the balancer, its
    micro-batcher and the bus stay one plane.
  * the CONCURRENCY throttle reads the balancer's in-flight counters
    cross-thread (GIL-atomic dict reads — the same already-racy
    read-then-admit the serial path does) and keeps the intra-batch
    accounting per shard flush.

Partition count is the `CONFIG_whisk_frontend_shards` knob. `shards=1`
(the default) builds NOTHING: `LocalEntitlementProvider` keeps its
single `AdmissionPlane` on the controller loop — bit-exact with today's
behavior (the off-switch contract; parity-fuzzed in
tests/test_columnar_batch.py).
"""
from __future__ import annotations

import asyncio
import threading
import zlib
from dataclasses import dataclass
from typing import List, Optional

from ..utils.config import load_config


@dataclass(frozen=True)
class FrontendConfig:
    """`CONFIG_whisk_frontend_*` env overrides."""
    #: admission worker loops; 1 = single-loop (today's exact behavior)
    shards: int = 1

    @classmethod
    def from_env(cls) -> "FrontendConfig":
        return load_config(cls, env_path="frontend")


class _ShardFacade:
    """The provider facade one shard's AdmissionPlane flushes against:
    shard-LOCAL rate throttlers (this shard's namespace slice), the
    SHARED balancer counters for the concurrency throttle, and throttle
    events forwarded threadsafe to the owning provider's loop."""

    def __init__(self, provider, plane: "FrontendShardPlane"):
        from .entitlement import RateThrottler
        self._provider = provider
        self._plane = plane
        self.invoke_rate = RateThrottler(provider.invoke_rate.description,
                                         provider.invoke_rate.default_per_minute)
        self.fire_rate = RateThrottler(provider.fire_rate.description,
                                       provider.fire_rate.default_per_minute)
        self.load_balancer = provider.load_balancer
        self.concurrent = provider.concurrent

    def _throttle_event(self, which: str, identity) -> None:
        """Shard threads must not touch the main loop's producer/tasks:
        hop the event back to the loop that owns them."""
        main = self._plane.main_loop
        if main is None or main.is_closed():
            return
        main.call_soon_threadsafe(self._provider._throttle_event, which,
                                  identity)


class _Shard:
    """One admission worker: a daemon thread running an event loop that
    owns one namespace slice's throttle state + admission micro-batcher."""

    def __init__(self, index: int, provider, plane: "FrontendShardPlane",
                 admission_config=None):
        from .admission import AdmissionPlane
        self.index = index
        self.facade = _ShardFacade(provider, plane)
        self.loop = asyncio.new_event_loop()
        self.admission = AdmissionPlane(self.facade, admission_config)
        self._thread = threading.Thread(
            target=self._run, name=f"frontend-shard-{index}", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    def signal_stop(self) -> None:
        if self.loop.is_running():
            self.loop.call_soon_threadsafe(self.loop.stop)

    def join(self, timeout: float = 5.0) -> None:
        self._thread.join(timeout=timeout)
        if not self.loop.is_closed():
            self.loop.close()

    def stop(self) -> None:
        self.signal_stop()
        self.join()


class FrontendShardPlane:
    """Routes ACTIVATE throttle checks to the shard owning the caller's
    namespace (see module doc). Constructed ONLY for shards >= 2 —
    `maybe_shard_frontend` returns None otherwise, leaving the serial
    single-loop admission path in place bit-exactly."""

    def __init__(self, provider, shards: int, admission_config=None):
        self.shards_n = max(2, int(shards))
        #: the loop that owns the provider's producer/event side effects;
        #: captured at the first check (the provider may be constructed
        #: before any loop runs)
        self.main_loop: Optional[asyncio.AbstractEventLoop] = None
        self._shards: List[_Shard] = [
            _Shard(i, provider, self, admission_config)
            for i in range(self.shards_n)]
        self.routed = 0

    def shard_of(self, namespace_id: str) -> int:
        """Deterministic namespace -> shard map (crc32, not hash():
        stable across processes and PYTHONHASHSEED)."""
        return zlib.crc32(namespace_id.encode()) % self.shards_n

    async def check_throttles(self, identity, is_trigger_fire: bool) -> None:
        """The sharded stand-in for the single-loop admission check:
        returns on admit, raises the serial path's exact throttle
        exceptions on reject (they propagate through the cross-thread
        future untouched)."""
        if self.main_loop is None:
            self.main_loop = asyncio.get_running_loop()
        shard = self._shards[self.shard_of(identity.namespace.uuid.asString)]
        self.routed += 1
        cf = asyncio.run_coroutine_threadsafe(
            shard.admission.check_throttles(identity, is_trigger_fire),
            shard.loop)
        await asyncio.wrap_future(cf)

    def stats(self) -> dict:
        return {
            "shards": self.shards_n,
            "routed": self.routed,
            "per_shard_checked": [s.admission.checked for s in self._shards],
            "per_shard_batches": [s.admission.batches for s in self._shards],
        }

    def close(self) -> None:
        """Stage the shutdown: signal every shard loop first, then join —
        total wall is bounded by the slowest shard, not the sum. Blocking
        (thread joins): async callers run it on the executor
        (LocalEntitlementProvider.close does)."""
        for s in self._shards:
            s.signal_stop()
        for s in self._shards:
            s.join()


def maybe_shard_frontend(provider, config: Optional[FrontendConfig] = None,
                         admission_config=None
                         ) -> Optional[FrontendShardPlane]:
    """The wiring hook (the `maybe_coalesce` pattern): a plane when
    `CONFIG_whisk_frontend_shards` >= 2, None — today's exact single-loop
    behavior — otherwise."""
    cfg = config if config is not None else FrontendConfig.from_env()
    if cfg.shards <= 1:
        return None
    return FrontendShardPlane(provider, cfg.shards, admission_config)

"""Aux subsystem tests: tracing, user events, blacklist, attachments,
file activation storage, admin CLI, balancer snapshot/restore."""
import asyncio
import json
import os

import pytest

from openwhisk_tpu.core.entity import (ActivationId, ActivationResponse,
                                       CodeExec, ControllerInstanceId,
                                       EntityName, EntityPath, Identity,
                                       InvokerInstanceId, MB, Parameters,
                                       Subject, UserLimits, WhiskAction,
                                       WhiskActivation, WhiskAuthRecord)
from openwhisk_tpu.core.entity.parameters import ParameterValue
from openwhisk_tpu.database import (AuthStore, EntityStore, MemoryArtifactStore,
                                    SqliteArtifactStore)
from openwhisk_tpu.database.file_activation_store import (
    ArtifactWithFileStorageActivationStore)
from openwhisk_tpu.invoker.blacklist import NamespaceBlacklist
from openwhisk_tpu.messaging import EventMessage, MemoryMessagingProvider
from openwhisk_tpu.controller.monitoring import UserEventsRecorder
from openwhisk_tpu.utils.tracing import Tracer
from openwhisk_tpu.utils.transaction import TransactionId


def run(coro):
    return asyncio.run(coro)


class TestTracing:
    def test_span_hierarchy_and_report(self):
        tracer = Tracer()
        tid = TransactionId()
        parent = tracer.start_span("controller_activation", tid)
        child = tracer.start_span("loadbalancer_schedule", tid)
        assert child.trace_id == parent.trace_id
        assert child.parent_id == parent.span_id
        tracer.finish_span(tid)
        tracer.finish_span(tid, tags={"action": "ns/a"})
        spans = tracer.reporter.spans
        assert [s.name for s in spans] == ["loadbalancer_schedule",
                                           "controller_activation"]
        assert spans[1].tags["action"] == "ns/a"

    def test_context_survives_the_bus(self):
        t_controller, t_invoker = Tracer(), Tracer()
        tid = TransactionId()
        span = t_controller.start_span("controller_activation", tid)
        ctx = t_controller.get_trace_context(tid)
        assert ctx and "traceparent" in ctx
        # invoker side: restore and open a child
        remote_tid = TransactionId(tid.id)
        t_invoker.set_trace_context(remote_tid, ctx)
        child = t_invoker.start_span("invoker_run", remote_tid)
        assert child.trace_id == span.trace_id  # one distributed trace


class TestUserEvents:
    def test_activation_and_metric_events_recorded(self):
        async def go():
            provider = MemoryMessagingProvider()
            rec = UserEventsRecorder(provider)
            rec.start()
            prod = provider.get_producer()
            act = WhiskActivation(EntityPath("guest"), EntityName("hello"),
                                  Subject("guest-user"), ActivationId.generate(),
                                  1.0, 2.0, ActivationResponse.success({}),
                                  duration=42)
            await prod.send("events", EventMessage.for_activation(
                "invoker0", act, "uuid-1", kind="python:3", init_time=7))
            await prod.send("events", EventMessage.for_metric(
                "controller", "ConcurrentRateLimit", 1, "guest-user", "guest",
                "uuid-1"))
            await asyncio.sleep(0.15)
            text = rec.prometheus_text()
            await rec.stop()
            return text

        text = run(go())
        assert ('openwhisk_userevents_activations_total'
                '{action="guest/hello"} 1') in text
        assert ('openwhisk_userevents_cold_starts_total'
                '{action="guest/hello"} 1') in text
        assert ('openwhisk_userevents_rate_limit_total'
                '{metric="ConcurrentRateLimit",namespace="guest"} 1') in text


class TestBlacklist:
    def test_blocked_and_zero_limit_namespaces(self):
        async def go():
            store = AuthStore(MemoryArtifactStore())
            ok = Identity.generate("goodns")
            await store.put(WhiskAuthRecord(ok.subject, [ok.namespace],
                                            [ok.authkey]))
            blocked = Identity.generate("badns")
            await store.put(WhiskAuthRecord(blocked.subject, [blocked.namespace],
                                            [blocked.authkey], blocked=True))
            zero = Identity.generate("zerons")
            rec = WhiskAuthRecord(zero.subject, [zero.namespace], [zero.authkey],
                                  limits={"zerons": UserLimits(
                                      concurrent_invocations=0)})
            await store.put(rec)
            bl = NamespaceBlacklist(store)
            await bl.refresh()
            zero_with_limits = rec.identities()[0]
            return (bl.is_blacklisted(ok), bl.is_blacklisted(blocked),
                    bl.is_blacklisted(zero_with_limits), len(bl))

        ok, blocked, zero, n = run(go())
        assert not ok and blocked and zero
        assert n == 2


class TestCodeAttachments:
    def test_large_code_roundtrips_via_attachment(self):
        async def go():
            raw = MemoryArtifactStore()
            es = EntityStore(raw)
            big_code = "def main(a):\n    return {'x': 1}\n" + "#" * (80 * 1024)
            action = WhiskAction(EntityPath("guest"), EntityName("big"),
                                 CodeExec(kind="python:3", code=big_code))
            await es.put(action)
            # raw doc must NOT inline the code
            doc = await raw.get("guest/big")
            assert isinstance(doc["exec"]["code"], dict)
            ct, data = await raw.read_attachment(
                "guest/big", doc["exec"]["code"]["attachmentName"])
            assert len(data) == len(big_code.encode())
            # fresh store (cold cache) inflates transparently
            es2 = EntityStore(raw)
            got = await es2.get_action("guest/big")
            return got.exec.code == big_code

        assert run(go())


class TestFileActivationStore:
    def test_records_appended_as_ndjson(self, tmp_path):
        async def go():
            path = str(tmp_path / "activations.log")
            st = ArtifactWithFileStorageActivationStore(
                MemoryArtifactStore(), path, write_logs_to_artifact=False)
            act = WhiskActivation(EntityPath("guest"), EntityName("hello"),
                                  Subject("guest-user"), ActivationId.generate(),
                                  1.0, 2.0, ActivationResponse.success({"r": 1}),
                                  logs=["stdout: x"], duration=5)
            await st.store(act)
            stored = await st.get("guest", act.activation_id)
            lines = [json.loads(l) for l in open(path)]
            return stored, lines

        stored, lines = run(go())
        assert stored.logs == []          # logs stripped from the artifact
        assert len(lines) == 1
        assert lines[0]["logs"] == ["stdout: x"]  # ...but shipped to the file


class TestAdminCli:
    def test_user_lifecycle_and_limits(self, tmp_path, capsys):
        from openwhisk_tpu.tools import wskadmin
        db = str(tmp_path / "admin.db")
        assert wskadmin.main(["--db", db, "user", "create", "alice"]) == 0
        auth_line = capsys.readouterr().out.strip()
        assert ":" in auth_line
        assert wskadmin.main(["--db", db, "user", "list"]) == 0
        assert "alice" in capsys.readouterr().out
        assert wskadmin.main(["--db", db, "limits", "set", "alice",
                              "--invocations-per-minute", "5"]) == 0
        capsys.readouterr()
        assert wskadmin.main(["--db", db, "limits", "get", "alice"]) == 0
        assert json.loads(capsys.readouterr().out)["invocationsPerMinute"] == 5
        assert wskadmin.main(["--db", db, "user", "block", "alice"]) == 0
        capsys.readouterr()
        assert wskadmin.main(["--db", db, "user", "list"]) == 0
        assert "(blocked)" in capsys.readouterr().out

    def test_limits_flow_into_identity(self, tmp_path):
        from openwhisk_tpu.tools import wskadmin
        db = str(tmp_path / "admin2.db")
        wskadmin.main(["--db", db, "user", "create", "bobby"])
        wskadmin.main(["--db", db, "limits", "set", "bobby",
                       "--concurrent-invocations", "3"])

        async def go():
            store = AuthStore(SqliteArtifactStore(db))
            ident = await store.identity_by_namespace("bobby")
            return ident.limits.concurrent_invocations

        assert run(go()) == 3


class TestBalancerSnapshot:
    def test_snapshot_restore_roundtrip(self):
        async def go():
            from openwhisk_tpu.controller.loadbalancer import TpuBalancer
            from tests.test_balancers import (SimInvoker, _fleet, _ping_all,
                                              make_action, make_msg)
            import numpy as np
            provider = MemoryMessagingProvider()
            bal = TpuBalancer(provider, ControllerInstanceId("0"),
                              managed_fraction=1.0, blackbox_fraction=0.0)
            await bal.start()
            invokers, producer = await _fleet(provider, 4, delay=5.0)  # holds stay
            await _ping_all(invokers, producer)
            ident = Identity.generate("guest")
            action = make_action("snapme", memory=256)
            await bal.publish(action, make_msg(action, ident, True))
            snap = bal.snapshot()
            # restore into a brand-new balancer
            bal2 = TpuBalancer(provider, ControllerInstanceId("0"),
                               managed_fraction=1.0, blackbox_fraction=0.0)
            bal2.restore(snap)
            same_free = (np.asarray(bal2.state.free_mb).tolist() ==
                         np.asarray(bal.state.free_mb).tolist())
            same_reg = [i.instance for i in bal2._registry] == \
                [i.instance for i in bal._registry]
            await bal.close()
            for inv in invokers:
                await inv.stop()
            return same_free, same_reg, json.dumps(snap) is not None

        same_free, same_reg, serializable = run(go())
        assert same_free and same_reg and serializable

"""Executable kinds of an action.

Ref: common/scala/.../core/entity/Exec.scala:49-231 — the kind taxonomy:
  CodeExec      — managed-runtime code ("python:3", "nodejs:14", ...),
                  inline string or attachment, optional `main`, binary flag
  BlackBoxExec  — arbitrary docker image (+ optional code injected at /init)
  SequenceExec  — ordered list of component actions (control-flow construct)
plus the *metadata* twins used on the control plane where shipping code bodies
is wasteful (ExecMetaDataBase — only kind/binary/image are needed by the
balancer and pool).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from .names import FullyQualifiedEntityName
from .parameters import MalformedEntity

SEQUENCE_KIND = "sequence"
BLACKBOX_KIND = "blackbox"


class Exec:
    kind: str = ""

    @property
    def deprecated(self) -> bool:
        return False

    def to_json(self) -> dict:
        raise NotImplementedError

    @staticmethod
    def from_json(j: dict) -> "Exec":
        if j is not None and not isinstance(j, dict):
            raise MalformedEntity("exec must be an object")
        kind = (j or {}).get("kind", "")
        if not isinstance(kind, str):
            raise MalformedEntity("exec kind must be a string")
        if kind == SEQUENCE_KIND:
            return SequenceExec.from_json(j)
        if kind == BLACKBOX_KIND:
            return BlackBoxExec.from_json(j)
        if not kind:
            raise ValueError("exec has no kind")
        return CodeExec.from_json(j)


@dataclass
class CodeExec(Exec):
    """Managed-runtime code (ref Exec.scala CodeExecAsString/AsAttachment)."""
    kind: str = "python:3"
    code: str = ""
    main: Optional[str] = None
    binary: bool = False
    image: Optional[str] = None       # resolved runtime image from the manifest
    entry_point: Optional[str] = None

    @property
    def pull(self) -> bool:
        return False

    def to_json(self) -> dict:
        j = {"kind": self.kind, "code": self.code, "binary": self.binary}
        if self.main:
            j["main"] = self.main
        if self.image:
            j["image"] = self.image
        return j

    @classmethod
    def from_json(cls, j: dict) -> "CodeExec":
        return cls(kind=j["kind"], code=j.get("code", ""), main=j.get("main"),
                   binary=bool(j.get("binary", False)), image=j.get("image"))


@dataclass
class BlackBoxExec(Exec):
    """User-supplied docker image (ref Exec.scala BlackBoxExec)."""
    image: str = ""
    code: Optional[str] = None
    main: Optional[str] = None
    binary: bool = False
    native: bool = False  # true when the image is a system runtime image
    kind: str = field(default=BLACKBOX_KIND, init=False)

    @property
    def pull(self) -> bool:
        return not self.native

    def to_json(self) -> dict:
        j = {"kind": BLACKBOX_KIND, "image": self.image, "binary": self.binary}
        if self.code:
            j["code"] = self.code
        if self.main:
            j["main"] = self.main
        return j

    @classmethod
    def from_json(cls, j: dict) -> "BlackBoxExec":
        if not isinstance(j.get("image"), str):
            raise MalformedEntity("blackbox exec needs a string image")
        return cls(image=j["image"], code=j.get("code"), main=j.get("main"),
                   binary=bool(j.get("binary", False)))


@dataclass
class SequenceExec(Exec):
    """A pipeline of component actions executed in order
    (ref Exec.scala SequenceExec; executed by SequenceActions.scala)."""
    components: List[FullyQualifiedEntityName] = field(default_factory=list)
    kind: str = field(default=SEQUENCE_KIND, init=False)

    def to_json(self) -> dict:
        return {"kind": SEQUENCE_KIND,
                "components": [str(c) for c in self.components]}

    @classmethod
    def from_json(cls, j: dict) -> "SequenceExec":
        comps = j.get("components", [])
        if not isinstance(comps, list) or \
                not all(isinstance(c, str) for c in comps):
            raise MalformedEntity(
                "sequence components must be a list of action names")
        return cls(components=[FullyQualifiedEntityName.parse(c) for c in comps])


# ---------------------------------------------------------------------------
# Metadata twins (ref Exec.scala ExecMetaDataBase): enough for scheduling.
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class ExecMetaData:
    kind: str
    binary: bool = False
    image: Optional[str] = None

    @property
    def is_blackbox(self) -> bool:
        return self.kind == BLACKBOX_KIND

    @property
    def is_sequence(self) -> bool:
        return self.kind == SEQUENCE_KIND

    @classmethod
    def of(cls, e: Exec) -> "ExecMetaData":
        img = getattr(e, "image", None)
        return cls(kind=e.kind, binary=getattr(e, "binary", False), image=img)

    def to_json(self):
        return {"kind": self.kind, "binary": self.binary, "image": self.image}

"""Placement flight recorder + scheduler introspection plane (ISSUE 1).

Covers: SeqRingBuffer wraparound; FlightRecorder explain() hit/miss and the
index staying consistent across wrap; the recorder-disabled config path;
occupancy math against a known books state; all three balancers reporting
through the shared base-class hook; and the three /admin/placement/*
controller endpoints (auth required, JSON shape, 404 after wrap).
"""
import asyncio
import base64
import time

import aiohttp
import numpy as np
import pytest

from openwhisk_tpu.controller.loadbalancer import (LeanBalancer,
                                                   ShardingBalancer,
                                                   TpuBalancer)
from openwhisk_tpu.controller.loadbalancer.flight_recorder import (
    BatchRecord, FlightRecorder, free_slot_histogram)
from openwhisk_tpu.core.entity import (ControllerInstanceId, Identity,
                                       WhiskAuthRecord)
from openwhisk_tpu.messaging import MemoryMessagingProvider
from openwhisk_tpu.utils.ring_buffer import SeqRingBuffer
from tests.test_balancers import _fleet, _ping_all, make_action, make_msg


class TestSeqRingBuffer:
    def test_fill_and_wrap(self):
        r = SeqRingBuffer(3)
        assert len(r) == 0 and r.evicted == 0
        seqs = [r.append(f"i{i}")[0] for i in range(3)]
        assert seqs == [0, 1, 2]
        assert len(r) == 3 and r.evicted == 0
        seq, evicted = r.append("i3")  # wraps: i0 out
        assert (seq, evicted) == (3, "i0")
        assert r.evicted == 1
        assert r.get(0) is None          # wrapped past
        assert r.get(3) == "i3"
        assert r.get(99) is None         # never written
        assert r.last(2) == ["i2", "i3"]
        assert r.last(10) == ["i1", "i2", "i3"]

    def test_rejects_nonpositive_size(self):
        with pytest.raises(ValueError):
            SeqRingBuffer(0)


def _one_decision_record(aid, invoker="invoker0"):
    return BatchRecord(digest={"kernel": "cpu"}, decisions=[
        (aid, "guest/act", 0, invoker, False, False, 128)])


class TestFlightRecorder:
    def test_explain_hit_and_miss(self):
        fr = FlightRecorder(size=4)
        fr.record(_one_decision_record("aid-1"))
        out = fr.explain("aid-1")
        assert out["decision"]["activation_id"] == "aid-1"
        assert out["decision"]["invoker"] == "invoker0"
        assert out["batch"]["digest"]["kernel"] == "cpu"
        assert fr.explain("aid-unknown") is None

    def test_wrap_evicts_index(self):
        fr = FlightRecorder(size=2)
        for i in range(5):
            fr.record(_one_decision_record(f"aid-{i}"))
        assert fr.dropped == 3
        # wrapped-past activations answer None; live ones still resolve
        for i in range(3):
            assert fr.explain(f"aid-{i}") is None
        for i in (3, 4):
            assert fr.explain(f"aid-{i}")["decision"]["activation_id"] == f"aid-{i}"
        # the index never outgrows the live window
        assert len(fr._index) == 2

    def test_recent_order_and_decision_toggle(self):
        fr = FlightRecorder(size=8)
        for i in range(3):
            fr.record(_one_decision_record(f"aid-{i}"))
        recs = fr.recent(2)
        assert [r["seq"] for r in recs] == [1, 2]
        assert "decisions" in recs[0]
        slim = fr.recent(2, with_decisions=False)
        assert "decisions" not in slim[0]
        assert slim[0]["batch_size"] == 1

    def test_disabled_via_env_config(self, monkeypatch):
        monkeypatch.setenv(
            "CONFIG_whisk_loadBalancer_flightRecorder_enabled", "false")
        monkeypatch.setenv(
            "CONFIG_whisk_loadBalancer_flightRecorder_size", "17")
        fr = FlightRecorder.from_config()
        assert fr.enabled is False
        assert fr.size == 17

    def test_free_slot_histogram_buckets(self):
        # 0 slots, 1 slot, 4 slots, 16 slots, 100 slots (slot_mb=128)
        hist = free_slot_histogram([0, 128, 512, 2048, 12800], 128)
        # buckets: 0 | 1-2 | 3-4 | 5-8 | 9-16 | 17-32 | 33-64 | >64 slots
        assert hist == [1, 1, 1, 0, 1, 0, 0, 1]
        assert sum(hist) == 5


class TestTpuBalancerRecording:
    def test_publish_records_and_explains(self):
        async def go():
            provider = MemoryMessagingProvider()
            bal = TpuBalancer(provider, ControllerInstanceId("0"),
                              managed_fraction=1.0, blackbox_fraction=0.0)
            await bal.start()
            invokers, producer = await _fleet(provider, 2)
            await _ping_all(invokers, producer)
            ident = Identity.generate("guest")
            action = make_action("recorded", memory=256)
            msgs = [make_msg(action, ident, True) for _ in range(4)]
            await asyncio.gather(*[
                await bal.publish(action, m) for m in msgs])
            fr = bal.flight_recorder
            ex = fr.explain(msgs[0].activation_id.asString)
            healthy = bal.metrics.gauge_value("loadbalancer_healthy_invokers")
            qd = bal.metrics.gauge_value("loadbalancer_placement_queue_depth")
            occ = bal.metrics.gauge_value("loadbalancer_fleet_occupancy_ratio")
            dropped = bal.metrics.gauge_value(
                "loadbalancer_flight_recorder_dropped")
            await bal.close()
            for inv in invokers:
                await inv.stop()
            return ex, healthy, qd, occ, dropped

        ex, healthy, qd, occ, dropped = asyncio.run(go())
        d = ex["decision"]
        assert d["invoker"] in ("invoker0", "invoker1")
        assert d["forced"] is False and d["throttled"] is False
        assert d["slot_mb"] == 256
        batch = ex["batch"]
        assert batch["digest"]["kernel"] in ("xla", "pallas")
        assert batch["digest"]["healthy_invokers"] == 2
        assert sum(batch["digest"]["free_slot_hist"]) == 2  # 2 invokers
        for phase in ("assembly_ms", "dispatch_ms", "readback_ms",
                      "fanout_ms"):
            assert phase in batch["timings"]
        # gauges refreshed per batch
        assert healthy == 2
        assert qd is not None and occ is not None and dropped == 0

    def test_ring_wrap_forgets_old_activations(self):
        async def go():
            provider = MemoryMessagingProvider()
            bal = TpuBalancer(provider, ControllerInstanceId("0"),
                              managed_fraction=1.0, blackbox_fraction=0.0,
                              batch_window=0.0, max_batch=1)
            bal.flight_recorder = FlightRecorder(size=2)
            await bal.start()
            invokers, producer = await _fleet(provider, 2)
            await _ping_all(invokers, producer)
            ident = Identity.generate("guest")
            action = make_action("wrapped", memory=128)
            msgs = [make_msg(action, ident, True) for _ in range(6)]
            # max_batch=1: every publish is its own batch record
            for m in msgs:
                await (await bal.publish(action, m))
            fr = bal.flight_recorder
            first = fr.explain(msgs[0].activation_id.asString)
            last = fr.explain(msgs[-1].activation_id.asString)
            dropped = fr.dropped
            await bal.close()
            for inv in invokers:
                await inv.stop()
            return first, last, dropped

        first, last, dropped = asyncio.run(go())
        assert first is None          # wrapped past
        assert last is not None
        assert dropped >= 4

    def test_disabled_recorder_records_nothing(self):
        async def go():
            provider = MemoryMessagingProvider()
            bal = TpuBalancer(provider, ControllerInstanceId("0"),
                              managed_fraction=1.0, blackbox_fraction=0.0)
            bal.flight_recorder.enabled = False
            await bal.start()
            invokers, producer = await _fleet(provider, 2)
            await _ping_all(invokers, producer)
            ident = Identity.generate("guest")
            action = make_action("dark", memory=128)
            msg = make_msg(action, ident, True)
            await (await bal.publish(action, msg))
            n = len(bal.flight_recorder)
            ex = bal.flight_recorder.explain(msg.activation_id.asString)
            await bal.close()
            for inv in invokers:
                await inv.stop()
            return n, ex

        n, ex = asyncio.run(go())
        assert n == 0 and ex is None

    def test_occupancy_math_against_known_books(self):
        async def go():
            provider = MemoryMessagingProvider()
            bal = TpuBalancer(provider, ControllerInstanceId("0"),
                              managed_fraction=1.0, blackbox_fraction=0.0)
            await bal.start()
            # slow invokers keep the placement in flight while we read books
            invokers, producer = await _fleet(provider, 2, memory_mb=2048,
                                              delay=0.6)
            await _ping_all(invokers, producer)
            ident = Identity.generate("guest")
            action = make_action("occupied", memory=256)
            promise = await bal.publish(action, make_msg(action, ident, True))
            mid = bal.occupancy()
            await promise
            # drain the release into the books
            for _ in range(100):
                await asyncio.sleep(0.01)
                after = bal.occupancy()
                if after["fleet"]["used_mb"] == 0:
                    break
            await bal.close()
            for inv in invokers:
                await inv.stop()
            return mid, after

        mid, after = asyncio.run(go())
        assert mid["kernel"] in ("xla", "pallas")
        assert len(mid["invokers"]) == 2
        assert all(r["capacity_mb"] == 2048 for r in mid["invokers"])
        # exactly the in-flight 256 MB is held, on exactly one invoker
        assert mid["fleet"] == {"capacity_mb": 4096, "used_mb": 256,
                                "occupancy": round(256 / 4096, 4)}
        held = [r for r in mid["invokers"] if r["used_mb"] == 256]
        assert len(held) == 1
        assert held[0]["free_mb"] == 2048 - 256
        assert held[0]["occupancy"] == round(256 / 2048, 4)
        # after completion the books are square again
        assert after["fleet"]["used_mb"] == 0


class TestCpuBalancersRecord:
    def test_sharding_balancer_records_cpu_digest(self):
        async def go():
            provider = MemoryMessagingProvider()
            bal = ShardingBalancer(provider, ControllerInstanceId("0"),
                                   managed_fraction=1.0,
                                   blackbox_fraction=0.0)
            await bal.start()
            invokers, producer = await _fleet(provider, 2)
            await _ping_all(invokers, producer)
            ident = Identity.generate("guest")
            action = make_action("cpurec", memory=256)
            msg = make_msg(action, ident, True)
            await (await bal.publish(action, msg))
            ex = bal.flight_recorder.explain(msg.activation_id.asString)
            occ = bal.occupancy()
            await bal.close()
            for inv in invokers:
                await inv.stop()
            return ex, occ

        ex, occ = asyncio.run(go())
        assert ex["batch"]["digest"]["kernel"] == "cpu"
        assert ex["batch"]["digest"]["healthy_invokers"] == 2
        assert ex["decision"]["invoker"] in ("invoker0", "invoker1")
        assert occ["kernel"] == "cpu"
        assert len(occ["invokers"]) == 2
        assert occ["fleet"]["capacity_mb"] == 4096

    def test_lean_balancer_records_cpu_digest(self):
        async def go():
            provider = MemoryMessagingProvider()

            class _DummyInvoker:
                async def stop(self):
                    pass

            async def factory(invoker_id, messaging_provider):
                return _DummyInvoker()

            bal = LeanBalancer(provider, ControllerInstanceId("0"), factory)
            await bal.start()
            ident = Identity.generate("guest")
            action = make_action("leanrec", memory=128)
            msg = make_msg(action, ident, False)
            await bal.publish(action, msg)
            ex = bal.flight_recorder.explain(msg.activation_id.asString)
            occ = bal.occupancy()
            await bal.close()
            return ex, occ

        ex, occ = asyncio.run(go())
        assert ex["batch"]["digest"]["kernel"] == "cpu"
        assert ex["decision"]["invoker"] == "invoker0"
        assert occ["kernel"] == "cpu"
        # the un-acked activation rides in the in-flight occupancy view
        assert occ["fleet"]["used_mb"] == 128


PORT = 13377


class TestAdminEndpoints:
    """The three /admin/placement/* endpoints on a live controller HTTP
    surface, with a TpuBalancer placing through publish()."""

    def _run(self, scenario):
        from openwhisk_tpu.controller.core import Controller

        async def go():
            provider = MemoryMessagingProvider()
            bal = TpuBalancer(provider, ControllerInstanceId("0"),
                              managed_fraction=1.0, blackbox_fraction=0.0)
            controller = Controller(ControllerInstanceId("0"), provider,
                                    load_balancer=bal)
            ident = Identity.generate("guest")
            await controller.auth_store.put(WhiskAuthRecord(
                ident.subject, [ident.namespace], [ident.authkey]))
            await controller.start(port=PORT)
            invokers, producer = await _fleet(provider, 2)
            await _ping_all(invokers, producer)
            hdrs = {"Authorization": "Basic " + base64.b64encode(
                ident.authkey.compact.encode()).decode()}
            try:
                async with aiohttp.ClientSession() as s:
                    return await scenario(bal, ident, s, hdrs)
            finally:
                await controller.stop()
                for inv in invokers:
                    await inv.stop()

        return asyncio.run(go())

    def test_auth_required(self):
        async def scenario(bal, ident, s, hdrs):
            base = f"http://127.0.0.1:{PORT}/admin/placement"
            out = {}
            for path in ("/recent", "/explain/deadbeef", "/occupancy"):
                async with s.get(base + path) as r:
                    out[path] = r.status
            return out

        statuses = self._run(scenario)
        assert all(v == 401 for v in statuses.values()), statuses

    def test_recent_explain_occupancy_shapes(self):
        async def scenario(bal, ident, s, hdrs):
            base = f"http://127.0.0.1:{PORT}/admin/placement"
            action = make_action("adminseen", memory=256)
            msgs = [make_msg(action, ident, True) for _ in range(3)]
            await asyncio.gather(*[
                await bal.publish(action, m) for m in msgs])
            out = {}
            async with s.get(base + "/recent?limit=2", headers=hdrs) as r:
                out["recent"] = (r.status, await r.json())
            aid = msgs[0].activation_id.asString
            async with s.get(base + f"/explain/{aid}", headers=hdrs) as r:
                out["explain"] = (r.status, await r.json())
            async with s.get(base + "/explain/notanid", headers=hdrs) as r:
                out["explain_miss"] = (r.status, await r.json())
            async with s.get(base + "/occupancy", headers=hdrs) as r:
                out["occupancy"] = (r.status, await r.json())
            return out

        out = self._run(scenario)
        status, recent = out["recent"]
        assert status == 200
        assert recent["enabled"] is True and recent["dropped"] == 0
        assert 1 <= len(recent["records"]) <= 2
        rec = recent["records"][-1]
        assert {"seq", "ts", "digest", "timings", "batch_size",
                "decisions"} <= set(rec)
        status, ex = out["explain"]
        assert status == 200
        assert ex["decision"]["invoker"] in ("invoker0", "invoker1")
        assert ex["decision"]["forced"] is False
        assert ex["decision"]["throttled"] is False
        assert "dispatch_ms" in ex["batch"]["timings"]
        status, miss = out["explain_miss"]
        assert status == 404 and "error" in miss
        status, occ = out["occupancy"]
        assert status == 200
        assert len(occ["invokers"]) == 2
        assert occ["fleet"]["capacity_mb"] == sum(
            r["capacity_mb"] for r in occ["invokers"])

    def test_explain_404_after_ring_wrap(self):
        async def scenario(bal, ident, s, hdrs):
            from openwhisk_tpu.controller.loadbalancer.flight_recorder import \
                FlightRecorder as FR
            bal.flight_recorder = FR(size=2)
            bal.max_batch = 1  # one record per publish
            base = f"http://127.0.0.1:{PORT}/admin/placement"
            action = make_action("wrapadmin", memory=128)
            msgs = [make_msg(action, ident, True) for _ in range(5)]
            for m in msgs:
                await (await bal.publish(action, m))
            out = {}
            first = msgs[0].activation_id.asString
            last = msgs[-1].activation_id.asString
            async with s.get(base + f"/explain/{first}", headers=hdrs) as r:
                out["first"] = r.status
            async with s.get(base + f"/explain/{last}", headers=hdrs) as r:
                out["last"] = (r.status, await r.json())
            async with s.get(base + "/recent", headers=hdrs) as r:
                out["recent"] = await r.json()
            return out

        out = self._run(scenario)
        assert out["first"] == 404
        status, ex = out["last"]
        assert status == 200
        assert ex["decision"]["activation_id"]
        assert out["recent"]["dropped"] >= 3

"""Active/active partitioned control: the namespace partition ring.

PR 8 made the stateful balancer HA as active/standby — ONE controller
places while the rest idle, so controller capacity cannot scale
horizontally and every failover parks the whole fleet behind one
promote+replay. This module is the ownership half of the active/active
generalization (ROADMAP item 3): the namespace space is hashed into a
fixed power-of-two number of VIRTUAL PARTITIONS, and each partition is
mapped to one of the N live controllers by rendezvous (highest-random-
weight) hashing — removing a member moves ONLY that member's partitions,
adding one steals only the partitions it now wins, and every observer
with the same live set derives the SAME ownership map with no
coordination round.

Three layers share this ring and must agree, so it lives in one place:

  * the EDGE PROXY ranks upstreams by `rank(pid)` so a request's first
    hop is its partition's owner (a miss is a 503 the bounded retry
    walks to the next candidate — routing is an optimization, the
    owner-side refusal is the correctness gate);
  * CONTROLLER MEMBERSHIP (membership.py) folds per-partition epoch
    claims over the same heartbeats that carry the global leadership
    claim in PR 8's active/standby mode — higher epoch wins, ties break
    to the lower instance, PER PARTITION;
  * each BALANCER refuses placement for partitions it does not own and
    stamps `(fence_part, fence_epoch)` on every dispatch so invokers
    discard a superseded owner's late batches per partition.

Partition handoff (member death OR planned ring rebalance) reuses the
PR 8 machinery per partition: the new owner bumps the partition's epoch
and replays the previous owner's journal tail FILTERED to exactly the
partitions it absorbed (journal records carry the partition ids of
their rows; see TpuBalancer.replay_journal's `parts_filter`).

Off-switch: `CONFIG_whisk_ha_activeActive=false` (the default) — no
ring is built anywhere and every path is bit-exact with the PR 8
single-active behavior.

This module lives in utils (not controller/loadbalancer, which
re-exports it) because the EDGE proxy imports the ring too, and the
loadbalancer package init pulls the full JAX balancer stack — seconds of
import and hundreds of MB a reverse proxy must never pay.
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional

from .config import load_config


@dataclass(frozen=True)
class ActiveActiveConfig:
    """`CONFIG_whisk_ha_activeActive[_*]` env overrides. The bare scalar
    form (`CONFIG_whisk_ha_activeActive=true`) toggles `enabled`; the
    nested form (`CONFIG_whisk_ha_activeActive_partitions=32`) sets the
    knobs. `partitions` is rounded up to a power of two."""
    enabled: bool = False
    #: virtual partitions on the ring (pow2): many more than controllers,
    #: so ownership moves in small slices on a membership change
    partitions: int = 16
    #: cross-partition spillover for hot namespaces (spillover.py): an
    #: overloaded owner forwards its overflow admission batch to the
    #: least-loaded peer. Separate switch — spillover is an optimization
    #: on top of the ownership protocol, not part of it.
    spillover: bool = False
    #: pending-queue depth past which publish_many diverts its overflow
    spillover_depth: int = 256


def _next_pow2(n: int) -> int:
    n = max(1, int(n))
    return 1 << (n - 1).bit_length()


def active_active_config() -> ActiveActiveConfig:
    """Read the config, accepting the scalar form AND the nested knobs
    TOGETHER (`CONFIG_whisk_ha_activeActive=true` beside
    `CONFIG_whisk_ha_activeActive_partitions=8` — the generic nested
    env parser can't hold a scalar and a subtree under one key, so this
    reads the raw environment directly)."""
    import os
    data = {}
    scalar = os.environ.get("CONFIG_whisk_ha_activeActive")
    if scalar is not None:
        data["enabled"] = scalar
    prefix = "CONFIG_whisk_ha_activeActive_"
    for k, v in os.environ.items():
        if k.startswith(prefix) and k != prefix.rstrip("_"):
            data[_snake_key(k[len(prefix):])] = v
    return load_config(ActiveActiveConfig, data)


def _snake_key(name: str) -> str:
    import re
    return re.sub(r"(?<!^)(?=[A-Z])", "_", name).lower()


def ring_from_config(cfg: Optional[ActiveActiveConfig] = None
                     ) -> Optional["PartitionRing"]:
    """A ring when active/active is on, else None (the off-switch: every
    caller treats a None ring as the PR 8 single-active path)."""
    cfg = cfg if cfg is not None else active_active_config()
    if not cfg.enabled:
        return None
    return PartitionRing(cfg.partitions)


def _h64(key: str) -> int:
    """Stable 64-bit hash — deterministic across processes and Python
    builds (never the salted builtin hash): the edge, every controller
    and every replayer must derive identical ownership."""
    return int.from_bytes(
        hashlib.blake2b(key.encode(), digest_size=8).digest(), "big")


class PartitionRing:
    """pow2 virtual partitions + rendezvous partition->member mapping."""

    def __init__(self, n_partitions: int = 16):
        self.n_partitions = _next_pow2(n_partitions)
        self._mask = self.n_partitions - 1

    # -- namespace -> partition -------------------------------------------
    def partition_of(self, namespace: str) -> int:
        return _h64(str(namespace)) & self._mask

    # -- partition -> member (rendezvous) ---------------------------------
    @staticmethod
    def _score(pid: int, member: int) -> int:
        return _h64(f"p{pid}@c{member}")

    def rank(self, pid: int, members: Iterable[int]) -> List[int]:
        """Members ordered by descending rendezvous weight for `pid`
        (ties break to the LOWER instance, matching the membership
        protocol's claim tie-break). rank()[0] is the owner; the edge
        walks the rest on a 503."""
        return sorted(set(int(m) for m in members),
                      key=lambda m: (-self._score(pid, m), m))

    def owner_of(self, pid: int, members: Iterable[int]) -> Optional[int]:
        ranked = self.rank(pid, members)
        return ranked[0] if ranked else None

    def ownership(self, members: Iterable[int]) -> Dict[int, int]:
        """The full partition->owner map for a live set. Every observer
        with the same `members` derives the same map."""
        ms = sorted(set(int(m) for m in members))
        if not ms:
            return {}
        return {pid: self.rank(pid, ms)[0]
                for pid in range(self.n_partitions)}

    def partitions_of(self, member: int, members: Iterable[int]) -> List[int]:
        own = self.ownership(members)
        return [pid for pid, m in own.items() if m == int(member)]

"""Packages and package bindings.

Ref: WhiskPackage.scala — a package groups actions and carries parameters
that are inherited by its actions at invoke time; a *binding* is a package
document whose `binding` field references another package (possibly in
another namespace), layering its own parameters on top
(parameter precedence: provider package < binding < action < invoke payload,
ref Packages.scala `mergePackageWithBinding`).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from .entity import WhiskEntity
from .names import EntityName, EntityPath, FullyQualifiedEntityName
from .parameters import Parameters
from .semver import SemVer


@dataclass(frozen=True)
class Binding:
    namespace: EntityPath
    name: EntityName

    @property
    def fqn(self) -> FullyQualifiedEntityName:
        return FullyQualifiedEntityName(self.namespace, self.name)

    def to_json(self):
        return {"namespace": str(self.namespace), "name": str(self.name)}

    @classmethod
    def from_json(cls, j) -> "Binding":
        return cls(EntityPath(j["namespace"]), EntityName(j["name"]))


class WhiskPackage(WhiskEntity):
    collection = "packages"

    def __init__(self, namespace: EntityPath, name: EntityName,
                 binding: Optional[Binding] = None,
                 parameters: Optional[Parameters] = None,
                 version: Optional[SemVer] = None, publish: bool = False,
                 annotations: Optional[Parameters] = None,
                 updated: Optional[float] = None):
        super().__init__(namespace, name, version, publish, annotations, updated)
        self.binding = binding
        self.parameters = parameters or Parameters()

    @property
    def is_binding(self) -> bool:
        return self.binding is not None

    def to_json(self) -> dict:
        j = self.base_json()
        j["binding"] = self.binding.to_json() if self.binding else {}
        j["parameters"] = self.parameters.to_json()
        return j

    @classmethod
    def from_json(cls, j: dict) -> "WhiskPackage":
        b = j.get("binding") or {}
        return cls(
            EntityPath(j["namespace"]), EntityName(j["name"]),
            Binding.from_json(b) if b else None,
            Parameters.from_json(j.get("parameters")),
            SemVer.from_string(j.get("version", "0.0.1")),
            bool(j.get("publish", False)),
            Parameters.from_json(j.get("annotations")),
            (j.get("updated", 0) / 1000.0) or None,
        )

"""Domain model (ref common/scala/.../core/entity — SURVEY §2.3)."""
from .size import B, KB, MB, GB, ByteSize
from .semver import SemVer
from .ids import (ActivationId, BasicAuthenticationAuthKey, ControllerInstanceId,
                  DocInfo, DocRevision, InstanceId, InvokerInstanceId, Secret,
                  Subject, UUID)
from .names import (DEFAULT_NAMESPACE, EntityName, EntityPath,
                    FullyQualifiedEntityName)
from .parameters import MalformedEntity, Parameters, ParameterValue
from .limits import (ActionLimits, ConcurrencyLimit, LimitViolation, LogLimit,
                     MemoryLimit, TimeLimit)
from .exec import (BLACKBOX_KIND, SEQUENCE_KIND, BlackBoxExec, CodeExec, Exec,
                   ExecMetaData, SequenceExec)
from .manifest import (DEFAULT_MANIFEST_JSON, ExecManifest, ImageName,
                       RuntimeManifest, Runtimes, StemCell)
from .entity import WhiskEntity
from .action import ExecutableWhiskAction, WhiskAction
from .activation import (APPLICATION_ERROR, DEVELOPER_ERROR, SUCCESS,
                         WHISK_INTERNAL_ERROR, ActivationResponse,
                         WhiskActivation)
from .trigger_rule import (ACTIVE, INACTIVE, ReducedRule, Status, WhiskRule,
                           WhiskTrigger)
from .package import Binding, WhiskPackage
from .identity import (ACTIVATE, ALL_RIGHTS, DELETE, PUT, READ, REJECT,
                       Identity, Namespace, UserLimits, WhiskAuthRecord)

__all__ = [n for n in dir() if not n.startswith("_")]

"""Active/active partitioned controllers (ISSUE 15), tier-1 half.

Covers the partition ring (determinism, rendezvous stability), the
membership generalization (per-partition claims over the heartbeats,
failover + planned-rebalance handoff, per-partition zombie demotion),
the balancer's per-partition refusal/fence stamping, the invoker's
per-partition discard, cross-partition spillover, the edge ring routing
+ bounded retry plumbing, /admin/ready, and the off-switch/N=1 parity
acceptance. The SIGKILL-mid-burst chaos proof lives in
tests/test_ha_chaos.py (slow) and the bench `partition_chaos` rider.
"""
import asyncio
import json

import numpy as np
import pytest

from openwhisk_tpu.controller.loadbalancer import (LoadBalancerException,
                                                   TpuBalancer)
from openwhisk_tpu.controller.loadbalancer.journal import PlacementJournal
from openwhisk_tpu.controller.loadbalancer.membership import \
    ControllerMembership
from openwhisk_tpu.controller.loadbalancer.partitions import (
    ActiveActiveConfig, PartitionRing, active_active_config,
    ring_from_config)
from openwhisk_tpu.core.entity import ControllerInstanceId, Identity
from openwhisk_tpu.messaging import MemoryMessagingProvider

from tests.test_balancers import _fleet, _ping_all, make_action, make_msg


def _balancer(provider, instance="0", **kw):
    return TpuBalancer(provider, ControllerInstanceId(instance),
                       managed_fraction=1.0, blackbox_fraction=0.0, **kw)


def _ns_for_partition(ring, pid, tag="ns"):
    """A namespace name hashing to `pid` (deterministic hash: scan)."""
    i = 0
    while True:
        ns = f"{tag}{i}"
        if ring.partition_of(ns) == pid:
            return ns
        i += 1


async def until(cond, timeout=8.0, step=0.02):
    for _ in range(int(timeout / step)):
        if cond():
            return True
        await asyncio.sleep(step)
    return cond()


class TestPartitionRing:
    def test_pow2_rounding_and_determinism(self):
        assert PartitionRing(10).n_partitions == 16
        r1, r2 = PartitionRing(16), PartitionRing(16)
        for ns in ("guest", "alice", "bob", "hot-ns"):
            assert r1.partition_of(ns) == r2.partition_of(ns)
            assert 0 <= r1.partition_of(ns) < 16

    def test_ownership_covers_all_partitions_disjointly(self):
        ring = PartitionRing(32)
        own = ring.ownership([0, 1, 2])
        assert sorted(own) == list(range(32))
        assert set(own.values()) <= {0, 1, 2}
        # each member's partition list matches the map
        for m in (0, 1, 2):
            assert ring.partitions_of(m, [0, 1, 2]) == \
                [p for p, o in own.items() if o == m]

    def test_rendezvous_stability_on_member_death(self):
        """Removing a member must move ONLY that member's partitions —
        the property that makes a rebalance a bounded failover."""
        ring = PartitionRing(64)
        before = ring.ownership([0, 1, 2])
        after = ring.ownership([0, 2])
        for pid, owner in before.items():
            if owner != 1:
                assert after[pid] == owner, \
                    f"partition {pid} moved without cause"
            else:
                assert after[pid] in (0, 2)

    def test_rank_walks_owner_first(self):
        ring = PartitionRing(16)
        for pid in range(16):
            ranked = ring.rank(pid, [0, 1, 2])
            assert sorted(ranked) == [0, 1, 2]
            assert ranked[0] == ring.owner_of(pid, [0, 1, 2])

    def test_config_off_switch_and_scalar_form(self, monkeypatch):
        monkeypatch.delenv("CONFIG_whisk_ha_activeActive", raising=False)
        assert ring_from_config() is None  # default off
        monkeypatch.setenv("CONFIG_whisk_ha_activeActive", "true")
        ring = ring_from_config()
        assert ring is not None and ring.n_partitions == 16
        monkeypatch.setenv("CONFIG_whisk_ha_activeActive", "false")
        assert ring_from_config() is None

    def test_config_nested_form(self, monkeypatch):
        monkeypatch.delenv("CONFIG_whisk_ha_activeActive", raising=False)
        monkeypatch.setenv("CONFIG_whisk_ha_activeActive_enabled", "true")
        monkeypatch.setenv("CONFIG_whisk_ha_activeActive_partitions", "8")
        monkeypatch.setenv("CONFIG_whisk_ha_activeActive_spillover", "true")
        cfg = active_active_config()
        assert cfg.enabled and cfg.partitions == 8 and cfg.spillover
        assert ring_from_config(cfg).n_partitions == 8

    def test_config_scalar_and_knobs_together(self, monkeypatch):
        # the documented deployment form: scalar enable + nested knobs
        monkeypatch.setenv("CONFIG_whisk_ha_activeActive", "true")
        monkeypatch.setenv("CONFIG_whisk_ha_activeActive_partitions", "8")
        monkeypatch.setenv("CONFIG_whisk_ha_activeActive_spilloverDepth",
                           "64")
        cfg = active_active_config()
        assert cfg.enabled and cfg.partitions == 8
        assert cfg.spillover_depth == 64


class _BalancerStub:
    cluster_size = 3
    metrics = None

    def update_cluster(self, n):
        self.cluster_size = n


def _membership(provider, i, ring, events, heartbeat=0.05, timeout=1.0):
    # timeout is deliberately generous vs the 0.05s heartbeat: these tests
    # assert EXACT ownership maps, and a pegged CI box can starve an event
    # loop past a tight member timeout — a correct-but-unwanted failover
    # that breaks the planned-rebalance invariants being tested
    def cb(gained, lost):
        events[i].append(("gain", gained) if gained else ("lose", lost))

    m = ControllerMembership(provider, ControllerInstanceId(str(i)),
                             _BalancerStub(), heartbeat_s=heartbeat,
                             member_timeout_s=timeout, ring=ring,
                             on_partitions=cb,
                             load_hint=lambda: float(i))
    m.start()
    return m


class TestMembershipPartitions:
    def test_three_actives_converge_to_disjoint_full_ownership(self):
        ring = PartitionRing(16)

        async def go():
            provider = MemoryMessagingProvider()
            events = {0: [], 1: [], 2: []}
            ms = [_membership(provider, i, ring, events) for i in range(3)]
            ok = await until(lambda: sum(
                len(m.owned_partitions) for m in ms) == 16 and all(
                m.owned_partitions for m in ms) or False, timeout=10.0)
            owned = [m.owned_partitions for m in ms]
            expected = ring.ownership([0, 1, 2])
            loads = dict(ms[0].peer_loads)
            for m in ms:
                await m.stop()
            return ok, owned, expected, loads

        ok, owned, expected, loads = asyncio.run(go())
        assert ok, owned
        # disjoint and exactly the rendezvous map
        assert not (owned[0] & owned[1] or owned[0] & owned[2]
                    or owned[1] & owned[2])
        for i in range(3):
            assert owned[i] == {p for p, o in expected.items() if o == i}
        # heartbeats carried the spillover load hints
        assert loads.get(1) == 1.0 and loads.get(2) == 2.0

    def test_member_death_moves_its_partitions_with_epoch_bump(self):
        ring = PartitionRing(16)

        async def go():
            provider = MemoryMessagingProvider()
            events = {0: [], 1: [], 2: []}
            ms = [_membership(provider, i, ring, events) for i in range(3)]
            assert await until(lambda: sum(
                len(m.owned_partitions) for m in ms) == 16, timeout=10.0)
            dead_parts = set(ms[0].owned_partitions)
            # hard death: no leave, just silence
            await ms[0]._ticker.stop()
            await ms[0]._feed.stop()
            ok = await until(lambda: (ms[1].owned_partitions
                                      | ms[2].owned_partitions)
                             >= dead_parts, timeout=12.0)
            # every absorbed partition claimed at a HIGHER epoch, with
            # the dead instance named as the previous owner
            gains = [g for i in (1, 2) for kind, g in events[i]
                     if kind == "gain"]
            absorbed = {pid: (epoch, prev)
                        for g in gains for pid, epoch, prev in g}
            for m in ms[1:]:
                await m.stop()
            return ok, dead_parts, absorbed

        ok, dead_parts, absorbed = asyncio.run(go())
        assert ok, "survivors never absorbed the dead member's partitions"
        for pid in dead_parts:
            epoch, prev = absorbed[pid]
            assert epoch >= 2, f"partition {pid} claimed without a bump"
            assert prev == 0, \
                f"partition {pid} gained without naming the dead owner"

    def test_join_rebalances_only_the_joiners_partitions(self):
        """Planned ring rebalance: a new controller joining steals only
        the partitions the ring assigns it (higher-epoch claims), and
        the old owners demote exactly those."""
        ring = PartitionRing(16)

        async def go():
            provider = MemoryMessagingProvider()
            events = {0: [], 1: [], 2: []}
            ms = {i: _membership(provider, i, ring, events)
                  for i in (0, 1)}
            assert await until(lambda: sum(
                len(m.owned_partitions) for m in ms.values()) == 16,
                timeout=10.0)
            before = {i: set(ms[i].owned_partitions) for i in (0, 1)}
            ms[2] = _membership(provider, 2, ring, events)
            want2 = set(ring.partitions_of(2, [0, 1, 2]))
            # converged = the joiner claimed its rendezvous set AND the
            # old owners demoted theirs — waiting on the joiner alone
            # races the snapshot against the in-flight demotions
            ok = await until(
                lambda: (ms[2].owned_partitions == want2
                         and ms[0].owned_partitions == before[0] - want2
                         and ms[1].owned_partitions == before[1] - want2),
                timeout=12.0)
            after = {i: set(ms[i].owned_partitions) for i in (0, 1, 2)}
            for m in ms.values():
                await m.stop()
            return ok, before, after, want2

        ok, before, after, want2 = asyncio.run(go())
        assert ok, "joiner never took its rendezvous partitions"
        assert after[2] == want2
        for i in (0, 1):
            # the old owners kept everything the ring still gives them
            assert after[i] == before[i] - want2

    def test_zombie_demotes_per_partition_keeping_the_rest(self):
        """Satellite: a stale-epoch old owner is demoted for EXACTLY the
        partitions a peer superseded while keeping the ones it still
        owns (the per-partition generalization of PR 8's zombie test)."""
        ring = PartitionRing(16)

        async def go():
            provider = MemoryMessagingProvider()
            events = {0: []}
            m = _membership(provider, 0, ring, events)
            assert await until(
                lambda: len(m.owned_partitions) == 16, timeout=10.0)
            victim = sorted(m.owned_partitions)[:4]
            for pid in victim:
                # a peer's forged higher-epoch claim supersedes this
                # partition only
                m._observe_part_claim(pid, m._pepoch[pid] + 3, 9)
            owned_after = set(m.owned_partitions)
            lost_events = [lost for kind, lost in events[0]
                           if kind == "lose"]
            await m.stop()
            return victim, owned_after, lost_events

        victim, owned_after, lost_events = asyncio.run(go())
        assert owned_after == set(range(16)) - set(victim)
        lost_pids = {pid for lost in lost_events for pid, _e in lost}
        assert lost_pids == set(victim)


class TestPartitionFencingBalancer:
    def test_refuses_unowned_partition_and_stamps_owned(self):
        ring = PartitionRing(8)

        async def go():
            provider = MemoryMessagingProvider()
            bal = _balancer(provider)
            bal.set_partition_mode(ring)
            await bal.start()
            invokers, producer = await _fleet(provider, 2)
            await _ping_all(invokers, producer)
            action = make_action("pf", memory=128)
            ns_owned = _ns_for_partition(ring, 3, "own")
            ns_other = _ns_for_partition(ring, 5, "oth")
            bal.set_partition_leadership(3, 7, True)
            with pytest.raises(LoadBalancerException):
                await bal.publish(action, make_msg(
                    action, Identity.generate(ns_other), True))
            p = await bal.publish(action, make_msg(
                action, Identity.generate(ns_owned), True))
            await asyncio.wait_for(p, 10)
            await asyncio.sleep(0.1)
            stamps = [(m.fence_part, m.fence_epoch)
                      for inv in invokers for m in inv.handled]
            ready = bal.partitions_json()
            await bal.close()
            for inv in invokers:
                await inv.stop()
            return stamps, ready

        stamps, ready = asyncio.run(go())
        assert stamps and all(s == (3, 7) for s in stamps)
        assert ready[3] == {"partition": 3, "epoch": 7, "role": "active",
                            "replay": "ready"}
        assert ready[5]["role"] == "standby"

    def test_spillover_credential_admits_fenced_row(self):
        """A row fence-stamped at the partition's current epoch passes
        the refusal even on a non-owner (the spillover admission), while
        a stale-epoch stamp is refused like any zombie work."""
        ring = PartitionRing(8)

        async def go():
            provider = MemoryMessagingProvider()
            bal = _balancer(provider)
            bal.set_partition_mode(ring)
            await bal.start()
            invokers, producer = await _fleet(provider, 2)
            await _ping_all(invokers, producer)
            action = make_action("sc", memory=128)
            ns = _ns_for_partition(ring, 2, "sp")
            ident = Identity.generate(ns)
            # peer knowledge: epoch 5 claimed elsewhere
            bal.partition_epochs[2] = 5
            fresh = make_msg(action, ident, True)
            fresh.fence_part, fresh.fence_epoch = 2, 5
            stale = make_msg(action, ident, True)
            stale.fence_part, stale.fence_epoch = 2, 4
            with pytest.raises(LoadBalancerException):
                await bal.publish(action, stale)
            p = await bal.publish(action, fresh)
            await asyncio.wait_for(p, 10)
            await bal.close()
            for inv in invokers:
                await inv.stop()
            return True

        assert asyncio.run(go())


class TestInvokerPartitionFence:
    def test_invoker_discards_stale_epoch_per_partition(self):
        from openwhisk_tpu.containerpool import ContainerPoolConfig
        from openwhisk_tpu.core.entity import (ActivationId, ExecManifest,
                                               InvokerInstanceId, MB)
        from openwhisk_tpu.database import (ArtifactActivationStore,
                                            EntityStore, MemoryArtifactStore)
        from openwhisk_tpu.invoker.reactive import InvokerReactive
        from openwhisk_tpu.messaging import ActivationMessage
        from openwhisk_tpu.utils.transaction import TransactionId

        async def go():
            ExecManifest.initialize()
            provider = MemoryMessagingProvider()
            store = MemoryArtifactStore()

            class FactoryStub:
                async def cleanup(self):
                    pass

            inv = InvokerReactive(
                InvokerInstanceId(0, user_memory=MB(1024)), provider,
                EntityStore(store), ArtifactActivationStore(store),
                FactoryStub(),
                pool_config=ContainerPoolConfig(user_memory=MB(1024)))
            released = []

            class FeedStub:
                def processed(self):
                    released.append(1)

            ident = Identity.generate("guest")
            action = make_action("pfence", memory=128)

            def payload(part, epoch):
                return ActivationMessage(
                    TransactionId(), action.fully_qualified_name, None,
                    ident, ActivationId.generate(),
                    ControllerInstanceId("0"), False, {},
                    fence_epoch=epoch, fence_part=part).serialize()

            # partition 1 adopts epoch 4; partition 2 adopts epoch 1
            await inv._process(payload(1, 4), FeedStub())
            await inv._process(payload(2, 1), FeedStub())
            assert inv.fenced_discards == 0
            # partition 1's zombie (epoch 2) is discarded...
            before = len(released)
            await inv._process(payload(1, 2), FeedStub())
            assert inv.fenced_discards == 1
            assert len(released) == before + 1, \
                "a discarded message must still release feed capacity"
            # ...while partition 2's epoch-1 traffic still runs, and the
            # legacy global fence is untouched by partition traffic
            await inv._process(payload(2, 1), FeedStub())
            assert inv.fenced_discards == 1
            assert inv._max_fence_epoch == -1
            return inv._fence_epochs

        fences = asyncio.run(go())
        assert fences == {1: 4, 2: 1}


class TestPartitionJournalAbsorb:
    def _drive(self, bal, ring, namespaces, per_ns=3):
        """Serial publishes for each namespace (await each → quiesced,
        deterministic batches)."""

        async def go(invokers):
            action = make_action("pj", memory=128)
            for ns in namespaces:
                ident = Identity.generate(ns)
                for _ in range(per_ns):
                    p = await bal.publish(action, make_msg(action, ident,
                                                           True))
                    await asyncio.wait_for(p, 10)

        return go

    def test_records_carry_parts_and_absorb_filters_to_them(self,
                                                            tmp_path):
        ring = PartitionRing(8)
        jdir = str(tmp_path / "wal0")

        async def go():
            provider = MemoryMessagingProvider()
            bal = _balancer(provider)
            bal.set_partition_mode(ring)
            bal.attach_journal(PlacementJournal(jdir))
            await bal.start()
            invokers, producer = await _fleet(provider, 4)
            await _ping_all(invokers, producer)
            ns_a = _ns_for_partition(ring, 1, "a")
            ns_b = _ns_for_partition(ring, 6, "b")
            bal.set_partition_leadership(1, 2, True)
            bal.set_partition_leadership(6, 3, True)
            await self._drive(bal, ring, [ns_a, ns_b])(invokers)
            for _ in range(50):
                if not (bal._pending or bal._inflight_steps):
                    break
                await asyncio.sleep(0.05)
            assert bal.journal.flush()

            reader = PlacementJournal(jdir)
            recs = list(reader.records(0))
            batches = [r for r in recs if r.get("t") == "batch"]
            # the survivor absorbs ONLY partition 1
            surv = _balancer(provider, "1")
            surv.set_partition_mode(ring)
            await surv.start()
            await _ping_all(invokers, producer)
            surv.set_partition_leadership(1, 3, True)
            stats = surv.absorb_partitions([1], PlacementJournal(jdir))
            own_seq = surv._journal_seq
            await bal.close()
            await surv.close()
            for inv in invokers:
                await inv.stop()
            return recs, batches, stats, own_seq

        recs, batches, stats, own_seq = asyncio.run(go())
        assert batches, "the run must journal batch records"
        for b in batches:
            assert b["parts"] and set(b["parts"]) <= {1, 6}
            assert set(b["pe"]) == {str(p) for p in b["parts"]}
        only_a = [b for b in batches if b["parts"] == [1]]
        only_b = [b for b in batches if b["parts"] == [6]]
        assert only_a and only_b, "serial publishes batch per namespace"
        # the absorb replayed partition 1's records (plus their acks) and
        # filtered partition 6's out, without touching the absorber's own
        # journal numbering
        assert stats["replayed"] >= len(only_a)
        assert stats["filtered_out"] >= len(only_b)
        assert stats["absorbed_partitions"] == [1]
        assert own_seq == 0, "foreign seqs must not move the own cursor"

    def test_replay_drops_stale_epochs_per_partition(self, tmp_path):
        """Satellite: a zombie owner's late records for a superseded
        partition drop at replay while the SAME journal's records for a
        still-owned partition replay — per-partition staleness."""
        ring = PartitionRing(8)
        jdir = str(tmp_path / "walz")

        async def go():
            provider = MemoryMessagingProvider()
            bal = _balancer(provider)
            bal.set_partition_mode(ring)
            bal.attach_journal(PlacementJournal(jdir))
            await bal.start()
            invokers, producer = await _fleet(provider, 4)
            await _ping_all(invokers, producer)
            ns_a = _ns_for_partition(ring, 1, "a")
            ns_b = _ns_for_partition(ring, 6, "b")
            bal.set_partition_leadership(1, 2, True)
            bal.set_partition_leadership(6, 2, True)
            # zombie half: partition 1 records at epoch 2
            await self._drive(bal, ring, [ns_a])(invokers)
            # partition 1 is superseded (epoch 3 elsewhere); partition 6
            # stays ours — later records stamp the NEW epoch for 1 only
            # if it were still placed here, but ownership was lost:
            bal.set_partition_leadership(1, 3, False)
            await self._drive(bal, ring, [ns_b])(invokers)
            for _ in range(50):
                if not (bal._pending or bal._inflight_steps):
                    break
                await asyncio.sleep(0.05)
            assert bal.journal.flush()
            # forge the supersession evidence INTO the journal stream, as
            # the new owner's first record for partition 1 would carry it
            bal._journal_append({"t": "batch", "R": 1, "H": 1, "B": 8,
                                 "rows": 0, "b": 0, "buf": "",
                                 "aids": [], "parts": [1],
                                 "pe": {"1": 3}})
            assert bal.journal.flush()

            reader = PlacementJournal(jdir)
            recs = list(reader.records(0))
            # replay with the supersession bound present: partition 1's
            # epoch-2 batches are stale ONLY if they follow the epoch-3
            # first-seq — here the forged record is LAST, so everything
            # before it stays fresh; now reorder: treat the forged
            # record's seq as 0 by replaying a reversed-bounds stream
            surv = _balancer(provider, "1")
            surv.set_partition_mode(ring)
            await surv.start()
            await _ping_all(invokers, producer)
            # move the forged supersession to the FRONT (first_seq for
            # (1, epoch 3) = smallest): zombie epoch-2 partition-1
            # records now all drop; partition 6 records all survive
            forged = dict(recs[-1], seq=0)
            stats = surv.absorb_partitions(
                [1, 6], _FakeJournal([forged] + recs[:-1]))
            await bal.close()
            await surv.close()
            for inv in invokers:
                await inv.stop()
            return recs, stats

        recs, stats = asyncio.run(go())
        a_batches = [r for r in recs
                     if r.get("t") == "batch" and r.get("parts") == [1]
                     and r.get("pe", {}).get("1") == 2]
        b_batches = [r for r in recs
                     if r.get("t") == "batch" and r.get("parts") == [6]]
        assert a_batches and b_batches
        assert stats["stale_epoch_dropped"] >= len(a_batches)
        assert stats["replayed"] >= len(b_batches)


class _FakeJournal:
    def __init__(self, recs):
        self._recs = recs

    def records(self, after_seq=0):
        return iter([r for r in self._recs
                     if int(r.get("seq", 0)) > after_seq or "seq" not in r
                     or int(r.get("seq", 0)) == 0])


class TestOffSwitchParity:
    def test_off_journal_wire_format_unchanged(self, tmp_path):
        """CONFIG off (no ring): journal records carry NO partition keys
        — byte-compatible with the PR 8 format."""
        jdir = str(tmp_path / "waloff")

        async def go():
            provider = MemoryMessagingProvider()
            bal = _balancer(provider)
            bal.attach_journal(PlacementJournal(jdir))
            await bal.start()
            invokers, producer = await _fleet(provider, 2)
            await _ping_all(invokers, producer)
            action = make_action("off", memory=128)
            ident = Identity.generate("guest")
            p = await bal.publish(action, make_msg(action, ident, True))
            await asyncio.wait_for(p, 10)
            await asyncio.sleep(0.2)
            assert bal.journal.flush()
            recs = list(PlacementJournal(jdir).records(0))
            fences = [m.fence_epoch for inv in invokers
                      for m in inv.handled]
            parts = [m.fence_part for inv in invokers
                     for m in inv.handled]
            await bal.close()
            for inv in invokers:
                await inv.stop()
            return recs, fences, parts

        recs, fences, parts = asyncio.run(go())
        assert recs
        for r in recs:
            assert "parts" not in r and "pe" not in r
        assert all(f is None for f in fences)
        assert all(p is None for p in parts)

    def test_n1_on_placement_parity_with_off(self):
        """N=1 with the ring on (one controller owning every partition)
        places bit-identically to the ring-off path, and its journal
        records differ ONLY by the additive parts/pe keys."""

        async def run_one(ring_on, jdir=None):
            provider = MemoryMessagingProvider()
            bal = _balancer(provider)
            ring = PartitionRing(8)
            if ring_on:
                bal.set_partition_mode(ring)
                for pid in range(8):
                    bal.set_partition_leadership(pid, 1, True)
            if jdir is not None:
                bal.attach_journal(PlacementJournal(jdir))
            await bal.start()
            invokers, producer = await _fleet(provider, 4)
            await _ping_all(invokers, producer)
            actions = [make_action(f"par{i}", memory=128) for i in range(3)]
            idents = [Identity.generate(f"pns{i}") for i in range(4)]
            placed = []
            for i in range(12):
                a = actions[i % 3]
                p = await bal.publish(a, make_msg(a, idents[i % 4], True))
                await asyncio.wait_for(p, 10)
            for inv in invokers:
                for m in inv.handled:
                    placed.append((m.action.name.name,
                                   inv.instance.instance))
            books = np.asarray(bal.state.free_mb).copy()
            await bal.close()
            for inv in invokers:
                await inv.stop()
            return placed, books

        async def go(tmpdir=None):
            on = await run_one(True)
            off = await run_one(False)
            return on, off

        (placed_on, books_on), (placed_off, books_off) = asyncio.run(go())
        assert sorted(placed_on) == sorted(placed_off), \
            "N=1 active/active must place exactly like the off path"
        assert np.array_equal(books_on, books_off), \
            "N=1 active/active books must equal the off path's"


class TestSpillover:
    def test_overflow_batch_forwards_to_peer_and_executes(self):
        from openwhisk_tpu.controller.loadbalancer.spillover import (
            SpilloverReceiver, SpilloverSender)

        ring = PartitionRing(8)

        async def go():
            provider = MemoryMessagingProvider()
            b0 = _balancer(provider, "0")
            b1 = _balancer(provider, "1")
            for b in (b0, b1):
                b.set_partition_mode(ring)
                await b.start()
            invokers, producer = await _fleet(provider, 2)
            await _ping_all(invokers, producer)
            action = make_action("hot", memory=128)
            ns = _ns_for_partition(ring, 4, "hot")
            ident = Identity.generate(ns)
            b0.set_partition_leadership(4, 2, True)
            b1.partition_epochs[4] = 2  # peer folded the claim

            class MembershipStub:
                @staticmethod
                def least_loaded_peer():
                    return 1

            class StoreStub:
                @staticmethod
                async def get_action(name, rev=None):
                    class Doc:
                        @staticmethod
                        def to_executable():
                            return action
                    return Doc()

            b0.spillover_sink = SpilloverSender(provider, MembershipStub())
            b0.spillover_depth = 2
            receiver = SpilloverReceiver(
                provider, ControllerInstanceId("1"), b1, StoreStub())
            receiver.start()
            # 6 non-blocking rows through the batched publish: depth 2
            # → 4 rows divert to the peer
            pairs = [(action, make_msg(action, ident, False))
                     for _ in range(6)]
            outs = b0.publish_many(pairs)
            await asyncio.gather(*outs)
            # every row executes exactly once, across the two books
            for _ in range(100):
                if sum(len(inv.handled) for inv in invokers) >= 6:
                    break
                await asyncio.sleep(0.05)
            handled = [m for inv in invokers for m in inv.handled]
            spilled = [m for m in handled
                       if m.root_controller_index.name == "1"]
            local = [m for m in handled
                     if m.root_controller_index.name == "0"]
            stamps = {(m.fence_part, m.fence_epoch) for m in handled}
            counts = (b0.spilled_rows, receiver.received, receiver.refused)
            await receiver.stop()
            await b0.close()
            await b1.close()
            for inv in invokers:
                await inv.stop()
            return handled, spilled, local, stamps, counts

        handled, spilled, local, stamps, counts = asyncio.run(go())
        assert len(handled) == 6, "every row must execute exactly once"
        assert len(spilled) == 4 and len(local) == 2
        assert stamps == {(4, 2)}, "every hop is fenced at the epoch"
        assert counts == (4, 4, 0)


class TestEdgeRingRouting:
    def _proxy(self, n=3, ring=None, **kw):
        from openwhisk_tpu.edge.proxy import EdgeProxy
        return EdgeProxy.for_controllers(
            [f"http://127.0.0.1:{3000 + i}" for i in range(n)],
            ring=ring, **kw)

    def test_owner_first_order_and_fallback(self):
        ring = PartitionRing(16)
        proxy = self._proxy(ring=ring)
        ns = "alice"
        pid = ring.partition_of(ns)
        ranked = ring.rank(pid, [0, 1, 2])
        order = proxy._pick_order(ns)
        assert [u.url for u in order] == \
            [f"http://127.0.0.1:{3000 + i}" for i in ranked]
        # no namespace (or `_`): round-robin, all upstreams present
        assert len(proxy._pick_order(None)) == 3

    def test_path_namespace_extraction(self):
        proxy = self._proxy()
        f = proxy._path_namespace
        assert f("/api/v1/namespaces/alice/actions/x") == "alice"
        assert f("/api/v1/namespaces/_/actions/x") is None
        assert f("/metrics") is None
        assert f("/api/v1/namespaces/") is None

    def test_backoff_is_jittered_and_bounded(self):
        proxy = self._proxy(retry_backoff_ms=20, retry_backoff_max_ms=100)
        for attempt in (1, 2, 3, 8):
            for _ in range(16):
                d = proxy._backoff_s(attempt)
                assert 0.0 <= d <= 0.1
        assert proxy.retry_attempts == 0  # auto: two passes, min 4

    def test_retry_counter_shape(self):
        proxy = self._proxy()
        proxy._count_retry("http_503")
        proxy._count_retry("http_503")
        proxy._count_retry("connect")
        assert proxy.retry_total == {"http_503": 2, "connect": 1}


class TestAdminReady:
    def _ready(self, lb, membership=None):
        from openwhisk_tpu.controller.api import ControllerApi

        class ControllerStub:
            load_balancer = lb

        ControllerStub.membership = membership
        api = ControllerApi(ControllerStub())
        return asyncio.run(api.admin_ready(None))

    def test_single_mode_is_ready(self):
        async def go():
            provider = MemoryMessagingProvider()
            bal = _balancer(provider)
            resp = None
            try:
                from openwhisk_tpu.controller.api import ControllerApi

                class C:
                    load_balancer = bal
                    membership = None

                resp = await ControllerApi(C()).admin_ready(None)
            finally:
                await bal.close()
            return resp

        resp = asyncio.run(go())
        assert resp.status == 200
        doc = json.loads(resp.body)
        assert doc["mode"] == "single" and doc["ready"]

    def test_active_active_roles_and_standby_for_all_503(self):
        async def go():
            provider = MemoryMessagingProvider()
            bal = _balancer(provider)
            bal.set_partition_mode(PartitionRing(8))
            from openwhisk_tpu.controller.api import ControllerApi

            class C:
                load_balancer = bal
                membership = None

            api = ControllerApi(C())
            standby = await api.admin_ready(None)
            bal.set_partition_leadership(2, 5, True)
            active = await api.admin_ready(None)
            await bal.close()
            return standby, active

        standby, active = asyncio.run(go())
        assert standby.status == 503, "standby-for-all must answer 503"
        doc = json.loads(active.body)
        assert active.status == 200
        assert doc["mode"] == "active_active" and doc["owned_partitions"] == 1
        assert doc["partitions"][2]["role"] == "active"
        assert doc["journal"] == {"attached": False,
                                  "stall_firing": False}

    def test_standby_and_journal_stall_surface(self, tmp_path):
        async def go():
            provider = MemoryMessagingProvider()
            bal = _balancer(provider)
            bal.set_leadership(4, False)
            bal.attach_journal(PlacementJournal(str(tmp_path / "w")))
            from openwhisk_tpu.controller.api import ControllerApi

            class C:
                load_balancer = bal
                membership = None

            api = ControllerApi(C())
            resp = await api.admin_ready(None)
            await bal.close()
            return resp

        resp = asyncio.run(go())
        assert resp.status == 503
        doc = json.loads(resp.body)
        assert doc == {"mode": "active_standby", "role": "standby",
                       "epoch": 4, "ready": False,
                       "journal": {"attached": True, "lag_batches": 0,
                                   "stall_firing": False}}


class TestJournalStallAlert:
    def test_rule_exists_and_fires_on_sustained_lag(self):
        from openwhisk_tpu.controller.loadbalancer.anomaly import (
            AlertEngine, build_rules)

        rules = build_rules(None)
        assert "journal_stall" in rules
        rule = rules["journal_stall"]
        assert rule.scope == "global" and rule.severity == "critical"
        engine = AlertEngine({"journal_stall": rule})
        # lag above threshold, sustained past for_s -> firing
        engine.evaluate(0.0, {"journal_stall": [((), 100.0)]})
        assert not engine.firing_counts()
        engine.evaluate(rule.for_s + 1.0, {"journal_stall": [((), 120.0)]})
        assert ("journal_stall", "critical") in engine.firing_counts()
        # lag recovers -> resolves
        engine.evaluate(rule.for_s + 2.0, {"journal_stall": [((), 0.0)]})
        assert not engine.firing_counts()

    def test_attach_journal_registers_the_signal(self, tmp_path):
        async def go():
            provider = MemoryMessagingProvider()
            bal = _balancer(provider)
            bal.attach_journal(PlacementJournal(str(tmp_path / "w")))
            sig = bal.anomaly.extra_signals["journal_lag_batches"]
            v0 = sig()
            bal.journal = None  # detach: the subject vanishes
            v1 = sig()
            await bal.close()
            return v0, v1

        v0, v1 = asyncio.run(go())
        assert v0 == 0.0 and v1 is None

"""Smoke coverage for the performance harness (tiny sample counts).

Mirrors the reference's practice of keeping its perf harness compiling and
runnable in CI even though real measurements need dedicated hardware: each
tool runs end-to-end with minimal work so regressions surface in the unit
suite, not on the benchmark box.
"""
import json
import os
import subprocess
import sys

import pytest

PERF_DIR = os.path.join(os.path.dirname(__file__), "performance")
sys.path.insert(0, PERF_DIR)

import simulations  # noqa: E402


class TestSimulations:
    def test_latency_and_apiv1_report_stats(self, capsys):
        ok = simulations.run(["latency", "apiv1"], requests=3, concurrency=2,
                             port=13441)
        lines = [json.loads(l) for l in
                 capsys.readouterr().out.strip().splitlines()]
        assert ok
        assert [l["simulation"] for l in lines] == ["latency", "apiv1"]
        for l in lines:
            assert l["errors"] == 0
            assert l["requests"] == 3
            assert l["rps"] > 0 and l["mean_ms"] > 0
            assert l["p50_ms"] <= l["p99_ms"]

    def test_threshold_violation_fails(self, capsys, monkeypatch):
        monkeypatch.setenv("MIN_REQUESTS_PER_SEC", "1e12")
        assert not simulations.run(["apiv1"], requests=2, concurrency=2,
                                   port=13442)

    def test_cold_and_throughput(self, capsys):
        ok = simulations.run(["throughput", "cold"], requests=3, concurrency=2,
                             port=13443)
        lines = [json.loads(l) for l in
                 capsys.readouterr().out.strip().splitlines()]
        assert ok and [l["errors"] for l in lines] == [0, 0]

    def test_soak_smoke_asserts_clean_books(self, capsys):
        """3s soak over the TPU balancer: mixed load, then zero leaked
        activation slots / concurrency refcounts (the assertions live
        inside soak_simulation)."""
        ok = simulations.run_soak(duration=3.0, concurrency=4, port=13444)
        lines = [json.loads(l) for l in
                 capsys.readouterr().out.strip().splitlines()]
        assert ok
        books = next(l["soak_books"] for l in lines if "soak_books" in l)
        assert books["active_activations"] == 0
        assert books["conc_refcounts"] == 0
        stats = next(l for l in lines if l.get("simulation") == "soak")
        assert stats["errors"] == 0 and stats["requests"] > 0


class TestPlacementSweep:
    def test_single_and_sharded_rows(self):
        import placement_sweep
        row = placement_sweep.bench_single(16, batch=8, iters=2)
        assert row["placements_per_sec"] > 0
        row = placement_sweep.bench_sharded(64, batch=8, iters=2, n_shards=8)
        assert row["config"] == "8-shard" and row["placements_per_sec"] > 0


@pytest.mark.slow
class TestOwperf:
    def test_owperf_csv(self):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        out = subprocess.run(
            [sys.executable, os.path.join(PERF_DIR, "owperf.py"),
             "--samples", "2", "--ratio", "1", "--port", "13444"],
            capture_output=True, text=True, timeout=180, env=env)
        assert out.returncode == 0, out.stderr
        lines = out.stdout.strip().splitlines()
        assert lines[0].startswith("phase,samples,mean_ms")
        phases = [l.split(",")[0] for l in lines[1:]]
        assert phases == ["action_e2e", "rule_e2e_x1", "waitTime", "initTime",
                          "duration"]


class TestWarmHitParity:
    def test_kernel_matches_oracle_warm_rates(self):
        import warmhit
        out = warmhit.simulate(n_invokers=24, rounds=6, batch=48,
                               n_actions=16)
        assert out["decision_parity"] == 1.0
        assert out["kernel_warm_rate"] == out["oracle_warm_rate"]
        assert out["kernel_warm_rate"] > 0.5  # the workload produces warm hits


class TestBenchRiderBackendFallback:
    """Satellite: a backend that dies LAZILY at the first dispatched op
    (past bench.py's subprocess probe) must not kill the rider — it re-runs
    under JAX_PLATFORMS=cpu and tags the JSON `"backend": "cpu_fallback"`."""

    def test_backend_unavailable_classifier(self):
        import bench
        assert bench._backend_unavailable(RuntimeError(
            "Unable to initialize backend 'axon': UNAVAILABLE: TPU backend "
            "setup/compile error (Unavailable)."))
        assert not bench._backend_unavailable(RuntimeError("boom"))
        assert not bench._backend_unavailable(
            ValueError("Unable to initialize backend"))

    def test_run_rider_tags_cpu_fallback(self, monkeypatch):
        import bench
        monkeypatch.setattr(bench, "_rider_subprocess_cpu",
                            lambda name: {"overhead_pct": 1.2})

        def dead_rider():
            raise RuntimeError("Unable to initialize backend 'axon': "
                               "UNAVAILABLE")

        out = bench._run_rider("_dead_rider", dead_rider)
        assert out == {"overhead_pct": 1.2, "backend": "cpu_fallback"}

    def test_run_rider_passes_healthy_result_through(self):
        import bench
        assert bench._run_rider("_ok", lambda: {"overhead_pct": 0.4}) == \
            {"overhead_pct": 0.4}

    def test_run_rider_reraises_other_errors(self):
        import bench
        with pytest.raises(RuntimeError, match="boom"):
            bench._run_rider("_x", lambda: (_ for _ in ()).throw(
                RuntimeError("boom")))

"""Fleet observatory (ISSUE 16): federation merge math, the causal event
log, peer-directory announcements, and the federation endpoints.

The merge invariant the property tests pin: per-process log2 bucket
counts summed bucket-wise equal the histogram a single process would
have built from the pooled samples — bucketing is per-sample and
bucket-wise integer addition is exact. The off-switch contract: disabled
is a TRUE no-op — heartbeats and pings byte-exact with pre-16 payloads,
fleet endpoints 404.
"""
import asyncio
import base64
import json
import random
import re
import time

import pytest

from openwhisk_tpu.controller.monitoring import (PHASE_MARKS,
                                                 join_spill_rows,
                                                 merge_serialized_counters,
                                                 merged_host_report,
                                                 merged_metrics,
                                                 merged_slo_report,
                                                 merged_timeline,
                                                 merged_waterfall_report,
                                                 metrics_raw,
                                                 reconstruct_phases)
from openwhisk_tpu.utils.eventlog import (EventLog, GLOBAL_EVENT_LOG,
                                          fleet_config, identity,
                                          reset_identity, set_identity)
from openwhisk_tpu.utils.waterfall import (ActivationWaterfall, N_STAGES,
                                           STAGE_API_ACCEPT,
                                           STAGE_COMPLETION_ACK,
                                           STAGE_INVOKER_PICKUP,
                                           STAGE_PUBLISH_ENQUEUE,
                                           STAGE_RECORD_WRITE, STAGE_RUN,
                                           STAGE_SPILL_FORWARD,
                                           WaterfallConfig)

_NAME = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


# -- identity & event log --------------------------------------------------
class TestIdentityAndEventLog:
    def teardown_method(self):
        reset_identity()

    def test_identity_block_shape_and_live_pid(self):
        import os
        set_identity(instance=3, role="controller",
                     partitions_fn=lambda: [5, 1])
        ident = identity()
        assert ident == {"instance": 3, "pid": os.getpid(),
                         "role": "controller", "partitions": [1, 5]}

    def test_identity_never_raises(self):
        set_identity(instance=0, role="controller",
                     partitions_fn=lambda: 1 / 0)
        assert identity()["partitions"] == []

    def test_record_stamps_both_clocks_and_seq(self):
        log = EventLog(size=8)
        set_identity(instance=7, role="controller")
        a = log.record("lead_claim", epoch=2)
        b = log.record("member_silent", instance=9, peer=7)
        assert a["kind"] == "lead_claim" and a["epoch"] == 2
        assert a["instance"] == 7          # from identity()
        assert b["instance"] == 9          # explicit wins
        assert b["seq"] == a["seq"] + 1
        assert a["mono"] <= b["mono"] and a["ts"] <= b["ts"]

    def test_disabled_records_nothing(self):
        log = EventLog(size=8, enabled=False)
        assert log.record("lead_claim") is None
        assert log.recent() == []

    def test_ring_eviction_counted(self):
        log = EventLog(size=4)
        for i in range(10):
            log.record("k", i=i)
        recent = log.recent()
        assert len(recent) == 4 and recent[-1]["i"] == 9
        assert log.evicted == 6

    def test_publisher_sees_records_and_never_breaks_recording(self):
        log = EventLog(size=8)
        seen = []
        log.attach_publisher(seen.append)
        log.record("a")
        log.attach_publisher(lambda rec: 1 / 0)
        assert log.record("b") is not None   # raising publisher swallowed
        log.attach_publisher(None)
        log.record("c")
        assert [r["kind"] for r in seen] == ["a"]
        assert [r["kind"] for r in log.recent()] == ["a", "b", "c"]


class TestReconstructPhases:
    @staticmethod
    def _ev(kind, mono, **f):
        return {"kind": kind, "mono": mono, "ts": 1000.0 + mono,
                "seq": int(mono * 1000), **f}

    def test_phases_telescope_to_downtime(self):
        ev = [self._ev("chaos_kill", 10.0),
              self._ev("member_silent", 10.4, peer=0),
              self._ev("part_claim", 10.45),
              self._ev("absorb_end", 10.6),
              self._ev("first_placement", 10.7)]
        out = reconstruct_phases(ev)
        assert out["complete"]
        assert out["phases"] == {"detect_s": 0.4, "claim_s": 0.05,
                                 "absorb_s": 0.15,
                                 "first_placement_s": 0.1}
        assert round(sum(out["phases"].values()), 6) == out["downtime_s"]

    def test_first_mark_at_or_after_previous_wins(self):
        # marks BEFORE the kill and post-recovery duplicates must not
        # pollute the phases
        ev = [self._ev("member_silent", 5.0, peer=9),   # pre-kill noise
              self._ev("chaos_kill", 10.0),
              self._ev("member_silent", 10.4),
              self._ev("part_claim", 10.45),
              self._ev("absorb_end", 10.6),
              self._ev("first_placement", 10.7),
              self._ev("member_silent", 20.0),          # recovered regime
              self._ev("first_placement", 21.0)]
        out = reconstruct_phases(ev)
        assert out["phases"]["detect_s"] == 0.4
        assert out["downtime_s"] == 0.7

    def test_missing_mark_is_incomplete_not_an_error(self):
        ev = [self._ev("chaos_kill", 10.0),
              self._ev("member_silent", 10.4)]
        out = reconstruct_phases(ev)
        assert not out["complete"]
        assert out["downtime_s"] is None
        assert "claim_s" not in out["phases"]

    def test_phase_marks_catalog_is_causal_order(self):
        kinds = [k for k, _ in PHASE_MARKS]
        assert kinds == ["chaos_kill", "member_silent", "part_claim",
                         "absorb_end", "first_placement"]


# -- off-switch byte-exactness ---------------------------------------------
class TestWireByteExactness:
    def test_heartbeat_without_admin_url_is_byte_exact(self):
        from openwhisk_tpu.controller.loadbalancer.membership import \
            ControllerMembership
        from openwhisk_tpu.core.entity import ControllerInstanceId
        from openwhisk_tpu.messaging import MemoryMessagingProvider

        def mk(**kw):
            return ControllerMembership(MemoryMessagingProvider(),
                                        ControllerInstanceId("0"),
                                        object(), **kw)

        plain = mk()._heartbeat_msg()
        assert plain == json.dumps({"kind": "heartbeat",
                                    "instance": 0}).encode()
        assert b"admin" not in mk(admin_url=None)._heartbeat_msg()
        assert b"admin" not in mk(admin_url="")._heartbeat_msg()
        announced = mk(admin_url="http://127.0.0.1:3233")._heartbeat_msg()
        assert json.loads(announced)["admin"] == "http://127.0.0.1:3233"

    def test_ping_without_admin_is_byte_exact_and_parse_tolerates(self):
        from openwhisk_tpu.core.entity import InvokerInstanceId, MB
        from openwhisk_tpu.messaging.message import PingMessage

        inst = InvokerInstanceId(0, user_memory=MB(256))
        plain = PingMessage(inst)
        assert plain.to_json() == {"name": inst.to_json()}
        assert b"admin" not in plain.serialize()
        # legacy payload (no admin key) parses to admin=None
        assert PingMessage.parse(plain.serialize()).admin is None
        ann = PingMessage(inst, admin="http://127.0.0.1:9001")
        back = PingMessage.parse(ann.serialize())
        assert back.admin == "http://127.0.0.1:9001"
        assert back.instance.instance == 0

    def test_peer_directory_tracks_announcing_live_peers(self):
        from openwhisk_tpu.controller.loadbalancer.membership import \
            ControllerMembership
        from openwhisk_tpu.core.entity import ControllerInstanceId
        from openwhisk_tpu.messaging import MemoryMessagingProvider

        class _Balancer:
            def update_cluster(self, n):
                pass

        m = ControllerMembership(MemoryMessagingProvider(),
                                 ControllerInstanceId("0"), _Balancer(),
                                 member_timeout_s=60.0)
        m._on_message(json.dumps(
            {"kind": "heartbeat", "instance": 1,
             "admin": "http://127.0.0.1:41"}).encode())
        m._on_message(json.dumps(
            {"kind": "heartbeat", "instance": 2}).encode())
        assert m.peer_directory() == {1: "http://127.0.0.1:41"}
        m._on_message(json.dumps(
            {"kind": "leave", "instance": 1}).encode())
        assert m.peer_directory() == {}


# -- exact-merge property tests --------------------------------------------
def _feed(wf: ActivationWaterfall, samples, t0=1_000_000_000):
    """samples: list of per-stage microsecond deltas dicts."""
    for i, deltas in enumerate(samples):
        aid = f"a{t0}-{i}"
        now = t0
        wf.begin(aid, t0_ns=now)
        for stage in sorted(deltas):
            now += deltas[stage] * 1000
            wf.stamp(aid, stage, now_ns=now)
        wf.finish(aid)


def _rand_samples(rng, n):
    out = []
    for _ in range(n):
        out.append({STAGE_API_ACCEPT: rng.randint(1, 50),
                    STAGE_PUBLISH_ENQUEUE: rng.randint(1, 2000),
                    STAGE_INVOKER_PICKUP: rng.randint(1, 500),
                    STAGE_RUN: rng.randint(10, 100_000),
                    STAGE_COMPLETION_ACK: rng.randint(1, 300),
                    STAGE_RECORD_WRITE: rng.randint(1, 300)})
    return out


class TestBitExactMerge:
    def teardown_method(self):
        reset_identity()

    @pytest.mark.parametrize("seed", [1, 7, 23])
    def test_bucketwise_merge_equals_pooled_samples(self, seed):
        rng = random.Random(seed)
        cfg = dict(enabled=True, buckets=30)
        a = ActivationWaterfall(WaterfallConfig(**cfg))
        b = ActivationWaterfall(WaterfallConfig(**cfg))
        pooled = ActivationWaterfall(WaterfallConfig(**cfg))
        sa, sb = _rand_samples(rng, 120), _rand_samples(rng, 80)
        _feed(a, sa)
        _feed(b, sb, t0=2_000_000_000)
        _feed(pooled, sa)
        _feed(pooled, sb, t0=2_000_000_000)

        ra, rb = a.raw_counts(), b.raw_counts()
        merged = merged_waterfall_report([ra, rb])
        ref = pooled.report()
        # the rendered per-stage/budget views and the tail threshold +
        # dominant-stage attribution derive purely from the bucket counts
        # and sums — equality here IS bucket-wise exactness
        assert merged["per_stage"] == ref["per_stage"]
        # the merge recomputes the p99 threshold over the MERGED total
        # hist (the pooled instance's own copy refreshes lazily every 64
        # finishes, so it can be stale — the recomputed one cannot)
        from openwhisk_tpu.utils.waterfall import bucket_bounds_ms
        true_tb = pooled._pctl_bucket(pooled._total_hist, 0.99)
        assert merged["tail"]["tail_threshold_ms"] == \
            bucket_bounds_ms(pooled.n_buckets)[true_tb]
        assert merged["tail"]["dominant"] == ref["tail"]["dominant"]
        # dominant_tail is an ONLINE tally against each process's moving
        # p99 threshold — not derivable from buckets; the fleet semantics
        # are "sum of per-member judgments", pinned exactly:
        summed = [x + y for x, y in zip(ra["dominant_tail"],
                                        rb["dominant_tail"])]
        from openwhisk_tpu.utils.waterfall import STAGES
        assert merged["tail"]["dominant_tail"] == {
            STAGES[i]: summed[i] for i in range(N_STAGES) if summed[i]}
        assert merged["finished"] == ref["finished"] == 200
        assert merged["buckets_le_ms"] == ref["buckets_le_ms"]
        assert merged["identity"]["role"] == "fleet"
        assert len(merged["members"]) == 2

    def test_mismatched_bucket_grids_are_skipped_not_pooled(self):
        set_identity(instance=0, role="controller")
        a = ActivationWaterfall(WaterfallConfig(enabled=True, buckets=30))
        b = ActivationWaterfall(WaterfallConfig(enabled=True, buckets=16))
        _feed(a, _rand_samples(random.Random(1), 5))
        _feed(b, _rand_samples(random.Random(2), 5))
        ra, rb = a.raw_counts(), b.raw_counts()
        rb["identity"] = {"instance": 9, "role": "controller"}
        merged = merged_waterfall_report([ra, rb])
        assert merged["finished"] == 5
        assert [m.get("instance") for m in merged["members_skipped"]] == [9]

    def test_merged_slo_is_judged_over_merged_counts(self):
        # two processes whose namespace histograms only violate the p99
        # target when POOLED: a mean of per-process verdicts cannot see it
        from openwhisk_tpu.ops.telemetry import N_OUTCOMES, bucket_bounds_ms
        nb = 24
        bounds = bucket_bounds_ms(nb)

        def raw(inst, hits_slow):
            buckets = [0] * nb
            buckets[4] = 90
            buckets[20] = hits_slow  # ~100ms+ bucket
            return {"identity": {"instance": inst, "role": "controller"},
                    "enabled": True, "kernel": "xla", "buckets": nb,
                    "targets": {"e2e_p99_ms": bounds[10],
                                "error_ratio": 0.5},
                    "overrides": {}, "dropped_events": 0,
                    "namespaces": {"guest": {
                        "buckets": buckets,
                        "outcomes": [sum(buckets)] + [0] * (N_OUTCOMES - 1),
                        "lat_ms": {}}},
                    "invokers": {}}

        merged = merged_slo_report([raw(0, 0), raw(1, 4)])
        ns = merged["namespaces"]["guest"]
        assert ns["count"] == 184
        assert merged["members"] == [
            {"instance": 0, "role": "controller"},
            {"instance": 1, "role": "controller"}]
        # 4/184 > 1% of samples in the slow bucket -> merged p99 blows the
        # target even though member 0 alone was clean
        assert ns["p99_le_ms"] > bounds[10]
        assert ns["latency_compliant"] is False
        # the clean member judged alone is compliant — proving the fleet
        # verdict is a re-judgment of pooled counts, not a vote
        solo = merged_slo_report([raw(0, 0)])
        assert solo["namespaces"]["guest"]["latency_compliant"] is True

    def test_merged_metrics_counters_sum_gauges_stay_per_member(self):
        def raw(inst, n):
            return {"identity": {"instance": inst},
                    "counters": [["requests_total", [["code", "200"]], n]],
                    "gauges": [["load", [], inst * 1.5]],
                    "histograms": [["lat_ms", [], {"count": n,
                                                   "sum": 10.0 * n}]]}

        out = merged_metrics([raw(0, 3), raw(1, 4)])
        assert out["counters"] == [["requests_total", [["code", "200"]], 7]]
        assert out["histograms"] == [["lat_ms", [],
                                      {"count": 7, "sum": 70.0}]]
        assert [g["identity"]["instance"] for g in out["gauges_by_member"]] \
            == [0, 1]
        # a fleet sum of a utilization gauge is a lie: no merged gauges key
        assert "gauges" not in out

    def test_merged_host_report_bucketwise(self):
        def raw(inst, lag_bucket, n):
            nb = 30
            lag = [0] * nb
            lag[lag_bucket] = n
            return {"identity": {"instance": inst, "role": "controller"},
                    "enabled": True, "buckets": nb, "uptime_s": 1.0,
                    "lag": {"hist": lag, "sum_us": 100 * n, "max_us": 900,
                            "ticks": n},
                    "stalls": {"count": 1, "sum_us": 50},
                    "gc": {"hist": [[0] * nb] * 3, "sum_us": [0, 0, 0],
                           "count": [0, 0, 0], "collected": 2,
                           "uncollectable": 0, "overlapping_dispatch": 1},
                    "tasks": {"created": 10 * n, "finished": 9 * n},
                    "serde": [["health", "encode", n, 64 * n, 1000 * n]]}

        out = merged_host_report([raw(0, 5, 10), raw(1, 9, 10)])
        assert out["loop_lag"]["ticks"] == 20
        assert out["tasks"] == {"created": 200, "finished": 180,
                                "active": 20}
        assert out["serde"] == [{"hop": "health", "direction": "encode",
                                 "count": 20, "bytes": 1280, "ms": 0.02}]
        assert [m["instance"] for m in out["members"]] == [0, 1]

    def test_metrics_raw_wire_shape_roundtrips_through_merge(self):
        from openwhisk_tpu.utils.logging import MetricEmitter
        a, b = MetricEmitter(), MetricEmitter()
        for m in (a, b):
            m.counter("loadbalancer_activations_total",
                      tags={"invoker": "invoker0"})
        a.counter("loadbalancer_activations_total",
                  tags={"invoker": "invoker0"})
        ra = metrics_raw(a.snapshot(), {"instance": 0})
        rb = metrics_raw(b.snapshot(), {"instance": 1})
        merged = merge_serialized_counters([ra, rb])
        assert merged == [["loadbalancer_activations_total",
                           [["invoker", "invoker0"]], 3]]


# -- spillover continuity --------------------------------------------------
class TestSpilloverContinuity:
    def test_trace_context_survives_the_ctrlspill_columnar_frame(self):
        from openwhisk_tpu.core.entity import (ActivationId,
                                               ControllerInstanceId,
                                               FullyQualifiedEntityName,
                                               Identity)
        from openwhisk_tpu.messaging.columnar import (ActivationBatchMessage,
                                                      parse_batch)
        from openwhisk_tpu.messaging.message import ActivationMessage
        from openwhisk_tpu.utils.transaction import TransactionId

        tc = {"traceparent":
              "00-0af7651916cd43dd8448eb211c80319c-b7ad6b7169203331-01"}
        msg = ActivationMessage(
            TransactionId(), FullyQualifiedEntityName.parse("guest/spilled"),
            "1-a", Identity.generate("guest"), ActivationId.generate(),
            ControllerInstanceId("0"), True, {}, trace_context=tc)
        plain = ActivationMessage(
            TransactionId(), FullyQualifiedEntityName.parse("guest/other"),
            "1-a", Identity.generate("guest"), ActivationId.generate(),
            ControllerInstanceId("0"), True, {})
        _, out = parse_batch(
            ActivationBatchMessage([msg, plain]).serialize())
        assert out[0].trace_context == tc
        assert out[1].trace_context is None

    def test_join_spill_rows_telescopes_origin_and_peer_halves(self):
        def half(aid, stamped, inst, trace=None, ts=1.0):
            deltas = [-1] * N_STAGES
            for stage, us in stamped.items():
                deltas[stage] = us
            return {"activation_id": aid, "trace_id": trace, "ts": ts,
                    "total_us": sum(stamped.values()),
                    "deltas_us": deltas, "clamped": 0, "instance": inst}

        origin = half("aid1", {STAGE_API_ACCEPT: 100,
                               STAGE_SPILL_FORWARD: 400}, 0,
                      trace="t-origin", ts=1.0)
        peer = half("aid1", {STAGE_PUBLISH_ENQUEUE: 50, STAGE_RUN: 250},
                    1, ts=1.1)
        lone = half("aid2", {STAGE_API_ACCEPT: 10, STAGE_RUN: 20}, 1,
                    ts=0.5)
        rows = join_spill_rows([peer, lone, origin])
        assert [r["activation_id"] for r in rows] == ["aid2", "aid1"]
        joined = rows[1]
        assert joined["joined"] is True
        assert joined["origin_instance"] == 0
        assert joined["peer_instance"] == 1
        assert joined["trace_id"] == "t-origin"
        # telescoping: total == sum of present deltas across BOTH halves
        assert joined["total_us"] == 100 + 400 + 50 + 250
        assert joined["deltas_us"][STAGE_SPILL_FORWARD] == 400
        assert joined["deltas_us"][STAGE_RUN] == 250

    def test_merged_waterfall_renders_joined_row_with_provenance(self):
        a = ActivationWaterfall(WaterfallConfig(enabled=True, buckets=30))
        b = ActivationWaterfall(WaterfallConfig(enabled=True, buckets=30))
        t0 = 1_000_000_000
        # origin half: accepted, then handed off to the spill frame
        a.begin("sp1", t0_ns=t0)
        a.stamp("sp1", STAGE_API_ACCEPT, now_ns=t0 + 100_000)
        a.stamp("sp1", STAGE_SPILL_FORWARD, now_ns=t0 + 500_000)
        a.finish("sp1")
        # peer half: resumed at publish, ran, acked
        b.begin("sp1", t0_ns=t0 + 500_000)
        b.stamp("sp1", STAGE_PUBLISH_ENQUEUE, now_ns=t0 + 600_000)
        b.stamp("sp1", STAGE_RUN, now_ns=t0 + 900_000)
        b.finish("sp1")
        ra = a.raw_counts(rows=8)
        rb = b.raw_counts(rows=8)
        ra["identity"] = {"instance": 0, "role": "controller"}
        rb["identity"] = {"instance": 1, "role": "controller"}
        merged = merged_waterfall_report([ra, rb], recent=8)
        assert merged["joined_rows"] == 1
        row = [r for r in merged["recent"]
               if r["activation_id"] == "sp1"][0]
        assert row["joined"] is True
        assert row["origin_instance"] == 0 and row["peer_instance"] == 1
        assert row["total_ms"] == 0.9  # 0.5ms origin + 0.4ms peer


# -- merged timeline -------------------------------------------------------
class TestMergedTimeline:
    def test_orders_by_wall_then_mono_then_seq(self):
        ev = {
            0: [{"kind": "b", "ts": 2.0, "mono": 5.0, "seq": 1},
                {"kind": "d", "ts": 3.0, "mono": 6.0, "seq": 2}],
            1: [{"kind": "a", "ts": 1.0, "mono": 9.0, "seq": 0},
                {"kind": "c", "ts": 2.0, "mono": 5.5, "seq": 0}],
        }
        out = merged_timeline(ev)
        assert out["members"] == [0, 1]
        assert out["count"] == 4
        assert [e["kind"] for e in out["events"]] == ["a", "b", "c", "d"]

    def test_limit_keeps_the_tail_and_member_key_backfills_instance(self):
        ev = {3: [{"kind": f"k{i}", "ts": float(i)} for i in range(5)]}
        out = merged_timeline(ev, limit=2)
        assert [e["kind"] for e in out["events"]] == ["k3", "k4"]
        assert all(e["instance"] == 3 for e in out["events"])


# -- exposition grammar for the new families -------------------------------
class TestNewFamilyGrammar:
    EDGE_FAMILIES = ("edge_retry_total", "edge_upstream_attempts_total",
                     "edge_upstream_http_503_total")

    def test_edge_stats_counter_rows_obey_the_grammar(self):
        from openwhisk_tpu.edge import EdgeProxy, Upstream
        edge = EdgeProxy(upstreams=[Upstream("http://127.0.0.1:3233")],
                         admin_token="tok")
        edge.retry_total["http_503"] = 2
        edge.upstreams[0].attempts = 5
        edge.upstreams[0].http_503 = 2
        payload = json.loads(self._stats_body(edge))
        names = [row[0] for row in payload["counters"]]
        for fam in self.EDGE_FAMILIES:
            assert fam in names
        for name, tags, value in payload["counters"]:
            assert _NAME.match(name), name
            for k, _v in tags:
                assert _LABEL_NAME.match(k), k
            assert isinstance(value, int) and value >= 0
        assert payload["identity"]["role"] == "edge"

    @staticmethod
    def _stats_body(edge) -> bytes:
        from aiohttp.test_utils import make_mocked_request
        req = make_mocked_request(
            "GET", "/admin/edge/stats",
            headers={"Authorization": "Bearer tok"})
        return edge._edge_stats(req).body

    def test_edge_stats_denied_without_or_with_wrong_token(self):
        from aiohttp import web
        from aiohttp.test_utils import make_mocked_request
        from openwhisk_tpu.edge import EdgeProxy, Upstream
        sealed = EdgeProxy(upstreams=[Upstream("http://127.0.0.1:3233")])
        gated = EdgeProxy(upstreams=[Upstream("http://127.0.0.1:3233")],
                          admin_token="tok")
        for edge, hdrs in ((sealed, {}),
                           (sealed, {"Authorization": "Bearer anything"}),
                           (gated, {}),
                           (gated, {"Authorization": "Bearer wrong"}),
                           (gated, {"Authorization": "Basic dG9r"})):
            req = make_mocked_request("GET", "/admin/edge/stats",
                                      headers=hdrs)
            with pytest.raises(web.HTTPForbidden):
                edge._edge_stats(req)

    def test_metrics_page_posture_unchanged(self):
        from openwhisk_tpu.edge import EdgeProxy, Upstream
        edge = EdgeProxy(upstreams=[Upstream("http://127.0.0.1:3233")])
        assert "/metrics" in edge.extra_denied_paths


# -- federation endpoints over HTTP ----------------------------------------
AUTH_PORT = 13441
PEER_PORT = 13442


def _controller(port, logger=None):
    from openwhisk_tpu.controller.core import Controller
    from openwhisk_tpu.controller.loadbalancer.lean import LeanBalancer
    from openwhisk_tpu.core.entity import (ControllerInstanceId, Identity,
                                           MB, WhiskAuthRecord)
    from openwhisk_tpu.messaging import MemoryMessagingProvider
    from openwhisk_tpu.utils.logging import NullLogging

    async def noop_factory(invoker_id, provider):
        class _Stub:
            async def stop(self):
                pass

        return _Stub()

    logger = logger or NullLogging()
    provider = MemoryMessagingProvider()
    lb = LeanBalancer(provider, ControllerInstanceId("0"), noop_factory,
                      logger=logger, metrics=logger.metrics,
                      user_memory=MB(512))
    c = Controller(ControllerInstanceId("0"), provider, logger=logger,
                   load_balancer=lb)
    ident = Identity.generate("guest")
    return c, ident


class TestFederationEndpoints:
    def teardown_method(self):
        reset_identity()

    def _hdrs(self, ident):
        return {"Authorization": "Basic " + base64.b64encode(
            ident.authkey.compact.encode()).decode()}

    def test_partial_failure_is_labeled_not_an_error(self):
        import aiohttp
        from aiohttp import web
        from openwhisk_tpu.core.entity import WhiskAuthRecord
        from openwhisk_tpu.utils.waterfall import GLOBAL_WATERFALL

        wf_was = GLOBAL_WATERFALL.enabled

        async def go():
            GLOBAL_WATERFALL.enabled = True
            GLOBAL_WATERFALL.reset()
            c, ident = _controller(AUTH_PORT)
            await c.auth_store.put(WhiskAuthRecord(
                ident.subject, [ident.namespace], [ident.authkey]))
            assert c.fleet_config.enabled  # default ON

            # a live stub peer: answers the ?raw=1 scrapes with a second
            # waterfall's raw export — a ≥2-process merge over real HTTP
            peer_wf = ActivationWaterfall(WaterfallConfig(enabled=True,
                                                          buckets=30))
            _feed(peer_wf, _rand_samples(random.Random(3), 10))
            praw = peer_wf.raw_counts(rows=4)
            praw["identity"] = {"instance": 1, "role": "controller"}

            async def peer_waterfall(request):
                assert request.query.get("raw") == "1"
                return web.json_response(praw)

            papp = web.Application()
            papp.router.add_get("/admin/latency/waterfall", peer_waterfall)
            prunner = web.AppRunner(papp)
            await prunner.setup()
            await web.TCPSite(prunner, "127.0.0.1", PEER_PORT).start()

            class _Stub:
                def peer_directory(self):
                    return {1: f"http://127.0.0.1:{PEER_PORT}",
                            2: "http://127.0.0.1:9"}  # dead peer

                async def stop(self):
                    pass

            await c.start(port=AUTH_PORT)
            c.membership = _Stub()
            out = {}
            try:
                base = f"http://127.0.0.1:{AUTH_PORT}"
                async with aiohttp.ClientSession() as s:
                    async with s.get(f"{base}/admin/fleet/waterfall") as r:
                        out["wf_status"] = r.status
                        out["wf"] = await r.json()
                    async with s.get(f"{base}/admin/fleet/metrics") as r:
                        out["m_status"] = r.status
                        out["m"] = await r.json()
                    async with s.get(f"{base}/admin/fleet/timeline") as r:
                        out["t_status"] = r.status
                        out["t"] = await r.json()
                    async with s.get(f"{base}/admin/fleet/waterfall",
                                     headers=self._hdrs(ident)) as r:
                        out["wf_auth_status"] = r.status
                        out["wf_auth"] = await r.json()
                    async with s.get(f"{base}/admin/metrics/raw",
                                     headers=self._hdrs(ident)) as r:
                        out["raw_status"] = r.status
            finally:
                await prunner.cleanup()
                await c.stop()
            return out

        out = asyncio.run(go())
        GLOBAL_WATERFALL.enabled = wf_was
        # federation endpoints sit behind the same admin auth gate
        assert out["wf_status"] == 401
        assert out["m_status"] == 401
        assert out["t_status"] == 401
        assert out["raw_status"] == 200
        body = out["wf_auth"]
        assert out["wf_auth_status"] == 200      # partial, never a 500
        assert body["members_missing"] == [2]    # the dead peer, labeled
        roles = [m.get("role") for m in body["members"]]
        assert "controller" in roles
        assert body["finished"] >= 10            # peer counts merged in

    def test_disabled_is_a_404_no_op(self, monkeypatch):
        import aiohttp
        from openwhisk_tpu.core.entity import WhiskAuthRecord

        monkeypatch.setenv("CONFIG_whisk_fleetObservatory_enabled", "false")
        assert fleet_config().enabled is False

        async def go():
            c, ident = _controller(AUTH_PORT + 2)
            await c.auth_store.put(WhiskAuthRecord(
                ident.subject, [ident.namespace], [ident.authkey]))
            assert c.fleet_config.enabled is False
            await c.start(port=AUTH_PORT + 2)
            out = {}
            try:
                assert c.fleet_events is None    # no ctrlevents plumbing
                base = f"http://127.0.0.1:{AUTH_PORT + 2}"
                async with aiohttp.ClientSession() as s:
                    for path in ("/admin/fleet/metrics",
                                 "/admin/fleet/waterfall",
                                 "/admin/fleet/slo", "/admin/fleet/host",
                                 "/admin/fleet/timeline",
                                 "/admin/metrics/raw"):
                        async with s.get(base + path,
                                         headers=self._hdrs(ident)) as r:
                            out[path] = r.status
            finally:
                await c.stop()
            return out

        out = asyncio.run(go())
        assert all(status == 404 for status in out.values()), out


# -- ctrlevents bus bridging -----------------------------------------------
class TestFleetEvents:
    def test_frames_fold_into_peer_rings_and_own_frames_skip(self):
        from openwhisk_tpu.controller.fleet import FleetEvents
        from openwhisk_tpu.messaging import MemoryMessagingProvider

        async def go():
            provider = MemoryMessagingProvider()
            log0, log1 = EventLog(size=16), EventLog(size=16)
            fe0 = FleetEvents(provider, 0, event_log=log0)
            fe1 = FleetEvents(provider, 1, event_log=log1)
            fe0.start()
            fe1.start()
            try:
                log0.record("lead_claim", instance=0, epoch=1)
                log1.record("part_claim", instance=1,
                            parts={"3": 2}, prev={})
                for _ in range(100):
                    if fe0.peer_events.get(1) and fe1.peer_events.get(0):
                        break
                    await asyncio.sleep(0.05)
            finally:
                await fe0.stop()
                await fe1.stop()
            return fe0, fe1

        fe0, fe1 = asyncio.run(go())
        assert [r["kind"] for r in fe0.peer_events[1]] == ["part_claim"]
        assert [r["kind"] for r in fe1.peer_events[0]] == ["lead_claim"]
        assert 0 not in fe0.peer_events  # own frames echo back, skipped
        ev0 = fe0.events_by_member()
        assert set(ev0) == {0, 1}
        merged = merged_timeline(ev0)
        assert [e["kind"] for e in merged["events"]] == ["lead_claim",
                                                         "part_claim"]


# -- identity blocks on existing snapshots ---------------------------------
class TestIdentityOnSnapshots:
    def teardown_method(self):
        reset_identity()

    def test_waterfall_and_hostprof_and_slo_raw_carry_identity(self):
        from openwhisk_tpu.utils.hostprof import HostObservatory
        set_identity(instance=4, role="controller")
        wf = ActivationWaterfall(WaterfallConfig(enabled=True, buckets=8))
        for snap in (wf.report(), wf.raw_counts(),
                     HostObservatory().raw_counts()):
            ident = snap["identity"]
            assert ident["instance"] == 4
            assert ident["role"] == "controller"
            assert isinstance(ident["pid"], int)
            assert "partitions" in ident

"""Dev playground UI for the standalone server.

Rebuild of the reference standalone's playground
(core/standalone/.../StandaloneOpenWhisk.scala `--no-ui` option +
PlaygroundLauncher): a single self-contained HTML page served beside
/api/v1 that creates, lists and invokes actions over the REST API with the
standalone guest credentials pre-wired. No external assets — the page must
work with zero egress.
"""
from __future__ import annotations

import base64

from aiohttp import web

_PAGE = """<!DOCTYPE html>
<html lang="en">
<head>
<meta charset="utf-8">
<title>OpenWhisk-TPU playground</title>
<style>
  body { font-family: system-ui, sans-serif; margin: 2rem auto; max-width: 64rem;
         color: #222; }
  h1 { font-size: 1.3rem; }
  textarea, input, select { font-family: ui-monospace, monospace;
         font-size: 0.9rem; width: 100%; box-sizing: border-box; }
  textarea { min-height: 9rem; }
  #params { min-height: 3rem; }
  button { margin: 0.3rem 0.5rem 0.3rem 0; padding: 0.4rem 1rem; }
  pre { background: #f4f4f4; padding: 0.8rem; overflow-x: auto;
        white-space: pre-wrap; }
  .row { display: flex; gap: 1rem; } .row > div { flex: 1; }
  .muted { color: #777; font-size: 0.85rem; }
</style>
</head>
<body>
<h1>OpenWhisk-TPU playground</h1>
<p class="muted">Dev UI on the standalone server — actions run as
<code>guest</code> against <code>/api/v1</code> on this port.</p>
<div class="row">
  <div>
    <label>Action name <input id="name" value="hello"></label>
    <label>Code (python:3)
      <textarea id="code">def main(args):
    name = args.get('name', 'stranger')
    return {'greeting': 'Hello ' + name + '!'}</textarea></label>
    <label>Invoke parameters (JSON) <textarea id="params">{"name": "TPU"}</textarea></label>
    <button id="save">Save action</button>
    <button id="run">Invoke (blocking)</button>
    <span class="muted">actions: <select id="actions"></select></span>
  </div>
  <div>
    <label>Result <pre id="out">—</pre></label>
    <label>Activation <pre id="act">—</pre></label>
  </div>
</div>
<script>
const AUTH = "Basic __AUTH__";
const H = {"Authorization": AUTH, "Content-Type": "application/json"};
const $ = id => document.getElementById(id);
async function api(method, path, body) {
  const r = await fetch("/api/v1" + path,
    {method, headers: H, body: body === undefined ? undefined : JSON.stringify(body)});
  let j = null;
  try { j = await r.json(); } catch (e) {}
  return {status: r.status, body: j};
}
async function refresh() {
  const r = await api("GET", "/namespaces/_/actions");
  if (r.status !== 200) return;
  const sel = $("actions"); sel.innerHTML = "";
  for (const a of r.body) {
    const o = document.createElement("option");
    o.textContent = a.name; sel.appendChild(o);
  }
}
$("actions").onchange = async () => {
  const name = $("actions").value;
  const r = await api("GET", "/namespaces/_/actions/" + name);
  if (r.status === 200 && r.body.exec && typeof r.body.exec.code === "string") {
    $("name").value = name; $("code").value = r.body.exec.code;
  }
};
$("save").onclick = async () => {
  const r = await api("PUT",
    "/namespaces/_/actions/" + $("name").value + "?overwrite=true",
    {exec: {kind: "python:3", code: $("code").value}});
  $("out").textContent = r.status === 200 ? "saved (version " +
    r.body.version + ")" : JSON.stringify(r.body, null, 2);
  refresh();
};
$("run").onclick = async () => {
  let params = {};
  try { params = JSON.parse($("params").value || "{}"); }
  catch (e) { $("out").textContent = "bad params JSON: " + e; return; }
  $("out").textContent = "running…";
  const r = await api("POST",
    "/namespaces/_/actions/" + $("name").value + "?blocking=true", params);
  if (r.body && r.body.response) {
    $("out").textContent = JSON.stringify(r.body.response.result, null, 2);
    const {activationId, duration, logs} = r.body;
    $("act").textContent = JSON.stringify({activationId, duration, logs}, null, 2);
  } else {
    $("out").textContent = "HTTP " + r.status + "\\n" +
      JSON.stringify(r.body, null, 2);
  }
};
refresh();
</script>
</body>
</html>
"""


def playground_routes(guest_uuid: str, guest_key: str):
    """(method, path, handler) triples for Controller's extra_routes seam."""
    auth = base64.b64encode(f"{guest_uuid}:{guest_key}".encode()).decode()
    page = _PAGE.replace("__AUTH__", auth)

    async def serve(request: web.Request) -> web.Response:
        return web.Response(text=page, content_type="text/html")

    async def root(request: web.Request) -> web.Response:
        raise web.HTTPFound("/playground")

    return [("GET", "/playground", serve), ("GET", "/", root)]

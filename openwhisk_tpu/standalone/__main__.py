"""CLI: python -m openwhisk_tpu.standalone [--port 3233] [--db PATH]."""
from __future__ import annotations

import argparse
import asyncio
import json
import sys

from . import GUEST_KEY, GUEST_UUID, make_standalone
from ..utils.config import honor_jax_platforms_env
from ..utils.tasks import wait_for_shutdown


def preflight(port: int, manifest: dict = None,
              manifest_path: str = None) -> bool:
    """Boot-time environment checks (ref standalone PreFlightChecks): each
    prints one OK/FAIL line; returns False when any check fails. `manifest`
    is the already-parsed runtimes dict (main() reads the file exactly once
    and hands the same dict to the server, so what preflight validated is
    what runs)."""
    import shutil
    import socket

    from ..core.entity import ExecManifest

    ok = True

    def check(name, passed, hint=""):
        nonlocal ok
        print(f"  [{'OK' if passed else 'FAIL'}] {name}" +
              (f" — {hint}" if (hint and not passed) else ""))
        ok = ok and passed

    try:
        with socket.socket() as s:
            # match the server's bind semantics (asyncio sets SO_REUSEADDR),
            # else lingering TIME_WAIT sockets false-fail a quick restart
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("127.0.0.1", port))
        free = True
    except OSError:
        free = False
    check(f"port {port} available", free,
          "another process is listening — pick --port")
    check("python3 for action sandboxes",
          shutil.which("python3") is not None, "python3 not on PATH")
    manifest_ok = True
    if manifest is not None:
        try:
            ExecManifest.initialize(manifest)
            check(f"runtimes manifest {manifest_path or '(inline)'}", True)
        except Exception as e:  # noqa: BLE001 — ANY malformed shape is a
            # FAIL line, not a traceback (wrong structure raises
            # TypeError/AttributeError, not just ValueError)
            check(f"runtimes manifest {manifest_path or '(inline)'}", False,
                  str(e) or type(e).__name__)
            manifest_ok = False
    else:
        ExecManifest.initialize(None)
    if manifest_ok:
        print(f"  runtimes: {', '.join(ExecManifest.runtimes().kinds)}")
    return ok


def main() -> None:
    honor_jax_platforms_env()
    parser = argparse.ArgumentParser(description="Standalone OpenWhisk-TPU server")
    parser.add_argument("--port", type=int, default=3233)
    parser.add_argument("--db", type=str, default=None,
                        help="sqlite path for durable storage (default: in-memory)")
    parser.add_argument("--memory", type=int, default=2048,
                        help="invoker user memory (MB)")
    parser.add_argument("--prewarm", action="store_true",
                        help="start prewarm stem cells from the runtimes manifest")
    parser.add_argument("--balancer", choices=("lean", "tpu"), default="lean",
                        help="load balancer: lean (in-process) or tpu "
                             "(device placement kernel)")
    parser.add_argument("--no-ui", action="store_true",
                        help="do not serve the /playground dev UI")
    parser.add_argument("--manifest", default=None,
                        help="runtimes manifest JSON file (default: built-in "
                             "python:3 + nodejs:14)")
    parser.add_argument("--balancer-snapshot", default=None,
                        help="(tpu balancer) path for periodic balancer "
                             "snapshots, restored at boot; the final dump "
                             "rides the SIGTERM shutdown path")
    parser.add_argument("--balancer-snapshot-interval", type=float,
                        default=10.0)
    parser.add_argument("--balancer-journal", default=None,
                        help="(tpu balancer) write-ahead placement journal "
                             "directory (snapshot + tail replay at boot)")
    args = parser.parse_args()

    # parse the manifest file exactly once; preflight and the server get
    # the same dict (no validate/run TOCTOU window)
    manifest = None
    if args.manifest:
        try:
            with open(args.manifest) as f:
                manifest = json.load(f)
        except (OSError, ValueError) as e:
            print(f"error: cannot read manifest {args.manifest}: {e}",
                  file=sys.stderr)
            raise SystemExit(1)

    print("preflight:")
    if not preflight(args.port, manifest=manifest,
                     manifest_path=args.manifest):
        raise SystemExit(1)

    async def run():
        from ..utils.tracing import maybe_enable_zipkin
        zipkin = maybe_enable_zipkin("standalone")
        controller = None
        try:
            store = None
            if args.db:
                from ..database import open_store
                store = open_store(args.db)
            controller = await make_standalone(
                port=args.port, artifact_store=store,
                user_memory_mb=args.memory, prewarm=args.prewarm,
                balancer=args.balancer, ui=not args.no_ui,
                manifest=manifest,
                snapshot_path=args.balancer_snapshot,
                snapshot_interval=args.balancer_snapshot_interval,
                journal_dir=args.balancer_journal)
            print(f"OpenWhisk-TPU standalone listening on :{args.port} "
                  f"(balancer={args.balancer})")
            print(f"  AUTH     {GUEST_UUID}:{GUEST_KEY}")
            print(f"  API      http://127.0.0.1:{args.port}/api/v1")
            if not args.no_ui:
                print(f"  UI       http://127.0.0.1:{args.port}/playground")
            await wait_for_shutdown()
        finally:
            if controller is not None:
                await controller.stop()
            if zipkin is not None:
                await zipkin.close()

    asyncio.run(run())


if __name__ == "__main__":
    main()

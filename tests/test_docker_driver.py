"""Docker driver executed for real against the `docker` CLI contract.

Round-3 verdict: the Docker driver had "never run against a daemon" — only
in-process fakes shaped by the implementation's own assumptions. Here the
driver shells out to a faithful CLI shim (tests/fake_docker/docker) whose
"containers" are real actionproxy processes on per-container loopback IPs,
so DockerClient's subprocess plumbing, IP discovery, the HTTP /init+/run
contract, SIGSTOP/SIGCONT pause semantics, name-filtered ps, forced
remove, and log capture all execute end-to-end (contract:
DockerClient.scala:81-179, DockerContainer.scala).
"""
import asyncio
import os
import pathlib
import signal

import pytest

from openwhisk_tpu.containerpool.docker_factory import (DockerClient,
                                                        DockerContainerFactory,
                                                        docker_available)
from openwhisk_tpu.core.entity import MB
from openwhisk_tpu.utils.transaction import TransactionId

SHIM_DIR = str(pathlib.Path(__file__).parent / "fake_docker")

CODE = """
def main(args):
    print('running for', args.get('name'))
    return {'greeting': 'Hello ' + args.get('name', 'world')}
"""


@pytest.fixture
def docker_env(tmp_path, monkeypatch):
    monkeypatch.setenv("PATH", SHIM_DIR + os.pathsep + os.environ["PATH"])
    monkeypatch.setenv("FAKE_DOCKER_STATE", str(tmp_path / "state"))
    assert docker_available()
    yield
    # reap anything a failing test left behind
    state = tmp_path / "state"
    if state.exists():
        import json
        for f in state.glob("*.json"):
            try:
                pid = json.loads(f.read_text())["pid"]
                os.killpg(os.getpgid(pid), signal.SIGKILL)
            except (OSError, json.JSONDecodeError, KeyError):
                pass


async def _make(factory, name="c0"):
    return await factory.create_container(
        TransactionId(), name, "python:3", MB(256))


class TestDockerDriverExecutes:
    def test_cold_start_init_run_destroy(self, docker_env):
        async def go():
            factory = DockerContainerFactory()
            c = await _make(factory)
            assert c.addr[0].startswith("127.77.0.") and c.addr[1] == 8080
            await c.initialize({"name": "hello", "code": CODE,
                                "main": "main", "binary": False})
            result = await c.run({"name": "TPU"}, {})
            logs = await c.logs()
            await c.destroy()
            # removed: a fresh client must not find it
            remaining = await factory.client.ps()
            return result, logs, remaining

        result, logs, remaining = asyncio.run(go())
        assert result.response["greeting"] == "Hello TPU"
        assert any("running for TPU" in l for l in logs)
        assert remaining == []

    def test_pause_stops_execution_resume_restores(self, docker_env):
        async def go():
            factory = DockerContainerFactory()
            c = await _make(factory, "pausy")
            await c.initialize({"name": "hello", "code": CODE,
                                "main": "main", "binary": False})
            await c.run({"name": "warm"}, {})
            await c.suspend()
            # SIGSTOPped process must not answer within the timeout
            # (Container.run converts timeouts into a failed RunResult)
            paused = await c.run({"name": "while-paused"}, {}, timeout=0.6)
            paused_failed = not paused.ok
            await c.resume()
            revived = await c.run({"name": "back"}, {}, timeout=10.0)
            await c.destroy()
            return paused_failed, revived

        paused_failed, revived = asyncio.run(go())
        assert paused_failed, "a paused container must not serve /run"
        assert revived.response["greeting"] == "Hello back"

    def test_cleanup_reaps_only_prefixed_containers(self, docker_env):
        async def go():
            factory = DockerContainerFactory()
            a = await _make(factory, "reap-a")
            b = await _make(factory, "reap-b")
            # a container outside our name prefix must survive cleanup
            alien_id = await factory.client.run(
                "python:3", ["--name", "alien_thing", "--network", "bridge",
                             "-m", "256m"])
            await factory.cleanup()
            left = await DockerClient().ps(name_prefix="")  # everything
            await factory.client.rm(alien_id)
            return left, alien_id

        left, alien_id = asyncio.run(go())
        assert left == [alien_id], "cleanup must reap exactly the prefixed set"

    def test_cleanup_scoped_per_invoker(self, docker_env):
        """Boot-time init()/cleanup() of one invoker must never reap a
        co-hosted invoker's live containers (per-invoker name prefix)."""
        async def go():
            fac_a = DockerContainerFactory("inv-a")
            fac_b = DockerContainerFactory("inv-b")
            await _make(fac_a, "mine")
            b = await _make(fac_b, "theirs")
            await fac_a.init()  # the boot path that reaps leftovers
            left = await DockerClient().ps(name_prefix="")
            still_serves = False
            try:
                await b.initialize({"name": "x", "code": CODE,
                                    "main": "main", "binary": False})
                still_serves = (await b.run({"name": "b"}, {})).ok
            finally:
                await fac_b.cleanup()
            return left, still_serves

        left, still_serves = asyncio.run(go())
        assert len(left) == 1, "inv-a's init must reap only inv-a's containers"
        assert still_serves, "inv-b's container must still be alive and serving"

    def test_failed_image_surfaces_error(self, docker_env):
        async def go():
            factory = DockerContainerFactory()
            from openwhisk_tpu.containerpool.container import ContainerError
            with pytest.raises(ContainerError, match="failed"):
                await factory.create_container(
                    TransactionId(), "bad", "fail/va", MB(256))

        asyncio.run(go())

    def test_containerpool_cold_warm_via_docker(self, docker_env):
        """The pool + proxy FSM driving the docker driver end to end: cold
        start then a warm hit on the same (real) container process."""
        async def go():
            from openwhisk_tpu.containerpool import (ContainerPool,
                                                     ContainerPoolConfig)
            from openwhisk_tpu.containerpool.pool import Run
            from tests.test_containerpool import (AckRecorder, make_msg,
                                                  make_proxy)
            from tests.test_containerpool import make_action as base_action

            factory = DockerContainerFactory()
            recorder = AckRecorder()
            # generous pause_grace: with real SIGSTOP pause via subprocess,
            # make_pool's 20 ms grace races the second Run against an
            # in-flight docker pause under parallel-suite load
            config = ContainerPoolConfig(user_memory=MB(1024),
                                         pause_grace=10.0,
                                         idle_container_timeout=60)
            pool = ContainerPool(lambda: make_proxy(factory, recorder, config),
                                 config, prewarm_config=[])
            action = base_action("dockact")
            action.exec.code = CODE  # real greeting body

            pool.run(Run(action, make_msg(action, content={"name": "one"})))
            for _ in range(400):
                if recorder.stored:
                    break
                await asyncio.sleep(0.05)
            pool.run(Run(action, make_msg(action, content={"name": "two"})))
            for _ in range(400):
                if len(recorder.stored) == 2:
                    break
                await asyncio.sleep(0.05)
            containers = await factory.client.ps()
            await pool.shutdown()
            return recorder.stored, containers

        stored, containers = asyncio.run(go())
        assert len(stored) == 2
        assert all(a.response.is_success for a in stored)
        assert sorted(a.response.result["greeting"] for a in stored) == \
            ["Hello one", "Hello two"]
        assert len(containers) == 1, \
            "second run must warm-hit the same container, not cold start"

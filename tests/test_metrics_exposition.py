"""Prometheus text-exposition validator over a live /metrics page.

Fetches the controller's /metrics with a TpuBalancer placing real
activations (so the page carries counters, gauges, summaries with quantile
lines AND the telemetry plane's device-accumulated histogram families) and
checks every line against the exposition-format grammar: TYPE lines, metric
name / label name charsets, label-value escaping, and — for histogram
families — strictly increasing `le` bounds, monotone non-decreasing
cumulative bucket counts, and a `+Inf` bucket equal to `_count`.
"""
import asyncio
import base64
import re

import aiohttp

from openwhisk_tpu.controller.loadbalancer import TpuBalancer
from openwhisk_tpu.core.entity import (ControllerInstanceId, Identity,
                                       WhiskAuthRecord)
from openwhisk_tpu.messaging import MemoryMessagingProvider
from tests.test_balancers import _fleet, _ping_all, make_action, make_msg

PORT = 13379

_NAME = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
_SAMPLE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+"
    r"(-?[0-9]+(?:\.[0-9]+)?(?:[eE][+-]?[0-9]+)?|[+-]Inf|NaN)$")


def parse_labels(body: str) -> dict:
    """Parse a label block body ('a="x",b="y"') honoring \\\\, \\" and \\n
    escapes — a hand parser, because naive comma-splitting breaks on
    escaped quotes inside values."""
    labels = {}
    i = 0
    while i < len(body):
        eq = body.index("=", i)
        name = body[i:eq]
        assert body[eq + 1] == '"', f"unquoted label value near {body[i:]}"
        j = eq + 2
        val = []
        while body[j] != '"':
            if body[j] == "\\":
                assert body[j + 1] in ('\\', '"', 'n'), \
                    f"bad escape \\{body[j + 1]}"
                val.append({"\\": "\\", '"': '"', "n": "\n"}[body[j + 1]])
                j += 2
            else:
                assert body[j] != "\n"
                val.append(body[j])
                j += 1
        labels[name] = "".join(val)
        i = j + 1
        if i < len(body):
            assert body[i] == ",", f"expected ',' near {body[i:]}"
            i += 1
    return labels


def validate_exposition(text: str) -> dict:
    """Full-grammar pass over one exposition page. Returns
    {family: type} plus the parsed histogram groups for extra checks."""
    types = {}
    samples = []  # (name, labels, value)
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("#"):
            m = re.match(r"^# (TYPE|HELP) ([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\s+(.*))?$",
                         line)
            assert m, f"malformed comment line: {line!r}"
            if m.group(1) == "TYPE":
                fam, kind = m.group(2), (m.group(3) or "").strip()
                assert kind in ("counter", "gauge", "histogram", "summary",
                                "untyped"), line
                assert fam not in types, f"duplicate TYPE for {fam}"
                types[fam] = kind
            continue
        m = _SAMPLE.match(line)
        assert m, f"malformed sample line: {line!r}"
        name, label_body, value = m.groups()
        assert _NAME.match(name), name
        labels = parse_labels(label_body) if label_body else {}
        for ln in labels:
            assert _LABEL_NAME.match(ln), ln
        samples.append((name, labels, float(value)))

    # every sample belongs to a declared family (TYPE precedes samples in
    # this exposition: emitters declare per family before rendering)
    def family_of(name):
        if name in types:
            return name
        for suffix in ("_bucket", "_sum", "_count", "_total"):
            if name.endswith(suffix) and name[: -len(suffix)] in types:
                return name[: -len(suffix)]
        return None

    for name, labels, _ in samples:
        fam = family_of(name)
        assert fam is not None, f"sample {name} has no TYPE line"
        if "quantile" in labels:
            assert types[fam] == "summary", (name, types[fam])
        if "le" in labels:
            assert types[fam] == "histogram", (name, types[fam])

    # histogram semantics: per-series monotone cumulative le buckets,
    # +Inf present and equal to _count
    hist = {}
    counts = {}
    for name, labels, value in samples:
        fam = family_of(name)
        if types.get(fam) != "histogram":
            continue
        key_labels = tuple(sorted((k, v) for k, v in labels.items()
                                  if k != "le"))
        if name.endswith("_bucket"):
            le = labels["le"]
            hist.setdefault((fam, key_labels), []).append(
                (float("inf") if le == "+Inf" else float(le), value))
        elif name.endswith("_count"):
            counts[(fam, key_labels)] = value
    assert hist, "no histogram families on the page"
    for key, buckets in hist.items():
        les = [b[0] for b in buckets]
        assert les == sorted(les) and len(set(les)) == len(les), \
            f"le bounds not strictly increasing for {key}"
        assert les[-1] == float("inf"), f"missing +Inf bucket for {key}"
        cums = [b[1] for b in buckets]
        assert all(a <= b for a, b in zip(cums, cums[1:])), \
            f"cumulative counts not monotone for {key}: {cums}"
        assert key in counts and counts[key] == cums[-1], \
            f"+Inf bucket != _count for {key}"
    return {"types": types, "histograms": hist}


class TestExpositionFormat:
    def test_unit_validator_rejects_garbage(self):
        import pytest
        with pytest.raises(AssertionError):
            validate_exposition("bad-metric-name 1\n")
        with pytest.raises(AssertionError):
            validate_exposition(
                "# TYPE f histogram\n"
                'f_bucket{le="1"} 5\nf_bucket{le="+Inf"} 3\nf_count 3\n')

    def test_live_metrics_page_is_valid(self, tmp_path):
        from openwhisk_tpu.controller.core import Controller

        async def go():
            from openwhisk_tpu.controller.loadbalancer.journal import \
                PlacementJournal
            from openwhisk_tpu.utils.hostprof import GLOBAL_HOST_OBSERVATORY
            from openwhisk_tpu.utils.logging import NullLogging
            # the host observatory's families (ISSUE 11) must render on
            # the same page: Controller.start() installs it on this loop
            GLOBAL_HOST_OBSERVATORY.enabled = True
            GLOBAL_HOST_OBSERVATORY.reset()
            provider = MemoryMessagingProvider()
            # share one emitter between balancer and controller, the way
            # the production assemblies wire it (metrics=logger.metrics) —
            # that is what puts the telemetry renderer on the /metrics page
            logger = NullLogging()
            bal = TpuBalancer(provider, ControllerInstanceId("0"),
                              logger=logger, metrics=logger.metrics,
                              managed_fraction=1.0, blackbox_fraction=0.0)
            # the HA plane's families (ISSUE 9): a live journal + an
            # adopted leadership epoch must render on the same page
            bal.attach_journal(PlacementJournal(str(tmp_path / "wal")))
            bal.set_leadership(2, True)
            controller = Controller(ControllerInstanceId("0"), provider,
                                    logger=logger, load_balancer=bal)
            ident = Identity.generate("guest")
            await controller.auth_store.put(WhiskAuthRecord(
                ident.subject, [ident.namespace], [ident.authkey]))
            await controller.start(port=PORT)
            invokers, producer = await _fleet(provider, 2)
            await _ping_all(invokers, producer)
            try:
                action = make_action("exposed", memory=128)
                msgs = [make_msg(action, ident, True) for _ in range(8)]
                # waterfall contexts so the stage-duration family renders
                # (production opens them in the REST handler; this test
                # publishes straight into the balancer)
                from openwhisk_tpu.utils.waterfall import GLOBAL_WATERFALL
                GLOBAL_WATERFALL.enabled = True
                GLOBAL_WATERFALL.reset()
                for m in msgs:
                    GLOBAL_WATERFALL.begin(m.activation_id.asString)
                await asyncio.gather(*[await bal.publish(action, m)
                                       for m in msgs])
                await asyncio.sleep(0.3)
                bal.telemetry.device_fold()
                bal.telemetry.tick(bal.metrics)  # slo_* gauges on the page
                # journal gauges normally ride the supervision tick;
                # refresh them deterministically for the scrape
                bal.journal.flush()
                bal.journal.export_gauges(bal.metrics)
                # anomaly plane: two ticks (the device path harvests its
                # scores one tick late), then inject a synthetic firing
                # alert so all three new families render. Alert evaluation
                # is frozen afterwards so a racing supervision tick cannot
                # resolve the injected instance before the scrape.
                bal.anomaly.tick(bal.metrics)
                bal.anomaly.tick(bal.metrics)
                from openwhisk_tpu.controller.loadbalancer import \
                    AlertsConfig
                lbl = ((("invoker", "invoker0"),), 99.0)
                now = __import__("time").monotonic()
                bal.anomaly.engine.evaluate(now, {"straggler": [lbl]})
                bal.anomaly.engine.evaluate(now + 31, {"straggler": [lbl]})
                bal.anomaly.alerts_config = AlertsConfig(enabled=False)
                # tracing health gauges normally ride the supervision
                # tick; refresh them deterministically for the scrape
                from openwhisk_tpu.utils.tracing import \
                    export_tracing_gauges
                export_tracing_gauges(bal.metrics)
                # the trace observatory's counters (ISSUE 18) ride the
                # same page via the balancer's registered renderer: one
                # deterministic keep + one drop so both families render
                from openwhisk_tpu.utils.tracestore import \
                    GLOBAL_TRACE_STORE
                GLOBAL_TRACE_STORE.reset()
                GLOBAL_TRACE_STORE.complete("probe0", "feedbeef", 5.0,
                                            forced=True)
                GLOBAL_TRACE_STORE.complete("probe1", "feedbee1", 0.0)
                # HBM gauges: the CPU backend has no memory_stats, so feed
                # the guarded reader a canned answer — this validates the
                # loadbalancer_hbm_* family names against the grammar
                bal.profiler.memory_stats = lambda: {
                    "bytes_in_use": 1 << 20, "bytes_limit": 1 << 30}
                bal.profiler.refresh_memory(bal.metrics)
                # a value that needs label escaping must not corrupt a line
                bal.metrics.counter("exposition_escape_probe",
                                    tags={"metric": 'a"b\\c\nd'})
                # host observatory: force a GC pause so the per-generation
                # family has a row (lag ticks + serde counters accumulated
                # during the publishes above)
                import gc as _gc
                _gc.collect()
                await asyncio.sleep(0.1)  # a few probe ticks post-collect
                async with aiohttp.ClientSession() as s:
                    async with s.get(
                            f"http://127.0.0.1:{PORT}/metrics") as r:
                        return r.status, await r.text()
            finally:
                from openwhisk_tpu.utils.tracestore import \
                    GLOBAL_TRACE_STORE
                GLOBAL_TRACE_STORE.reset()
                GLOBAL_TRACE_STORE.detach()
                await controller.stop()
                for inv in invokers:
                    await inv.stop()

        status, text = asyncio.run(go())
        assert status == 200
        out = validate_exposition(text)
        types = out["types"]
        # the whole catalog rides one page: counters, gauges, summaries,
        # and the telemetry plane's REAL histogram families
        assert types["openwhisk_loadbalancer_activations_published"] == "counter"
        assert types["openwhisk_slo_burn_rate_1m"] == "gauge"
        assert types["openwhisk_loadbalancer_tpu_readback_ms"] == "summary"
        assert types[
            "openwhisk_invoker_activation_latency_seconds"] == "histogram"
        assert types[
            "openwhisk_namespace_activation_latency_seconds"] == "histogram"
        assert types[
            "openwhisk_invoker_activation_outcomes_total"] == "counter"
        # quantile lines present for summaries (satellite)
        assert 'quantile="0.99"' in text
        # at least one histogram series accumulated the 8 activations
        fam_groups = [k for k in out["histograms"]
                      if k[0] == "openwhisk_namespace_activation_latency_seconds"]
        assert fam_groups, "no namespace latency series rendered"
        # the kernel profiling plane's families (ISSUE 3): per-phase
        # device timing as a REAL histogram family, the tagged recompile
        # counter, and the HBM watermark gauges
        assert types[
            "openwhisk_loadbalancer_phase_duration_seconds"] == "histogram"
        phase_groups = {dict(k[1]).get("phase") for k in out["histograms"]
                        if k[0] ==
                        "openwhisk_loadbalancer_phase_duration_seconds"}
        assert {"assembly", "dispatch", "readback"} <= phase_groups
        assert types[
            "openwhisk_loadbalancer_kernel_recompiles_total"] == "counter"
        assert 'openwhisk_loadbalancer_kernel_recompiles_total' \
            '{expected="true"}' in text
        assert types["openwhisk_loadbalancer_hbm_bytes_in_use"] == "gauge"
        assert types["openwhisk_loadbalancer_hbm_utilization_ratio"] == "gauge"
        # the kernel-backend info gauge (ISSUE 10): one live series naming
        # the running backend + placement algorithm + how they were chosen
        assert types["openwhisk_loadbalancer_kernel_backend"] == "gauge"
        backend_series = [ln for ln in text.splitlines() if ln.startswith(
            "openwhisk_loadbalancer_kernel_backend{")]
        assert backend_series
        assert all('backend="' in ln and 'placement="' in ln
                   and 'chosen_by="' in ln for ln in backend_series)
        assert any(ln.endswith(" 1") for ln in backend_series)
        # the anomaly & alerting plane's families (ISSUE 4)
        assert types[
            "openwhisk_loadbalancer_invoker_anomaly_score"] == "gauge"
        score_series = [ln for ln in text.splitlines() if ln.startswith(
            "openwhisk_loadbalancer_invoker_anomaly_score{")]
        assert score_series and all('signal="' in ln for ln in score_series)
        assert types["openwhisk_alerts_firing"] == "gauge"
        assert ('openwhisk_alerts_firing{alertname="straggler",'
                'severity="warning"} 1') in text
        assert types["openwhisk_alert_transitions_total"] == "counter"
        assert ('openwhisk_alert_transitions_total{alertname="straggler",'
                'transition="firing"} 1') in text
        # tracing health gauges (satellite: orphan finishes are visible)
        assert types["openwhisk_tracing_orphan_finishes"] == "gauge"
        # the trace observatory's tail-sampling verdict counters
        # (ISSUE 18) ride the page via the balancer's registered renderer
        assert types["openwhisk_trace_kept_total"] == "counter"
        assert 'openwhisk_trace_kept_total{reason="forced"} 1' in text
        assert types["openwhisk_trace_dropped_total"] == "counter"
        assert "openwhisk_trace_dropped_total 1" in text
        # the HA plane's families (ISSUE 9): journal durability lag /
        # size / fsync tail + the adopted leadership epoch
        assert types["openwhisk_loadbalancer_journal_lag_batches"] == "gauge"
        assert types["openwhisk_loadbalancer_journal_bytes"] == "gauge"
        assert types[
            "openwhisk_loadbalancer_journal_fsync_p99_ms"] == "gauge"
        assert types["openwhisk_controller_leadership_epoch"] == "gauge"
        assert "openwhisk_controller_leadership_epoch 2" in text
        # the latency-waterfall plane's families (ISSUE 7): per-stage e2e
        # timing as a REAL histogram family — the grammar pass above
        # already proved names, label escaping and monotone cumulative
        # `le` for every histogram on the page, this pins the family in
        assert types[
            "openwhisk_activation_stage_duration_seconds"] == "histogram"
        wf_stages = {dict(k[1]).get("stage") for k in out["histograms"]
                     if k[0] == "openwhisk_activation_stage_duration_seconds"}
        assert {"publish_enqueue", "device_dispatch", "produce",
                "completion_ack"} <= wf_stages
        assert types[
            "openwhisk_activation_dominant_stage_total"] == "counter"
        assert 'openwhisk_activation_dominant_stage_total{scope="all"' \
            in text
        # the host hot-loop observatory's families (ISSUE 11): loop lag
        # as a REAL histogram, per-generation GC pauses, task churn, and
        # the per-hop serde cost counters
        assert types[
            "openwhisk_host_event_loop_lag_seconds"] == "histogram"
        assert 'openwhisk_host_event_loop_lag_seconds_bucket' \
            '{le="1e-06",thread="event_loop"}' in text \
            or 'openwhisk_host_event_loop_lag_seconds_bucket' \
            '{thread="event_loop"' in text
        assert types["openwhisk_host_gc_pause_seconds"] == "histogram"
        gc_series = {dict(k[1]).get("generation")
                     for k in out["histograms"]
                     if k[0] == "openwhisk_host_gc_pause_seconds"}
        assert gc_series, "no gc pause series rendered"
        assert types["openwhisk_host_tasks_created_total"] == "counter"
        assert types["openwhisk_host_tasks_finished_total"] == "counter"
        assert types["openwhisk_host_tasks_active"] == "gauge"
        assert types["openwhisk_host_loop_stalls_total"] == "counter"
        assert types[
            "openwhisk_host_gc_pauses_in_dispatch_total"] == "counter"
        assert types["openwhisk_host_serde_seconds_total"] == "counter"
        assert types["openwhisk_host_serde_bytes_total"] == "counter"
        serde_lines = [ln for ln in text.splitlines() if ln.startswith(
            "openwhisk_host_serde_seconds_total{")]
        assert serde_lines and all(
            'hop="' in ln and 'direction="' in ln for ln in serde_lines)
        # the publish path serializes ActivationMessages (the coalescing
        # producer's caller-turn encode) — that hop must be on the page
        assert any('hop="activation"' in ln and 'direction="serialize"'
                   in ln for ln in serde_lines)


class TestOpenMetricsExemplars:
    """Satellite: flight-recorder rows that carry a trace context leave a
    `# {trace_id="..."}` exemplar on the matching phase-histogram bucket
    line — but ONLY when the scrape negotiates OpenMetrics (the classic
    text format has no exemplar syntax and its parsers reject one)."""

    PORT = 13381

    def test_exemplar_only_on_openmetrics_scrape(self):
        from openwhisk_tpu.controller.core import Controller

        trace_id = "ab" * 16

        async def go():
            from openwhisk_tpu.utils.logging import NullLogging
            provider = MemoryMessagingProvider()
            logger = NullLogging()
            bal = TpuBalancer(provider, ControllerInstanceId("0"),
                              logger=logger, metrics=logger.metrics,
                              managed_fraction=1.0, blackbox_fraction=0.0)
            controller = Controller(ControllerInstanceId("0"), provider,
                                    logger=logger, load_balancer=bal)
            ident = Identity.generate("guest")
            await controller.auth_store.put(WhiskAuthRecord(
                ident.subject, [ident.namespace], [ident.authkey]))
            await controller.start(port=self.PORT)
            invokers, producer = await _fleet(provider, 2)
            await _ping_all(invokers, producer)
            try:
                action = make_action("traced", memory=128)
                msgs = [make_msg(action, ident, True) for _ in range(4)]
                for m in msgs:
                    m.trace_context = {
                        "traceparent": f"00-{trace_id}-{'cd' * 8}-01"}
                await asyncio.gather(*[await bal.publish(action, m)
                                       for m in msgs])
                await asyncio.sleep(0.2)
                out = {}
                async with aiohttp.ClientSession() as s:
                    om_hdrs = {"Accept": "application/openmetrics-text; "
                                         "version=1.0.0"}
                    async with s.get(
                            f"http://127.0.0.1:{self.PORT}/metrics",
                            headers=om_hdrs) as r:
                        out["om"] = (r.content_type, await r.text())
                    async with s.get(
                            f"http://127.0.0.1:{self.PORT}/metrics") as r:
                        out["text"] = (r.content_type, await r.text())
                return out
            finally:
                await controller.stop()
                for inv in invokers:
                    await inv.stop()

        out = asyncio.run(go())
        om_type, om_text = out["om"]
        assert om_type == "application/openmetrics-text"
        assert om_text.endswith("# EOF\n")
        ex_lines = [ln for ln in om_text.splitlines()
                    if f'# {{trace_id="{trace_id}"}}' in ln]
        assert ex_lines, "no exemplar on the OpenMetrics page"
        assert all(
            ln.startswith(
                "openwhisk_loadbalancer_phase_duration_seconds_bucket{")
            for ln in ex_lines)
        # OpenMetrics counter naming: the family is suffix-free, every
        # sample carries `_total` — Prometheus's OM parser rejects the
        # whole page otherwise, so exemplar scraping would lose all
        # metrics instead of adding trace links.
        om_counters = set()
        for ln in om_text.splitlines():
            m = re.match(r"^# TYPE (\S+) counter$", ln)
            if m:
                assert not m.group(1).endswith("_total"), \
                    f"OM counter family keeps _total suffix: {m.group(1)}"
                om_counters.add(m.group(1))
        assert om_counters, "no counter families on the OM page"
        sample_names = {m.group(1) for m in (
            _SAMPLE.match(ln.split(" # {")[0])
            for ln in om_text.splitlines()
            if ln and not ln.startswith("#")) if m}
        for fam in om_counters:
            assert fam + "_total" in sample_names, \
                f"OM counter {fam} has no _total sample"
        # the classic page still types counters by their full sample name
        txt_text = out["text"][1]
        classic_counters = {
            m.group(1) for m in (
                re.match(r"^# TYPE (\S+) counter$", ln)
                for ln in txt_text.splitlines()) if m}
        assert any(c.endswith("_total") for c in classic_counters)
        # exemplar format: `value # {labels} exemplar_value timestamp`
        for ln in ex_lines:
            suffix = ln.split("# {", 1)[1].split("} ", 1)[1]
            ex_val, ex_ts = suffix.split(" ")
            assert float(ex_val) > 0 and float(ex_ts) > 0
        txt_type, txt_text = out["text"]
        assert txt_type == "text/plain"
        assert "# {" not in txt_text and "# EOF" not in txt_text
        # the classic page still passes the full exposition grammar
        validate_exposition(txt_text)


class TestPlacementQualityFamilies:
    """ISSUE 17: the placement-quality plane's three families pass the
    same exposition grammar as the live page — the regret histogram on
    the telemetry bucket grid (strictly increasing `le`, monotone
    cumulative counts, `+Inf` == `_count`), the per-invoker divergence
    counter (with OM `_total` negotiation), and the imbalance gauge."""

    def _plane(self):
        import numpy as np

        from openwhisk_tpu.controller.loadbalancer.quality import (
            QualityConfig, QualityPlane)
        from openwhisk_tpu.ops.decision_quality import (N_SUMMARY,
                                                        S_IMBALANCE_COV,
                                                        S_ROWS,
                                                        init_quality_state)
        qp = QualityPlane(QualityConfig(enabled=True))
        qs = init_quality_state(4, qp.n_buckets, numpy=True)
        qs.regret_hist[0] = 3
        qs.regret_hist[5] = 2
        qs.inv_regret_ms[1] = 12.5
        qs.inv_divergence[1] = 3
        qs.counters[0] = 5
        qp._qstate = qs
        s = np.zeros(N_SUMMARY, np.float32)
        s[S_ROWS] = 5
        s[S_IMBALANCE_COV] = 0.25
        qp.note_summary(s)
        return qp

    def test_families_pass_exposition_grammar(self):
        qp = self._plane()
        # a label value that needs escaping must not corrupt a line
        text = qp.prometheus_text(["inv0", 'inv"one\\two'])
        out = validate_exposition(text)
        types = out["types"]
        assert types[
            "openwhisk_loadbalancer_placement_regret"] == "histogram"
        assert types[
            "openwhisk_loadbalancer_decision_divergence_total"] == "counter"
        assert types["openwhisk_loadbalancer_fleet_imbalance"] == "gauge"
        # the regret histogram accumulated both synthetic rows
        hist = [v for k, v in out["histograms"].items()
                if k[0] == "openwhisk_loadbalancer_placement_regret"]
        assert hist and hist[0][-1] == (float("inf"), 5.0)
        # only the divergent invoker renders a counter row, with its
        # escaped label value intact
        div_lines = [ln for ln in text.splitlines() if ln.startswith(
            "openwhisk_loadbalancer_decision_divergence_total{")]
        assert len(div_lines) == 1
        assert parse_labels(
            div_lines[0].split("{", 1)[1].rsplit("}", 1)[0]
        ) == {"invoker": 'inv"one\\two'}
        assert ('openwhisk_loadbalancer_fleet_imbalance{scope="fleet"} '
                "0.25") in text

    def test_openmetrics_counter_negotiation(self):
        qp = self._plane()
        om = qp.prometheus_text(["inv0", "inv1"], openmetrics=True)
        assert ("# TYPE openwhisk_loadbalancer_decision_divergence "
                "counter") in om
        assert "openwhisk_loadbalancer_decision_divergence_total{" in om

    def test_disabled_plane_renders_nothing(self):
        from openwhisk_tpu.controller.loadbalancer.quality import (
            QualityConfig, QualityPlane)
        qp = QualityPlane(QualityConfig(enabled=False))
        assert qp.prometheus_text(["inv0"]) == ""


class TestOpenMetricsCounterNaming:
    """Unit twin of the live OM-page counter check: both render paths
    (the family helpers and MetricEmitter's own counters) switch to
    suffix-free family names + `_total` samples only when asked for
    OpenMetrics, leaving the classic text format untouched."""

    def test_counter_family_text_negotiates_total_suffix(self):
        from openwhisk_tpu.controller.monitoring import counter_family_text
        rows = [({"a": "b"}, 3)]
        classic = counter_family_text("x_total", rows)
        assert classic[0] == "# TYPE x_total counter"
        assert classic[1] == 'x_total{a="b"} 3'
        om = counter_family_text("x_total", rows, openmetrics=True)
        assert om[0] == "# TYPE x counter"
        assert om[1] == 'x_total{a="b"} 3'
        # a family named without the suffix gains it on the OM page only
        om = counter_family_text("y", rows, openmetrics=True)
        assert om[0] == "# TYPE y counter"
        assert om[1] == 'y_total{a="b"} 3'

    def test_metric_emitter_counters_openmetrics(self):
        from openwhisk_tpu.utils.logging import MetricEmitter
        m = MetricEmitter()
        m.counter("completions_total", 2)
        m.counter("bare", 1, tags={"k": "v"})
        om = m.prometheus_text(openmetrics=True)
        assert "# TYPE openwhisk_completions counter" in om
        assert "openwhisk_completions_total 2" in om
        assert "# TYPE openwhisk_bare counter" in om
        assert 'openwhisk_bare_total{k="v"} 1' in om
        classic = m.prometheus_text()
        assert "# TYPE openwhisk_completions_total counter" in classic
        assert "openwhisk_completions_total 2" in classic
        assert 'openwhisk_bare{k="v"} 1' in classic
        assert "openwhisk_bare_total" not in classic


class TestTraceCounterFamilies:
    """ISSUE 18: the trace observatory's tail-sampling verdict counters
    pass the exposition grammar in both renderings. The store's text is
    pure counters — `validate_exposition` demands at least one histogram
    family per PAGE, which the live-page test above covers by composing
    this renderer with the balancer's — so this class checks the line
    grammar, label values and OM `_total` negotiation directly."""

    def _store(self):
        from openwhisk_tpu.utils.tracestore import (TraceStore,
                                                    TraceTailConfig)
        s = TraceStore(TraceTailConfig(enabled=True, keep_ring=8,
                                       pending_limit=16, keep_floor=0.0))
        s.complete("a0", "t0" * 8, 5.0, forced=True)
        s.complete("a1", "t1" * 8, 5.0, error=True)
        s.complete("a2", "t2" * 8, 5.0, error=True)
        s.complete("a3", "t3" * 8, 0.0)  # clean: dropped
        return s

    def test_classic_grammar(self):
        text = self._store().prometheus_text()
        lines = text.splitlines()
        assert "# TYPE openwhisk_trace_kept_total counter" in lines
        assert "# TYPE openwhisk_trace_dropped_total counter" in lines
        # every sample line matches the exposition sample grammar
        samples = {}
        for ln in lines:
            if ln.startswith("#"):
                continue
            m = _SAMPLE.match(ln)
            assert m, f"malformed sample line: {ln!r}"
            samples[(m.group(1), m.group(2) or "")] = float(m.group(3))
        # reason labels come from the verdict priority list, counts add up
        from openwhisk_tpu.utils.tracestore import REASONS
        kept = {parse_labels(lbl)["reason"]: v
                for (name, lbl), v in samples.items()
                if name == "openwhisk_trace_kept_total"}
        assert set(kept) <= set(REASONS)
        assert kept == {"error": 2.0, "forced": 1.0}
        assert samples[("openwhisk_trace_dropped_total", "")] == 1.0

    def test_openmetrics_counter_negotiation(self):
        om = self._store().prometheus_text(openmetrics=True)
        # OM types the suffix-free base name; samples keep `_total`
        assert "# TYPE openwhisk_trace_kept counter" in om
        assert "# TYPE openwhisk_trace_dropped counter" in om
        assert "openwhisk_trace_kept_total{" in om
        assert "openwhisk_trace_dropped_total 1" in om
        assert "# TYPE openwhisk_trace_kept_total" not in om
        assert "# TYPE openwhisk_trace_dropped_total" not in om

    def test_disabled_store_renders_nothing(self):
        from openwhisk_tpu.utils.tracestore import (TraceStore,
                                                    TraceTailConfig)
        s = TraceStore(TraceTailConfig(enabled=False))
        assert s.prometheus_text() == ""
        assert s.prometheus_text(openmetrics=True) == ""

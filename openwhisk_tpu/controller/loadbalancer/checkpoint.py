"""Balancer checkpoint/resume (SURVEY §5.4).

The balancer's scheduling state is soft — reconstructible from pings and
acks — so its whole durability story is a periodic host-side snapshot of
the device capacity matrix plus registry/slot bookkeeping
(TpuBalancer.snapshot()/restore()). This module wires that into the
service lifecycle: restore at boot (skipping the warm-up window where
in-flight holds would otherwise be forgotten and capacity double-booked
until forced-timeout self-healing catches up), then an atomic periodic
dump. Reference posture: no ML checkpointing exists; controller caches
rebuild cold (SURVEY §5.4) — the snapshot is strictly an optimization,
so every failure path here degrades to a cold start, never an abort.
"""
from __future__ import annotations

import asyncio
import json
import os
import tempfile
import threading
from typing import Optional

from ...utils.scheduler import Scheduler


def load_snapshot(balancer, path: str, logger=None,
                  cluster_size: Optional[int] = None) -> bool:
    """Restore at boot; returns True on success. A missing, corrupt, or
    incompatible snapshot means a cold start — never a boot failure.
    `cluster_size` is the OPERATOR's current topology: a stale snapshot
    from a different cluster size must not override it (re-sharding resets
    in-flight holds, exactly as a live membership change would)."""
    if not hasattr(balancer, "restore"):
        # BalancerSnapshotter.start() warns once for this condition
        return False
    try:
        with open(path) as f:
            snap = json.load(f)
    except FileNotFoundError:
        return False
    except (OSError, json.JSONDecodeError) as e:
        if logger:
            logger.warn(None, f"balancer snapshot {path} unreadable "
                              f"({e}); cold start")
        return False
    try:
        balancer.restore(snap)
    except Exception as e:  # noqa: BLE001 — incompatible snapshot: cold start
        if logger:
            logger.warn(None, f"balancer snapshot {path} not restorable "
                              f"({e}); cold start")
        return False
    if cluster_size is not None and \
            getattr(balancer, "cluster_size", cluster_size) != cluster_size:
        if logger:
            logger.warn(None, f"snapshot carries cluster_size="
                              f"{balancer.cluster_size}, topology says "
                              f"{cluster_size}: re-sharding (holds reset)")
        balancer.update_cluster(cluster_size)
    if logger:
        logger.info(None, f"balancer state restored from {path} "
                          f"({len(snap.get('registry', []))} invokers)")
    return True


def write_snapshot(balancer, path: str, parts: Optional[dict] = None) -> None:
    """Atomic dump: write-temp + rename, so a crash mid-write can never
    leave a torn snapshot for the next boot. With `parts` (captured on the
    event loop via snapshot_parts) this is safe to run on a worker
    thread."""
    snap = balancer.snapshot(parts) if parts is not None \
        else balancer.snapshot()
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(prefix=".balancer-snap-", dir=d)
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(snap, f)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


class BalancerSnapshotter:
    """Periodic snapshot loop for a service process."""

    def __init__(self, balancer, path: str, interval: float = 10.0,
                 logger=None):
        self.balancer = balancer
        self.path = path
        self.interval = interval
        self.logger = logger
        self._scheduler: Optional[Scheduler] = None
        #: set when the dump thread finishes; survives task cancellation
        #: (the asyncio wrapper future dies on cancel, the thread does not)
        self._inflight_done: Optional[threading.Event] = None

    def start(self) -> "BalancerSnapshotter":
        if hasattr(self.balancer, "snapshot"):
            self._scheduler = Scheduler(
                self.interval, self._dump, logger=self.logger,
                initial_delay=self.interval,
                name="balancer-snapshotter").start()
        elif self.logger:
            self.logger.warn(None, f"balancer snapshotting requested but "
                                   f"{type(self.balancer).__name__} keeps "
                                   "no snapshotable state; ignoring")
        return self

    async def _dump(self) -> None:
        # capture on the loop (consistent device-state ref + host-book
        # copies), then do the device->host transfer + serialize + write on
        # a worker thread — at the 64k north-star fleet the dump must not
        # stall the 2 ms batch-window data plane. Thread completion is
        # tracked by a threading.Event, NOT the asyncio future: cancelling
        # the awaiting task marks the future done while the thread keeps
        # running, and its late os.replace must never land on top of the
        # final shutdown snapshot.
        parts = self.balancer.snapshot_parts()
        done = threading.Event()
        self._inflight_done = done

        def work():
            try:
                write_snapshot(self.balancer, self.path, parts)
            finally:
                done.set()

        await asyncio.to_thread(work)

    async def stop(self, final_dump: bool = True) -> None:
        if self._scheduler is not None:
            await self._scheduler.stop()
        if self._inflight_done is not None and \
                not self._inflight_done.is_set():
            # drain the orphaned dump thread before the final dump
            drained = await asyncio.to_thread(self._inflight_done.wait, 30)
            if not drained:
                # the stuck thread could still os.replace AFTER our final
                # dump, silently shipping stale state to the next boot —
                # better to keep the last periodic snapshot and say so
                if self.logger:
                    self.logger.warn(
                        None, "balancer dump thread still running after "
                              "30s; skipping the final shutdown snapshot "
                              "(last periodic dump remains)")
                final_dump = False
        if final_dump and hasattr(self.balancer, "snapshot"):
            try:
                write_snapshot(self.balancer, self.path)
            except Exception as e:  # noqa: BLE001 — shutdown must proceed;
                # a broken device during an exceptional teardown must not
                # mask the original error or skip sibling cleanup
                if self.logger:
                    self.logger.warn(None, f"final balancer snapshot "
                                           f"failed: {e}")

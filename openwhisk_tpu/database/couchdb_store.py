"""CouchDB REST ArtifactStore.

Rebuild of common/scala/.../core/database/CouchDbRestStore.scala (+
CouchDbRestClient.scala): documents live in a CouchDB database with MVCC
revisions (`_rev`), list views are served by a design document installed at
ensure() time (the reference ships `whisks.v2.1.0` design docs via
ansible/tools/db; here one `_design/openwhisk` doc with an `all` view
emitting `[entityType, rootNamespace, timestamp]`), and attachments use
CouchDB's native attachment API (the reference's default before S3 is
wired in).

Wire surface used (all standard CouchDB API):
  PUT    /{db}                      create database (412 = exists)
  PUT    /{db}/{id}[?rev]           insert/update, 409 = conflict
  GET    /{db}/{id}                 fetch, 404 = missing
  DELETE /{db}/{id}?rev=            delete, 409 = stale rev
  GET    /{db}/_design/openwhisk/_view/all?startkey&endkey&descending&...
  PUT    /{db}/{id}/{att}?rev=      attach
  GET    /{db}/{id}/{att}           read attachment
  DELETE /{db}/{id}/{att}?rev=      delete attachment

Attachments live on a SIDECAR document (`att/{doc_id}`) rather than on the
entity document itself: the entity layer writes the attachment BEFORE the
document exists (entities.py — a reader must never see a stub whose
attachment is missing) and must not have its revision chain disturbed by
attachment writes. Sidecars carry no entityType, so views never see them;
deleting the entity deletes its sidecar.

Contract-tested against a faithful in-process CouchDB fake
(tests/test_couchdb_store.py) that enforces rev MVCC, CouchDB view
collation, and PUT-without-_attachments-stubs dropping attachments, over
real HTTP.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import quote

import aiohttp

from .store import (ArtifactStore, ArtifactStoreException, DocumentConflict,
                    NoDocumentException)

#: the view map function REAL CouchDB executes; the test fake implements
#: identical semantics natively
_DESIGN_DOC = {
    "_id": "_design/openwhisk",
    "views": {
        "all": {
            "map": (
                "function (doc) {\n"
                "  if (doc.entityType) {\n"
                "    var ns = (doc.namespace || '').split('/')[0];\n"
                "    emit([doc.entityType, ns,\n"
                "          doc.start || doc.updated || 0], null);\n"
                "  }\n"
                "}")
        }
    },
}

#: CouchDB collation: {} sorts after every string/number
_MAX = {}


class CouchDbArtifactStore(ArtifactStore):
    def __init__(self, url: str = "http://127.0.0.1:5984", db: str = "whisks",
                 username: Optional[str] = None, password: Optional[str] = None):
        self.base = url.rstrip("/")
        self.db = db
        self._auth = (aiohttp.BasicAuth(username, password)
                      if username else None)
        self._session: Optional[aiohttp.ClientSession] = None
        self._ensured = False

    def _http(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(auth=self._auth)
        return self._session

    def _doc_url(self, doc_id: str, att: Optional[str] = None) -> str:
        url = f"{self.base}/{self.db}/{quote(doc_id, safe='')}"
        if att is not None:
            url += f"/{quote(att, safe='')}"
        return url

    async def ensure(self) -> None:
        """Create the database + design doc (idempotent; ref: the deploy
        step installs design docs, ansible couchdb role / tools/db)."""
        async with self._http().put(f"{self.base}/{self.db}") as resp:
            if resp.status not in (201, 202, 412):
                raise ArtifactStoreException(
                    f"cannot create database {self.db}: {resp.status}")
        async with self._http().get(
                self._doc_url("_design/openwhisk")) as resp:
            if resp.status == 200:
                self._ensured = True
                return
        async with self._http().put(
                self._doc_url("_design/openwhisk"),
                json={k: v for k, v in _DESIGN_DOC.items() if k != "_id"}
                ) as resp:
            if resp.status not in (201, 202, 409):
                raise ArtifactStoreException(
                    f"cannot install design doc: {resp.status}")
        self._ensured = True

    async def _ensure_once(self) -> None:
        if not self._ensured:
            await self.ensure()

    # -- CRUD --------------------------------------------------------------
    async def put(self, doc_id: str, doc: Dict[str, Any],
                  rev: Optional[str] = None) -> str:
        await self._ensure_once()
        body = {k: v for k, v in doc.items() if k not in ("_id", "_rev")}
        if rev is not None:
            body["_rev"] = rev
        async with self._http().put(self._doc_url(doc_id), json=body) as resp:
            if resp.status in (201, 202):
                return (await resp.json(content_type=None))["rev"]
            if resp.status == 409:
                raise DocumentConflict(doc_id)
            # a proxy/LB 5xx may carry HTML: never let a decode error mask
            # the real failure
            raise ArtifactStoreException(
                f"put {doc_id} failed ({resp.status}): "
                f"{(await resp.text())[:256]}")

    async def get(self, doc_id: str) -> Dict[str, Any]:
        await self._ensure_once()
        async with self._http().get(self._doc_url(doc_id)) as resp:
            if resp.status == 404:
                raise NoDocumentException(doc_id)
            if resp.status != 200:
                raise ArtifactStoreException(
                    f"get {doc_id} failed ({resp.status})")
            doc = await resp.json(content_type=None)
        doc["_id"] = doc_id
        return doc

    async def delete(self, doc_id: str, rev: Optional[str] = None) -> bool:
        await self._ensure_once()
        if rev is None:
            rev = (await self.get(doc_id))["_rev"]
        async with self._http().delete(self._doc_url(doc_id),
                                       params={"rev": rev}) as resp:
            if resp.status in (200, 202):
                await self._drop_sidecar(doc_id)
                return True
            if resp.status == 404:
                raise NoDocumentException(doc_id)
            if resp.status == 409:
                raise DocumentConflict(doc_id)
            raise ArtifactStoreException(
                f"delete {doc_id} failed ({resp.status})")

    async def _drop_sidecar(self, doc_id: str) -> None:
        sid = self._att_doc_id(doc_id)
        try:
            sidecar = await self.get(sid)
        except NoDocumentException:
            return
        async with self._http().delete(self._doc_url(sid),
                                       params={"rev": sidecar["_rev"]}):
            pass  # best-effort GC; a racing writer just recreates it

    # -- views -------------------------------------------------------------
    async def _view_rows(self, collection: str, ns_root: Optional[str],
                         since: Optional[float], upto: Optional[float],
                         skip: int, limit: int, descending: bool,
                         include_docs: bool,
                         pushdown_paging: bool) -> List[Dict[str, Any]]:
        """One /_view/all range read over [collection, root-namespace, ts]
        keys. When `ns_root` is None a single key range cannot bound the
        timestamp (ns varies mid-key), so the ts filter — and therefore
        paging — runs client-side over the row keys."""
        await self._ensure_once()
        cross_ns = ns_root is None
        lo = [collection, "" if cross_ns else ns_root,
              0 if cross_ns or since is None else since]
        hi = [collection, _MAX if cross_ns else ns_root,
              _MAX if cross_ns or upto is None else upto]
        params = {
            "include_docs": "true" if include_docs else "false",
            "descending": "true" if descending else "false",
            # with descending=true CouchDB walks the index backwards, so the
            # range bounds swap (CouchDbRestClient does the same)
            "startkey": json.dumps(hi if descending else lo),
            "endkey": json.dumps(lo if descending else hi),
        }
        if pushdown_paging and not cross_ns:
            if skip:
                params["skip"] = str(skip)
            if limit:
                params["limit"] = str(limit)
        url = f"{self.base}/{self.db}/_design/openwhisk/_view/all"
        async with self._http().get(url, params=params) as resp:
            if resp.status != 200:
                raise ArtifactStoreException(
                    f"view query failed ({resp.status}): "
                    f"{(await resp.text())[:256]}")
            body = await resp.json(content_type=None)
        rows = body.get("rows", [])
        if cross_ns and (since is not None or upto is not None):
            rows = [r for r in rows
                    if (since is None or r["key"][2] >= since)
                    and (upto is None or r["key"][2] <= upto)]
        return rows

    async def query(self, collection: str, namespace: Optional[str] = None,
                    name: Optional[str] = None,
                    since: Optional[float] = None, upto: Optional[float] = None,
                    skip: int = 0, limit: int = 0,
                    descending: bool = True) -> List[Dict[str, Any]]:
        # the view keys carry only the ROOT namespace, so a package-
        # qualified query ('ns/pkg') reads the root's range and narrows
        # client-side; name filtering is also client-side (the reference
        # has dedicated byName views; one view + filter keeps the design
        # doc minimal). Paging pushes down only without client-side filters.
        ns_root = namespace.split("/")[0] if namespace is not None else None
        packaged = namespace is not None and "/" in namespace
        pushdown = name is None and not packaged and namespace is not None
        rows = await self._view_rows(collection, ns_root, since, upto,
                                     skip, limit, descending,
                                     include_docs=True,
                                     pushdown_paging=pushdown)
        docs = [row["doc"] for row in rows if row.get("doc") is not None]
        if packaged:
            docs = [d for d in docs
                    if str(d.get("namespace", "")) == namespace
                    or str(d.get("namespace", "")).startswith(namespace + "/")]
        if name is not None:
            docs = [d for d in docs if d.get("name") == name]
        if not pushdown:
            docs = docs[skip:] if skip else docs
            docs = docs[:limit] if limit else docs
        return docs

    async def count(self, collection: str, namespace: Optional[str] = None,
                    name: Optional[str] = None,
                    since: Optional[float] = None, upto: Optional[float] = None
                    ) -> int:
        if name is not None or (namespace is not None and "/" in namespace):
            # client-side filters need document bodies
            return len(await self.query(collection, namespace, name,
                                        since, upto))
        # keys alone carry the timestamp: no document bodies on the wire
        rows = await self._view_rows(collection, namespace, since, upto,
                                     0, 0, True, include_docs=False,
                                     pushdown_paging=False)
        return len(rows)

    # -- attachments (sidecar doc: see module docstring) -------------------
    @staticmethod
    def _att_doc_id(doc_id: str) -> str:
        # ':' cannot appear in entity ids (ENTITY_NAME_RX excludes it), so
        # the sidecar namespace can never collide with a real document —
        # 'att/{id}' WOULD collide with entities of a user namespace 'att'
        return f"att:{doc_id}"

    async def attach(self, doc_id: str, name: str, content_type: str,
                     data: bytes) -> None:
        await self._ensure_once()
        sid = self._att_doc_id(doc_id)
        for _ in range(5):  # create/update races with concurrent attachers
            try:
                rev = (await self.get(sid))["_rev"]
            except NoDocumentException:
                try:
                    rev = await self.put(sid, {"parent": doc_id})
                except DocumentConflict:
                    continue  # another attacher created it first
            async with self._http().put(
                    self._doc_url(sid, name), data=data,
                    params={"rev": rev},
                    headers={"Content-Type": content_type}) as resp:
                if resp.status in (201, 202):
                    return
                if resp.status != 409:  # 409: rev moved under us — retry
                    raise ArtifactStoreException(
                        f"attach {doc_id}/{name} failed ({resp.status})")
        raise DocumentConflict(f"{doc_id}/{name}")

    async def read_attachment(self, doc_id: str, name: str) -> Tuple[str, bytes]:
        await self._ensure_once()
        async with self._http().get(
                self._doc_url(self._att_doc_id(doc_id), name)) as resp:
            if resp.status == 404:
                raise NoDocumentException(f"{doc_id}/{name}")
            if resp.status != 200:
                raise ArtifactStoreException(
                    f"read attachment failed ({resp.status})")
            return (resp.headers.get("Content-Type",
                                     "application/octet-stream"),
                    await resp.read())

    async def delete_attachments(self, doc_id: str,
                                 except_name: Optional[str] = None) -> None:
        await self._ensure_once()
        sid = self._att_doc_id(doc_id)
        for _ in range(5):  # rev races with concurrent attachers: re-read
            try:
                sidecar = await self.get(sid)
            except NoDocumentException:
                return
            rev = sidecar["_rev"]
            doomed = [a for a in sidecar.get("_attachments", {})
                      if a != except_name]
            if not doomed:
                if except_name is None or not sidecar.get("_attachments"):
                    async with self._http().delete(
                            self._doc_url(sid), params={"rev": rev}) as resp:
                        if resp.status == 409:
                            continue  # a late attacher revived it — retry
                return
            for att in doomed:
                async with self._http().delete(
                        self._doc_url(sid, att), params={"rev": rev}) as resp:
                    if resp.status in (200, 202):
                        rev = (await resp.json(content_type=None))["rev"]
                    elif resp.status == 404:
                        pass  # already gone
                    elif resp.status == 409:
                        break  # rev moved under us: re-read and retry
                    else:
                        raise ArtifactStoreException(
                            f"delete attachment {doc_id}/{att} failed "
                            f"({resp.status})")
            # loop re-reads: verifies deletions stuck, retries conflicts,
            # and GCs the now-empty sidecar
        else:
            raise DocumentConflict(
                f"attachments of {doc_id}: persistent revision conflicts")

    async def close(self) -> None:
        await super().close()
        if self._session is not None and not self._session.closed:
            await self._session.close()


class CouchDbArtifactStoreProvider:
    """ArtifactStoreProvider SPI binding
    (CONFIG_whisk_spi_ArtifactStoreProvider=
     openwhisk_tpu.database.couchdb_store:CouchDbArtifactStoreProvider)."""

    @staticmethod
    def instance(**kwargs) -> CouchDbArtifactStore:
        return CouchDbArtifactStore(**kwargs)

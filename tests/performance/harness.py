"""Shared scaffolding for the performance harness.

Rebuild of the reference's tests/performance driver conventions
(tests/performance/README.md:31-140): each simulation runs against a live
system, reports latency/throughput statistics, and — exactly like the
reference's Gatling assertions — fails the run only when an operator-supplied
environment threshold is present and violated:

  MEAN_RESPONSE_TIME / MAX_MEAN_RESPONSE_TIME   upper bounds, milliseconds
  REQUESTS_PER_SEC   / MIN_REQUESTS_PER_SEC     lower bounds, requests/second

Simulations here drive the in-process standalone server (the framework's
single-host deployment) over real HTTP, so they measure the full stack:
edge-less REST -> entitlement -> balancer -> bus -> invoker -> sandbox.
"""
from __future__ import annotations

import asyncio
import base64
import json
import os
import sys
import time
from dataclasses import dataclass
from typing import Awaitable, Callable, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

import aiohttp  # noqa: E402

NOOP_CODE = "def main(args):\n    return {'ok': True}\n"


@dataclass
class Stats:
    """Latency/throughput summary over one simulation run."""
    name: str
    samples_ms: List[float]
    wall_s: float
    errors: int = 0

    def _pct(self, p: float) -> float:
        xs = sorted(self.samples_ms)
        if not xs:
            return float("nan")
        return xs[min(len(xs) - 1, int(p * len(xs)))]

    @property
    def mean_ms(self) -> float:
        return sum(self.samples_ms) / max(len(self.samples_ms), 1)

    @property
    def rps(self) -> float:
        return len(self.samples_ms) / self.wall_s if self.wall_s else 0.0

    def row(self) -> dict:
        return {
            "simulation": self.name,
            "requests": len(self.samples_ms),
            "errors": self.errors,
            "mean_ms": round(self.mean_ms, 2),
            "p50_ms": round(self._pct(0.50), 2),
            "p90_ms": round(self._pct(0.90), 2),
            "p99_ms": round(self._pct(0.99), 2),
            "rps": round(self.rps, 1),
        }

    def report(self) -> None:
        print(json.dumps(self.row()))

    def check_thresholds(self) -> bool:
        """Apply the reference's env-var assertions; True = pass."""
        ok = True
        gated = any(os.environ.get(v) for v in
                    ("MEAN_RESPONSE_TIME", "MAX_MEAN_RESPONSE_TIME",
                     "REQUESTS_PER_SEC", "MIN_REQUESTS_PER_SEC"))
        if gated and (self.errors or not self.samples_ms):
            print(f"FAIL {self.name}: {self.errors} errors, "
                  f"{len(self.samples_ms)} successful samples",
                  file=sys.stderr)
            return False
        for var in ("MEAN_RESPONSE_TIME", "MAX_MEAN_RESPONSE_TIME"):
            v = os.environ.get(var)
            if v and self.mean_ms > float(v):
                print(f"FAIL {self.name}: mean {self.mean_ms:.1f}ms > {var}={v}",
                      file=sys.stderr)
                ok = False
        for var in ("REQUESTS_PER_SEC", "MIN_REQUESTS_PER_SEC"):
            v = os.environ.get(var)
            if v and self.rps < float(v):
                print(f"FAIL {self.name}: {self.rps:.1f} rps < {var}={v}",
                      file=sys.stderr)
                ok = False
        return ok


class Client:
    """Minimal authenticated REST client for the simulations."""

    def __init__(self, session: aiohttp.ClientSession, base: str, uuid: str,
                 key: str):
        self.session = session
        self.base = base
        auth = base64.b64encode(f"{uuid}:{key}".encode()).decode()
        self.headers = {"Authorization": f"Basic {auth}",
                        "Content-Type": "application/json"}

    async def put_action(self, name: str, code: str = NOOP_CODE,
                         kind: str = "python:3", **fields) -> int:
        async with self.session.put(
                f"{self.base}/namespaces/_/actions/{name}?overwrite=true",
                headers=self.headers,
                json={"exec": {"kind": kind, "code": code}, **fields}) as r:
            return r.status

    async def invoke(self, name: str, payload: Optional[dict] = None,
                     blocking: bool = True) -> tuple:
        qs = "?blocking=true" if blocking else ""
        async with self.session.post(
                f"{self.base}/namespaces/_/actions/{name}{qs}",
                headers=self.headers, json=payload or {}) as r:
            return r.status, await r.json()

    async def get(self, path: str) -> tuple:
        async with self.session.get(f"{self.base}{path}",
                                    headers=self.headers) as r:
            return r.status, await r.json()

    async def post(self, path: str, payload: Optional[dict] = None) -> tuple:
        async with self.session.post(f"{self.base}{path}",
                                     headers=self.headers,
                                     json=payload or {}) as r:
            body = await r.json() if r.content_type == "application/json" else {}
            return r.status, body

    async def put(self, path: str, payload: Optional[dict] = None) -> tuple:
        async with self.session.put(f"{self.base}{path}",
                                    headers=self.headers,
                                    json=payload or {}) as r:
            body = await r.json() if r.content_type == "application/json" else {}
            return r.status, body

    async def delete(self, path: str) -> int:
        async with self.session.delete(f"{self.base}{path}",
                                       headers=self.headers) as r:
            return r.status


async def open_loop(n_requests: int, rate: float,
                    one: Callable[[int], Awaitable[bool]],
                    dist: str = "poisson", seed: int = 1) -> Stats:
    """Open-loop counterpart of `timed_loop`: arrivals follow a fixed
    schedule (tools/loadgen.make_schedule — the shared arrival-schedule
    helper) independent of completions, and each latency is measured from
    the SCHEDULED arrival time, so queueing behind a stalled system is
    charged to the system (coordinated-omission-correct; `timed_loop`'s
    semaphore workers self-throttle and under-report exactly that).
    Unfinished requests after the drain window count as errors."""
    from tools.loadgen import make_schedule
    from tools.loadgen import open_loop as _drive

    async def wrapped(i: int, sched_ns: int) -> bool:
        return await one(i)

    row = await _drive(wrapped, make_schedule(rate, n_requests, dist=dist,
                                              seed=seed))
    return Stats("", row["samples_ms"], row["wall_s"],
                 row["errors"] + row["unfinished"])


async def timed_loop(n_requests: int, concurrency: int,
                     one: Callable[[int], Awaitable[bool]]) -> Stats:
    """Run `one(i)` n_requests times at the given concurrency; time each.
    CLOSED loop: arrivals gate on completions — fine for smoke coverage,
    use `open_loop` when the percentiles are the point."""
    samples: List[float] = []
    errors = 0
    sem = asyncio.Semaphore(concurrency)

    async def worker(i: int):
        nonlocal errors
        async with sem:
            t0 = time.perf_counter()
            try:
                ok = await one(i)
            except Exception as e:  # transport/parse errors count, not abort
                print(f"request {i} failed: {e!r}", file=sys.stderr)
                ok = False
            dt = (time.perf_counter() - t0) * 1e3
            if ok:
                samples.append(dt)
            else:
                errors += 1

    t0 = time.perf_counter()
    await asyncio.gather(*(worker(i) for i in range(n_requests)))
    wall = time.perf_counter() - t0
    return Stats("", samples, wall, errors)


def run_with_standalone(coro_fn, port: int = 13366, pass_controller: bool = False,
                        **standalone_kw):
    """Boot the standalone server, run coro_fn(client), tear down.

    Throttles are raised far past what any simulation drives (the reference
    perf setups do the same in their deployment config,
    tests/performance/README.md) — the harness measures the data plane, not
    the 60/min namespace rate limit; ThrottleTests cover enforcement.
    `pass_controller=True` calls coro_fn(client, controller) for simulations
    that inspect the balancer's books (soak)."""
    from openwhisk_tpu.standalone import (GUEST_KEY, GUEST_UUID,
                                          make_standalone)

    standalone_kw.setdefault("invocations_per_minute", 1_000_000)
    standalone_kw.setdefault("concurrent_invocations", 10_000)
    standalone_kw.setdefault("fires_per_minute", 1_000_000)

    async def go():
        controller = await make_standalone(port=port, **standalone_kw)
        try:
            async with aiohttp.ClientSession() as session:
                client = Client(session, f"http://127.0.0.1:{port}/api/v1",
                                GUEST_UUID, GUEST_KEY)
                if pass_controller:
                    return await coro_fn(client, controller)
                return await coro_fn(client)
        finally:
            await controller.stop()

    return asyncio.run(go())

"""Namespace blacklist: invoker-side protection against abusive namespaces.

Rebuild of core/invoker/.../NamespaceBlacklist.scala + the polling wiring at
InvokerReactive.scala:156-164: the invoker periodically queries the auth
store for identities that are blocked or limited to zero concurrent
invocations, and short-circuits their activations with an error activation
instead of running containers for them.
"""
from __future__ import annotations

from typing import Set

from ..database import AuthStore


class NamespaceBlacklist:
    def __init__(self, auth_store: AuthStore):
        self.auth_store = auth_store
        self._blacklist: Set[str] = set()

    async def refresh(self) -> Set[str]:
        """Poll the store (ref: every 5 min via Scheduler)."""
        blocked: Set[str] = set()
        for record in await self.auth_store.subjects():
            limits_blocked = record.blocked
            for ident in record.identities():
                if limits_blocked or ident.limits.concurrent_invocations == 0 \
                        or ident.limits.invocations_per_minute == 0:
                    blocked.add(ident.namespace.uuid.asString)
        self._blacklist = blocked
        return blocked

    def is_blacklisted(self, identity) -> bool:
        return identity.namespace.uuid.asString in self._blacklist

    def __len__(self) -> int:
        return len(self._blacklist)

"""Blocking-invoke activation-store polling (ref PrimitiveActions.scala
waitForActivationResponse/pollActivation :592-658): when the active ack is
lost, the controller must keep polling the activation store until the wait
window closes — a record that lands late (but in time) still yields a 200.
"""
import asyncio

import pytest

from openwhisk_tpu.controller.invoke import ActionInvoker, InvokeOutcome
from openwhisk_tpu.core.entity import (ActivationId, ActivationResponse,
                                       ControllerInstanceId, EntityPath,
                                       Identity, WhiskActivation)
from openwhisk_tpu.database import NoDocumentException

from tests.test_balancers import make_action


class DelayedWriteActivationStore:
    """The activation record appears only after `delay` seconds — simulating
    a slow async store write racing the controller's blocking wait."""

    def __init__(self, delay: float):
        self.delay = delay
        self._t0 = None
        self.polls = 0

    def arm(self, activation: WhiskActivation) -> None:
        self._activation = activation
        self._t0 = asyncio.get_event_loop().time()

    async def get(self, namespace, activation_id):
        self.polls += 1
        if (self._t0 is not None and
                asyncio.get_event_loop().time() - self._t0 >= self.delay):
            return self._activation
        raise NoDocumentException(str(activation_id))


class DroppedAckBalancer:
    """publish() succeeds but the result promise never resolves (the
    completion ack was lost on the wire — at-most-once delivery)."""

    async def publish(self, action, msg):
        return asyncio.get_event_loop().create_future()


def _activation(ident: Identity, msg_id: ActivationId) -> WhiskActivation:
    import time
    now = time.time()
    return WhiskActivation(EntityPath(str(ident.namespace.name)), "act",
                           ident.subject, msg_id, now, now,
                           ActivationResponse.success({"ok": True}), duration=1)


class TestBlockingPollFallback:
    def test_lost_ack_slow_write_returns_200(self):
        """Ack dropped + activation write lands 0.5 s in: repeated polls find
        it and the invoke resolves with the result (not a 202)."""
        async def go():
            ident = Identity.generate("guest")
            action = make_action()
            store = DelayedWriteActivationStore(delay=0.5)
            inv = ActionInvoker(None, store, DroppedAckBalancer(),
                                ControllerInstanceId("0"))

            async def invoke():
                from openwhisk_tpu.core.entity import Parameters
                return await inv.invoke(ident, action, Parameters(), None,
                                        blocking=True, wait_override=3.0)

            task = asyncio.get_event_loop().create_task(invoke())
            await asyncio.sleep(0.05)
            # the activation id is minted inside invoke(); recover it from the
            # store's armed record instead: arm with a matching-get store
            store.arm(_activation(ident, ActivationId.generate()))

            outcome: InvokeOutcome = await task
            assert not outcome.accepted, "late activation write must yield 200"
            assert outcome.activation is not None
            assert store.polls >= 2, "must poll repeatedly, not once"
        asyncio.new_event_loop().run_until_complete(go())

    def test_no_record_at_all_returns_202(self):
        async def go():
            ident = Identity.generate("guest")
            action = make_action()
            store = DelayedWriteActivationStore(delay=999)
            store.arm(_activation(ident, ActivationId.generate()))
            inv = ActionInvoker(None, store, DroppedAckBalancer(),
                                ControllerInstanceId("0"))
            from openwhisk_tpu.core.entity import Parameters
            outcome = await inv.invoke(ident, action, Parameters(), None,
                                       blocking=True, wait_override=0.6)
            assert outcome.accepted, "no record within the window -> 202"
            assert store.polls >= 2
        asyncio.new_event_loop().run_until_complete(go())

    def test_failed_promise_still_polls_to_success(self):
        """A forced-timeout exception on the promise must not short-circuit
        the poll loop (the record can still land before the deadline)."""
        class FailingPromiseBalancer:
            async def publish(self, action, msg):
                fut = asyncio.get_event_loop().create_future()

                def fail():
                    if not fut.done():
                        fut.set_exception(RuntimeError("forced timeout"))
                asyncio.get_event_loop().call_later(0.05, fail)
                return fut

        async def go():
            ident = Identity.generate("guest")
            action = make_action()
            store = DelayedWriteActivationStore(delay=0.4)
            store.arm(_activation(ident, ActivationId.generate()))
            inv = ActionInvoker(None, store, FailingPromiseBalancer(),
                                ControllerInstanceId("0"))
            from openwhisk_tpu.core.entity import Parameters
            outcome = await inv.invoke(ident, action, Parameters(), None,
                                       blocking=True, wait_override=3.0)
            assert not outcome.accepted
            assert outcome.activation is not None
        asyncio.new_event_loop().run_until_complete(go())

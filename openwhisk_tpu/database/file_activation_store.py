"""Activation store variants with file sinks.

Rebuild of common/scala/.../core/database/ArtifactWithFileStorageActivationStore
/ ActivationFileStorage: activation records (and optionally their logs) are
appended as newline-delimited JSON to a rolling file for out-of-band log
shipping, in addition to (or instead of) the artifact store.
"""
from __future__ import annotations

import json
import os
from typing import List, Optional

from ..core.entity import ActivationId, Identity, WhiskActivation
from .activation_store import ActivationStore, ArtifactActivationStore
from .store import ArtifactStore


class ActivationFileStorage:
    def __init__(self, path: str, max_bytes: int = 100 * 1024 * 1024):
        self.path = path
        self.max_bytes = max_bytes
        self._index = 0

    def _target(self) -> str:
        return self.path if self._index == 0 else f"{self.path}.{self._index}"

    def write(self, activation: WhiskActivation, namespace: str) -> None:
        target = self._target()
        try:
            if os.path.exists(target) and os.path.getsize(target) > self.max_bytes:
                self._index += 1
                target = self._target()
        except OSError:
            pass
        record = activation.to_json()
        record["namespaceId"] = namespace
        with open(target, "a") as f:
            f.write(json.dumps(record, separators=(",", ":")) + "\n")


class ArtifactWithFileStorageActivationStore(ArtifactActivationStore):
    """Store in the artifact store AND append to the activation log file
    (optionally stripping logs from the stored record, as the reference does
    when logs ship via the file)."""

    def __init__(self, store: ArtifactStore, file_path: str,
                 write_logs_to_artifact: bool = True, batch_size: int = 500):
        super().__init__(store, batch_size=batch_size)
        self.file_storage = ActivationFileStorage(file_path)
        self.write_logs_to_artifact = write_logs_to_artifact

    async def store(self, activation: WhiskActivation,
                    context: Optional[Identity] = None) -> Optional[str]:
        import asyncio
        # file IO off the event loop: this runs on the activation hot path
        await asyncio.get_event_loop().run_in_executor(
            None, self.file_storage.write, activation, str(activation.namespace))
        to_store = activation if self.write_logs_to_artifact \
            else activation.without_logs()
        return await super().store(to_store, context)

"""Invoker liveness HTTP server (ref BasicRasService /ping +
DefaultInvokerServer in core/invoker)."""
from __future__ import annotations

from aiohttp import web


class InvokerServer:
    def __init__(self, invoker, port: int = 8085):
        self.invoker = invoker
        self.port = port
        self._runner = None

    async def start(self) -> None:
        app = web.Application()
        app.router.add_get("/ping", self._ping)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, "0.0.0.0", self.port)
        await site.start()

    async def _ping(self, request):
        return web.json_response("pong")

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()


class DefaultInvokerServerProvider:
    @staticmethod
    def instance(invoker, port: int = 8085) -> InvokerServer:
        return InvokerServer(invoker, port)

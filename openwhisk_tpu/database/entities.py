"""Typed entity store: cached CRUD over the ArtifactStore.

Rebuild of the WhiskEntityStore/WhiskAuthStore helpers
(common/scala/.../core/entity/WhiskStore.scala): typed get/put/delete with a
revision-keyed read-through cache and cross-instance invalidation hooks —
the controller's view of persistence (SURVEY §3.5).
"""
from __future__ import annotations

import uuid
from typing import Callable, List, Optional, Type

from ..core.entity import (Identity, WhiskAction, WhiskActivation, WhiskEntity,
                           WhiskAuthRecord, WhiskPackage, WhiskRule, WhiskTrigger)
from ..core.entity.ids import DocRevision
from .cache import EntityCache
from .store import ArtifactStore, NoDocumentException

_TYPES = {
    "actions": WhiskAction,
    "triggers": WhiskTrigger,
    "rules": WhiskRule,
    "packages": WhiskPackage,
}


def _rev_older_than(cached: Optional[str], routed: str) -> bool:
    """True when `cached` is an older document revision than `routed`.
    Revisions are couch-style "gen-digest" strings across all stores
    (sqlite_store.py:92, memory/couchdb alike); compare the generation.
    Unparsable revisions fall back to plain inequality — conservative: every
    mismatching message reloads, so a store with opaque revs trades the cache
    for correctness."""
    if cached == routed:
        return False
    try:
        return int((cached or "0").split("-", 1)[0]) < int(routed.split("-", 1)[0])
    except (ValueError, AttributeError):
        return True


class EntityStore:
    # action code above this inlining threshold is stored as an attachment
    # (ref WhiskAction CodeExecAsAttachment + AttachmentStore SPI)
    ATTACHMENT_THRESHOLD = 64 * 1024

    def __init__(self, store: ArtifactStore, cache: Optional[EntityCache] = None,
                 on_invalidate: Optional[Callable] = None):
        self.store = store
        self.cache = cache if cache is not None else EntityCache()
        self.on_invalidate = on_invalidate  # async (key) -> None, bus notify

    async def _notify(self, key: str) -> None:
        if self.on_invalidate is not None:
            await self.on_invalidate(key)

    async def put(self, entity: WhiskEntity) -> DocRevision:
        doc = entity.to_document()
        attachment = None
        attachment_name = None
        exec_json = doc.get("exec")
        if isinstance(exec_json, dict):
            code = exec_json.get("code")
            if isinstance(code, str) and len(code) > self.ATTACHMENT_THRESHOLD:
                attachment = code.encode()
                # unique name per put (ref: per-revision "sha-..." names): a
                # concurrent loser's attachment write must never be paired
                # with the winner's document stub. Orphans are reaped by
                # delete_attachments on entity delete.
                attachment_name = f"codefile-{uuid.uuid4().hex[:12]}"
                exec_json["code"] = {"attachmentName": attachment_name,
                                     "attachmentType": "text/plain"}
        # attachment FIRST: a reader (or crash) between the two writes must
        # never see a stub document whose attachment does not exist yet
        if attachment is not None:
            await self.store.attach(entity.docid, attachment_name,
                                    "text/plain", attachment)
        rev = await self.store.put(entity.docid, doc,
                                   entity.rev.rev if not entity.rev.empty else None)
        entity.rev = DocRevision(rev)
        if attachment is not None:
            # GC superseded per-put attachments now that this put WON the
            # revision race (losers must never delete the winner's bytes)
            await self.store.delete_attachments(entity.docid,
                                                except_name=attachment_name)
        self.cache.update(entity.docid, entity)
        await self._notify(entity.docid)
        return entity.rev

    async def get(self, cls: Type, doc_id: str, use_cache: bool = True,
                  rev: Optional[str] = None):
        """Typed read-through get. When `rev` is given and the cached entity's
        revision generation is OLDER than the routed one, the entry is
        reloaded (ref InvokerReactive.scala:244-258 / WhiskStore get-by-rev:
        the invoker must never execute an older revision than the controller
        routed; stores serve latest, which is never older than the routed
        rev). A cached entry at the SAME or a newer generation is served as-is
        — a backlog of old-rev activations draining after an update must not
        thrash the cache with one store read per message."""
        async def materialize(doc):
            exec_json = doc.get("exec")
            if isinstance(exec_json, dict) and isinstance(exec_json.get("code"), dict):
                _, data = await self.store.read_attachment(
                    doc_id, exec_json["code"].get("attachmentName", "codefile"))
                exec_json["code"] = data.decode()
            ent = cls.from_json(doc)
            ent.rev = DocRevision(doc.get("_rev"))
            return ent

        async def load():
            doc = await self.store.get(doc_id)  # missing doc: raise directly
            try:
                return await materialize(doc)
            except NoDocumentException:
                # a concurrent update GC'd the attachment our stale stub
                # named — the re-fetched doc names the current attachment
                doc = await self.store.get(doc_id)
                return await materialize(doc)

        if use_cache:
            ent = await self.cache.get_or_load(doc_id, load)
            if rev and _rev_older_than(ent.rev.rev, rev):
                self.cache.invalidate(doc_id)
                ent = await self.cache.get_or_load(doc_id, load)
            return ent
        return await load()

    async def get_action(self, doc_id: str, rev: Optional[str] = None
                         ) -> WhiskAction:
        return await self.get(WhiskAction, doc_id, rev=rev)

    async def get_trigger(self, doc_id: str) -> WhiskTrigger:
        return await self.get(WhiskTrigger, doc_id)

    async def get_rule(self, doc_id: str) -> WhiskRule:
        return await self.get(WhiskRule, doc_id)

    async def get_package(self, doc_id: str) -> WhiskPackage:
        return await self.get(WhiskPackage, doc_id)

    async def delete(self, entity: WhiskEntity) -> bool:
        ok = await self.store.delete(entity.docid,
                                     entity.rev.rev if not entity.rev.empty else None)
        self.cache.invalidate(entity.docid)
        await self.store.delete_attachments(entity.docid)
        await self._notify(entity.docid)
        return ok

    async def list(self, collection: str, namespace: str, skip: int = 0,
                   limit: int = 30, descending: bool = True) -> List[dict]:
        return await self.store.query(collection, namespace, skip=skip,
                                      limit=limit, descending=descending)

    def entity_class(self, collection: str) -> Type:
        return _TYPES[collection]


class AuthStore:
    """Subject/identity store (ref WhiskAuthStore + Identity views).

    Identities are looked up by (a) basic-auth uuid:key on every request and
    (b) namespace name for package resolution; both paths are cached.
    """

    COLLECTION = "subjects"

    def __init__(self, store: ArtifactStore, cache: Optional[EntityCache] = None):
        self.store = store
        self.cache = cache if cache is not None else EntityCache(ttl_seconds=60)

    async def put(self, record: WhiskAuthRecord) -> None:
        doc = record.to_json()
        doc["entityType"] = self.COLLECTION
        doc["namespace"] = str(record.subject)
        doc["name"] = str(record.subject)
        doc["updated"] = 0
        try:
            existing = await self.store.get(f"subject/{record.subject}")
            rev = existing.get("_rev")
        except NoDocumentException:
            rev = None
        await self.store.put(f"subject/{record.subject}", doc, rev)
        for ident in record.identities():
            self.cache.update(f"uuid/{ident.authkey.uuid.asString}", ident)
            self.cache.update(f"ns/{ident.namespace.name}", ident)

    async def identity_by_key(self, uuid: str, key: str) -> Optional[Identity]:
        ident = await self._find("uuid/" + uuid,
                                 lambda i: i.authkey.uuid.asString == uuid)
        if ident is not None and ident.authkey.key.asString == key:
            return ident
        return None

    async def identity_by_namespace(self, namespace: str) -> Optional[Identity]:
        return await self._find("ns/" + namespace,
                                lambda i: str(i.namespace.name) == namespace)

    async def _find(self, cache_key: str, pred) -> Optional[Identity]:
        async def load():
            docs = await self.store.query(self.COLLECTION)
            for d in docs:
                rec = WhiskAuthRecord.from_json(d)
                if rec.blocked:
                    continue
                for ident in rec.identities():
                    if pred(ident):
                        return ident
            return None

        try:
            return await self.cache.get_or_load(cache_key, load)
        except NoDocumentException:
            return None

    async def subjects(self) -> List[WhiskAuthRecord]:
        docs = await self.store.query(self.COLLECTION)
        return [WhiskAuthRecord.from_json(d) for d in docs]

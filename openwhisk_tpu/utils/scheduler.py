"""Repeating-task scheduler on asyncio.

Rebuild of the reference's Scheduler actor
(common/scala/.../common/Scheduler.scala): run a (possibly async) closure
every `interval` seconds, either fixed-rate ("scheduleAtFixedRate") or
wait-at-least ("scheduleWaitAtLeast" — next run starts `interval` after the
previous run *completed*). Errors are logged, never fatal.
"""
from __future__ import annotations

import asyncio
import inspect
from typing import Awaitable, Callable, Optional, Union

Work = Callable[[], Union[None, Awaitable[None]]]


class Scheduler:
    def __init__(self, interval: float, work: Work, *, fixed_rate: bool = False,
                 initial_delay: float = 0.0, logger=None, name: str = "scheduler"):
        self.interval = interval
        self.work = work
        self.fixed_rate = fixed_rate
        self.initial_delay = initial_delay
        self.logger = logger
        self.name = name
        self._task: Optional[asyncio.Task] = None
        self._stopped = asyncio.Event()

    def start(self) -> "Scheduler":
        self._stopped.clear()
        self._task = asyncio.get_event_loop().create_task(self._run(), name=self.name)
        return self

    async def _run(self) -> None:
        try:
            if self.initial_delay:
                await asyncio.sleep(self.initial_delay)
            loop = asyncio.get_event_loop()
            next_at = loop.time()
            while not self._stopped.is_set():
                try:
                    r = self.work()
                    if inspect.isawaitable(r):
                        await r
                except asyncio.CancelledError:
                    raise
                except Exception as e:  # noqa: BLE001 — scheduler must survive task errors
                    if self.logger:
                        from .transaction import TransactionId
                        self.logger.warn(TransactionId.SYSTEM,
                                         f"scheduled task {self.name} failed: {e!r}")
                if self.fixed_rate:
                    next_at += self.interval
                    delay = max(0.0, next_at - loop.time())
                else:
                    delay = self.interval
                try:
                    await asyncio.wait_for(self._stopped.wait(), timeout=delay)
                except asyncio.TimeoutError:
                    pass
        except asyncio.CancelledError:
            pass

    async def stop(self) -> None:
        self._stopped.set()
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None

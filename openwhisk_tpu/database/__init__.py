from .store import (ArtifactStore, ArtifactStoreException, DocumentConflict,
                    NoDocumentException, StaleParameter)
from .attachment_store import (AttachmentStore, FileAttachmentStore,
                               FileAttachmentStoreProvider,
                               MemoryAttachmentStore,
                               MemoryAttachmentStoreProvider)
from .memory_store import MemoryArtifactStore, MemoryArtifactStoreProvider
from .sqlite_store import SqliteArtifactStore, SqliteArtifactStoreProvider
from .remote_store import (DocStoreServer, RemoteArtifactStore,
                           RemoteArtifactStoreProvider, open_store)
from .batcher import Batcher
from .cache import EntityCache, RemoteCacheInvalidation
from .change_feed import CacheInvalidatorService
from .entities import EntityStore, AuthStore
from .activation_store import (ActivationStore, ArtifactActivationStore,
                               ArtifactActivationStoreProvider,
                               NoopActivationStore)

__all__ = [n for n in dir() if not n.startswith("_")]

"""Malformed-body robustness across the entity PUT surface: wrong-typed
JSON must answer the reference's 400 "The request content was malformed"
(ErrorResponse semantics), never an unhandled 500. The parsers raise
MalformedEntity (core/entity/parameters.py) and the auth middleware maps
it once for every route."""
import asyncio
import base64

import aiohttp
import pytest

from openwhisk_tpu.core.entity import (ActionLimits, Exec, MalformedEntity,
                                       Parameters)
from openwhisk_tpu.standalone import GUEST_KEY, GUEST_UUID, make_standalone

AUTH = "Basic " + base64.b64encode(f"{GUEST_UUID}:{GUEST_KEY}".encode()).decode()
HDRS = {"Authorization": AUTH, "Content-Type": "application/json"}
PORT = 13245
BASE = f"http://127.0.0.1:{PORT}/api/v1"

BAD_BODIES = [
    {"annotations": "notalist"},
    {"annotations": [{"novalue": 1}]},
    {"annotations": [{"key": 7}]},
    {"parameters": [["k", "v"]]},
    {"limits": "notadict"},
    {"limits": {"timeout": "soon"}},
    {"limits": {"memory": []}},
    {"limits": {"memory": True}},
    {"limits": {"concurrency": {"max": 2}}},
    {"exec": "notadict"},
    {"exec": {"kind": []}},
    {"exec": {"kind": "blackbox"}},
    {"exec": {"kind": "sequence", "components": "notalist"}},
    {"exec": {"kind": "sequence", "components": [123]}},
]


class TestParsersRejectWrongTypes:
    def test_parameters(self):
        for bad in ("notalist", [["k", "v"]], [{"novalue": 1}], [{"key": 7}]):
            with pytest.raises(MalformedEntity):
                Parameters.from_json(bad)
        # None, {k: v} shorthand and the wire list stay accepted
        assert len(Parameters.from_json(None)) == 0
        assert Parameters.from_json({"a": 1}).get("a") == 1
        assert Parameters.from_json([{"key": "a", "value": 2}]).get("a") == 2

    def test_limits(self):
        for bad in ("notadict", 7):
            with pytest.raises(MalformedEntity):
                ActionLimits.from_json(bad)
        for bad in ({"timeout": "soon"}, {"memory": []}, {"memory": True},
                    {"logs": {}}, {"concurrency": {"max": 2}}):
            with pytest.raises(MalformedEntity):
                ActionLimits.from_json(bad)
        assert ActionLimits.from_json({"timeout": 60000}).timeout.millis == 60000
        # numeric STRINGS are malformed too: the reference accepts only
        # JsNumber limit values
        with pytest.raises(MalformedEntity):
            ActionLimits.from_json({"memory": "256"})

    def test_exec(self):
        for bad in ("notadict", {"kind": []}, {"kind": "blackbox"},
                    {"kind": "sequence", "components": "notalist"},
                    {"kind": "sequence", "components": [123]}):
            with pytest.raises(MalformedEntity):
                Exec.from_json(bad)


class TestRestSurfaceNever500s:
    def test_entity_puts_with_malformed_bodies(self):
        async def go():
            controller = await make_standalone(port=PORT)
            statuses = []
            try:
                async with aiohttp.ClientSession() as s:
                    for kind in ("actions", "triggers", "rules", "packages"):
                        for i, body in enumerate(BAD_BODIES):
                            b = dict(body)
                            if kind == "actions" and "exec" not in b:
                                b["exec"] = {"kind": "python:3", "code": "x"}
                            if kind == "rules":
                                b.setdefault("trigger", "/_/t")
                                b.setdefault("action", "/_/a")
                            async with s.put(
                                    f"{BASE}/namespaces/_/{kind}/f{i}",
                                    headers=HDRS, json=b) as r:
                                statuses.append(
                                    (kind, body, r.status, await r.json()))
            finally:
                await controller.stop()
            return statuses

        for kind, body, status, resp in asyncio.run(go()):
            # the invariant is NO 500s; a 200 is legitimate when the entity
            # type simply has no such field (e.g. trigger `limits`)
            assert status < 500, (kind, body, status, resp)
            if kind == "actions":
                assert 400 <= status, (kind, body, status, resp)
        # the malformed ones carry the reference's message
        async def probe():
            controller = await make_standalone(port=PORT)
            try:
                async with aiohttp.ClientSession() as s:
                    async with s.put(f"{BASE}/namespaces/_/actions/m",
                                     headers=HDRS,
                                     json={"exec": {"kind": "python:3",
                                                    "code": "x"},
                                           "annotations": "notalist"}) as r:
                        return r.status, await r.json()
            finally:
                await controller.stop()

        status, body = asyncio.run(probe())
        assert status == 400
        assert body["error"].startswith("The request content was malformed")


class TestLimitEdgeValues:
    def test_infinite_and_fractional_limits_rejected(self):
        for bad in ({"timeout": 1e999}, {"timeout": float("inf")},
                    {"memory": 256.9}, {"timeout": 59999.9}):
            with pytest.raises(MalformedEntity):
                ActionLimits.from_json(bad)
        # integral floats remain accepted (JSON numbers)
        assert ActionLimits.from_json({"memory": 256.0}).memory.megabytes == 256

    def test_falsy_wrong_types_rejected(self):
        for bad in ([], "", 0, False):
            with pytest.raises(MalformedEntity):
                ActionLimits.from_json(bad)
        assert ActionLimits.from_json(None) is not None


class TestWebAndQuerySurfacesNever500:
    CASES = [
        ("GET", "/api/v1/web/guest/default/w.bogus", None, False),
        ("GET", "/api/v1/web/guest/default/w.json/deep/proj", None, False),
        ("POST", "/api/v1/web/guest/default/w.json", b"{bad", False),
        ("POST", "/api/v1/web/guest/default/w.json", b"\xff\xfe", False),
        ("GET", "/api/v1/web/guest/nopkg/nosuch.json", None, False),
        ("GET", "/api/v1/namespaces/_/activations?limit=abc", None, True),
        ("GET", "/api/v1/namespaces/_/activations?since=abc", None, True),
        ("GET", "/api/v1/namespaces/_/activations?upto=zzz&skip=-5", None, True),
        ("GET", "/api/v1/namespaces/_/activations/notanid", None, True),
        ("GET", "/api/v1/namespaces/_/actions?limit=99999999999999999999",
         None, True),
        ("POST", "/api/v1/namespaces/_/actions/w?timeout=nope&blocking=true",
         b"{}", True),
        ("GET", "/api/v1/namespaces/%2e%2e/actions", None, True),
        ("PUT", "/api/v1/namespaces/_/apis", b"{bad", True),
        ("POST", "/api/v1/namespaces/_/apis", b'{"x": 1}', True),
    ]

    def test_web_and_query_fuzz(self):
        root = f"http://127.0.0.1:{PORT}"

        async def go():
            controller = await make_standalone(port=PORT)
            out = []
            try:
                async with aiohttp.ClientSession() as s:
                    async with s.put(f"{BASE}/namespaces/_/actions/w",
                                     headers=HDRS,
                                     json={"exec": {"kind": "python:3",
                                                    "code": "def main(a):\n"
                                                            "    return {'k': 1}"},
                                           "annotations": [
                                               {"key": "web-export",
                                                "value": True}]}):
                        pass
                    for method, path, data, authed in self.CASES:
                        hdrs = HDRS if authed else None
                        async with s.request(method, root + path, data=data,
                                             headers=hdrs) as r:
                            out.append((method, path, r.status))
            finally:
                await controller.stop()
            return out

        for method, path, status in asyncio.run(go()):
            assert status < 500, (method, path, status)

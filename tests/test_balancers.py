"""Balancer integration tests: TpuBalancer + ShardingBalancer against
simulated invokers on the in-memory bus (the reference pattern of
ShardingContainerPoolBalancerTests + InvokerSupervisionTests: fake bus,
synthetic pings, direct cluster-size updates)."""
import asyncio
import time

import pytest

from openwhisk_tpu.core.entity import (ActivationId, ActivationResponse,
                                       CodeExec, ControllerInstanceId,
                                       EntityName, EntityPath,
                                       ExecutableWhiskAction, Identity,
                                       InvokerInstanceId, MB, ActionLimits,
                                       MemoryLimit, TimeLimit, WhiskActivation)
from openwhisk_tpu.core.entity.ids import DocRevision, Subject
from openwhisk_tpu.controller.loadbalancer import (ActiveAckTimeout, HEALTHY,
                                                   LoadBalancerException,
                                                   OFFLINE, ShardingBalancer,
                                                   TpuBalancer, UNHEALTHY)
from openwhisk_tpu.controller.loadbalancer.supervision import InvokerPool
from openwhisk_tpu.messaging import (ActivationMessage,
                                     CombinedCompletionAndResultMessage,
                                     MemoryMessagingProvider, MessageFeed,
                                     PingMessage)
from openwhisk_tpu.utils.transaction import TransactionId


def make_action(name="act", memory=256, kind="python:3"):
    a = ExecutableWhiskAction(EntityPath("guest"), EntityName(name),
                              CodeExec(kind=kind, code="x"),
                              limits=ActionLimits(TimeLimit(5000),
                                                  MemoryLimit(MB(memory))))
    a.rev = DocRevision("1-b")
    return a


def make_msg(action, ident, blocking=False):
    return ActivationMessage(
        TransactionId(), action.fully_qualified_name, action.rev.rev, ident,
        ActivationId.generate(), ControllerInstanceId("0"), blocking, {})


class SimInvoker:
    """A fake invoker: consumes its topic, acks immediately."""

    def __init__(self, provider, instance: InvokerInstanceId, delay=0.0):
        self.provider = provider
        self.instance = instance
        self.delay = delay
        self.handled = []
        self._feed = None

    async def start(self):
        topic = self.instance.as_string
        self.provider.ensure_topic(topic)
        consumer = self.provider.get_consumer(topic, topic)
        producer = self.provider.get_producer()
        box = {}

        async def handle(payload: bytes):
            # the batch wire ships one columnar frame per coalesced
            # micro-batch (messaging/columnar.py); lone messages still
            # arrive in the plain per-message format
            from openwhisk_tpu.messaging.columnar import (is_batch_payload,
                                                          parse_batch)
            if is_batch_payload(payload):
                _kind, msgs = parse_batch(payload)
            else:
                msgs = [ActivationMessage.parse(payload)]
            self.handled.extend(msgs)

            async def finish(msg):
                if self.delay:
                    await asyncio.sleep(self.delay)
                now = time.time()
                act = WhiskActivation(
                    EntityPath(str(msg.user.namespace.name)), msg.action.name,
                    msg.user.subject, msg.activation_id, now, now,
                    ActivationResponse.success({"ok": True}), duration=1)
                await producer.send(
                    f"completed{msg.root_controller_index.as_string}",
                    CombinedCompletionAndResultMessage(msg.transid, act,
                                                       self.instance))
                box["feed"].processed()
            for msg in msgs:
                asyncio.get_event_loop().create_task(finish(msg))

        self._feed = MessageFeed(topic, consumer, 64, handle)
        box["feed"] = self._feed
        self._feed.start()

    async def ping(self, producer):
        await producer.send("health", PingMessage(self.instance))

    async def stop(self):
        if self._feed:
            await self._feed.stop()


async def _fleet(provider, n, memory_mb=2048, delay=0.0):
    invokers = []
    producer = provider.get_producer()
    for i in range(n):
        inv = SimInvoker(provider, InvokerInstanceId(i, user_memory=MB(memory_mb)),
                         delay=delay)
        await inv.start()
        invokers.append(inv)
    return invokers, producer


async def _ping_all(invokers, producer, times=1):
    for _ in range(times):
        for inv in invokers:
            await inv.ping(producer)
    await asyncio.sleep(0.1)


@pytest.fixture(params=["tpu", "cpu"])
def balancer_cls(request):
    return TpuBalancer if request.param == "tpu" else ShardingBalancer


class TestBalancers:
    def test_publish_roundtrip_and_release(self, balancer_cls):
        async def go():
            provider = MemoryMessagingProvider()
            bal = balancer_cls(provider, ControllerInstanceId("0"),
                               managed_fraction=1.0, blackbox_fraction=0.0)
            await bal.start()
            invokers, producer = await _fleet(provider, 4)
            await _ping_all(invokers, producer)
            ident = Identity.generate("guest")
            action = make_action()
            promises = []
            for _ in range(8):
                msg = make_msg(action, ident, blocking=True)
                promises.append(await bal.publish(action, msg))
            results = await asyncio.gather(*[asyncio.wait_for(p, 5)
                                             for p in promises])
            # wait for slot releases to drain
            await asyncio.sleep(0.2)
            total = bal.total_active_activations
            slots = len(bal.activation_slots)
            await bal.close()
            for inv in invokers:
                await inv.stop()
            return results, total, slots, [len(i.handled) for i in invokers]

        results, total, slots, handled = asyncio.run(go())
        assert len(results) == 8
        assert all(r.response.is_success for r in results)
        assert total == 0 and slots == 0
        assert sum(handled) == 8

    def test_affinity_same_action_same_invoker(self, balancer_cls):
        async def go():
            provider = MemoryMessagingProvider()
            bal = balancer_cls(provider, ControllerInstanceId("0"),
                               managed_fraction=1.0, blackbox_fraction=0.0)
            await bal.start()
            invokers, producer = await _fleet(provider, 8)
            await _ping_all(invokers, producer)
            ident = Identity.generate("guest")
            action = make_action("affine", memory=128)
            for _ in range(4):
                p = await bal.publish(action, make_msg(action, ident, True))
                await asyncio.wait_for(p, 5)
                await asyncio.sleep(0.05)  # release between invokes
            await bal.close()
            for inv in invokers:
                await inv.stop()
            return [len(i.handled) for i in invokers]

        handled = asyncio.run(go())
        # all 4 sequential invokes land on the home invoker (warm affinity)
        assert sorted(handled) == [0, 0, 0, 0, 0, 0, 0, 4]

    def test_no_invokers_raises(self, balancer_cls):
        async def go():
            provider = MemoryMessagingProvider()
            bal = balancer_cls(provider, ControllerInstanceId("0"))
            await bal.start()
            ident = Identity.generate("guest")
            action = make_action()
            try:
                with pytest.raises(LoadBalancerException):
                    await bal.publish(action, make_msg(action, ident))
            finally:
                await bal.close()

        asyncio.run(go())

    def test_unhealthy_invoker_not_scheduled(self, balancer_cls):
        async def go():
            provider = MemoryMessagingProvider()
            bal = balancer_cls(provider, ControllerInstanceId("0"),
                               managed_fraction=1.0, blackbox_fraction=0.0)
            await bal.start()
            invokers, producer = await _fleet(provider, 4)
            await _ping_all(invokers, producer)
            ident = Identity.generate("guest")
            action = make_action("affine2", memory=128)
            p = await bal.publish(action, make_msg(action, ident, True))
            await asyncio.wait_for(p, 5)
            home = max(range(4), key=lambda i: len(invokers[i].handled))
            # flap the home invoker to unhealthy via system-error outcomes
            for _ in range(5):
                bal.supervision.on_invocation_finished(
                    invokers[home].instance, is_system_error=True, forced=False)
            await asyncio.sleep(0.05)
            p = await bal.publish(action, make_msg(action, ident, True))
            await asyncio.wait_for(p, 5)
            await bal.close()
            for inv in invokers:
                await inv.stop()
            return home, [len(i.handled) for i in invokers]

        home, handled = asyncio.run(go())
        assert handled[home] == 1  # second invoke avoided the unhealthy home
        assert sum(handled) == 2

    def test_offline_after_ping_silence(self):
        async def go():
            provider = MemoryMessagingProvider()
            statuses = {}
            pool = InvokerPool(provider,
                               on_status_change=lambda i, s: statuses.update(
                                   {i.instance: s}),
                               ping_timeout=0.3)
            pool.start()
            producer = provider.get_producer()
            inv = InvokerInstanceId(0, user_memory=MB(2048))
            await producer.send("health", PingMessage(inv))
            await asyncio.sleep(0.15)
            up = statuses.get(0)
            await asyncio.sleep(1.3)
            down = statuses.get(0)
            await pool.stop()
            return up, down

        up, down = asyncio.run(go())
        assert up == HEALTHY
        assert down == OFFLINE

    def test_forced_timeout_self_heals_slots(self, balancer_cls):
        async def go():
            provider = MemoryMessagingProvider()
            bal = balancer_cls(provider, ControllerInstanceId("0"),
                               managed_fraction=1.0, blackbox_fraction=0.0)
            bal.TIMEOUT_FACTOR = 0
            bal.TIMEOUT_ADDON = 0.2  # completion-ack timeout ~0.2s
            bal.STD_TIMEOUT = 0.0
            await bal.start()
            # an invoker that never acks
            dead_id = InvokerInstanceId(0, user_memory=MB(2048))
            provider.ensure_topic("invoker0")
            producer = provider.get_producer()
            await producer.send("health", PingMessage(dead_id))
            await asyncio.sleep(0.1)
            ident = Identity.generate("guest")
            action = make_action()
            msg = make_msg(action, ident, blocking=True)
            promise = await bal.publish(action, msg)
            assert bal.total_active_activations == 1
            with pytest.raises(ActiveAckTimeout):
                await asyncio.wait_for(promise, 5)
            healed = bal.total_active_activations
            await bal.close()
            return healed

        assert asyncio.run(go()) == 0


class TestTpuBalancerSpecifics:
    def test_batched_concurrent_publishes(self):
        async def go():
            provider = MemoryMessagingProvider()
            bal = TpuBalancer(provider, ControllerInstanceId("0"),
                              managed_fraction=1.0, blackbox_fraction=0.0,
                              batch_window=0.005, max_batch=64)
            await bal.start()
            invokers, producer = await _fleet(provider, 8, memory_mb=4096)
            await _ping_all(invokers, producer)
            ident = Identity.generate("guest")
            actions = [make_action(f"a{i}", memory=128) for i in range(16)]
            # 64 concurrent publishes -> batched into few device steps
            promises = await asyncio.gather(*[
                bal.publish(actions[i % 16], make_msg(actions[i % 16], ident, True))
                for i in range(64)])
            results = await asyncio.gather(*[asyncio.wait_for(p, 10)
                                             for p in promises])
            batches = bal.metrics.histogram_stats("loadbalancer_tpu_schedule_batch_ms")
            await bal.close()
            for inv in invokers:
                await inv.stop()
            return results, batches

        results, batches = asyncio.run(go())
        assert len(results) == 64
        assert all(r.response.is_success for r in results)
        assert batches["count"] < 64  # actually micro-batched

    def test_cluster_resharding(self):
        async def go():
            provider = MemoryMessagingProvider()
            bal = TpuBalancer(provider, ControllerInstanceId("0"),
                              managed_fraction=1.0, blackbox_fraction=0.0)
            await bal.start()
            invokers, producer = await _fleet(provider, 2, memory_mb=2048)
            await _ping_all(invokers, producer)
            import numpy as np
            full = np.asarray(bal.state.free_mb)[:2].tolist()
            bal.update_cluster(2)
            half = np.asarray(bal.state.free_mb)[:2].tolist()
            await bal.close()
            for inv in invokers:
                await inv.stop()
            return full, half

        full, half = asyncio.run(go())
        assert full == [2048, 2048]
        assert half == [1024, 1024]


class TestReviewRegressions:
    def test_burst_beyond_max_batch_all_complete(self):
        """Leftover pending requests past max_batch must flush without
        further traffic (review: _flush_later tail re-arm was a no-op)."""
        async def go():
            provider = MemoryMessagingProvider()
            bal = TpuBalancer(provider, ControllerInstanceId("0"),
                              managed_fraction=1.0, blackbox_fraction=0.0,
                              batch_window=0.005, max_batch=16)
            await bal.start()
            invokers, producer = await _fleet(provider, 4, memory_mb=8192)
            await _ping_all(invokers, producer)
            ident = Identity.generate("guest")
            actions = [make_action(f"b{i}", memory=128) for i in range(8)]
            promises = await asyncio.gather(*[
                bal.publish(actions[i % 8], make_msg(actions[i % 8], ident, True))
                for i in range(40)])  # 40 > max_batch=16
            results = await asyncio.gather(*[asyncio.wait_for(p, 10)
                                             for p in promises])
            await bal.close()
            for inv in invokers:
                await inv.stop()
            return results

        results = asyncio.run(go())
        assert len(results) == 40
        assert all(r.response.is_success for r in results)

    def test_fleet_growth_preserves_inflight_books(self):
        """A new invoker registering mid-flight must not reset existing
        capacity holds (review: _init_device_state wiped the books)."""
        async def go():
            import numpy as np
            provider = MemoryMessagingProvider()
            bal = TpuBalancer(provider, ControllerInstanceId("0"),
                              managed_fraction=1.0, blackbox_fraction=0.0,
                              initial_pad=2)
            await bal.start()
            invokers, producer = await _fleet(provider, 2, memory_mb=1024,
                                              delay=0.5)  # slow acks
            await _ping_all(invokers, producer)
            ident = Identity.generate("guest")
            action = make_action("grow", memory=256)
            # take capacity and keep it in flight
            p = await bal.publish(action, make_msg(action, ident, True))
            held = np.asarray(bal.state.free_mb)[:2].sum()
            # invoker 2 registers (also forces a re-pad beyond initial_pad=2)
            inv3 = SimInvoker(provider, InvokerInstanceId(2, user_memory=MB(1024)))
            await inv3.start()
            await inv3.ping(producer)
            await asyncio.sleep(0.15)
            after_grow = np.asarray(bal.state.free_mb)[:2].sum()
            new_row = int(np.asarray(bal.state.free_mb)[2])
            await asyncio.wait_for(p, 5)
            await asyncio.sleep(0.3)  # release folds in
            healed = np.asarray(bal.state.free_mb)[:3].sum()
            await bal.close()
            for inv in invokers + [inv3]:
                await inv.stop()
            return held, after_grow, new_row, healed

        held, after_grow, new_row, healed = asyncio.run(go())
        assert held == 2 * 1024 - 256        # hold visible
        assert after_grow == held            # growth preserved the hold
        assert new_row == 1024               # new invoker at full capacity
        assert healed == 3 * 1024            # release healed the books

    def test_close_fails_pending_publishers(self):
        """close() during a buffered publish must fail the future, not hang."""
        async def go():
            provider = MemoryMessagingProvider()
            bal = TpuBalancer(provider, ControllerInstanceId("0"),
                              managed_fraction=1.0, blackbox_fraction=0.0,
                              batch_window=5.0, pipeline_depth=1)
            await bal.start()
            invokers, producer = await _fleet(provider, 1)
            await _ping_all(invokers, producer)
            ident = Identity.generate("guest")
            action = make_action()
            # saturate the pipeline so the publish stays buffered (an idle
            # balancer flushes immediately; a busy one batches)
            bal._inflight_steps = bal.pipeline_depth
            task = asyncio.get_event_loop().create_task(
                bal.publish(action, make_msg(action, ident, True)))
            await asyncio.sleep(0.05)
            await bal.close()
            try:
                with pytest.raises(LoadBalancerException):
                    await asyncio.wait_for(task, 2)
            finally:
                for inv in invokers:
                    await inv.stop()

        asyncio.run(go())

    def test_out_of_order_first_ping_cpu_balancer(self):
        """Invoker 3 pinging first must not mark 0..2 usable (review:
        registry backfill misdispatch)."""
        async def go():
            provider = MemoryMessagingProvider()
            bal = ShardingBalancer(provider, ControllerInstanceId("0"),
                                   managed_fraction=1.0, blackbox_fraction=0.0)
            await bal.start()
            producer = provider.get_producer()
            inv3 = SimInvoker(provider, InvokerInstanceId(3, user_memory=MB(2048)))
            await inv3.start()
            await inv3.ping(producer)
            await asyncio.sleep(0.1)
            ident = Identity.generate("guest")
            # many publishes: every one must land on invoker 3
            for i in range(6):
                action = make_action(f"ooo{i}", memory=128)
                p = await bal.publish(action, make_msg(action, ident, True))
                await asyncio.wait_for(p, 5)
            handled = len(inv3.handled)
            await bal.close()
            await inv3.stop()
            return handled

        assert asyncio.run(go()) == 6


class TestPallasKernelOption:
    def test_pallas_kernel_end_to_end(self):
        """TpuBalancer(kernel='pallas') serves real publishes with the
        pallas schedule kernel (interpret mode on the CPU backend)."""
        async def go():
            provider = MemoryMessagingProvider()
            bal = TpuBalancer(provider, ControllerInstanceId("0"),
                              managed_fraction=1.0, blackbox_fraction=0.0,
                              batch_window=0.005, max_batch=32,
                              action_slots=256, kernel="pallas")
            assert bal.kernel == "pallas"
            await bal.start()
            invokers, producer = await _fleet(provider, 4, memory_mb=2048)
            await _ping_all(invokers, producer)
            ident = Identity.generate("guest")
            actions = [make_action(f"pl{i}", memory=256) for i in range(8)]
            promises = await asyncio.gather(*[
                bal.publish(actions[i % 8], make_msg(actions[i % 8], ident, True))
                for i in range(24)])
            results = await asyncio.gather(*[asyncio.wait_for(p, 10)
                                             for p in promises])
            await bal.close()
            for inv in invokers:
                await inv.stop()
            return results

        results = asyncio.run(go())
        assert len(results) == 24
        assert all(r.response.is_success for r in results)

    def test_pallas_falls_back_when_state_too_large(self):
        provider = MemoryMessagingProvider()
        bal = TpuBalancer(provider, ControllerInstanceId("0"),
                          action_slots=4096, initial_pad=1024,
                          kernel="pallas")
        assert bal.kernel == "xla"  # 1024x4096 state exceeds the VMEM budget


class TestHealthTestActions:
    def test_unhealthy_invoker_gets_test_activation(self):
        """ref InvokerSupervision: >3 system errors flip an invoker
        Unhealthy; the controller then probes it with the system test
        action (invokerHealthTestAction<controller>) instead of real
        traffic, and its acks feed recovery."""
        async def go():
            from openwhisk_tpu.database import EntityStore, MemoryArtifactStore
            from openwhisk_tpu.messaging.message import ActivationMessage

            provider = MemoryMessagingProvider()
            store = EntityStore(MemoryArtifactStore())
            bal = TpuBalancer(provider, ControllerInstanceId("0"),
                              managed_fraction=1.0, blackbox_fraction=0.0)
            await bal.start()
            await bal.prepare_health_test_action(store)
            # the system action exists in the store
            doc = await store.get_action("whisk.system/invokerHealthTestAction0")
            assert doc is not None

            inv = InvokerInstanceId(0, user_memory=MB(2048))
            producer = provider.get_producer()
            provider.ensure_topic("invoker0")
            probe = provider.get_consumer("invoker0", "probe")
            await producer.send("health", PingMessage(inv))
            await asyncio.sleep(0.15)
            # 4 system errors -> Unhealthy
            for _ in range(4):
                bal.supervision.on_invocation_finished(inv, True, False)
            assert bal.supervision.health()[0].status == "unhealthy"
            # next ping triggers the test-action probe (cooldown starts at 0)
            await producer.send("health", PingMessage(inv))
            await asyncio.sleep(0.2)
            msgs = await probe.peek(10, timeout=1.0)
            await bal.close()
            assert msgs, "no test activation published to the invoker topic"
            parsed = ActivationMessage.parse(msgs[0][3])
            return str(parsed.action), parsed.blocking

        action, blocking = asyncio.run(go())
        assert action == "whisk.system/invokerHealthTestAction0"
        assert blocking is False

    def test_healthcheck_ack_counts_as_healthcheck(self):
        """Probe acks must hit the healthcheck counter, not pollute the
        late-ack (regularAfterForced) metric operators watch."""
        async def go():
            from openwhisk_tpu.core.entity import ActivationId
            from openwhisk_tpu.database import EntityStore, MemoryArtifactStore

            provider = MemoryMessagingProvider()
            store = EntityStore(MemoryArtifactStore())
            bal = TpuBalancer(provider, ControllerInstanceId("0"),
                              managed_fraction=1.0, blackbox_fraction=0.0)
            await bal.start()
            await bal.prepare_health_test_action(store)
            inv = InvokerInstanceId(0, user_memory=MB(2048))
            await bal._send_health_test_action(inv)
            aid = next(iter(bal._health_probe_ids))
            bal.process_completion(ActivationId(aid), forced=False,
                                   is_system_error=False, invoker=inv)
            hc = bal.metrics.counter_value("loadbalancer_completion_ack_healthcheck")
            late = bal.metrics.counter_value("loadbalancer_completion_ack_regularAfterForced")
            await bal.close()
            return hc, late, aid in bal._health_probe_ids

        hc, late, still_tracked = asyncio.run(go())
        assert hc == 1 and late == 0
        assert not still_tracked

    def test_restore_past_vmem_budget_falls_back_to_xla(self):
        """A snapshot whose n_pad exceeds the pallas VMEM budget must swap
        in the XLA kernel on restore, exactly as _grow_padding does — and
        the swap must honor the placement-kernel knob (auto resolves the
        repair pair on the XLA path, scan keeps the legacy pair)."""
        from openwhisk_tpu.ops.placement import (release_batch,
                                                 release_batch_vector,
                                                 schedule_batch,
                                                 schedule_batch_repair)

        provider = MemoryMessagingProvider()
        bal = TpuBalancer(provider, ControllerInstanceId("0"),
                          action_slots=4096, initial_pad=1024)
        snap = bal.snapshot()

        small = TpuBalancer(MemoryMessagingProvider(), ControllerInstanceId("0"),
                            action_slots=4096, initial_pad=1, kernel="pallas")
        assert small.kernel == "pallas"
        small.restore(snap)
        assert small.kernel_resolved == "xla"
        assert small.placement_kernel_resolved == "repair"
        # auto = the per-bucket hybrid (scan below REPAIR_MIN_BATCH)
        assert getattr(small._sched_fn, "_placement_hybrid", False)
        assert getattr(small._release_fn, "_placement_hybrid", False)

        pinned = TpuBalancer(MemoryMessagingProvider(),
                             ControllerInstanceId("0"),
                             action_slots=4096, initial_pad=1,
                             kernel="pallas", placement_kernel="repair")
        pinned.restore(snap)
        assert pinned._sched_fn is schedule_batch_repair
        assert pinned._release_fn is release_batch_vector

        legacy = TpuBalancer(MemoryMessagingProvider(),
                             ControllerInstanceId("0"),
                             action_slots=4096, initial_pad=1,
                             kernel="pallas", placement_kernel="scan")
        legacy.restore(snap)
        assert legacy.placement_kernel_resolved == "scan"
        assert legacy._sched_fn is schedule_batch
        assert legacy._release_fn is release_batch


class TestPipelinedSteps:
    """Device-step pipelining (dispatch N+1 while N's readback is in
    flight): correctness across many overlapping micro-batches, and clean
    shutdown with work queued or in flight."""

    def test_many_overlapping_batches_all_place(self):
        async def go():
            provider = MemoryMessagingProvider()
            bal = TpuBalancer(provider, ControllerInstanceId("0"),
                              managed_fraction=1.0, blackbox_fraction=0.0,
                              batch_window=0.0005, max_batch=8,
                              pipeline_depth=3)
            await bal.start()
            invokers, producer = await _fleet(provider, 4)
            await _ping_all(invokers, producer)
            ident = Identity.generate("guest")
            action = make_action()
            promises = [await bal.publish(action,
                                          make_msg(action, ident, blocking=True))
                        for _ in range(48)]
            results = await asyncio.gather(*[asyncio.wait_for(p, 10)
                                             for p in promises])
            await asyncio.sleep(0.3)
            leaked = bal.total_active_activations
            slots = len(bal.activation_slots)
            await bal.close()
            for inv in invokers:
                await inv.stop()
            return results, leaked, slots

        results, leaked, slots = asyncio.run(go())
        assert len(results) == 48
        assert all(r.response.is_success for r in results)
        assert leaked == 0 and slots == 0

    def test_close_fails_queued_publishers_without_hanging(self):
        async def go():
            provider = MemoryMessagingProvider()
            # a saturated pipeline + far-away window keeps publishes queued
            # (an idle balancer flushes immediately; a busy one batches)
            bal = TpuBalancer(provider, ControllerInstanceId("0"),
                              managed_fraction=1.0, blackbox_fraction=0.0,
                              batch_window=30.0, pipeline_depth=1)
            await bal.start()
            invokers, producer = await _fleet(provider, 2)
            await _ping_all(invokers, producer)
            ident = Identity.generate("guest")
            action = make_action()
            bal._inflight_steps = bal.pipeline_depth
            tasks = [asyncio.create_task(
                bal.publish(action, make_msg(action, ident, blocking=True)))
                for _ in range(4)]
            await asyncio.sleep(0.05)  # queued; window has not fired
            await asyncio.wait_for(bal.close(), 5)  # must not hang
            outcomes = await asyncio.gather(*tasks, return_exceptions=True)
            for inv in invokers:
                await inv.stop()
            return outcomes

        outcomes = asyncio.run(go())
        assert len(outcomes) == 4
        assert all(isinstance(o, LoadBalancerException) for o in outcomes)

"""Asyncio edge reverse-proxy — the nginx role of the reference deployment.

Behavior ported from ansible/roles/nginx/templates/nginx.conf.j2:
  * upstream pool over all controllers with keepalive + failover: a
    connect-failed upstream is skipped for `fail_timeout` seconds
    (nginx `server ... fail_timeout=60s`);
  * vanity URLs: a request whose Host is `{namespace}.{domain}` is rewritten
    to `/api/v1/web/{namespace}{path}` (root → `/public/index.html`);
  * `/metrics` is denied from the edge (`location /metrics { deny all; }`);
  * a per-request transaction id header is injected and echoed
    (`proxy_set_header X-Request-ID`);
  * optional TLS termination via an `ssl.SSLContext`.

On top of that it serves API-gateway routes (reference: external gateway +
core/routemgmt): requests matching a registered (basePath, relPath, verb)
are forwarded to the backing web action.
"""
from __future__ import annotations

import asyncio
import secrets
import time
from dataclasses import dataclass, field
from typing import Awaitable, Callable, Dict, List, Optional

import aiohttp
from aiohttp import web

TRANSACTION_HEADER = "X-Request-ID"
MAX_BODY = 50 * 1024 * 1024  # nginx client_max_body_size 50M
HOP_HEADERS = {"connection", "keep-alive", "transfer-encoding", "upgrade",
               "proxy-authenticate", "proxy-authorization", "te", "trailers",
               "host", "content-length"}


@dataclass
class Upstream:
    url: str  # e.g. http://127.0.0.1:3233
    fail_until: float = 0.0
    fails: int = 0

    def usable(self) -> bool:
        return time.monotonic() >= self.fail_until


@dataclass
class EdgeProxy:
    upstreams: List[Upstream]
    domain: str = ""  # vanity base domain; "" disables subdomain rewrite
    fail_timeout: float = 60.0
    read_timeout: float = 75.0  # nginx proxy_read_timeout 75s
    route_matcher: Optional[Callable[[str, str], Awaitable[Optional[Dict]]]] = None
    _rr: int = 0
    _session: Optional[aiohttp.ClientSession] = None
    _runner: Optional[web.AppRunner] = None
    extra_denied_paths: tuple = ("/metrics",)

    @classmethod
    def for_controllers(cls, urls: List[str], **kwargs) -> "EdgeProxy":
        return cls(upstreams=[Upstream(u.rstrip("/")) for u in urls], **kwargs)

    # --------------------------------------------------------------- server
    def make_app(self) -> web.Application:
        app = web.Application(client_max_size=MAX_BODY)
        app.router.add_route("*", "/{tail:.*}", self.handle)
        return app

    async def start(self, host: str = "0.0.0.0", port: int = 8080,
                    ssl_context=None) -> None:
        self._session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=self.read_timeout))
        self._runner = web.AppRunner(self.make_app())
        await self._runner.setup()
        await web.TCPSite(self._runner, host, port,
                          ssl_context=ssl_context).start()

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()
        if self._session:
            await self._session.close()

    # -------------------------------------------------------------- routing
    def _vanity_namespace(self, request: web.Request) -> Optional[str]:
        if not self.domain:
            return None
        host = request.host.split(":")[0]
        suffix = "." + self.domain
        if host.endswith(suffix):
            ns = host[: -len(suffix)]
            if ns and all(c.isalnum() or c == "-" for c in ns):
                return ns
        return None

    async def _rewrite(self, request: web.Request) -> str:
        """Return the upstream path for this request; raise to deny/404."""
        path = request.path
        if path in self.extra_denied_paths:
            raise web.HTTPForbidden(text="forbidden")
        if path.startswith("/api/"):
            return path
        ns = self._vanity_namespace(request)
        if ns is not None:
            target = "/public/index.html" if path == "/" else path
            return f"/api/v1/web/{ns}{target}"
        if self.route_matcher is not None:
            op = await self.route_matcher(request.method, path)
            if op is not None:
                url = op.get("url", "")
                # strip any host prefix the route doc may carry
                if "://" in url:
                    rest = url.split("://", 1)[1]
                    _, _, tail = rest.partition("/")
                    url = "/" + tail
                return url
        # no API path, no vanity host, no gateway route: nothing to serve
        raise web.HTTPNotFound(text="no route")

    # ---------------------------------------------------------------- proxy
    async def handle(self, request: web.Request) -> web.Response:
        target = await self._rewrite(request)
        transid = request.headers.get(TRANSACTION_HEADER) or secrets.token_hex(8)
        body = await request.read() if request.can_read_body else None
        headers = {k: v for k, v in request.headers.items()
                   if k.lower() not in HOP_HEADERS}
        headers[TRANSACTION_HEADER] = transid

        qs = request.query_string
        suffix = target + (("?" + qs) if qs else "")
        last_error: Optional[Exception] = None
        last_503: Optional[web.Response] = None
        for upstream in self._pick_order():
            try:
                async with self._session.request(
                        request.method, upstream.url + suffix,
                        headers=headers, data=body,
                        allow_redirects=False) as resp:
                    payload = await resp.read()
                    upstream.fails = 0
                    out_headers = {k: v for k, v in resp.headers.items()
                                   if k.lower() not in HOP_HEADERS
                                   and k.lower() != "content-encoding"}
                    out_headers[TRANSACTION_HEADER] = transid
                    if resp.status == 503:
                        # a 503 is emitted BEFORE any state change (an HA
                        # standby refusing placement, or no usable fleet):
                        # trying the next upstream is safe for any method
                        # (nginx `proxy_next_upstream http_503`). No
                        # blacklist — a standby answers everything else
                        # fine and becomes active without re-resolving.
                        last_503 = web.Response(status=503, body=payload,
                                                headers=out_headers)
                        continue
                    return web.Response(status=resp.status, body=payload,
                                        headers=out_headers)
            except aiohttp.ClientConnectorError as e:
                # connect failed — the request was never sent, so retrying
                # the next upstream is safe for ANY method; blacklist this
                # upstream for fail_timeout (nginx `fail_timeout=60s`)
                upstream.fails += 1
                upstream.fail_until = time.monotonic() + self.fail_timeout
                last_error = e
            except (aiohttp.ClientConnectionError, asyncio.TimeoutError):
                # the request may already be executing upstream (e.g. a slow
                # blocking invoke hit read_timeout): do NOT re-send non-
                # idempotent methods (nginx proxy_next_upstream excludes
                # them), and a slow request is no reason to blacklist
                if request.method in ("GET", "HEAD", "OPTIONS"):
                    last_error = RuntimeError("upstream read failed")
                    continue
                return web.Response(status=504, text="upstream timeout")
        if last_503 is not None:
            # every upstream said 503: surface the real refusal (body and
            # all) instead of a generic 502
            return last_503
        return web.Response(status=502, text=f"no upstream available: {last_error}")

    def _pick_order(self) -> List[Upstream]:
        """Round-robin over usable upstreams; all down → try everyone anyway
        (nginx resurrects a dead pool rather than hard-failing)."""
        n = len(self.upstreams)
        order = [self.upstreams[(self._rr + i) % n] for i in range(n)]
        self._rr = (self._rr + 1) % n
        usable = [u for u in order if u.usable()]
        return usable or order

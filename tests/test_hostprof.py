"""Host hot-loop observatory (utils/hostprof.py, ISSUE 11).

Covers all four planes — the lag probe measuring an injected 50 ms stall
AND naming the offending coroutine, gc callback accounting under a forced
collect, serde counters matching a known message count/bytes, the sampler
census under synthetic load — plus disabled-is-a-true-no-op (no task
factory swap, no gc callbacks, tracemalloc-clean hot paths), the
generator self-check satellite in tools/loadgen, the bench_compare CLI,
and both admin endpoints auth-gated.

Sampler/timing assertions skip with a logged reason when the box can't
hold a schedule (the pallas-probe pattern from PR 9's conftest): a loaded
CI runner must not turn a timing assertion into a flake.
"""
import asyncio
import gc
import json
import sys
import time
import tracemalloc

import pytest

from openwhisk_tpu.utils.hostprof import (GLOBAL_HOST_OBSERVATORY,
                                          HostObservatory,
                                          HostProfilingConfig)

# ---------------------------------------------------------------------------
# timing probe (conftest pallas-probe pattern): sampler + stall assertions
# need sys._current_frames AND a box that can hold a rough schedule
# ---------------------------------------------------------------------------
_timing_probe_result = None


def _timing_probe():
    global _timing_probe_result
    if _timing_probe_result is not None:
        return _timing_probe_result
    if not hasattr(sys, "_current_frames"):
        _timing_probe_result = (False, "sys._current_frames unavailable")
        return _timing_probe_result
    t0 = time.perf_counter()
    time.sleep(0.05)
    dt = time.perf_counter() - t0
    if dt > 0.5:
        _timing_probe_result = (
            False, f"box too loaded to assert timing "
                   f"(a 50ms sleep took {dt * 1e3:.0f}ms)")
    else:
        _timing_probe_result = (True, "")
    return _timing_probe_result


def _skip_unless_timing():
    ok, reason = _timing_probe()
    if not ok:
        print(f"# skipping sampler/timing assertion: {reason}",
              file=sys.stderr)
        pytest.skip(f"sampler/timing unavailable: {reason}")


def make_obs(**kw) -> HostObservatory:
    return HostObservatory(HostProfilingConfig(**kw))


class TestLagProbeAndStalls:
    def test_lag_probe_measures_injected_stall_and_names_callback(self):
        _skip_unless_timing()
        obs = make_obs(lag_probe_ms=10.0, stall_threshold_ms=30.0,
                       sample_hz=0.0)

        async def blocker():
            time.sleep(0.05)  # a synchronous 50 ms loop stall

        async def go():
            assert obs.install() is True
            try:
                await asyncio.get_event_loop().create_task(blocker())
                # let the probe fire a few clean post-stall ticks
                await asyncio.sleep(0.06)
            finally:
                obs.uninstall()

        asyncio.run(go())
        snap = obs.snapshot()
        # the stall is visible in the lag histogram, measured from the
        # probe tick's SCHEDULED deadline
        assert snap["loop_lag"]["ticks"] >= 5
        assert snap["loop_lag"]["max_ms"] >= 35.0
        # ... and the interposer NAMED the coroutine that caused it
        worst = snap["stalls"]["worst"]
        assert worst, "no stall recorded"
        assert any("blocker" in (s["coro"] or "") for s in worst)
        assert worst[0]["ms"] >= 30.0
        assert snap["stalls"]["count"] >= 1

    def test_lag_backfills_missed_ticks_from_schedule(self):
        """Coordinated omission: one probe firing after a stall must
        record one sample PER missed tick (each from its own deadline),
        not collapse the stall into a single late sample."""
        _skip_unless_timing()
        obs = make_obs(lag_probe_ms=10.0, stall_threshold_ms=5000.0,
                       sample_hz=0.0)

        async def go():
            obs.install()
            try:
                await asyncio.sleep(0.03)  # a few clean ticks
                time.sleep(0.12)           # stall ~12 probe intervals
                await asyncio.sleep(0.03)
            finally:
                obs.uninstall()

        asyncio.run(go())
        snap = obs.snapshot()
        # ~180ms of run at 10ms ticks: backfill must keep tick count near
        # schedule (a non-backfilling probe would record ~6)
        assert snap["loop_lag"]["ticks"] >= 12
        assert snap["loop_lag"]["max_ms"] >= 90.0

    def test_uninstall_restores_task_factory(self):
        obs = make_obs(sample_hz=0.0)

        async def go():
            loop = asyncio.get_event_loop()
            before = loop.get_task_factory()
            assert obs.install() is True
            assert loop.get_task_factory() is not before
            obs.uninstall()
            assert loop.get_task_factory() is before

        asyncio.run(go())

    def test_wrapped_tasks_preserve_results_exceptions_cancellation(self):
        obs = make_obs(sample_hz=0.0)

        async def ok():
            await asyncio.sleep(0)
            return 42

        async def boom():
            raise ValueError("boom")

        async def sleeper():
            await asyncio.sleep(30)

        async def go():
            obs.install()
            try:
                loop = asyncio.get_event_loop()
                assert await loop.create_task(ok(), name="named") == 42
                with pytest.raises(ValueError):
                    await loop.create_task(boom())
                t = loop.create_task(sleeper())
                await asyncio.sleep(0)
                t.cancel()
                with pytest.raises(asyncio.CancelledError):
                    await t
            finally:
                obs.uninstall()

        asyncio.run(go())
        snap = obs.snapshot()
        assert snap["tasks"]["created"] >= 3
        assert snap["tasks"]["finished"] >= 3


class TestResetMidFlight:
    def test_reset_carries_inflight_tasks_so_active_stays_nonnegative(
            self):
        """Review regression: a reset while wrapped tasks are in flight
        (sweep_balancer's headline-window reset) must not let the later
        done-callbacks drive active below zero."""
        obs = make_obs(sample_hz=0.0)

        async def sleeper():
            await asyncio.sleep(0.05)

        async def go():
            obs.install()
            try:
                t = asyncio.get_event_loop().create_task(sleeper())
                await asyncio.sleep(0)
                obs.reset()
                assert obs.snapshot()["tasks"]["active"] >= 1
                await t
            finally:
                obs.uninstall()

        asyncio.run(go())
        tasks = obs.snapshot()["tasks"]
        assert tasks["active"] >= 0, tasks


class TestGcAccounting:
    def test_forced_collect_is_counted_per_generation(self):
        obs = make_obs(sample_hz=0.0)

        async def go():
            obs.install()
            try:
                # build garbage cycles so the collect has real work
                junk = []
                for _ in range(1000):
                    a, b = [], []
                    a.append(b)
                    b.append(a)
                    junk.append(a)
                del junk
                gc.collect()  # full collection -> generation 2
            finally:
                obs.uninstall()

        asyncio.run(go())
        snap = obs.snapshot()
        assert snap["gc"]["pauses"]["2"] >= 1
        assert snap["gc"]["collected"] >= 1000
        assert snap["gc"]["pause_ms"]["2"] >= 0.0
        assert snap["gc"]["pause_share_pct"] >= 0.0

    def test_gc_callback_is_lock_free_under_held_lock(self):
        """Review regression: an automatic collection can fire on an
        allocation made while THIS thread holds the observatory lock
        (snapshot copies, serde first-insert). The gc callback must never
        take that non-reentrant lock — the old version self-deadlocked
        the event loop."""
        obs = make_obs(sample_hz=0.0)
        gc.callbacks.append(obs._gc_cb)
        old = gc.get_threshold()
        try:
            gc.set_threshold(10, 1, 1)  # force frequent collections
            with obs._lock:
                junk = []
                for i in range(2000):
                    junk.append(([i], {"k": i}))
        finally:
            gc.set_threshold(*old)
            gc.callbacks.remove(obs._gc_cb)
        # reaching here at all is the assertion; pauses were still folded
        assert sum(obs.snapshot()["gc"]["pauses"].values()) >= 1

    def test_share_epoch_sane_without_install(self):
        """Review regression: serde accounting runs enabled-only (no
        install), so the share epoch must be the construction time, not
        an install stamp — the old version divided by a 1 us wall."""
        obs = make_obs(sample_hz=0.0)
        time.sleep(0.05)
        obs.serde_observe("activation", "serialize", 100, 1_000_000)
        snap = obs.snapshot()
        assert snap["uptime_s"] >= 0.05
        assert 0.0 < snap["serde"][0]["share_pct"] < 10.0

    def test_gc_pause_inside_dispatch_bracket_is_attributed(self):
        obs = make_obs(sample_hz=0.0)

        async def go():
            obs.install()
            try:
                gc.collect()
                before = obs.snapshot()["gc"]["overlapping_dispatch"]
                obs.begin_dispatch()
                gc.collect()
                obs.end_dispatch()
                gc.collect()
                return before
            finally:
                obs.uninstall()

        before = asyncio.run(go())
        after = obs.snapshot()["gc"]["overlapping_dispatch"]
        # exactly the bracketed collect counted (the two outside did not)
        assert after == before + 1


class TestSerdeAccounting:
    def test_counters_match_known_message_count_and_bytes(self):
        from openwhisk_tpu.messaging.connector import (decode_message,
                                                       encode_message)
        from tests.test_balancers import make_action, make_msg
        from openwhisk_tpu.core.entity import Identity
        from openwhisk_tpu.messaging.message import ActivationMessage

        obs = GLOBAL_HOST_OBSERVATORY
        was_enabled = obs.enabled
        obs.enabled = True
        obs.reset()
        try:
            action = make_action("serde", memory=128)
            msg = make_msg(action, Identity.generate("guest"), True)
            payload = msg.serialize()
            n = 7
            for _ in range(n):
                out = encode_message(msg)
                assert out == payload
                back = decode_message(ActivationMessage.parse, payload,
                                      "activation")
                assert back.activation_id.asString == \
                    msg.activation_id.asString
            snap = obs.snapshot()
            rows = {(r["hop"], r["direction"]): r for r in snap["serde"]}
            enc = rows[("activation", "serialize")]
            dec = rows[("activation", "deserialize")]
            assert enc["count"] == n and dec["count"] == n
            assert enc["bytes"] == n * len(payload)
            assert dec["bytes"] == n * len(payload)
            assert enc["ms"] > 0.0 and dec["ms"] > 0.0
        finally:
            obs.reset()
            obs.enabled = was_enabled

    def test_bytes_pass_through_untouched(self):
        from openwhisk_tpu.messaging.connector import encode_message
        raw = b'{"already": "encoded"}'
        assert encode_message(raw) is raw

    def test_hop_labels_by_message_class(self):
        from openwhisk_tpu.messaging.connector import hop_of
        from openwhisk_tpu.core.entity import (InvokerInstanceId, MB)
        from openwhisk_tpu.messaging.message import (CompletionMessage,
                                                     PingMessage)
        from openwhisk_tpu.utils.transaction import TransactionId
        from openwhisk_tpu.core.entity import ActivationId
        inst = InvokerInstanceId(0, user_memory=MB(256))
        assert hop_of(PingMessage(inst)) == "health_ping"
        assert hop_of(CompletionMessage(
            TransactionId(), ActivationId.generate(), False,
            inst)) == "completion_ack"
        assert hop_of(object()) == "other"


class TestSampler:
    def test_census_non_empty_under_synthetic_load(self):
        _skip_unless_timing()
        obs = make_obs(sample_hz=97.0, lag_probe_ms=50.0,
                       stall_threshold_ms=5000.0)

        def spin(deadline):
            while time.monotonic() < deadline:
                sum(i * i for i in range(500))

        async def go():
            obs.install()
            try:
                end = time.monotonic() + 0.5
                while time.monotonic() < end:
                    spin(min(end, time.monotonic() + 0.02))
                    await asyncio.sleep(0)
            finally:
                obs.uninstall()

        asyncio.run(go())
        snap = obs.snapshot()
        assert snap["sampler"]["samples"] > 0
        assert snap["sampler"]["top"], "self-time census is empty"
        assert all(t["samples"] >= 1 for t in snap["sampler"]["top"])

    def test_capture_window_returns_collapsed_stacks(self):
        _skip_unless_timing()
        obs = make_obs(sample_hz=29.0, lag_probe_ms=50.0,
                       stall_threshold_ms=5000.0, capture_limit_s=1.0)

        async def go():
            obs.install()
            try:
                # capture(5.0) must clamp to the 1 s configured limit
                t0 = time.monotonic()
                out = await obs.capture(5.0)
                assert time.monotonic() - t0 < 3.0
                return out
            finally:
                obs.uninstall()

        out = asyncio.run(go())
        assert out["seconds"] == 1.0
        assert out["samples"] > 0
        assert out["collapsed"], "no collapsed stacks"
        # flamegraph collapsed format: "frame;frame;... N" per line
        line = out["collapsed"].splitlines()[0]
        stack, count = line.rsplit(" ", 1)
        assert ";" in stack or ":" in stack
        assert int(count) >= 1

    def test_concurrent_capture_is_refused(self):
        _skip_unless_timing()
        obs = make_obs(sample_hz=29.0, capture_limit_s=2.0)

        async def go():
            obs.install()
            try:
                first = asyncio.ensure_future(obs.capture(0.5))
                await asyncio.sleep(0.05)
                with pytest.raises(RuntimeError):
                    await obs.capture(0.2)
                await first
            finally:
                obs.uninstall()

        asyncio.run(go())


class TestDisabledNoOp:
    def test_install_refuses_and_touches_nothing(self):
        obs = make_obs(enabled=False)

        async def go():
            loop = asyncio.get_event_loop()
            factory_before = loop.get_task_factory()
            gc_before = list(gc.callbacks)
            assert obs.install() is False
            assert loop.get_task_factory() is factory_before
            assert gc.callbacks == gc_before
            assert obs.sampler_running is False
            assert obs.snapshot() == {"enabled": False}
            assert obs.prometheus_text() == ""

        asyncio.run(go())

    def test_env_off_switch(self, monkeypatch):
        monkeypatch.setenv("CONFIG_whisk_hostProfiling_enabled", "false")
        assert HostObservatory.from_config().enabled is False
        monkeypatch.setenv("CONFIG_whisk_hostProfiling_enabled", "true")
        monkeypatch.setenv("CONFIG_whisk_hostProfiling_stallThresholdMs",
                           "75")
        obs = HostObservatory.from_config()
        assert obs.enabled is True
        assert obs.config.stall_threshold_ms == 75.0

    def test_disabled_hot_paths_allocate_nothing(self):
        from openwhisk_tpu.messaging import connector
        obs = GLOBAL_HOST_OBSERVATORY
        was_enabled = obs.enabled
        obs.enabled = False
        raw = b'{"k": 1}'

        def parse(b):
            return b

        try:
            # warm the paths once, then assert zero residual allocations
            connector.encode_message(raw)
            connector.decode_message(parse, raw, "activation")
            obs.begin_dispatch()
            obs.end_dispatch()
            tracemalloc.start()
            try:
                s1 = tracemalloc.take_snapshot()
                for _ in range(256):
                    connector.encode_message(raw)
                    connector.decode_message(parse, raw, "activation")
                    obs.begin_dispatch()
                    obs.end_dispatch()
                s2 = tracemalloc.take_snapshot()
            finally:
                tracemalloc.stop()
            flt = [tracemalloc.Filter(True, "*utils/hostprof.py"),
                   tracemalloc.Filter(True, "*messaging/connector.py")]
            grown = [d for d in s2.filter_traces(flt).compare_to(
                s1.filter_traces(flt), "lineno") if d.size_diff > 0]
            # proportionality, not zero-tolerance: a REAL per-call leak
            # over 256 iterations is kilobytes; a stray background thread
            # (the full suite leaves a few) touching an observatory
            # property mid-window costs a frame's worth of bytes
            total = sum(d.size_diff for d in grown)
            assert total < 2048, \
                f"disabled observatory allocated {total}B: {grown}"
        finally:
            obs.enabled = was_enabled


class TestLoadgenGeneratorSelfCheck:
    def test_open_loop_reports_generator_gc_and_lag_cause(self):
        from tools.loadgen import make_schedule, open_loop

        async def one(i, sched_ns):
            if i == 3:
                gc.collect()    # a generator-side pause inside the window
            await asyncio.sleep(0.001)
            return True

        row = asyncio.run(open_loop(one, make_schedule(
            200.0, 40, dist="constant")))
        gen = row["generator"]
        assert gen["gc_pauses"] >= 1
        assert gen["gc_pause_total_ms"] >= 0.0
        assert gen["max_fire_lag_ms"] >= 0.0
        assert gen["max_fire_lag_cause"] in ("gc_pause",
                                             "event_loop_stall", None)

    def test_verdict_attributes_generator_vs_system(self):
        from tools.loadgen import verdict
        ok = {"completed": 100, "errors": 0, "unfinished": 0,
              "p99_ms": 20.0, "fire_lag_max_ms": 1.0,
              "generator": {"gc_pauses": 0, "gc_pause_total_ms": 0.0,
                            "gc_pause_max_ms": 0.0,
                            "max_fire_lag_ms": 1.0,
                            "max_fire_lag_cause": None}}
        v = verdict(ok)
        assert v["sustainable"] and v["blames"] == "none"
        # generator-only failure: fire lag with a gc cause
        gen_fail = dict(ok, fire_lag_max_ms=120.0,
                        generator=dict(ok["generator"],
                                       max_fire_lag_ms=120.0,
                                       gc_pauses=2, gc_pause_max_ms=110.0,
                                       max_fire_lag_cause="gc_pause"))
        v = verdict(gen_fail)
        assert not v["sustainable"]
        assert v["blames"] == "generator"
        assert any("gc_pause" in f for f in v["failed"])
        # system failure: p99 blown
        sys_fail = dict(ok, p99_ms=5000.0)
        v = verdict(sys_fail)
        assert not v["sustainable"] and v["blames"] == "system"
        # mixed failure blames the system (the generator reason alone
        # would not have sunk the rung)
        both = dict(gen_fail, errors=3)
        assert verdict(both)["blames"] == "system"

    def test_sustainable_bool_contract_unchanged(self):
        from tools.loadgen import sustainable
        ok = {"completed": 100, "errors": 0, "unfinished": 0,
              "p99_ms": 20.0, "fire_lag_max_ms": 1.0}
        assert sustainable(ok)
        assert not sustainable({**ok, "fire_lag_max_ms": 500.0})


class TestBenchCompare:
    def _rounds(self, tmp_path, old, new):
        a, b = tmp_path / "old.json", tmp_path / "new.json"
        a.write_text(json.dumps(old))
        b.write_text(json.dumps(new))
        return str(a), str(b)

    def test_regression_exits_nonzero(self, tmp_path, capsys):
        import tools.bench_compare as bc
        old = {"value": 100.0, "e2e_open_loop":
               {"sustained_activations_per_sec": 1000.0, "p99_ms": 50.0}}
        new = {"value": 70.0, "e2e_open_loop":
               {"sustained_activations_per_sec": 990.0, "p99_ms": 55.0}}
        a, b = self._rounds(tmp_path, old, new)
        sys.argv = ["bench_compare", a, b]
        assert bc.main() == 1
        out = capsys.readouterr()
        assert "placements_per_sec" in out.out
        assert "REGRESSED" in out.out
        assert "REGRESSION" in out.err

    def test_within_threshold_exits_zero(self, tmp_path):
        import tools.bench_compare as bc
        old = {"value": 100.0}
        new = {"value": 85.0}  # -15% < 20% threshold
        a, b = self._rounds(tmp_path, old, new)
        sys.argv = ["bench_compare", a, b]
        assert bc.main() == 0
        # latency direction: higher is the regression
        a, b = self._rounds(tmp_path,
                            {"failover_downtime": {"downtime_ms": 100.0}},
                            {"failover_downtime": {"downtime_ms": 150.0}})
        sys.argv = ["bench_compare", a, b]
        assert bc.main() == 1

    def test_missing_metrics_skip_and_envelope_unwraps(self, tmp_path,
                                                       capsys):
        import tools.bench_compare as bc
        # the driver's BENCH_r*.json envelope: JSON line inside `tail`
        old = {"n": 1, "rc": 0,
               "tail": "noise\n" + json.dumps({"value": 100.0})}
        new = {"n": 2, "rc": 1, "tail": "died before the JSON line"}
        a, b = self._rounds(tmp_path, old, new)
        sys.argv = ["bench_compare", a, b]
        assert bc.main() == 0  # dead round: skipped, not regressed
        assert "skipped (missing)" in capsys.readouterr().out

    def test_backend_mismatch_is_advisory(self, tmp_path, capsys):
        import tools.bench_compare as bc
        old = {"value": 100.0, "balancer": {"backend": "tpu"}}
        new = {"value": 10.0, "balancer": {"backend": "cpu"},
               "backend": "cpu_fallback"}
        a, b = self._rounds(tmp_path, old, new)
        sys.argv = ["bench_compare", a, b]
        assert bc.main() == 0
        out = capsys.readouterr().out
        assert "BACKEND MISMATCH" in out


class TestAdminEndpoints:
    PORT = 13393

    def test_host_profile_and_capture_auth_gated(self):
        import base64

        import aiohttp

        from openwhisk_tpu.controller.core import Controller
        from openwhisk_tpu.controller.loadbalancer.lean import LeanBalancer
        from openwhisk_tpu.core.entity import (ControllerInstanceId,
                                               Identity, MB,
                                               WhiskAuthRecord)
        from openwhisk_tpu.messaging import MemoryMessagingProvider
        from openwhisk_tpu.utils.logging import NullLogging

        obs = GLOBAL_HOST_OBSERVATORY
        was_enabled = obs.enabled
        obs.enabled = True

        async def noop_factory(invoker_id, provider):
            class _Stub:
                async def stop(self):
                    pass

            return _Stub()

        async def go():
            provider = MemoryMessagingProvider()
            logger = NullLogging()
            lb = LeanBalancer(provider, ControllerInstanceId("0"),
                              noop_factory, logger=logger,
                              metrics=logger.metrics,
                              user_memory=MB(512))
            controller = Controller(ControllerInstanceId("0"), provider,
                                    logger=logger, load_balancer=lb)
            ident = Identity.generate("guest")
            await controller.auth_store.put(WhiskAuthRecord(
                ident.subject, [ident.namespace], [ident.authkey]))
            await controller.start(port=self.PORT)
            try:
                # the controller's start() installed the observatory
                assert obs.installed
                await asyncio.sleep(0.1)
                hdrs = {"Authorization": "Basic " + base64.b64encode(
                    ident.authkey.compact.encode()).decode()}
                base = f"http://127.0.0.1:{self.PORT}"
                out = {}
                async with aiohttp.ClientSession() as s:
                    async with s.get(f"{base}/admin/profile/host") as r:
                        out["anon_get"] = r.status
                    async with s.post(
                            f"{base}/admin/profile/host/capture",
                            json={"seconds": 0.2}) as r:
                        out["anon_post"] = r.status
                    async with s.get(f"{base}/admin/profile/host",
                                     headers=hdrs) as r:
                        out["get"] = (r.status, await r.json())
                    async with s.get(
                            f"{base}/admin/profile/host?collapsed=1",
                            headers=hdrs) as r:
                        out["collapsed"] = (r.status, await r.json())
                    async with s.post(
                            f"{base}/admin/profile/host/capture",
                            headers=hdrs, json={"seconds": 0.2}) as r:
                        out["post"] = (r.status, await r.json())
                    async with s.post(
                            f"{base}/admin/profile/host/capture",
                            headers=hdrs, json={"seconds": "xx"}) as r:
                        out["bad"] = r.status
                return out
            finally:
                await controller.stop()

        try:
            out = asyncio.run(go())
        finally:
            obs.enabled = was_enabled
        # auth-gated like every admin plane
        assert out["anon_get"] == 401
        assert out["anon_post"] == 401
        status, body = out["get"]
        assert status == 200
        assert body["enabled"] and body["installed"]
        assert "loop_lag" in body and "gc" in body and "tasks" in body
        assert body["tasks"]["created"] >= 0
        status, coll = out["collapsed"]
        assert status == 200 and "collapsed" in coll
        assert out["bad"] == 400
        status, cap = out["post"]
        if _timing_probe()[0]:
            assert status == 200
            assert cap["samples"] >= 0 and "collapsed" in cap
        else:
            assert status in (200, 409)
        # the observatory uninstalled with its controller
        assert not obs.installed

    def test_capture_refused_when_disabled(self):
        import base64

        import aiohttp

        from openwhisk_tpu.controller.core import Controller
        from openwhisk_tpu.controller.loadbalancer.lean import LeanBalancer
        from openwhisk_tpu.core.entity import (ControllerInstanceId,
                                               Identity, MB,
                                               WhiskAuthRecord)
        from openwhisk_tpu.messaging import MemoryMessagingProvider
        from openwhisk_tpu.utils.logging import NullLogging

        obs = GLOBAL_HOST_OBSERVATORY
        was_enabled = obs.enabled
        obs.enabled = False

        async def noop_factory(invoker_id, provider):
            class _Stub:
                async def stop(self):
                    pass

            return _Stub()

        async def go():
            provider = MemoryMessagingProvider()
            logger = NullLogging()
            lb = LeanBalancer(provider, ControllerInstanceId("0"),
                              noop_factory, logger=logger,
                              metrics=logger.metrics, user_memory=MB(512))
            controller = Controller(ControllerInstanceId("0"), provider,
                                    logger=logger, load_balancer=lb)
            ident = Identity.generate("guest")
            await controller.auth_store.put(WhiskAuthRecord(
                ident.subject, [ident.namespace], [ident.authkey]))
            await controller.start(port=self.PORT + 1)
            try:
                hdrs = {"Authorization": "Basic " + base64.b64encode(
                    ident.authkey.compact.encode()).decode()}
                base = f"http://127.0.0.1:{self.PORT + 1}"
                async with aiohttp.ClientSession() as s:
                    async with s.get(f"{base}/admin/profile/host",
                                     headers=hdrs) as r:
                        get = (r.status, await r.json())
                    async with s.post(
                            f"{base}/admin/profile/host/capture",
                            headers=hdrs, json={"seconds": 0.2}) as r:
                        post = r.status
                return get, post
            finally:
                await controller.stop()

        try:
            (status, body), post = asyncio.run(go())
        finally:
            obs.enabled = was_enabled
        assert status == 200 and body == {"enabled": False}
        assert post == 409

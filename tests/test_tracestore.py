"""ISSUE 18: tail-sampled distributed trace observatory.

Covers the acceptance contracts:
  * the completion-time verdict: every keep reason fires on its trigger,
    the counter label follows the REASONS priority order, the uniform
    floor is deterministic 1-in-N, pre-verdict marks are consumed;
  * bounds: the pending table ages out past `pending_limit` (counted),
    the kept ring's `get()` index never returns an evicted entry, and a
    runaway span producer saturates at SPAN_CAP;
  * cross-process assembly: the spilled half pins publish_enqueue to the
    origin's spill_forward, the invoker half pins invoker_pickup to the
    origin's publish_enqueue, anchorless halves fall back to wall-clock
    deltas, spans dedup by id and halves by identity, and every half's
    stage deltas telescope to its own measured total;
  * disabled is a TRUE no-op: attach() never tees the reporter, the
    verdict path allocates NOTHING (tracemalloc-asserted), and the
    /admin/trace* routes answer 404;
  * satellites: Tracer's time-based expiry sweep (the <1000-stacks leak),
    and the ack frames' sparse trace-context column (eager + lazy wire,
    byte-exact absent when no ack is traced).
"""
from __future__ import annotations

import asyncio
import base64
import time
import tracemalloc
from types import SimpleNamespace

import pytest

from openwhisk_tpu.utils.tracestore import (GLOBAL_TRACE_STORE, REASONS,
                                            TraceStore, TraceTailConfig,
                                            _TeeReporter, assemble_trace,
                                            synthetic_span, tail_config)
from openwhisk_tpu.utils.tracing import (BufferReporter, Tracer, trace_id_of)
from openwhisk_tpu.utils.waterfall import (N_STAGES, STAGE_API_ACCEPT,
                                           STAGE_COMPLETION_ACK,
                                           STAGE_INVOKER_PICKUP,
                                           STAGE_PUBLISH_ENQUEUE,
                                           STAGE_RUN, STAGE_SPILL_FORWARD)

CTL_PORT = 13461


def _store(**kw) -> TraceStore:
    cfg = {"enabled": True, "keep_ring": 16, "pending_limit": 64,
           "keep_floor": 0.0}
    cfg.update(kw)
    return TraceStore(TraceTailConfig(**cfg))


def _row(aid="a0", tid="t0", times=None, ts=1000.0):
    """A waterfall row from ABSOLUTE stage offsets (µs since t0): the
    deltas telescope by construction, exactly like _compute_row's."""
    deltas = [-1] * N_STAGES
    prev = total = 0
    for i in sorted(times or {}):
        deltas[i] = times[i] - prev
        prev = total = times[i]
    return {"activation_id": aid, "trace_id": tid, "ts": ts,
            "total_us": total, "deltas_us": deltas, "clamped": 0}


# -- the completion-time verdict --------------------------------------------
class TestVerdict:
    def test_error_outranks_everything(self):
        s = _store()
        s.mark("t0", "divergent")
        e = s.complete("a0", "t0", 5000.0, error=True, timeout=True,
                       fenced=True)
        assert e["reason"] == "error"
        # every other trigger still recorded, in priority order
        assert e["reasons"] == ["error", "timeout", "fenced", "divergent",
                                "slow"]
        assert s.kept_total == {"error": 1}

    @pytest.mark.parametrize("kw,reason", [
        ({"timeout": True}, "timeout"),
        ({"forced": True}, "forced"),
        ({"fenced": True}, "fenced"),
        ({"error": True}, "error"),
    ])
    def test_flag_reasons(self, kw, reason):
        s = _store()
        e = s.complete("a0", "t0", 5.0, **kw)
        assert e["reason"] == reason and s.kept_total == {reason: 1}

    def test_spilled_read_off_the_row(self):
        s = _store()
        row = _row(times={STAGE_API_ACCEPT: 50, STAGE_SPILL_FORWARD: 300})
        e = s.complete("a0", "t0", row=row)
        assert e["reason"] == "spilled"
        assert e["waterfall"]["total_us"] == 300

    def test_trace_id_falls_back_to_the_row(self):
        s = _store()
        e = s.complete("a0", None, row=_row(tid="from-row",
                                            times={STAGE_SPILL_FORWARD: 9}))
        assert e["trace_id"] == "from-row"

    def test_marks_are_consumed_by_the_verdict(self):
        s = _store()
        s.mark("t0", "exemplar")
        assert s.complete("a0", "t0", 5.0)["reason"] == "exemplar"
        # same trace id again: the mark is gone, nothing keeps it
        assert s.complete("a1", "t0", 5.0) is None

    def test_slow_against_live_threshold_source(self):
        s = _store()
        s.threshold_source = lambda: 10.0
        assert s.complete("a0", "t0", 11.0)["reason"] == "slow"
        assert s.complete("a1", "t1", 9.0) is None

    def test_broken_threshold_source_falls_back(self):
        s = _store()
        s.threshold_source = lambda: 1 / 0
        assert s.tail_threshold_ms() == s.default_threshold_ms
        assert s.complete("a0", "t0", s.default_threshold_ms + 1.0) \
            is not None

    def test_e2e_falls_back_to_the_row_total(self):
        s = _store()
        s.threshold_source = lambda: 10.0
        e = s.complete("a0", "t0",
                       row=_row(times={STAGE_COMPLETION_ACK: 50_000}))
        assert e["reason"] == "slow" and e["e2e_ms"] == 50.0

    def test_floor_is_deterministic_one_in_n(self):
        s = _store(keep_floor=0.25)
        assert s._floor_every == 4
        kept = [s.complete(f"a{i}", f"t{i}", 1.0) for i in range(100)]
        floor = [e for e in kept if e is not None]
        assert len(floor) == 25
        assert all(e["reason"] == "floor" for e in floor)
        # exactly every 4th completion, not a random 25%
        assert [i for i, e in enumerate(kept) if e] == list(range(3, 100, 4))
        assert s.dropped_total == 75
        assert s.kept_total == {"floor": 25}

    def test_clean_drop_pops_pending_and_counts(self):
        s = _store()
        s._ingest(synthetic_span("t0", "x", 1.0, 2.0))
        assert s.complete("a0", "t0", 1.0) is None
        assert s._pending == {} and s.dropped_total == 1

    def test_reasons_priority_tuple_is_the_contract(self):
        assert REASONS == ("error", "timeout", "fenced", "spilled",
                           "forced", "divergent", "exemplar", "slow",
                           "floor")


# -- bounds ------------------------------------------------------------------
class TestBounds:
    def test_pending_limit_ages_out_oldest(self):
        s = _store(pending_limit=4)
        for i in range(6):
            s._ingest(synthetic_span(f"t{i}", "x", 1.0, 2.0))
        assert len(s._pending) == 4
        assert s.pending_evicted == 2
        assert "t0" not in s._pending and "t5" in s._pending

    def test_span_cap_per_trace(self):
        s = _store()
        for _ in range(TraceStore.SPAN_CAP + 10):
            s._ingest(synthetic_span("t0", "x", 1.0, 2.0))
        assert len(s._pending["t0"]) == TraceStore.SPAN_CAP

    def test_kept_ring_eviction_keeps_get_consistent(self):
        s = _store(keep_ring=8)
        for i in range(12):
            s.complete(f"a{i}", f"t{i}", 5.0, forced=True)
        assert s.get("t0") is None and s.get("t3") is None
        assert s.get("t11")["activation_id"] == "a11"
        # the by-id index never outgrows the ring
        assert len(s._by_id) <= 8

    def test_get_returns_the_latest_keep_for_a_trace_id(self):
        s = _store()
        s.complete("a0", "t0", 5.0, forced=True)
        s.complete("a1", "t0", 5.0, fenced=True)
        assert s.get("t0")["activation_id"] == "a1"

    def test_entries_oldest_first_and_list_filters(self):
        s = _store()
        s.complete("a0", "t0", 5.0, forced=True)
        s.complete("a1", "t1", 5.0, fenced=True)
        assert [e["trace_id"] for e in s.entries()] == ["t0", "t1"]
        out = s.list(reason="fenced")
        assert [e["trace_id"] for e in out] == ["t1"]
        assert s.list()[0]["trace_id"] == "t1"  # newest first


# -- tee lifecycle -----------------------------------------------------------
class TestTeeLifecycle:
    def test_attach_tees_and_detach_restores(self):
        t = Tracer()
        inner = t.reporter
        s = _store()
        s.attach(t)
        assert isinstance(t.reporter, _TeeReporter)
        assert t.reporter.inner is inner
        assert s.active
        s.attach(t)  # idempotent: never double-wraps
        assert t.reporter.inner is inner
        s.emit(synthetic_span("t0", "x", 1.0, 2.0))
        assert len(s._pending["t0"]) == 1
        assert inner.sent_spans == 1  # the sink still sees every span
        s.detach()
        assert t.reporter is inner and not s.active

    def test_finished_tracer_spans_reach_the_pending_table(self):
        t = Tracer()
        s = _store()
        s.attach(t)
        transid = SimpleNamespace(id="tx1")
        span = t.start_span("op", transid)
        t.finish_span(transid, span=span)
        assert [sp.span_id for sp in s._pending[span.trace_id]] \
            == [span.span_id]
        s.detach()


# -- disabled = TRUE no-op ---------------------------------------------------
class TestDisabledNoop:
    def test_attach_never_wraps_when_disabled(self):
        t = Tracer()
        inner = t.reporter
        s = _store(enabled=False)
        s.attach(t)
        assert t.reporter is inner and not s.active

    def test_verdict_path_allocates_nothing(self):
        s = _store(enabled=False)
        row = _row(times={STAGE_COMPLETION_ACK: 500})
        s.complete("a0", "t0", 5.0, row=row)  # warm the code path
        s.mark("t0", "forced")
        import openwhisk_tpu.utils.tracestore as ts_mod
        filt = (tracemalloc.Filter(True, ts_mod.__file__),)
        tracemalloc.start()
        try:
            before = tracemalloc.take_snapshot()
            for _ in range(300):
                s.complete("a0", "t0", 5.0, row=row)
                s.mark("t0", "forced")
                s.force("t0")
            after = tracemalloc.take_snapshot()
        finally:
            tracemalloc.stop()
        stats = after.filter_traces(filt).compare_to(
            before.filter_traces(filt), "lineno")
        assert sum(st.size_diff for st in stats) <= 0, stats
        assert s._seen == 0 and s._pending == {} and s._marks == {}

    def test_prometheus_text_empty_when_disabled(self):
        assert _store(enabled=False).prometheus_text() == ""

    def test_env_off_switch(self, monkeypatch):
        monkeypatch.setenv("CONFIG_whisk_tracing_tail_enabled", "false")
        assert tail_config().enabled is False


# -- exposition --------------------------------------------------------------
class TestExposition:
    def test_counters_render_with_reason_labels(self):
        s = _store()
        s.complete("a0", "t0", 5.0, forced=True)
        s.complete("a1", "t1", 5.0, error=True)
        s.complete("a2", "t2", 5.0)
        text = s.prometheus_text()
        assert '# TYPE openwhisk_trace_kept_total counter' in text
        assert 'openwhisk_trace_kept_total{reason="forced"} 1' in text
        assert 'openwhisk_trace_kept_total{reason="error"} 1' in text
        assert 'openwhisk_trace_dropped_total 1' in text
        om = s.prometheus_text(openmetrics=True)
        # OM types the base name; samples keep the _total suffix
        assert '# TYPE openwhisk_trace_kept counter' in om
        assert '# TYPE openwhisk_trace_dropped counter' in om
        assert 'openwhisk_trace_dropped_total 1' in om


# -- cross-process assembly --------------------------------------------------
def _half(tid="t0", aid="a0", instance=0, role="controller", times=None,
          ts=1000.0, spans=(), reasons=("floor",), placement=None):
    return {"trace_id": tid, "activation_id": aid, "ts": ts,
            "reason": reasons[0], "reasons": list(reasons),
            "e2e_ms": None,
            "identity": {"instance": instance, "pid": 1, "role": role},
            "spans": list(spans),
            "waterfall": _row(aid=aid, tid=tid, times=times, ts=ts),
            "placement": placement, "quality": None}


class TestAssembly:
    def test_empty_is_found_false(self):
        out = assemble_trace("t0", [], members_missing=[2, 1])
        assert out["found"] is False and out["members_missing"] == [1, 2]

    def test_spilled_half_pins_to_the_spill_forward_stamp(self):
        origin = _half(times={STAGE_API_ACCEPT: 50,
                              STAGE_SPILL_FORWARD: 300},
                       reasons=("spilled",))
        peer = _half(aid="a0", instance=1, ts=1000.7,
                     times={STAGE_PUBLISH_ENQUEUE: 10,
                            STAGE_COMPLETION_ACK: 500},
                     reasons=("fenced",))
        out = assemble_trace("t0", [origin, peer])
        assert out["found"] and out["processes"] == ["controller0",
                                                     "controller1"]
        assert sorted(out["reasons"]) == ["fenced", "spilled"]
        groups = {g["name"]: g for g in out["root"]["children"]}
        # peer t0 sits at origin's spill stamp minus its own enqueue
        assert groups["proc:controller1"]["start_us"] == 300 - 10
        # the tree telescopes past the origin's own total
        assert out["e2e_us"] == (300 - 10) + 500

    def test_invoker_half_pins_to_publish_enqueue(self):
        origin = _half(times={STAGE_API_ACCEPT: 50,
                              STAGE_PUBLISH_ENQUEUE: 200,
                              STAGE_COMPLETION_ACK: 900})
        inv = _half(instance=5, role="invoker", ts=1000.4,
                    times={STAGE_INVOKER_PICKUP: 20, STAGE_RUN: 400})
        out = assemble_trace("t0", [origin, inv])
        groups = {g["name"]: g for g in out["root"]["children"]}
        assert groups["proc:invoker5"]["start_us"] == 200 - 20

    def test_anchorless_half_falls_back_to_wall_clock(self):
        origin = _half(times={STAGE_API_ACCEPT: 100_000}, ts=1000.0)
        other = _half(instance=1, ts=1000.5,
                      times={STAGE_RUN: 20_000})
        out = assemble_trace("t0", [origin, other])
        groups = {g["name"]: g for g in out["root"]["children"]}
        # (ts delta) + origin total - half total
        assert groups["proc:controller1"]["start_us"] == \
            500_000 + 100_000 - 20_000

    def test_each_halfs_stage_deltas_telescope(self):
        times = {STAGE_API_ACCEPT: 50, STAGE_PUBLISH_ENQUEUE: 200,
                 STAGE_COMPLETION_ACK: 900}
        out = assemble_trace("t0", [_half(times=times)])
        (group,) = out["root"]["children"]
        stages = [n for n in group["children"]
                  if n["name"].startswith("stage:")]
        assert sum(n["duration_us"] for n in stages) == 900
        assert group["duration_us"] == 900

    def test_spans_dedup_by_id_and_halves_by_identity(self):
        sp = synthetic_span("t0", "spill_forward", 1000.0, 1000.0,
                            tags={"proc": "controller0"}).to_json()
        h = _half(times={STAGE_API_ACCEPT: 50}, spans=[sp])
        out = assemble_trace("t0", [h, dict(h)])
        assert len(out["root"]["children"]) == 1  # one proc group
        (group,) = out["root"]["children"]
        names = [n["name"] for n in group["children"]]
        assert names.count("spill_forward") == 1

    def test_span_proc_tags_extend_the_process_set(self):
        sp = synthetic_span("t0", "invoker_run", 1000.0, 1000.1,
                            tags={"proc": "invoker3"}).to_json()
        out = assemble_trace(
            "t0", [_half(times={STAGE_API_ACCEPT: 50}, spans=[sp])])
        assert out["processes"] == ["controller0", "invoker3"]

    def test_device_dispatch_stage_carries_the_batch_join(self):
        out = assemble_trace("t0", [_half(
            times={STAGE_API_ACCEPT: 10, STAGE_COMPLETION_ACK: 500},
            placement={"seq": 7, "kernel": "xla", "trace_id": "tb"})])
        # placement join rides the device_dispatch stage only; this row
        # has none, so no stage carries batch tags
        (group,) = out["root"]["children"]
        assert all(not n["tags"] for n in group["children"])
        out2 = assemble_trace("t1", [_half(
            times={STAGE_API_ACCEPT: 10, 6: 300, STAGE_COMPLETION_ACK: 500},
            placement={"seq": 7, "kernel": "xla", "trace_id": "tb"})])
        (group2,) = out2["root"]["children"]
        tags = {n["name"]: n["tags"] for n in group2["children"]}
        assert tags["stage:device_dispatch"]["batch_seq"] == 7
        assert tags["stage:device_dispatch"]["kernel"] == "xla"


# -- admin read side ---------------------------------------------------------
class TestAdminEndpoints:
    def _hdrs(self, ident):
        return {"Authorization": "Basic " + base64.b64encode(
            ident.authkey.compact.encode()).decode()}

    def _controller(self):
        from openwhisk_tpu.controller.core import Controller
        from openwhisk_tpu.controller.loadbalancer.lean import LeanBalancer
        from openwhisk_tpu.core.entity import (ControllerInstanceId,
                                               Identity, MB)
        from openwhisk_tpu.messaging import MemoryMessagingProvider
        from openwhisk_tpu.utils.logging import NullLogging

        async def noop_factory(invoker_id, provider):
            class _Stub:
                async def stop(self):
                    pass
            return _Stub()

        logger = NullLogging()
        provider = MemoryMessagingProvider()
        lb = LeanBalancer(provider, ControllerInstanceId("0"), noop_factory,
                          logger=logger, metrics=logger.metrics,
                          user_memory=MB(512))
        c = Controller(ControllerInstanceId("0"), provider, logger=logger,
                       load_balancer=lb)
        return c, Identity.generate("guest")

    def test_disabled_plane_404s_and_enabled_answers(self):
        import aiohttp
        from openwhisk_tpu.core.entity import WhiskAuthRecord

        store = GLOBAL_TRACE_STORE
        was_enabled, was_cfg = store.enabled, store.config

        async def go():
            c, ident = self._controller()
            await c.auth_store.put(WhiskAuthRecord(
                ident.subject, [ident.namespace], [ident.authkey]))
            await c.start(port=CTL_PORT)
            out = {}
            try:
                base = f"http://127.0.0.1:{CTL_PORT}"
                async with aiohttp.ClientSession() as s:
                    # auth gate first: unauthenticated is 401, not 404
                    async with s.get(f"{base}/admin/traces") as r:
                        out["unauth"] = r.status
                    store.enabled = False
                    for key, path in (("list", "/admin/traces"),
                                      ("local", "/admin/trace/local/ff"),
                                      ("asm", "/admin/trace/ff")):
                        async with s.get(base + path,
                                         headers=self._hdrs(ident)) as r:
                            out[f"off_{key}"] = r.status
                    store.enabled = True
                    store.reset()
                    store.complete("a0", "aa11", 5.0, forced=True)
                    async with s.get(f"{base}/admin/trace/local/aa11",
                                     headers=self._hdrs(ident)) as r:
                        out["local"] = (r.status, await r.json())
                    async with s.get(f"{base}/admin/trace/local/none",
                                     headers=self._hdrs(ident)) as r:
                        out["local_miss"] = (r.status, await r.json())
                    async with s.get(
                            f"{base}/admin/traces?reason=forced",
                            headers=self._hdrs(ident)) as r:
                        out["list"] = (r.status, await r.json())
                    async with s.get(f"{base}/admin/trace/aa11",
                                     headers=self._hdrs(ident)) as r:
                        out["asm"] = (r.status, await r.json())
            finally:
                await c.stop()
            return out

        try:
            out = asyncio.run(go())
        finally:
            GLOBAL_TRACE_STORE.enabled = was_enabled
            GLOBAL_TRACE_STORE.config = was_cfg
            GLOBAL_TRACE_STORE.reset()
        assert out["unauth"] == 401
        assert out["off_list"] == out["off_local"] == out["off_asm"] == 404
        status, body = out["local"]
        assert status == 200 and body["found"] is True
        assert body["entry"]["activation_id"] == "a0"
        status, body = out["local_miss"]
        # a live peer that never kept the trace is NOT a missing member
        assert status == 200 and body["found"] is False
        status, body = out["list"]
        assert status == 200
        assert [t["trace_id"] for t in body["traces"]] == ["aa11"]
        assert body["stats"]["kept_total"] == {"forced": 1}
        status, body = out["asm"]
        assert status == 200 and body["found"] is True
        assert body["trace_id"] == "aa11"


# -- satellite: tracer expiry ------------------------------------------------
class TestTracerExpiry:
    def test_small_abandoned_populations_age_out(self):
        # the regression: fewer than 1000 abandoned stacks used to linger
        # forever (only the size trigger swept)
        t = Tracer(expiry_seconds=0.05)
        for i in range(5):
            t.start_span("s", SimpleNamespace(id=f"tx{i}"))
        assert len(t._stacks) == 5
        time.sleep(0.12)
        t.start_span("s", SimpleNamespace(id="fresh"))
        assert set(t._stacks) == {"fresh"}
        assert set(t._touched) == {"fresh"}

    def test_live_stacks_survive_the_sweep(self):
        t = Tracer(expiry_seconds=10.0)
        t._sweep_interval = 0.01
        t.start_span("s", SimpleNamespace(id="tx0"))
        time.sleep(0.02)
        t.start_span("s", SimpleNamespace(id="tx1"))
        assert set(t._stacks) == {"tx0", "tx1"}


# -- satellite: ack frames carry trace context -------------------------------
class TestAckTraceContext:
    def _fixtures(self):
        from openwhisk_tpu.core.entity import (ActivationId,
                                               ActivationResponse,
                                               ControllerInstanceId,
                                               EntityPath, Identity,
                                               InvokerInstanceId, MB,
                                               WhiskActivation)
        from openwhisk_tpu.core.entity.names import FullyQualifiedEntityName
        from openwhisk_tpu.messaging.message import (
            CombinedCompletionAndResultMessage, CompletionMessage)
        from openwhisk_tpu.utils.transaction import TransactionId
        ident = Identity.generate("guest")
        inv = InvokerInstanceId(0, user_memory=MB(512))
        name = FullyQualifiedEntityName.parse("guest/act0").name
        now = time.time()

        def combined(tc=None):
            aid = ActivationId.generate()
            act = WhiskActivation(EntityPath("guest"), name,
                                  ident.subject, aid, now, now,
                                  ActivationResponse.success({"ok": True}),
                                  duration=1)
            ack = CombinedCompletionAndResultMessage(TransactionId(), act,
                                                     inv)
            ack.trace_context = tc
            return ack

        def completion(tc=None):
            ack = CompletionMessage(TransactionId(),
                                    ActivationId.generate(), False, inv)
            ack.trace_context = tc
            return ack

        return combined, completion

    def test_serial_ack_roundtrip_and_absent_when_none(self):
        import json
        from openwhisk_tpu.messaging.message import parse_ack
        combined, completion = self._fixtures()
        tc = {"traceparent": "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"}
        traced = combined(tc)
        out = parse_ack(traced.serialize())
        assert out.trace_context == tc
        assert trace_id_of(out.trace_context) == "ab" * 16
        bare = completion(None)
        assert "traceContext" not in json.loads(bare.serialize())
        assert parse_ack(bare.serialize()).trace_context is None

    def test_eager_batch_sparse_column_roundtrip(self):
        import json
        from openwhisk_tpu.messaging.columnar import (AckBatchMessage,
                                                      parse_batch)
        combined, completion = self._fixtures()
        tc = {"traceparent": "00-" + "11" * 16 + "-" + "22" * 8 + "-01"}
        acks = [completion(None), combined(tc), completion(None)]
        raw = AckBatchMessage(acks).serialize()
        _kind, out = parse_batch(raw)
        assert [m.trace_context for m in out] == [None, tc, None]
        # untraced batches never grow the column: byte-exact absent
        untraced = AckBatchMessage([completion(None), combined(None)])
        assert "trace" not in json.loads(untraced.serialize())

    def test_lazy_batch_header_carries_the_column(self):
        import json
        from openwhisk_tpu.messaging.columnar import (AckBatchMessage,
                                                      parse_batch)
        combined, completion = self._fixtures()
        tc = {"traceparent": "00-" + "33" * 16 + "-" + "44" * 8 + "-01"}
        acks = [combined(tc), completion(None)]
        raw = AckBatchMessage(acks, lazy_results=True).serialize()
        _kind, out = parse_batch(raw)
        assert [m.trace_context for m in out] == [tc, None]
        # the traced ack's response survives the lazy wire untouched
        assert out[0].activation.response.result == {"ok": True}
        header = json.loads(raw.split(b"\n", 1)[0])
        assert header["trace"] == {"0": tc}
        untraced = AckBatchMessage([completion(None)],
                                   lazy_results=True).serialize()
        assert "trace" not in json.loads(untraced.split(b"\n", 1)[0])


# -- satellite: ring-shaped span buffer (regression companion) ---------------
class TestBufferReporterRing:
    def test_newest_spans_survive_saturation(self):
        rep = BufferReporter(max_spans=4)
        for i in range(10):
            rep.report(synthetic_span("t", f"s{i}", 1.0, 2.0))
        assert [s.name for s in rep.spans] == ["s6", "s7", "s8", "s9"]
        assert rep.sent_spans == 10 and rep.dropped_spans == 6

"""Anomaly & alerting plane: telemetry deltas -> scores -> alerts.

The fourth observability plane. PR 1 records *where* placements went, PR 2
measures *whether* the fleet meets its SLOs, PR 3 profiles *how* the kernel
runs — but an operator still had to eyeball `/admin/slo` to notice a sick
invoker. This plane closes the loop: per-invoker anomaly scores computed
where the telemetry already lives (ops/anomaly.py — on device for the TPU
balancer, the NumPy twin for sharding/lean, through the same base-class
hook), and a Prometheus-style alert rules engine on top.

Detection (the kernel, one program per tick, vectorized over invokers):
EWMA latency mean/variance per invoker, robust z-score against the fleet
median (straggler score), error/timeout-rate spike z-tests against the
EWMA baseline, boolean flags gated on a minimum sample count. The device
path is pipelined one tick deep: tick N dispatches the program and starts
an async device->host copy; tick N+1 harvests it — the supervision tick
never blocks on a device sync (the same no-sync-on-the-loop rule the
telemetry burn-rate math follows).

Alerting (host, pure python): rules with (signal, threshold, `for`
duration, severity) — built-in defaults for straggler, error spike, SLO
fast/slow burn (reusing the telemetry plane's burn-rate windows) and the
PR-3 recompile watchdog counter, each overridable via
`CONFIG_whisk_alerts_rules` JSON. A pending -> firing -> resolved state
machine per (alert, label set), every transition appended to a pre-sized
SeqRingBuffer alert log and counted.

Read sides:
  * `/metrics` families (MetricEmitter.register_renderer):
    `openwhisk_loadbalancer_invoker_anomaly_score{invoker,signal}`,
    `openwhisk_alerts_firing{alertname,severity}`,
    `openwhisk_alert_transitions_total{alertname,transition}`.
  * `GET /admin/alerts`: rules, active (pending+firing) alerts, the
    transition log.
  * `GET /admin/anomalies`: per-invoker scores with evidence — which
    latency buckets moved since the last tick (the kernel's prev-bucket
    snapshot doubles as the evidence baseline; syncing it is an endpoint
    cold path, never a tick cost).
  * an advisory `unhealthy_hint` pushed to InvokerPool when
    `CONFIG_whisk_anomaly_hintUnhealthy` is set (default OFF: this plane
    observes, it does not steer placement).

Off-switch: `CONFIG_whisk_anomaly_enabled=false` makes every entry point a
true no-op (no state allocated, empty exposition, `{"enabled": false}`
reports).
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field, replace
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ...ops.anomaly import (S_ANOMALY_FLAG, S_ERR_SPIKE, S_EWMA_MS,
                            S_STRAGGLER, S_STRAGGLER_FLAG, S_TM_SPIKE,
                            S_TOTAL, AnomalyState, anomaly_step_np,
                            init_anomaly, init_anomaly_np,
                            make_anomaly_step)
from ...utils.config import load_config
from ...utils.ring_buffer import SeqRingBuffer
from .telemetry import FAST_WINDOW_S, SLOW_WINDOW_S

#: alert FSM states (`resolved`/`cancelled` appear only as transition
#: targets in the log: the instance itself is dropped)
PENDING, FIRING = "pending", "firing"
RESOLVED, CANCELLED, INACTIVE = "resolved", "cancelled", "inactive"

#: recompile-watchdog hold: churn within this window keeps the signal up
CHURN_WINDOW_S = 60.0

#: invoker-scoped score signals -> packed score-matrix rows
_SIGNAL_ROWS = {
    "straggler_score": S_STRAGGLER,
    "error_spike_score": S_ERR_SPIKE,
    "timeout_spike_score": S_TM_SPIKE,
}


@dataclass(frozen=True)
class AnomalyConfig:
    """`CONFIG_whisk_anomaly_*` env overrides."""
    enabled: bool = True
    #: EWMA smoothing factor for the per-tick latency / rate estimates
    alpha: float = 0.3
    #: robust z-score above which an invoker counts as straggling
    z_threshold: float = 3.5
    #: spike z-score above which an error/timeout burst counts as anomalous
    spike_threshold: float = 3.0
    #: cumulative completions an invoker needs before it may flag
    min_samples: int = 8
    #: absolute floor (ms) on the MAD scale — a tightly-clustered fleet
    #: must not z-score its own micro-jitter into stragglers
    mad_floor_ms: float = 1.0
    #: push firing invoker-scoped alerts to InvokerPool as advisory hints
    hint_unhealthy: bool = False


@dataclass(frozen=True)
class AlertsConfig:
    """`CONFIG_whisk_alerts_*` env overrides. `rules` is a JSON dict of
    per-rule overrides, e.g. CONFIG_whisk_alerts_rules=
    '{"straggler": {"threshold": 2.5, "for_s": 10, "severity": "critical"}}'
    (unknown keys are ignored; `"enabled": false` drops a built-in)."""
    enabled: bool = True
    log_size: int = 256
    rules: dict = field(default_factory=dict)


@dataclass
class AlertRule:
    name: str
    signal: str
    threshold: float
    for_s: float
    severity: str
    scope: str  # "invoker" | "global"
    enabled: bool = True

    def to_json(self) -> dict:
        return {"name": self.name, "signal": self.signal,
                "threshold": self.threshold, "for_s": self.for_s,
                "severity": self.severity, "scope": self.scope,
                "enabled": self.enabled}


#: the built-in rule set (burn thresholds are the classic multi-window
#: pair: fast burn pages, slow burn tickets). The straggler/spike
#: thresholds here are placeholders: build_rules() re-derives them from
#: AnomalyConfig so the kernel's flag gate and the alert gate are ONE
#: knob (CONFIG_whisk_anomaly_{z,spike}Threshold) — an explicit
#: CONFIG_whisk_alerts_rules threshold still wins.
DEFAULT_RULES: Tuple[AlertRule, ...] = (
    AlertRule("straggler", "straggler_score", 3.5, 30.0, "warning",
              "invoker"),
    AlertRule("error_spike", "error_spike_score", 3.0, 30.0, "warning",
              "invoker"),
    AlertRule("timeout_spike", "timeout_spike_score", 3.0, 30.0, "warning",
              "invoker"),
    AlertRule("slo_fast_burn", "burn_rate_1m", 14.4, 60.0, "critical",
              "global"),
    AlertRule("slo_slow_burn", "burn_rate_10m", 6.0, 300.0, "warning",
              "global"),
    AlertRule("recompile_churn", "recompile_churn_60s", 0.0, 0.0, "warning",
              "global"),
    # journal writer stall (ISSUE 15): the appended-vs-durable gap stays
    # above threshold for the window — an fsync device stall. The signal
    # is fed by TpuBalancer.attach_journal via `extra_signals`; the
    # firing state also surfaces in GET /admin/ready.
    AlertRule("journal_stall", "journal_lag_batches", 64.0, 10.0,
              "critical", "global"),
)


def _rule_override(rule: AlertRule, ov: dict) -> AlertRule:
    def pick(snake, camel, cur, cast):
        v = ov.get(snake, ov.get(camel, cur))
        return cast(v)

    return replace(
        rule,
        threshold=pick("threshold", "threshold", rule.threshold, float),
        for_s=pick("for_s", "forS", ov.get("for", rule.for_s), float),
        severity=str(ov.get("severity", rule.severity)),
        enabled=bool(ov.get("enabled", rule.enabled)),
    )


def build_rules(overrides: Optional[dict],
                anomaly: Optional[AnomalyConfig] = None
                ) -> Dict[str, AlertRule]:
    """Built-in rules + `CONFIG_whisk_alerts_rules` overrides; operators
    may also add NEW rules over any known signal by including `signal`.
    When the detector config is given, the built-in straggler/spike rule
    thresholds track its flag gates (an invoker the kernel flags is an
    invoker the alert watches — the two surfaces must not disagree when
    an operator tunes CONFIG_whisk_anomaly_zThreshold)."""
    rules = {r.name: replace(r) for r in DEFAULT_RULES}
    if anomaly is not None:
        rules["straggler"] = replace(rules["straggler"],
                                     threshold=float(anomaly.z_threshold))
        for n in ("error_spike", "timeout_spike"):
            rules[n] = replace(rules[n],
                               threshold=float(anomaly.spike_threshold))
    for name, ov in (overrides or {}).items():
        if not isinstance(ov, dict):
            continue
        base = rules.get(name)
        if base is None:
            signal = ov.get("signal")
            if not isinstance(signal, str):
                continue  # a new rule must say what it watches
            scope = "invoker" if signal in _SIGNAL_ROWS else "global"
            base = AlertRule(name, signal, 0.0, 0.0, "warning", scope)
        rules[name] = _rule_override(base, ov)
    return rules


@dataclass
class _Instance:
    state: str
    since: float   # monotonic stamp when the condition first held
    value: Optional[float] = None


LabelSet = Tuple[Tuple[str, str], ...]


class AlertEngine:
    """The pending -> firing -> resolved state machine, one instance per
    (rule, label set). evaluate() is fed every breaching subject plus the
    current value of every subject with a live instance; a live subject
    absent from the feed counts as vanished and resolves/cancels."""

    def __init__(self, rules: Dict[str, AlertRule], log_size: int = 256,
                 logger=None):
        self.rules = rules
        self.logger = logger
        self.log: SeqRingBuffer[dict] = SeqRingBuffer(max(1, int(log_size)))
        self._instances: Dict[Tuple[str, LabelSet], _Instance] = {}
        #: (alertname, transition) -> count, for the counter family
        self.transition_counts: Dict[Tuple[str, str], int] = {}
        #: (firing_counts, transition_counts) copies republished after
        #: every evaluate(): /metrics renders on a worker thread while the
        #: tick mutates the live dicts on the event loop — the renderer
        #: must only ever iterate these immutable-once-published copies
        self._exposition: Tuple[dict, dict] = ({}, {})
        #: transition observers `(now, rule, labels, old, new, value)` —
        #: the incident recorder's firing trigger (ISSUE 19). Synchronous,
        #: must never block or raise into the evaluation tick.
        self.listeners: List[Callable] = []

    def _transition(self, now: float, rule: AlertRule, labels: LabelSet,
                    old: str, new: str, value: Optional[float]) -> None:
        self.log.append({
            "ts": round(time.time(), 3),
            "alert": rule.name,
            "severity": rule.severity,
            "labels": dict(labels),
            "from": old,
            "to": new,
            "value": None if value is None else round(float(value), 4),
        })
        key = (rule.name, new)
        self.transition_counts[key] = self.transition_counts.get(key, 0) + 1
        if self.logger is not None and new in (FIRING, RESOLVED):
            self.logger.warn(
                None, f"alert {rule.name}{dict(labels)} {old} -> {new} "
                f"(value={value}, severity={rule.severity})", "AlertEngine")
        for fn in tuple(self.listeners):
            try:
                fn(now, rule, labels, old, new, value)
            except Exception:  # noqa: BLE001 — observability never blocks
                pass

    def evaluate(self, now: float,
                 signals: Dict[str, List[Tuple[LabelSet, float]]]) -> None:
        for name, rule in self.rules.items():
            if not rule.enabled:
                continue
            seen = set()
            for labels, value in signals.get(name, []):
                key = (name, labels)
                seen.add(key)
                inst = self._instances.get(key)
                if value > rule.threshold:
                    if inst is None:
                        state = PENDING if rule.for_s > 0 else FIRING
                        self._instances[key] = _Instance(state, now, value)
                        self._transition(now, rule, labels, INACTIVE, state,
                                         value)
                    else:
                        inst.value = value
                        if inst.state == PENDING \
                                and now - inst.since >= rule.for_s:
                            self._transition(now, rule, labels, PENDING,
                                             FIRING, value)
                            inst.state = FIRING
                elif inst is not None:
                    to = RESOLVED if inst.state == FIRING else CANCELLED
                    self._transition(now, rule, labels, inst.state, to,
                                     value)
                    del self._instances[key]
            # subjects that vanished entirely (invoker left the score
            # matrix): their alerts must not fire forever on stale data
            for key in [k for k in self._instances
                        if k[0] == name and k not in seen]:
                inst = self._instances.pop(key)
                to = RESOLVED if inst.state == FIRING else CANCELLED
                self._transition(now, rule, key[1], inst.state, to, None)
        self._exposition = (self.firing_counts(),
                            dict(self.transition_counts))

    # -- read side ---------------------------------------------------------
    def active(self, now: Optional[float] = None) -> List[dict]:
        now = time.monotonic() if now is None else now
        out = []
        for (name, labels), inst in sorted(self._instances.items()):
            rule = self.rules[name]
            out.append({
                "alert": name,
                "labels": dict(labels),
                "state": inst.state,
                "severity": rule.severity,
                "for_s": rule.for_s,
                "active_s": round(now - inst.since, 3),
                "value": inst.value,
            })
        return out

    def firing_counts(self) -> Dict[Tuple[str, str], int]:
        """(alertname, severity) -> number of firing instances."""
        out: Dict[Tuple[str, str], int] = {}
        for (name, _labels), inst in self._instances.items():
            if inst.state == FIRING:
                key = (name, self.rules[name].severity)
                out[key] = out.get(key, 0) + 1
        return out

    def subjects(self, name: str) -> List[LabelSet]:
        """Label sets with a live instance under rule `name` (the plane
        feeds these their current value each tick so resolutions carry
        the observed number, not None)."""
        return [labels for (n, labels) in self._instances if n == name]

    def exposition_snapshot(self) -> Tuple[dict, dict]:
        """(firing_counts, transition_counts) as of the last evaluate(),
        safe to iterate from the /metrics worker thread."""
        return self._exposition


class AnomalyPlane:
    """One per balancer (base-class hook, like the other three planes)."""

    def __init__(self, config: Optional[AnomalyConfig] = None,
                 alerts: Optional[AlertsConfig] = None, logger=None):
        self.config = config or AnomalyConfig()
        self.alerts_config = alerts or AlertsConfig()
        self.enabled = self.config.enabled
        self.logger = logger
        self.engine = AlertEngine(build_rules(self.alerts_config.rules,
                                              anomaly=self.config),
                                  log_size=self.alerts_config.log_size,
                                  logger=logger)
        #: host-provided global alert signals: name -> zero-arg provider
        #: returning the current value (None = subject vanished). The
        #: journal stall watchdog registers `journal_lag_batches` here.
        self.extra_signals: Dict[str, Callable[[], Optional[float]]] = {}
        # attached collaborators (base-class wiring)
        self._telemetry = None
        self._profiler = None
        self._names_fn: Optional[Callable[[], List[str]]] = None
        self.hint_sink: Optional[Callable[[Dict[int, str]], None]] = None
        # detector state: allocated lazily on the first enabled tick
        self._state: Optional[AnomalyState] = None
        self._state_kernel: Optional[str] = None
        self._step = None
        self._scores: Optional[np.ndarray] = None   # harvested [R, N]
        self._pending_scores = None                 # device array in flight
        self._names: List[str] = []
        self._name_idx: Dict[str, int] = {}
        self._last_tick = 0.0
        self._last_unexpected = 0
        self._churn_events: List[Tuple[float, int]] = []
        self.hints: Dict[int, str] = {}

    @classmethod
    def from_config(cls, logger=None) -> "AnomalyPlane":
        return cls(config=load_config(AnomalyConfig, env_path="anomaly"),
                   alerts=load_config(AlertsConfig, env_path="alerts"),
                   logger=logger)

    def attach(self, telemetry=None, profiler=None,
               invoker_names: Optional[Callable[[], List[str]]] = None,
               hint_sink=None) -> None:
        """Wire the plane to its data sources (called by the balancer base
        class; harmless when disabled — nothing allocates until a tick)."""
        self._telemetry = telemetry
        self._profiler = profiler
        self._names_fn = invoker_names
        if hint_sink is not None:
            self.hint_sink = hint_sink

    @property
    def SYNCS_DEVICE(self) -> bool:
        """True when the evidence read in anomalies_report forces a
        device->host sync (callers then use a worker thread)."""
        tp = self._telemetry
        return bool(tp is not None and tp.enabled and tp.SYNCS_DEVICE)

    # -- detector ticks ----------------------------------------------------
    def _cfg_args(self) -> tuple:
        c = self.config
        return (c.alpha, c.z_threshold, c.spike_threshold, c.min_samples,
                c.mad_floor_ms)

    def _ensure_state(self, kernel: str, n: int, n_buckets: int) -> None:
        """(Re)allocate or zero-pad the carry state to the accumulator's
        current invoker axis. A kernel swap (cpu -> device via use_device)
        restarts the estimates — the accumulators are different arrays."""
        st = self._state
        # .shape is metadata on both numpy and jax arrays — never a sync
        if st is not None and self._state_kernel == kernel \
                and tuple(st.prev_buckets.shape) == (n, n_buckets):
            return
        shape = tuple(st.prev_buckets.shape) if st is not None else None
        if st is not None and self._state_kernel == kernel \
                and shape[1] == n_buckets and shape[0] < n:
            # invoker axis grew: zero-pad every carry array, preserving the
            # estimates (a fleet join must not reset everyone's EWMAs). On
            # the device path the pad stays ON DEVICE — syncing the carry
            # through the host here would stall the supervision tick, the
            # exact stall the one-tick-deep harvest pipeline avoids.
            n_old = shape[0]
            if kernel == "device":
                import jax.numpy as jnp
                grown = [jnp.zeros((n,) + tuple(o.shape[1:]), o.dtype)
                         .at[:n_old].set(o) for o in st]
            else:
                grown = []
                for o in st:
                    g = np.zeros((n,) + o.shape[1:], o.dtype)
                    g[:n_old] = o
                    grown.append(g)
            self._state = AnomalyState(*grown)
        else:
            self._state = (init_anomaly(n, n_buckets) if kernel == "device"
                           else init_anomaly_np(n, n_buckets))
        self._state_kernel = kernel

    def tick(self, metrics=None, now: Optional[float] = None) -> dict:
        """One detection + alert-evaluation pass. Rides the supervision
        tick (TPU/sharding) or the completion stream (lean, maybe_tick)."""
        if not self.enabled:
            return {}
        now = time.monotonic() if now is None else now
        self._last_tick = now
        tp = self._telemetry
        if tp is not None and tp.enabled:
            acc = tp.accumulator
            if getattr(acc, "kernel", "cpu") == "device":
                self._tick_device(acc)
            else:
                self._tick_cpu(acc)
        self._refresh_names()
        self._evaluate(now)
        n_straggling = n_anomalous = 0
        if self._scores is not None:
            n_straggling = int(self._scores[S_STRAGGLER_FLAG].sum())
            n_anomalous = int(self._scores[S_ANOMALY_FLAG].sum())
        firing = sum(self.engine.firing_counts().values())
        if metrics is not None:
            metrics.gauge("loadbalancer_anomaly_stragglers", n_straggling)
            metrics.gauge("loadbalancer_alerts_firing_count", firing)
        return {"stragglers": n_straggling, "anomalous": n_anomalous,
                "firing": firing}

    def maybe_tick(self, metrics=None) -> None:
        """Rate-limited tick for balancers without a supervision scheduler
        (lean): detection freshness rides the completion stream."""
        if self.enabled and time.monotonic() - self._last_tick >= 1.0:
            self.tick(metrics)

    def _tick_cpu(self, acc) -> None:
        self._ensure_state("cpu", acc.inv_buckets.shape[0], acc.n_buckets)
        self._state, scores = anomaly_step_np(
            self._state, acc.inv_buckets, acc.inv_lat_ms, acc.inv_outcomes,
            *self._cfg_args())
        self._scores = scores

    def _tick_device(self, acc) -> None:
        st = acc.state
        self._ensure_state("device", st.inv_buckets.shape[0],
                           st.inv_buckets.shape[1])
        if self._step is None:
            self._step = make_anomaly_step(*self._cfg_args())
        # harvest LAST tick's scores first: its device program has had a
        # full tick to complete and its host copy was started async, so
        # this conversion is a cache hit, not a blocking sync
        if self._pending_scores is not None:
            try:
                self._scores = np.asarray(self._pending_scores)
            except Exception as e:  # noqa: BLE001 — a dead device must not
                # kill the supervision tick; stale scores age out naturally
                if self.logger is not None:
                    self.logger.warn(None, f"anomaly harvest failed: {e!r}",
                                     "AnomalyPlane")
            self._pending_scores = None
        try:
            self._state, out = self._step(self._state, st.inv_buckets,
                                          st.inv_lat_ms, st.inv_outcomes)
            self._pending_scores = out
            try:
                out.copy_to_host_async()
            except Exception:  # noqa: BLE001 — async copy is best-effort;
                pass           # the next harvest falls back to a plain pull
        except Exception as e:  # noqa: BLE001
            if self.logger is not None:
                self.logger.warn(None, f"anomaly step failed: {e!r}",
                                 "AnomalyPlane")

    # -- alert evaluation --------------------------------------------------
    def _refresh_names(self) -> None:
        names = self._names_fn() if self._names_fn is not None else []
        self._names = names
        self._name_idx = {n: i for i, n in enumerate(names)}

    def _inv_name(self, i: int) -> str:
        return self._names[i] if i < len(self._names) else f"invoker{i}"

    def _global_signals(self, now: float) -> Dict[str, float]:
        gv: Dict[str, float] = {}
        # host-provided signals (e.g. journal_lag_batches from
        # attach_journal): a provider returning None means the subject
        # vanished — its live alert instances resolve/cancel
        for name, provider in self.extra_signals.items():
            try:
                v = provider()
            except Exception:  # noqa: BLE001 — a broken provider must not
                continue       # kill the supervision tick
            if v is not None:
                gv[name] = float(v)
        tp = self._telemetry
        if tp is not None and tp.enabled:
            gv["burn_rate_1m"] = tp._burn_rate(FAST_WINDOW_S, now)
            gv["burn_rate_10m"] = tp._burn_rate(SLOW_WINDOW_S, now)
        prof = self._profiler
        if prof is not None and getattr(prof, "enabled", False):
            cur = int(getattr(prof, "compiles_unexpected", 0))
            delta = cur - self._last_unexpected
            self._last_unexpected = cur
            if delta > 0:
                self._churn_events.append((now, delta))
            self._churn_events = [(t, d) for t, d in self._churn_events
                                  if t > now - CHURN_WINDOW_S]
            gv["recompile_churn_60s"] = float(
                sum(d for _, d in self._churn_events))
        return gv

    def _evaluate(self, now: float) -> None:
        if not self.alerts_config.enabled:
            return
        sc = self._scores
        gv = self._global_signals(now)
        signals: Dict[str, List[Tuple[LabelSet, float]]] = {}
        warm = (sc[S_TOTAL] >= max(1, self.config.min_samples)
                if sc is not None else None)
        for name, rule in self.engine.rules.items():
            if rule.scope == "invoker":
                row = _SIGNAL_ROWS.get(rule.signal)
                if row is None or sc is None:
                    signals[name] = []
                    continue
                # the breach test is one vectorized comparison — the
                # per-subject python list stays O(breaching + live
                # instances), not O(fleet), on the supervision tick
                vals = sc[row]
                entries = [
                    ((("invoker", self._inv_name(int(i))),),
                     float(vals[i]))
                    for i in np.nonzero(warm & (vals > rule.threshold))[0]]
                covered = {labels for labels, _ in entries}
                # live instances off the breach set are fed their current
                # value so resolutions carry the observed number; subjects
                # gone from the score matrix fall to the vanished path
                for labels in self.engine.subjects(name):
                    if labels in covered:
                        continue
                    idx = self._name_idx.get(dict(labels).get("invoker", ""))
                    if idx is not None and idx < vals.shape[0] \
                            and bool(warm[idx]):
                        entries.append((labels, float(vals[idx])))
                signals[name] = entries
            else:
                v = gv.get(rule.signal)
                signals[name] = [((), v)] if v is not None else []
        self.engine.evaluate(now, signals)
        # advisory hints: firing invoker-scoped alerts, pushed to the
        # supervision pool only when the operator opted in
        hints: Dict[int, str] = {}
        for (aname, labels), inst in self.engine._instances.items():
            rule = self.engine.rules.get(aname)
            if inst.state != FIRING or rule is None \
                    or rule.scope != "invoker":
                continue
            idx = self._name_idx.get(dict(labels).get("invoker", ""))
            if idx is not None and idx not in hints:
                hints[idx] = aname
        self.hints = hints
        if self.config.hint_unhealthy and self.hint_sink is not None:
            try:
                self.hint_sink(dict(hints))
            except Exception:  # noqa: BLE001 — a hint must never break
                pass           # the tick

    # -- exposition --------------------------------------------------------
    def prometheus_text(self, openmetrics: bool = False) -> str:
        # runs on the /metrics worker thread while the tick mutates the
        # plane on the event loop: read each racing reference ONCE into a
        # local (scores/names are replaced wholesale, never mutated) and
        # take the alert dicts from the engine's published snapshot
        if not self.enabled:
            return ""
        from ..monitoring import counter_family_text, gauge_family_text
        out: List[str] = []
        sc = self._scores
        names = self._names
        if sc is not None:
            rows = []
            for i in range(sc.shape[1]):
                if sc[S_TOTAL, i] <= 0:
                    continue
                name = names[i] if i < len(names) else f"invoker{i}"
                for label, row in (("straggler", S_STRAGGLER),
                                   ("error_spike", S_ERR_SPIKE),
                                   ("timeout_spike", S_TM_SPIKE)):
                    rows.append(({"invoker": name, "signal": label},
                                 round(float(sc[row, i]), 4)))
            out += gauge_family_text(
                "openwhisk_loadbalancer_invoker_anomaly_score", rows)
        firing, transitions = self.engine.exposition_snapshot()
        out += gauge_family_text(
            "openwhisk_alerts_firing",
            [({"alertname": n, "severity": s}, c)
             for (n, s), c in sorted(firing.items())])
        out += counter_family_text(
            "openwhisk_alert_transitions_total",
            [({"alertname": n, "transition": t}, c)
             for (n, t), c in sorted(transitions.items())],
            openmetrics=openmetrics)
        return "\n".join(out)

    # -- admin payloads ----------------------------------------------------
    def alerts_report(self, limit: int = 50) -> dict:
        """The `GET /admin/alerts` payload."""
        if not self.enabled:
            return {"enabled": False}
        return {
            "enabled": True,
            "alerts_enabled": self.alerts_config.enabled,
            "rules": [r.to_json()
                      for r in sorted(self.engine.rules.values(),
                                      key=lambda r: r.name)],
            "active": self.engine.active(),
            "transitions": self.engine.log.last(max(0, limit)),
            "transitions_dropped": self.engine.log.evicted,
        }

    def anomalies_report(self, invoker_names: Optional[List[str]] = None
                         ) -> dict:
        """The `GET /admin/anomalies` payload: per-invoker scores with
        evidence (which latency buckets moved since the last tick). A
        device sync on the TPU path — callers run it on a worker thread
        (SYNCS_DEVICE), same policy as `/admin/slo`."""
        if not self.enabled:
            return {"enabled": False}
        tp = self._telemetry
        names = invoker_names if invoker_names is not None else self._names
        sc = self._scores
        cur = prev = bounds = None
        if tp is not None and tp.enabled:
            cur = tp.counts()["inv_buckets"]
            bounds = tp.bounds_ms()
        if self._state is not None:
            prev = np.asarray(self._state.prev_buckets)
        invokers = []
        for i in range(sc.shape[1] if sc is not None else 0):
            if sc[S_TOTAL, i] <= 0:
                continue
            name = names[i] if i < len(names) else f"invoker{i}"
            row = {
                "invoker": name,
                "straggler_score": round(float(sc[S_STRAGGLER, i]), 4),
                "error_spike_score": round(float(sc[S_ERR_SPIKE, i]), 4),
                "timeout_spike_score": round(float(sc[S_TM_SPIKE, i]), 4),
                "straggler": bool(sc[S_STRAGGLER_FLAG, i]),
                "anomalous": bool(sc[S_ANOMALY_FLAG, i]),
                "ewma_latency_ms": round(float(sc[S_EWMA_MS, i]), 4),
                "samples": int(sc[S_TOTAL, i]),
                "unhealthy_hint": self.hints.get(i),
            }
            if cur is not None and prev is not None \
                    and i < min(cur.shape[0], prev.shape[0]):
                moved = []
                delta = np.asarray(cur[i], np.int64) - np.asarray(
                    prev[i], np.int64)
                for b in np.nonzero(delta > 0)[0]:
                    le = (bounds[b] if bounds is not None
                          and b < len(bounds) else None)  # None = +Inf
                    moved.append({"le_ms": le, "count": int(delta[b])})
                row["evidence"] = {"window": "since_last_tick",
                                   "buckets_moved": moved}
            invokers.append(row)
        ewma = (sc[S_EWMA_MS][sc[S_TOTAL] > 0]
                if sc is not None else np.zeros(0))
        return {
            "enabled": True,
            "kernel": ("device" if self._state_kernel == "device"
                       else "cpu"),
            "config": {
                "alpha": self.config.alpha,
                "z_threshold": self.config.z_threshold,
                "spike_threshold": self.config.spike_threshold,
                "min_samples": self.config.min_samples,
                "mad_floor_ms": self.config.mad_floor_ms,
                "hint_unhealthy": self.config.hint_unhealthy,
            },
            "fleet": {
                "active_invokers": int(ewma.shape[0]),
                "median_ewma_ms": (round(float(np.median(ewma)), 4)
                                   if ewma.shape[0] else None),
            },
            "invokers": invokers,
        }

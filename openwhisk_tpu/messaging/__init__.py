from .message import (AcknowledgementMessage, ActivationMessage,
                      CombinedCompletionAndResultMessage, CompletionMessage,
                      EventMessage, Message, PingMessage, ResultMessage,
                      parse_ack)
from .connector import MessageConsumer, MessageFeed, MessageProducer, MessagingProvider
from .memory import MemoryMessagingProvider

__all__ = [n for n in dir() if not n.startswith("_")]

"""Request authentication (ref controller RestAPIs.scala:323-349
AuthenticationDirectiveProvider + BasicAuthenticationDirective): HTTP Basic
credentials are the identity's uuid:key; lookups hit the auth store's cached
identity views."""
from __future__ import annotations

import base64
import binascii
from typing import Optional

from ..core.entity import Identity
from ..database import AuthStore


class BasicAuthenticationProvider:
    def __init__(self, auth_store: AuthStore):
        self.auth_store = auth_store

    async def identity_from_header(self, authorization: Optional[str]) -> Optional[Identity]:
        if not authorization or not authorization.lower().startswith("basic "):
            return None
        try:
            decoded = base64.b64decode(authorization[6:].strip()).decode()
        except (binascii.Error, UnicodeDecodeError):
            return None
        user, _, password = decoded.partition(":")
        if not user or not password:
            return None
        return await self.auth_store.identity_by_key(user, password)

    @staticmethod
    def instance(auth_store: AuthStore) -> "BasicAuthenticationProvider":
        return BasicAuthenticationProvider(auth_store)

"""The sharding placement policy (CPU reference semantics).

Behavioral rebuild of the scheduling math of
core/controller/.../loadBalancer/ShardingContainerPoolBalancer.scala:
  - deterministic home invoker: hash(namespace, action) % n  (:266-268)
  - probe progression in steps coprime to the fleet size, so every invoker
    is visited exactly once (:50-81, pairwiseCoprimeNumbersUntil)
  - per-invoker capacity as a NestedSemaphore (memory MB x per-action
    concurrency) — acquire on probe, forced acquire on overload (:398-436)
  - managed vs blackbox fleet partitioning by configured fractions
    (:461-468,512-523)
  - horizontal sharding: each controller owns 1/clusterSize of every
    invoker's memory, floored at one action slot (getInvokerSlot :485-499)

This module is pure python/pure function + explicit state: it is the oracle
the JAX kernel (openwhisk_tpu.ops.placement) must match and the CPU baseline
for bench.py.
"""
from __future__ import annotations

import math
import random
import zlib
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from ..utils.semaphores import NestedSemaphore

MIN_SLOT_MB = 128  # MemoryLimit.MIN: every controller shard can host >=1 action


def generate_hash(namespace: str, action: str) -> int:
    """Stable 31-bit hash of (namespace, fully-qualified action name).

    The reference uses JVM String.hashCode xor; any stable uniform hash
    preserves the semantics (deterministic home per action). CRC32 is stable
    across Python processes and cheap to mirror on device.
    """
    return zlib.crc32(f"{namespace}/{action}".encode()) & 0x7FFFFFFF


def pairwise_coprimes(x: int) -> List[int]:
    """Greedy list of numbers <= x coprime to x and pairwise coprime
    (ref pairwiseCoprimeNumbersUntil): for x=10 -> [1, 3, 7]."""
    out: List[int] = []
    for cur in range(1, x + 1):
        if math.gcd(cur, x) == 1 and all(math.gcd(cur, p) == 1 for p in out):
            out.append(cur)
    return out or [1]


@dataclass
class InvokerSlotState:
    """One invoker as seen by one controller: its share of memory permits."""
    instance: int
    semaphore: NestedSemaphore
    usable: bool = True
    user_memory_mb: int = 2048


@dataclass
class ShardingPolicyState:
    """The balancer's scheduling state for one controller."""
    invokers: List[InvokerSlotState] = field(default_factory=list)
    cluster_size: int = 1
    managed_fraction: float = 0.9
    blackbox_fraction: float = 0.1
    step_sizes_managed: List[int] = field(default_factory=lambda: [1])
    step_sizes_blackbox: List[int] = field(default_factory=lambda: [1])

    # -- setup -------------------------------------------------------------
    @classmethod
    def build(cls, invoker_memories_mb: List[int], cluster_size: int = 1,
              managed_fraction: float = 0.9, blackbox_fraction: float = 0.1
              ) -> "ShardingPolicyState":
        s = cls(cluster_size=cluster_size, managed_fraction=managed_fraction,
                blackbox_fraction=blackbox_fraction)
        for i, mem in enumerate(invoker_memories_mb):
            s.invokers.append(InvokerSlotState(
                i, NestedSemaphore(s.invoker_slot_mb(mem)), True, mem))
        s._recompute_steps()
        return s

    def invoker_slot_mb(self, user_memory_mb: int) -> int:
        """getInvokerSlot (:485-499): this controller's share, floored at one
        minimal action slot (knowingly overcommitting when clusterSize >
        memory/minSlot)."""
        share = user_memory_mb // self.cluster_size
        return max(share, MIN_SLOT_MB)

    def _recompute_steps(self) -> None:
        n = len(self.invokers)
        self.step_sizes_managed = pairwise_coprimes(max(1, self.managed_count))
        self.step_sizes_blackbox = pairwise_coprimes(max(1, self.blackbox_count))

    # -- fleet partitioning (:461-468) --------------------------------------
    # numInvokers(fraction, n) = max(n * fraction, 1).toInt — computed
    # independently per class; the slices may overlap for small fleets,
    # exactly as in the reference.
    @property
    def blackbox_count(self) -> int:
        n = len(self.invokers)
        if n == 0:
            return 0
        return max(int(self.blackbox_fraction * n), 1)

    @property
    def managed_count(self) -> int:
        n = len(self.invokers)
        if n == 0:
            return 0
        return max(int(self.managed_fraction * n), 1)

    def partition(self, blackbox: bool) -> Tuple[int, int]:
        """(offset, size) of the fleet slice for this workload class:
        managed = first managed_count, blackbox = last blackbox_count."""
        n = len(self.invokers)
        if n == 0:
            return 0, 0
        if blackbox:
            return n - self.blackbox_count, self.blackbox_count
        return 0, self.managed_count

    # -- elasticity (:512-584) ----------------------------------------------
    def update_invokers(self, invoker_memories_mb: List[int],
                        usable: Optional[List[bool]] = None) -> None:
        """Grow in place / refresh capacities (shrink is by health only)."""
        for i, mem in enumerate(invoker_memories_mb):
            if i < len(self.invokers):
                inv = self.invokers[i]
                inv.user_memory_mb = mem
                if usable is not None:
                    inv.usable = usable[i]
            else:
                self.invokers.append(InvokerSlotState(
                    i, NestedSemaphore(self.invoker_slot_mb(mem)), True, mem))
                if usable is not None:
                    self.invokers[i].usable = usable[i]
        self._recompute_steps()

    def update_cluster(self, cluster_size: int) -> None:
        """Re-shard capacity when controllers join/leave (:561-584): rebuild
        semaphores at the new share (in-flight permits are intentionally
        reset, exactly as the reference swaps in fresh semaphores)."""
        if cluster_size != self.cluster_size:
            self.cluster_size = cluster_size
            for inv in self.invokers:
                inv.semaphore = NestedSemaphore(
                    self.invoker_slot_mb(inv.user_memory_mb))

    def set_health(self, instance: int, usable: bool) -> None:
        if 0 <= instance < len(self.invokers):
            self.invokers[instance].usable = usable


def schedule(state: ShardingPolicyState, namespace: str, action: str,
             memory_mb: int, max_concurrent: int = 1, blackbox: bool = False,
             rng: Optional[random.Random] = None,
             forced_rand: Optional[int] = None
             ) -> Tuple[Optional[int], bool]:
    """One placement decision (ref schedule :398-436 + publish :257-317).

    Returns (invoker_instance | None, forced): probes the home invoker and
    then steps through the partition in a coprime progression, acquiring the
    first free slot; on total overload, forces a random usable invoker; with
    no usable invokers at all, returns None.
    """
    offset, size = state.partition(blackbox)
    if size == 0:
        return None, False
    h = generate_hash(namespace, action)
    steps = state.step_sizes_blackbox if blackbox else state.step_sizes_managed
    home = h % size
    step = steps[h % len(steps)]
    action_key = f"{action}:{memory_mb}"  # per-(action,mem) concurrency pool

    idx = home
    for _ in range(size):
        inv = state.invokers[offset + idx]
        if inv.usable and inv.semaphore.try_acquire_concurrent(
                action_key, max_concurrent, memory_mb):
            return inv.instance, False
        idx = (idx + step) % size

    # overload: force a random usable invoker (:417-424). With `forced_rand`
    # the choice is a deterministic rotation — the same rule the device
    # kernel uses, so host-passed randomness keeps both paths in lockstep.
    if forced_rand is not None:
        best = None
        for i in range(size):
            inv = state.invokers[offset + i]
            if inv.usable:
                r = (i - forced_rand) % size
                if best is None or r < best[0]:
                    best = (r, inv)
        if best is None:
            return None, False
        chosen = best[1]
    else:
        usable = [state.invokers[offset + i] for i in range(size)
                  if state.invokers[offset + i].usable]
        if not usable:
            return None, False
        rng = rng or random
        chosen = usable[rng.randrange(len(usable))]
    chosen.semaphore.force_acquire_concurrent(action_key, max_concurrent, memory_mb)
    return chosen.instance, True


def release(state: ShardingPolicyState, invoker_instance: int, action: str,
            memory_mb: int, max_concurrent: int = 1) -> None:
    """Release the slot on completion ack (ref releaseInvoker)."""
    if 0 <= invoker_instance < len(state.invokers):
        state.invokers[invoker_instance].semaphore.release_concurrent(
            f"{action}:{memory_mb}", max_concurrent, memory_mb)

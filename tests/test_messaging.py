"""Messaging tests (mirrors reference MessageFeedTests + TestConnector use)."""
import asyncio
import json

import pytest

from openwhisk_tpu.core.entity import (ActivationId, ControllerInstanceId,
                                       EntityName, EntityPath,
                                       FullyQualifiedEntityName, Identity,
                                       InvokerInstanceId, Subject,
                                       ActivationResponse, WhiskActivation)
from openwhisk_tpu.messaging import (ActivationMessage,
                                     CombinedCompletionAndResultMessage,
                                     CompletionMessage, MemoryMessagingProvider,
                                     MessageFeed, PingMessage, ResultMessage,
                                     parse_ack)
from openwhisk_tpu.utils.transaction import TransactionId


def _identity():
    return Identity.generate("guest")


def _activation_message(blocking=True):
    return ActivationMessage(
        TransactionId(), FullyQualifiedEntityName.parse("guest/hello"),
        "1-abc", _identity(), ActivationId.generate(),
        ControllerInstanceId("0"), blocking, {"payload": "x"})


class TestMessageSerde:
    def test_activation_message_roundtrip(self):
        m = _activation_message()
        r = ActivationMessage.parse(m.serialize())
        assert r.activation_id == m.activation_id
        assert str(r.action) == "guest/hello"
        assert r.blocking
        assert r.content == {"payload": "x"}

    def test_ack_roundtrips(self):
        act = WhiskActivation(EntityPath("guest"), EntityName("hello"),
                              Subject("guest-user"), ActivationId.generate(),
                              1.0, 2.0, ActivationResponse.success({"a": 1}))
        inv = InvokerInstanceId(3)
        for msg in (CompletionMessage(TransactionId(), act.activation_id, False, inv),
                    ResultMessage(TransactionId(), act),
                    CombinedCompletionAndResultMessage(TransactionId(), act, inv)):
            r = parse_ack(msg.serialize())
            assert type(r) is type(msg)
            assert r.activation_id == act.activation_id
        c = parse_ack(CombinedCompletionAndResultMessage(TransactionId(), act, inv).serialize())
        assert c.is_slot_free and c.invoker.instance == 3
        assert c.activation.response.result == {"a": 1}
        res = parse_ack(ResultMessage(TransactionId(), act).serialize())
        assert not res.is_slot_free

    def test_ping(self):
        p = PingMessage.parse(PingMessage(InvokerInstanceId(7)).serialize())
        assert p.instance.instance == 7


class TestMemoryBus:
    def test_produce_consume(self):
        async def run():
            prov = MemoryMessagingProvider()
            prod = prov.get_producer()
            cons = prov.get_consumer("t", "g")
            await prod.send("t", b"m1")
            await prod.send("t", b"m2")
            batch = await cons.peek(10)
            cons.commit()
            return [p for (_, _, _, p) in batch]

        assert asyncio.run(run()) == [b"m1", b"m2"]

    def test_messages_before_subscribe_are_retained(self):
        async def run():
            prov = MemoryMessagingProvider()
            prod = prov.get_producer()
            await prod.send("t", b"early")
            cons = prov.get_consumer("t", "g")
            batch = await cons.peek(10)
            return [p for (_, _, _, p) in batch]

        assert asyncio.run(run()) == [b"early"]

    def test_competing_consumers_split_messages(self):
        async def run():
            prov = MemoryMessagingProvider()
            prod = prov.get_producer()
            c1 = prov.get_consumer("t", "g")
            c2 = prov.get_consumer("t", "g")
            for i in range(4):
                await prod.send("t", f"m{i}".encode())
            b1 = await c1.peek(2)
            b2 = await c2.peek(2)
            return len(b1) + len(b2)

        assert asyncio.run(run()) == 4


class TestMessageFeed:
    def test_backpressure_and_delivery(self):
        async def run():
            prov = MemoryMessagingProvider()
            prod = prov.get_producer()
            cons = prov.get_consumer("activations", "invoker0")
            received = []
            feeds = {}

            async def handler(payload: bytes):
                received.append(payload)
                # simulate async completion later
                async def done():
                    await asyncio.sleep(0.01)
                    feeds["f"].processed()
                asyncio.get_event_loop().create_task(done())

            feed = MessageFeed("test", cons, maximum_handler_capacity=2,
                               handler=handler, long_poll_timeout=0.05)
            feeds["f"] = feed
            feed.start()
            for i in range(6):
                await prod.send("activations", f"m{i}".encode())
            await asyncio.sleep(0.3)
            await feed.stop()
            return received

        received = asyncio.run(run())
        assert received == [f"m{i}".encode() for i in range(6)]

    def test_capacity_limits_inflight(self):
        async def run():
            prov = MemoryMessagingProvider()
            prod = prov.get_producer()
            cons = prov.get_consumer("t", "g")
            inflight = {"now": 0, "max": 0}
            feeds = {}

            async def handler(payload: bytes):
                inflight["now"] += 1
                inflight["max"] = max(inflight["max"], inflight["now"])

                async def done():
                    await asyncio.sleep(0.02)
                    inflight["now"] -= 1
                    feeds["f"].processed()
                asyncio.get_event_loop().create_task(done())

            feed = MessageFeed("test", cons, maximum_handler_capacity=3,
                               handler=handler, long_poll_timeout=0.05)
            feeds["f"] = feed
            feed.start()
            for i in range(12):
                await prod.send("t", f"m{i}".encode())
            await asyncio.sleep(0.4)
            await feed.stop()
            return inflight["max"]

        assert asyncio.run(run()) <= 3

    def test_handler_error_does_not_kill_feed(self):
        async def run():
            prov = MemoryMessagingProvider()
            prod = prov.get_producer()
            cons = prov.get_consumer("t", "g")
            good = []
            feeds = {}

            async def handler(payload: bytes):
                if payload == b"bad":
                    raise RuntimeError("boom")
                good.append(payload)
                feeds["f"].processed()

            feed = MessageFeed("test", cons, maximum_handler_capacity=2,
                               handler=handler, long_poll_timeout=0.05)
            feeds["f"] = feed
            feed.start()
            await prod.send("t", b"bad")
            await prod.send("t", b"ok")
            await asyncio.sleep(0.2)
            await feed.stop()
            return good

        assert asyncio.run(run()) == [b"ok"]

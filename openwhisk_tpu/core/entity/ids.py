"""Identifiers: activation ids, subjects, auth keys, doc ids, instance ids.

Refs: ActivationId.scala, Subject.scala, AuthKey.scala, DocInfo.scala,
InstanceId.scala (common/scala/.../core/entity/).
"""
from __future__ import annotations

import os
import re
import secrets
import uuid
from dataclasses import dataclass
from typing import Optional


class ActivationId:
    """32-lowercase-hex activation id (ref ActivationId.scala: UUID sans
    dashes; accepts UUID-with-dashes on parse)."""

    __slots__ = ("asString",)
    _RX = re.compile(r"^[0-9a-f]{32}$")

    def __init__(self, as_string: str):
        s = as_string.replace("-", "").lower()
        if not self._RX.match(s):
            raise ValueError(f"activation id is not valid: {as_string!r}")
        self.asString = s

    @classmethod
    def generate(cls) -> "ActivationId":
        # os.urandom(16).hex() is 32 lowercase hex by construction — the
        # same 128 random bits as uuid4().hex at ~1/4 the cost (uuid4
        # builds a UUID object, int-converts and re-formats; id minting
        # is once per activation on the publish hot path and showed up
        # in the host observatory's self-time census)
        aid = object.__new__(cls)
        aid.asString = os.urandom(16).hex()
        return aid

    def to_json(self) -> str:
        return self.asString

    @classmethod
    def from_json(cls, j) -> "ActivationId":
        return cls(str(j))

    def __eq__(self, other):
        return isinstance(other, ActivationId) and self.asString == other.asString

    def __hash__(self):
        return hash(self.asString)

    def __repr__(self):
        return self.asString


@dataclass(frozen=True)
class Subject:
    """An authenticated subject name, >= 5 chars (ref Subject.scala)."""
    asString: str

    def __post_init__(self):
        if len(self.asString) < 5:
            raise ValueError("subject must be at least 5 characters")

    @classmethod
    def generate(cls) -> "Subject":
        return cls("anon-" + secrets.token_hex(8))

    def to_json(self):
        return self.asString

    def __str__(self):
        return self.asString


@dataclass(frozen=True)
class UUID:
    """Namespace uuid (ref UUID in entity package)."""
    asString: str

    @classmethod
    def generate(cls) -> "UUID":
        return cls(str(uuid.uuid4()))

    def to_json(self):
        return self.asString

    def __str__(self):
        return self.asString


@dataclass(frozen=True)
class Secret:
    asString: str

    @classmethod
    def generate(cls) -> "Secret":
        return cls(secrets.token_hex(32))

    def to_json(self):
        return self.asString


@dataclass(frozen=True)
class BasicAuthenticationAuthKey:
    """uuid:key credential pair (ref BasicAuthenticationAuthKey.scala)."""
    uuid: UUID
    key: Secret

    @classmethod
    def generate(cls) -> "BasicAuthenticationAuthKey":
        return cls(UUID.generate(), Secret.generate())

    @classmethod
    def parse(cls, compact: str) -> "BasicAuthenticationAuthKey":
        u, _, k = compact.partition(":")
        if not u or not k:
            raise ValueError("malformed auth key, want '<uuid>:<key>'")
        return cls(UUID(u), Secret(k))

    @property
    def compact(self) -> str:
        return f"{self.uuid.asString}:{self.key.asString}"

    def to_json(self):
        return {"api_key": self.compact}


@dataclass(frozen=True)
class DocRevision:
    rev: Optional[str] = None

    @property
    def empty(self) -> bool:
        return self.rev is None

    def to_json(self):
        return self.rev

    def __repr__(self):
        return self.rev or ""


@dataclass(frozen=True)
class DocInfo:
    """Document id + revision (ref DocInfo.scala)."""
    id: str
    rev: DocRevision = DocRevision()

    def to_json(self):
        return {"id": self.id, "rev": self.rev.to_json()}


class InstanceId:
    """Numbered component instance (ref InstanceId.scala:31-60)."""

    __slots__ = ("instance", "unique_name", "display_name")
    prefix = "instance"

    def __init__(self, instance: int, unique_name: Optional[str] = None,
                 display_name: Optional[str] = None):
        if instance < 0:
            raise ValueError("instance id must be >= 0")
        self.instance = instance
        self.unique_name = unique_name
        self.display_name = display_name

    @property
    def as_string(self) -> str:
        return f"{self.prefix}{self.instance}"

    def to_json(self):
        return {"instance": self.instance, "uniqueName": self.unique_name,
                "displayName": self.display_name, "instanceType": self.prefix}

    @classmethod
    def from_json(cls, j) -> "InstanceId":
        return cls(int(j["instance"]), j.get("uniqueName"), j.get("displayName"))

    def __eq__(self, other):
        return type(self) is type(other) and self.instance == other.instance

    def __hash__(self):
        return hash((self.prefix, self.instance))

    def __repr__(self):
        return self.as_string


class InvokerInstanceId(InstanceId):
    """Invoker N; carries its user-memory pool size for the balancer
    (ref InstanceId.scala InvokerInstanceId with userMemory)."""
    prefix = "invoker"
    __slots__ = ("user_memory",)

    def __init__(self, instance: int, unique_name: Optional[str] = None,
                 display_name: Optional[str] = None, user_memory: Optional[object] = None):
        super().__init__(instance, unique_name, display_name)
        from .size import MB, ByteSize
        self.user_memory: ByteSize = user_memory if user_memory is not None else MB(2048)

    def to_json(self):
        j = super().to_json()
        j["userMemory"] = self.user_memory.to_json()
        return j

    @classmethod
    def from_json(cls, j) -> "InvokerInstanceId":
        from .size import ByteSize
        um = j.get("userMemory")
        return cls(int(j["instance"]), j.get("uniqueName"), j.get("displayName"),
                   ByteSize.from_json(um) if um is not None else None)


class ControllerInstanceId(InstanceId):
    prefix = "controller"

    def __init__(self, asString: str | int):
        if isinstance(asString, int):
            super().__init__(asString)
            self.name = str(asString)
        else:
            try:
                super().__init__(int(asString))
            except ValueError:
                super().__init__(abs(hash(asString)) % (2**31))
            self.name = str(asString)

    @property
    def as_string(self) -> str:
        return f"{self.prefix}{self.name}"

"""owperf equivalent: rule (trigger->action) vs direct-action performance.

Parity with the reference's tools/owperf (tools/owperf/README.md:19-46): for
each sample, fire a trigger bound to a rule (or invoke the action directly),
then mine the resulting activation records for the client-observed latency
plus the system's own timing breakdown — the `waitTime` annotation (queueing:
balancer + bus + pool), `initTime` (cold-start init) and `duration` (user
code) — and emit per-phase statistics as CSV, one row per measurement, like
owperf's CSV output mode.

    python tests/performance/owperf.py --samples 50 --ratio 2
"""
from __future__ import annotations

import argparse
import asyncio
import statistics
import sys
import time

try:
    from harness import NOOP_CODE, Client, open_loop, run_with_standalone
except ImportError:
    from .harness import NOOP_CODE, Client, open_loop, run_with_standalone


def _summary(name: str, xs) -> str:
    if not xs:
        return f"{name},0,,,,"
    xs = sorted(xs)
    return (f"{name},{len(xs)},{statistics.mean(xs):.2f},"
            f"{xs[int(0.5 * (len(xs) - 1))]:.2f},"
            f"{xs[int(0.9 * (len(xs) - 1))]:.2f},{xs[-1]:.2f}")


async def _activation_timings(client: Client, activation_id: str,
                              tries: int = 80) -> dict:
    """Poll the activation record; return its timing annotations."""
    for _ in range(tries):
        status, act = await client.get(f"/namespaces/_/activations/{activation_id}")
        if status == 200:
            ann = {a["key"]: a["value"] for a in act.get("annotations", [])}
            return {"waitTime": ann.get("waitTime", 0),
                    "initTime": ann.get("initTime", 0),
                    "duration": act.get("duration", 0)}
        await asyncio.sleep(0.05)
    return {}


async def _main(client: Client, samples: int, ratio: int,
                rate: float = 0.0) -> None:
    # setup: one action, one trigger, `ratio` rules binding them
    assert await client.put_action("owperf-act") == 200
    async with client.session.put(
            f"{client.base}/namespaces/_/triggers/owperf-t?overwrite=true",
            headers=client.headers, json={}) as r:
        assert r.status == 200, r.status
    for i in range(ratio):
        async with client.session.put(
                f"{client.base}/namespaces/_/rules/owperf-r{i}?overwrite=true",
                headers=client.headers,
                json={"trigger": "_/owperf-t", "action": "_/owperf-act"}) as r:
            assert r.status == 200, await r.text()
    await client.invoke("owperf-act")  # warm the sandbox

    e2e_action, e2e_rule = [], []
    waits, inits, durs = [], [], []

    # direct action samples (owperf "action" test). With --rate the phase
    # runs OPEN-loop through the shared arrival schedule (tools/loadgen
    # via harness.open_loop): invokes fire at scheduled times, latency is
    # measured from the schedule, and the record mining happens after the
    # drive so polling never perturbs the arrival process.
    if rate > 0:
        aids = []

        async def one(i: int) -> bool:
            status, body = await client.invoke("owperf-act")
            if status != 200:
                return False
            aids.append(body["activationId"])
            return True

        stats = await open_loop(samples, rate, one)
        e2e_action = stats.samples_ms
        if stats.errors:
            print(f"{stats.errors} open-loop action samples failed",
                  file=sys.stderr)
        for aid in aids:
            t = await _activation_timings(client, aid)
            if not t:
                print(f"activation {aid} record missing", file=sys.stderr)
                continue
            waits.append(t["waitTime"])
            inits.append(t["initTime"])
            durs.append(t["duration"])
    else:
        for _ in range(samples):
            t0 = time.perf_counter()
            status, body = await client.invoke("owperf-act")
            e2e_action.append((time.perf_counter() - t0) * 1e3)
            assert status == 200
            t = await _activation_timings(client, body["activationId"])
            if not t:  # record never surfaced: drop, don't zero-fill
                print(f"activation {body['activationId']} record missing",
                      file=sys.stderr)
                continue
            waits.append(t["waitTime"])
            inits.append(t["initTime"])
            durs.append(t["duration"])

    # rule samples (owperf "rule" test): fire -> poll for the rule-driven
    # activation recorded in the trigger activation's log entries
    for _ in range(samples):
        t0 = time.perf_counter()
        status, body = await client.post("/namespaces/_/triggers/owperf-t")
        assert status == 202, status
        trig_id = body["activationId"]
        # the trigger activation logs carry per-rule action activation ids
        action_ids = []
        for _ in range(80):
            s, act = await client.get(f"/namespaces/_/activations/{trig_id}")
            if s == 200 and act.get("logs"):
                import json as _json
                action_ids = [aid for aid in
                              (_json.loads(l).get("activationId")
                               for l in act["logs"]) if aid]
                break
            await asyncio.sleep(0.05)
        deadline = time.perf_counter() + 30.0
        done = 0
        while done < len(action_ids) and time.perf_counter() < deadline:
            done = 0
            for aid in action_ids:
                s, _ = await client.get(f"/namespaces/_/activations/{aid}")
                done += (s == 200)
            if done < len(action_ids):
                await asyncio.sleep(0.05)
        if not action_ids or done < len(action_ids):
            print(f"rule sample dropped: {done}/{len(action_ids)} "
                  "activations surfaced within 30s", file=sys.stderr)
            continue
        e2e_rule.append((time.perf_counter() - t0) * 1e3)

    print("phase,samples,mean_ms,p50_ms,p90_ms,max_ms")
    print(_summary("action_e2e" + (f"_open@{rate:g}" if rate > 0 else ""),
                   e2e_action))
    print(_summary(f"rule_e2e_x{ratio}", e2e_rule))
    print(_summary("waitTime", waits))
    print(_summary("initTime", inits))
    print(_summary("duration", durs))


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--samples", type=int, default=50)
    ap.add_argument("--ratio", type=int, default=1,
                    help="rules per trigger (owperf -ratio)")
    ap.add_argument("--rate", type=float, default=0.0,
                    help="open-loop offered rate for the action phase "
                         "(requests/s, 0 = legacy closed-loop sampling)")
    ap.add_argument("--port", type=int, default=13377)
    args = ap.parse_args()

    async def go(client: Client):
        await _main(client, args.samples, args.ratio, rate=args.rate)

    run_with_standalone(go, port=args.port)


if __name__ == "__main__":
    main()

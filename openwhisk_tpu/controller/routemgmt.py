"""API-gateway route management.

Rebuild of core/routemgmt/ (reference: createApi/createApi.js, getApi/getApi.js,
deleteApi/deleteApi.js, common/apigw-utils.js) — in the reference these are
JavaScript *actions* installed into the system namespace that CRUD route
documents in an external API gateway. Here route management is a first-class
controller service instead of a loopback through the action path: API
definitions are swagger-shaped documents in the artifact store (collection
``apis``), and the edge proxy (openwhisk_tpu.edge) serves them by forwarding
matched requests to the target web action — the role the external gateway
plays in the reference deployment.

Document shape follows the gateway's generated swagger (apigw-utils.js
``generateBaseSwaggerApi``/``addEndpointToSwaggerApi``): one doc per
(namespace, basePath) holding ``paths[relPath][verb]`` operations, each
carrying an ``x-openwhisk`` block naming the backing web action.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from ..database import NoDocumentException
from ..database.store import ArtifactStore

VERBS = ("get", "put", "post", "delete", "patch", "head", "options")
RESPONSE_TYPES = ("json", "http", "text", "html", "svg")


class ApiManagementException(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message


def _doc_id(namespace: str, base_path: str) -> str:
    return f"{namespace}/apis{base_path}"


def _normalize_base_path(base_path: str) -> str:
    if not base_path.startswith("/"):
        base_path = "/" + base_path
    return base_path.rstrip("/") or "/"


def _normalize_rel_path(rel_path: str) -> str:
    if not rel_path.startswith("/"):
        rel_path = "/" + rel_path
    return rel_path


class ApiRouteManager:
    """CRUD of API route documents + route matching for the edge proxy."""

    def __init__(self, store: ArtifactStore, api_host: str = "",
                 route_table_ttl: float = 2.0):
        self.store = store
        self.api_host = api_host
        # match() runs on the edge hot path for every non-/api request: keep a
        # short-TTL snapshot of the route table instead of querying the store
        # per request; writes through this manager invalidate it immediately.
        self.route_table_ttl = route_table_ttl
        self._route_docs: Optional[List[Dict[str, Any]]] = None
        self._route_docs_expiry = 0.0

    def _invalidate_routes(self) -> None:
        self._route_docs = None
        self._route_docs_expiry = 0.0

    # ------------------------------------------------------------- create
    async def create_api(self, namespace: str, apidoc: Dict[str, Any]
                         ) -> Dict[str, Any]:
        """createApi.js semantics: add/update one endpoint (or install a full
        swagger doc) under `namespace`."""
        if "swagger" in apidoc:
            return await self._put_swagger(namespace, apidoc["swagger"])

        for field in ("gatewayBasePath", "gatewayPath", "gatewayMethod", "action"):
            if field not in apidoc:
                raise ApiManagementException(
                    400, f"Missing required field '{field}' in apidoc")
        verb = apidoc["gatewayMethod"].lower()
        if verb not in VERBS:
            raise ApiManagementException(400, f"Invalid gatewayMethod '{verb}'")
        action = apidoc["action"]
        for field in ("name", "namespace"):
            if field not in action:
                raise ApiManagementException(
                    400, f"Missing required field 'action.{field}' in apidoc")
        response_type = apidoc.get("responsetype", "json")
        if response_type not in RESPONSE_TYPES:
            raise ApiManagementException(
                400, f"Invalid responsetype '{response_type}'")

        base_path = _normalize_base_path(apidoc["gatewayBasePath"])
        rel_path = _normalize_rel_path(apidoc["gatewayPath"])
        doc_id = _doc_id(namespace, base_path)
        try:
            doc = await self.store.get(doc_id)
        except NoDocumentException:
            doc = self._base_doc(namespace, base_path,
                                 apidoc.get("apiName") or base_path)
        if apidoc.get("apiName"):
            doc["apiName"] = apidoc["apiName"]
        op = {
            "operationId": f"{verb}{rel_path}",
            "responses": {"default": {"description": "Default response"}},
            "x-openwhisk": {
                "namespace": action["namespace"],
                "package": action.get("package", ""),
                "action": action["name"].split("/")[-1],
                "responsetype": response_type,
                "url": self._backend_url(action, response_type),
            },
        }
        doc.setdefault("swagger", {}).setdefault("paths", {}) \
           .setdefault(rel_path, {})[verb] = op
        doc["updated"] = time.time()
        rev = await self.store.put(doc_id, doc, rev=doc.get("_rev"))
        doc["_rev"] = rev
        self._invalidate_routes()
        return self._public_view(doc)

    async def _put_swagger(self, namespace: str, swagger: Dict[str, Any]
                           ) -> Dict[str, Any]:
        # validate per-operation shape up front: match() relies on every
        # operation carrying an x-openwhisk block naming the backing action
        for rel, ops in (swagger.get("paths") or {}).items():
            if not isinstance(ops, dict):
                raise ApiManagementException(
                    400, f"swagger path {rel!r} must map verbs to operations")
            for verb, op in ops.items():
                if verb not in VERBS:
                    raise ApiManagementException(
                        400, f"Invalid verb {verb!r} at swagger path {rel!r}")
                xow = op.get("x-openwhisk") if isinstance(op, dict) else None
                if not isinstance(xow, dict) or "namespace" not in xow \
                        or "action" not in xow:
                    raise ApiManagementException(
                        400, f"operation {verb} {rel} must carry an "
                             "x-openwhisk block with namespace and action")
        base_path = _normalize_base_path(swagger.get("basePath", "/"))
        doc_id = _doc_id(namespace, base_path)
        try:
            existing = await self.store.get(doc_id)
            rev = existing.get("_rev")
        except NoDocumentException:
            rev = None
        doc = self._base_doc(namespace, base_path,
                             swagger.get("info", {}).get("title") or base_path)
        doc["swagger"] = swagger
        doc["updated"] = time.time()
        doc["_rev"] = await self.store.put(doc_id, doc, rev=rev)
        self._invalidate_routes()
        return self._public_view(doc)

    # ---------------------------------------------------------------- get
    async def get_apis(self, namespace: str,
                       base_path: Optional[str] = None,
                       rel_path: Optional[str] = None,
                       verb: Optional[str] = None) -> List[Dict[str, Any]]:
        """getApi.js semantics: list APIs, optionally filtered down to one
        basePath (or apiName), relPath, and verb."""
        docs = await self.store.query("apis", namespace, limit=1000)
        out = []
        for doc in docs:
            if base_path and doc.get("basePath") != _normalize_base_path(base_path) \
                    and doc.get("apiName") != base_path:
                continue
            view = self._public_view(doc)
            if rel_path or verb:
                paths = view["swagger"].get("paths", {})
                rel = _normalize_rel_path(rel_path) if rel_path else None
                filtered = {}
                for p, ops in paths.items():
                    if rel and p != rel:
                        continue
                    ops = {v: op for v, op in ops.items()
                           if verb is None or v == verb.lower()}
                    if ops:
                        filtered[p] = ops
                if not filtered:
                    continue
                view["swagger"] = dict(view["swagger"], paths=filtered)
            out.append(view)
        return out

    # ------------------------------------------------------------- delete
    async def delete_api(self, namespace: str, base_path: str,
                         rel_path: Optional[str] = None,
                         verb: Optional[str] = None) -> None:
        """deleteApi.js semantics: delete the whole API, one path, or one
        operation; the doc disappears when its last operation does."""
        base_path = _normalize_base_path(base_path)
        doc_id = _doc_id(namespace, base_path)
        doc = await self.store.get(doc_id)  # NoDocumentException → 404 upstream
        if rel_path is None:
            await self.store.delete(doc_id, rev=doc.get("_rev"))
            self._invalidate_routes()
            return
        rel = _normalize_rel_path(rel_path)
        paths = doc.get("swagger", {}).get("paths", {})
        if rel not in paths:
            raise NoDocumentException(f"no such path {rel}")
        if verb is None:
            del paths[rel]
        else:
            v = verb.lower()
            if v not in paths[rel]:
                raise NoDocumentException(f"no such operation {v} {rel}")
            del paths[rel][v]
            if not paths[rel]:
                del paths[rel]
        if not paths:
            await self.store.delete(doc_id, rev=doc.get("_rev"))
        else:
            doc["updated"] = time.time()
            await self.store.put(doc_id, doc, rev=doc.get("_rev"))
        self._invalidate_routes()

    # ------------------------------------------------------------ routing
    async def match(self, method: str, path: str
                    ) -> Optional[Dict[str, Any]]:
        """Edge-proxy lookup: longest-basePath-prefix match of (method, path)
        over every namespace's APIs → the operation's x-openwhisk block."""
        verb = method.lower()
        now = time.monotonic()
        if self._route_docs is None or now >= self._route_docs_expiry:
            self._route_docs = await self.store.query("apis", None, limit=10_000)
            self._route_docs_expiry = now + self.route_table_ttl
        docs = self._route_docs
        best: Optional[Dict[str, Any]] = None
        best_len = -1
        for doc in docs:
            base = doc.get("basePath", "")
            if not (path == base or path.startswith(base.rstrip("/") + "/")):
                continue
            if len(base) <= best_len:
                continue
            rel = path[len(base.rstrip("/")):] or "/"
            ops = doc.get("swagger", {}).get("paths", {}).get(rel, {})
            op = ops.get(verb)
            if isinstance(op, dict) and isinstance(op.get("x-openwhisk"), dict):
                best = op["x-openwhisk"]
                best_len = len(base)
        return best

    # ------------------------------------------------------------ helpers
    def _base_doc(self, namespace: str, base_path: str, api_name: str
                  ) -> Dict[str, Any]:
        return {
            "entityType": "apis",
            "namespace": namespace,
            "name": base_path,
            "basePath": base_path,
            "apiName": api_name,
            "swagger": {
                "swagger": "2.0",
                "basePath": base_path,
                "info": {"title": api_name, "version": "1.0.0"},
                "paths": {},
            },
            "updated": time.time(),
        }

    def _backend_url(self, action: Dict[str, Any], response_type: str) -> str:
        if action.get("backendUrl"):  # caller supplied the full URL
            return action["backendUrl"]
        pkg = action.get("package") or "default"
        name = action["name"].split("/")[-1]
        return (f"{self.api_host}/api/v1/web/{action['namespace']}/{pkg}/"
                f"{name}.{response_type}")

    @staticmethod
    def _public_view(doc: Dict[str, Any]) -> Dict[str, Any]:
        return {
            "namespace": doc["namespace"],
            "basePath": doc["basePath"],
            "apiName": doc.get("apiName", doc["basePath"]),
            "swagger": doc.get("swagger", {}),
        }

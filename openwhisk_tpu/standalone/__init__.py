"""Standalone server: controller + lean balancer + in-process invoker.

Rebuild of core/standalone/.../StandaloneOpenWhisk.scala — a single process
serving the full API on one port with an in-memory (or sqlite) store, the
in-memory bus, a LeanBalancer and an in-process InvokerReactive running
subprocess action sandboxes. Boots with a `guest` identity whose credentials
are printed (and stable for dev use).
"""
from __future__ import annotations

import asyncio
from typing import Optional

from ..containerpool import ContainerPoolConfig
from ..containerpool.logstore import ContainerLogStore
from ..containerpool.process_factory import ProcessContainerFactory
from ..controller.core import Controller
from ..controller.loadbalancer.lean import LeanBalancer
from ..core.entity import (BasicAuthenticationAuthKey, ControllerInstanceId,
                           EntityName, ExecManifest, Identity, InvokerInstanceId,
                           MB, Namespace, Secret, Subject, UUID, WhiskAuthRecord)
from ..database import ArtifactActivationStore, EntityStore
from ..invoker.reactive import InvokerReactive
from ..messaging.memory import MemoryMessagingProvider
from ..utils.logging import Logging

# stable dev credentials (standalone/dev only, like the reference's guest key)
GUEST_UUID = "2c9f4ad1-4a5e-4d7e-9b11-2c9f4ad10e66"
GUEST_KEY = "tpu-native-openwhisk-standalone-guest-key-0123456789abcdef012345"


def guest_identity() -> Identity:
    return Identity(Subject("guest-subject"),
                    Namespace(EntityName("guest"), UUID(GUEST_UUID)),
                    BasicAuthenticationAuthKey(UUID(GUEST_UUID), Secret(GUEST_KEY)))


async def make_standalone(port: int = 3233, artifact_store=None,
                          user_memory_mb: int = 2048, logger=None,
                          prewarm: bool = False, manifest: Optional[dict] = None,
                          balancer: str = "lean", ui: bool = True,
                          snapshot_path: Optional[str] = None,
                          snapshot_interval: float = 10.0,
                          journal_dir: Optional[str] = None,
                          **controller_kw) -> Controller:
    """Assemble and start a standalone server; returns the running Controller.

    balancer: "lean" (in-process dispatch, no supervision — the reference's
    LeanBalancer mode) or "tpu" (the device placement kernel fed by the
    in-process invoker's real health pings). Extra keyword arguments pass
    through to Controller (e.g. invocations_per_minute for perf runs that
    must not trip the default throttles).

    snapshot_path/journal_dir (tpu balancer only): checkpoint/journal the
    balancer's books — restored at boot (snapshot + deterministic journal
    tail replay) and dumped one final time on a clean shutdown, wired
    through Controller.owned_resources so SIGTERM cannot skip the final
    dump."""
    logger = logger or Logging(level="warn")
    ExecManifest.initialize(manifest)
    provider = MemoryMessagingProvider()
    instance = ControllerInstanceId("0")

    async def invoker_factory(invoker_id, messaging_provider):
        store = controller.artifact_store
        invoker = InvokerReactive(
            invoker_id, messaging_provider,
            EntityStore(store),
            ArtifactActivationStore(store),
            ProcessContainerFactory(logger=logger),
            pool_config=ContainerPoolConfig(user_memory=MB(user_memory_mb),
                                            pause_grace=1.0),
            logstore=ContainerLogStore(), logger=logger)
        await invoker.start(start_prewarm=prewarm)
        return invoker

    journal = None
    snapshotter = None
    if balancer == "tpu":
        from ..controller.loadbalancer.tpu_balancer import TpuBalancer
        lb = TpuBalancer(provider, instance, logger=logger,
                         metrics=logger.metrics,
                         managed_fraction=1.0, blackbox_fraction=0.0)
        if snapshot_path or journal_dir:
            from ..controller.loadbalancer.checkpoint import (
                BalancerSnapshotter, load_snapshot)
            if journal_dir:
                from ..controller.loadbalancer.journal import \
                    journal_from_config
                journal = journal_from_config(journal_dir, logger=logger)
                if journal is not None:
                    lb.attach_journal(journal)
            load_snapshot(lb, snapshot_path or "", logger, journal=journal)
            if snapshot_path:
                snapshotter = BalancerSnapshotter(
                    lb, snapshot_path, snapshot_interval, logger,
                    journal=journal).start()
    else:
        # metrics=logger.metrics: the controller serves this emitter at
        # /metrics — sharing it puts the lean balancer's counters AND its
        # telemetry histogram families on the scrape page
        lb = LeanBalancer(provider, instance, invoker_factory, logger=logger,
                          metrics=logger.metrics,
                          user_memory=MB(user_memory_mb))
    if ui and "extra_routes" not in controller_kw:
        # playground dev UI beside /api/v1 (ref standalone PlaygroundLauncher)
        from .playground import playground_routes
        controller_kw["extra_routes"] = playground_routes(GUEST_UUID, GUEST_KEY)
    controller = Controller(instance, provider, artifact_store=artifact_store,
                            logger=logger, load_balancer=lb, **controller_kw)
    if snapshotter is not None:
        # Controller.stop() drains owned_resources BEFORE closing the
        # balancer: the final dump always sees live books, and the SIGTERM
        # path (utils.tasks.wait_for_shutdown -> controller.stop) can no
        # longer skip it
        controller.owned_resources.append(snapshotter)
    if journal is not None:
        class _JournalCloser:
            async def stop(self_inner) -> None:
                await asyncio.to_thread(journal.close)

        controller.owned_resources.append(_JournalCloser())
    # seed the guest identity
    ident = guest_identity()
    await controller.auth_store.put(
        WhiskAuthRecord(ident.subject, [ident.namespace], [ident.authkey]))
    await controller.start(port=port)
    if balancer == "tpu":
        # the TPU balancer talks to invokers over the bus + health pings:
        # boot the in-process invoker beside it and wait for its first ping
        invoker = await invoker_factory(
            InvokerInstanceId(0, unique_name="standalone",
                              user_memory=MB(user_memory_mb)), provider)
        controller.owned_resources.append(invoker)
        for _ in range(100):
            if any(lb._healthy):
                break
            await asyncio.sleep(0.05)
    return controller

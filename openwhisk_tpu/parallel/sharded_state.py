"""Sharded placement: invoker axis split over a device mesh.

Layout: PlacementState.free_mb/health are sharded on the "inv" mesh axis,
conc_free on ("inv", None); the request batch is replicated. Each scan step:
  1. every device reduces its local shard to (best probe-rank, its global
     index) plus the forced-placement fallback candidate,
  2. one all_gather of those 4 scalars per device elects the global winner
     (the collective is tiny and rides ICI),
  3. only the owning device applies the capacity update (masked scatter).
This preserves the exact sequential semantics of the single-device kernel —
and therefore of the reference's one-at-a-time scheduler — at any shard
count, which the parity tests assert on an 8-way virtual mesh.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # newer jax exports shard_map at the top level (check_vma keyword)
    from jax import shard_map as _shard_map_impl
except ImportError:  # jax 0.4.x: the experimental module (check_rep keyword)
    from jax.experimental.shard_map import shard_map as _shard_map_impl

from ..ops.placement import PlacementState, RequestBatch, _mulmod


def shard_map(f, mesh, in_specs, out_specs, **kwargs):
    """Compat shim over the two shard_map generations: forward the
    skip-replication-check flag under whichever keyword this jax spells it
    (`check_vma` at the top level, `check_rep` in the experimental module)
    and drop it entirely if neither is understood."""
    import inspect

    params = inspect.signature(_shard_map_impl).parameters
    check = kwargs.pop("check_vma", kwargs.pop("check_rep", None))
    if check is not None:
        if "check_vma" in params:
            kwargs["check_vma"] = check
        elif "check_rep" in params:
            kwargs["check_rep"] = check
    return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, **kwargs)


def make_mesh(n_devices: Optional[int] = None, axis: str = "inv") -> Mesh:
    """Mesh over the default backend; when it has too few devices (e.g. one
    real TPU chip) fall back to the virtual CPU devices created by
    --xla_force_host_platform_device_count."""
    want = n_devices or len(jax.devices())
    devices = jax.devices()
    if len(devices) < want:
        devices = jax.devices("cpu")
    if len(devices) < want:
        raise ValueError(f"need {want} devices, have {len(jax.devices())} "
                         f"default + {len(devices)} cpu")
    return Mesh(devices[:want], (axis,))


def shard_state(state: PlacementState, mesh: Mesh, axis: Optional[str] = None
                ) -> PlacementState:
    """Place the state arrays with the invoker axis sharded over the mesh.
    `axis=None` infers the mesh's (single) axis name, so the same call
    works for the prototype "inv" meshes and the production "fleet" ones."""
    axis = axis or mesh.axis_names[0]
    n = state.free_mb.shape[0]
    assert n % mesh.shape[axis] == 0, \
        f"invoker padding {n} must divide evenly over {mesh.shape[axis]} shards"
    sh1 = NamedSharding(mesh, P(axis))
    sh2 = NamedSharding(mesh, P(axis, None))
    return PlacementState(jax.device_put(state.free_mb, sh1),
                          jax.device_put(state.conc_free, sh2),
                          jax.device_put(state.health, sh1))


def make_sharded_schedule(mesh: Mesh, axis: str = "inv"):
    """Build the jitted sharded schedule_batch for this mesh."""
    n_shards = mesh.shape[axis]

    def _local_body(state: PlacementState, req, shard_offset, n_total):
        offset, size, home, step_inv, need, slot, max_conc, rand, valid = req
        n_local = state.free_mb.shape[0]
        big = jnp.int32(n_total + 2)
        bigidx = jnp.int32(n_total + 2)

        gidx = shard_offset + jnp.arange(n_local, dtype=jnp.int32)
        local = gidx - offset
        in_part = (local >= 0) & (local < size)
        size_safe = jnp.maximum(size, 1)
        rank = _mulmod(local - home, step_inv, size_safe)

        conc_col = jax.lax.dynamic_index_in_dim(state.conc_free, slot, axis=1,
                                                keepdims=False)
        eligible = in_part & state.health & ((conc_col > 0) | (state.free_mb >= need))
        key = jnp.where(eligible, rank, big)
        a = jnp.argmin(key)
        my_best = (key[a], gidx[a])

        usable = in_part & state.health
        fkey = jnp.where(usable, jnp.mod(local - rand, size_safe), big)
        fa = jnp.argmin(fkey)
        my_forced = (fkey[fa], gidx[fa])

        # one tiny all_gather elects the global winner
        packed = jnp.stack([my_best[0], my_best[1], my_forced[0], my_forced[1]])
        allv = jax.lax.all_gather(packed, axis)  # [n_shards, 4]
        bkeys, bidx, fkeys, fidx = allv[:, 0], allv[:, 1], allv[:, 2], allv[:, 3]
        # winner = lexicographic min over (key, global index)
        best_key = jnp.min(bkeys)
        best_idx = jnp.min(jnp.where(bkeys == best_key, bidx, bigidx))
        found = best_key < big
        fbest_key = jnp.min(fkeys)
        fbest_idx = jnp.min(jnp.where(fkeys == fbest_key, fidx, bigidx))
        have_usable = fbest_key < big

        sel = jnp.where(found, best_idx, fbest_idx)
        placed = valid & (found | have_usable)
        forced = valid & ~found & have_usable

        # owner-masked update
        lsel = jnp.clip(sel - shard_offset, 0, n_local - 1)
        mine = (sel >= shard_offset) & (sel < shard_offset + n_local)
        sel_conc = conc_col[lsel] > 0
        use_conc = placed & mine & sel_conc
        take_mem = placed & mine & ~sel_conc
        free_mb = state.free_mb.at[lsel].add(
            jnp.where(take_mem, -need, 0).astype(jnp.int32))
        conc_delta = jnp.where(use_conc, -1,
                               jnp.where(take_mem & (max_conc > 1),
                                         max_conc - 1, 0))
        conc_free = state.conc_free.at[lsel, slot].add(conc_delta.astype(jnp.int32))
        new_state = PlacementState(free_mb, conc_free, state.health)
        return new_state, (jnp.where(placed, sel, -1), forced)

    def _sharded(state: PlacementState, batch: RequestBatch):
        n_local = state.free_mb.shape[0]  # inside shard_map: local shape
        shard_offset = jax.lax.axis_index(axis).astype(jnp.int32) * n_local
        n_total = n_local * n_shards
        reqs = (batch.offset, batch.size, batch.home, batch.step_inv,
                batch.need_mb, batch.conc_slot, batch.max_conc, batch.rand,
                batch.valid)
        new_state, (chosen, forced) = jax.lax.scan(
            lambda s, r: _local_body(s, r, shard_offset, n_total), state, reqs)
        return new_state, chosen, forced

    state_spec = PlacementState(P(axis), P(axis, None), P(axis))
    batch_spec = RequestBatch(*([P()] * 9))
    fn = shard_map(_sharded, mesh=mesh,
                   in_specs=(state_spec, batch_spec),
                   out_specs=(state_spec, P(), P()),
                   check_vma=False)
    return jax.jit(fn)


def make_sharded_release(mesh: Mesh, axis: str = "inv"):
    """Jitted sharded release: owner-shard-masked updates, no collectives."""

    def _local(state: PlacementState, rel, shard_offset):
        inv, slot, need, max_conc, valid = rel
        n_local = state.free_mb.shape[0]
        mine = valid & (inv >= shard_offset) & (inv < shard_offset + n_local)
        linv = jnp.clip(inv - shard_offset, 0, n_local - 1)
        simple = mine & (max_conc <= 1)
        conc_val = state.conc_free[linv, slot] + 1
        reduced = mine & (max_conc > 1) & (conc_val >= max_conc)
        conc_delta = jnp.where(mine & (max_conc > 1),
                               jnp.where(reduced, 1 - max_conc, 1), 0)
        free_delta = jnp.where(simple | reduced, need, 0)
        return PlacementState(
            state.free_mb.at[linv].add(free_delta.astype(jnp.int32)),
            state.conc_free.at[linv, slot].add(conc_delta.astype(jnp.int32)),
            state.health), ()

    def _sharded(state: PlacementState, inv, slot, need, max_conc, valid):
        n_local = state.free_mb.shape[0]
        shard_offset = jax.lax.axis_index(axis).astype(jnp.int32) * n_local
        new_state, _ = jax.lax.scan(
            lambda s, r: _local(s, r, shard_offset), state,
            (inv, slot, need, max_conc, valid))
        return new_state

    state_spec = PlacementState(P(axis), P(axis, None), P(axis))
    fn = shard_map(_sharded, mesh=mesh,
                   in_specs=(state_spec, P(), P(), P(), P(), P()),
                   out_specs=state_spec, check_vma=False)
    return jax.jit(fn)

"""Docker container driver: shells out to the docker CLI.

Rebuild of core/invoker/.../containerpool/docker/DockerClient.scala:81-179
(+ DockerContainer.scala, DockerContainerFactory.scala): `docker run` with
memory/cpu-share flags, IP discovery via `docker inspect`, pause/unpause, and
janitorial `docker rm` of leftovers tagged with a name prefix. Parallel
`docker run`s are semaphore-limited exactly as the reference's
`maxParallelRuns`. Gated: only usable where a docker daemon exists (not in
the build environment — covered by the process driver + contract tests).
"""
from __future__ import annotations

import asyncio
import shutil
import uuid
from typing import List, Optional

from ..core.entity import ByteSize
from .container import Container, ContainerError
from .factory import ContainerFactory

NAME_PREFIX = "wsk_owtpu"


def docker_available() -> bool:
    return shutil.which("docker") is not None


async def _exec(args: List[str], timeout: float = 60.0) -> str:
    proc = await asyncio.create_subprocess_exec(
        *args, stdout=asyncio.subprocess.PIPE, stderr=asyncio.subprocess.PIPE)
    try:
        out, err = await asyncio.wait_for(proc.communicate(), timeout)
    except asyncio.TimeoutError:
        proc.kill()
        raise ContainerError(f"command timed out: {' '.join(args[:3])}")
    if proc.returncode != 0:
        raise ContainerError(f"{' '.join(args[:3])} failed ({proc.returncode}): "
                             f"{err.decode()[:512]}")
    return out.decode()


class DockerClient:
    """Thin async docker CLI wrapper (ref DockerClient.scala)."""

    def __init__(self, max_parallel_runs: int = 10):
        self._run_sem = asyncio.Semaphore(max_parallel_runs)

    async def run(self, image: str, args: List[str]) -> str:
        async with self._run_sem:
            out = await _exec(["docker", "run", "-d"] + args + [image])
            return out.strip()

    async def inspect_ip(self, container_id: str, network: str = "bridge") -> str:
        out = await _exec(["docker", "inspect", "--format",
                           "{{.NetworkSettings.Networks." + network + ".IPAddress}}",
                           container_id])
        ip = out.strip()
        if not ip or ip == "<no value>":
            raise ContainerError(f"no IP for container {container_id}")
        return ip

    async def pause(self, container_id: str) -> None:
        await _exec(["docker", "pause", container_id])

    async def unpause(self, container_id: str) -> None:
        await _exec(["docker", "unpause", container_id])

    async def rm(self, container_id: str) -> None:
        await _exec(["docker", "rm", "-f", container_id])

    async def ps(self, name_prefix: str = NAME_PREFIX, all_: bool = True) -> List[str]:
        out = await _exec(["docker", "ps", "-q"] + (["-a"] if all_ else []) +
                          ["--filter", f"name={name_prefix}"])
        return [l for l in out.splitlines() if l]

    async def pull(self, image: str) -> None:
        await _exec(["docker", "pull", image], timeout=600)

    async def logs(self, container_id: str, since: Optional[str] = None) -> str:
        args = ["docker", "logs", container_id]
        if since:
            args += ["--since", since]
        return await _exec(args)


class DockerContainer(Container):
    def __init__(self, client: DockerClient, container_id: str, ip: str,
                 kind: str, memory: ByteSize, port: int = 8080):
        super().__init__(container_id, (ip, port))
        self.client = client
        self.kind = kind
        self.memory = memory

    async def suspend(self) -> None:
        await self.client.pause(self.container_id)

    async def resume(self) -> None:
        await self.client.unpause(self.container_id)

    async def destroy(self) -> None:
        await super().destroy()
        await self.client.rm(self.container_id)

    async def logs(self, limit_bytes: int = 10 * 1024 * 1024,
                   wait_for_sentinel: bool = True) -> List[str]:
        raw = await self.client.logs(self.container_id)
        return raw.splitlines()[-1000:]


class DockerContainerFactory(ContainerFactory):
    def __init__(self, invoker_name: str = "invoker0",
                 client: Optional[DockerClient] = None,
                 network: str = "bridge", extra_args: Optional[List[str]] = None):
        if not docker_available():
            raise ContainerError("docker CLI not found on PATH")
        self.client = client or DockerClient()
        self.network = network
        self.extra_args = extra_args or []
        # per-invoker name prefix (ref DockerContainerFactory.scala names
        # containers wsk<id>_...): boot-time init()->cleanup() must reap
        # only THIS invoker's leftovers, never a co-hosted invoker's live
        # containers. Trailing '_' so "inv1" never prefix-matches "inv10".
        # `docker ps --filter name=` treats the value as an unanchored
        # regex, so the prefix is whitelisted to regex-inert chars and, when
        # sanitization lost information (e.g. 'inv:1' and 'inv/1' both map
        # to 'inv-1'), a CRC of the raw name keeps distinct invokers from
        # matching each other's containers.
        safe = "".join(c if (c.isalnum() or c == "_") else "-"
                       for c in invoker_name)
        if safe != invoker_name:
            import zlib
            safe += f"-{zlib.crc32(invoker_name.encode()) & 0xffff:04x}"
        self.name_prefix = f"{NAME_PREFIX}_{safe}_"

    async def create_container(self, transid, name: str, image: str,
                               memory: ByteSize, cpu_shares: int = 0,
                               action=None) -> DockerContainer:
        cname = f"{self.name_prefix}{name}_{uuid.uuid4().hex[:8]}"
        args = ["--name", cname, "--network", self.network,
                "-m", f"{memory.to_mb}m", "--memory-swap", f"{memory.to_mb}m"]
        if cpu_shares:
            args += ["--cpu-shares", str(cpu_shares)]
        args += self.extra_args
        cid = await self.client.run(image, args)
        ip = await self.client.inspect_ip(cid, self.network)
        return DockerContainer(self.client, cid, ip, kind=image, memory=memory)

    async def cleanup(self) -> None:
        for cid in await self.client.ps(name_prefix=self.name_prefix):
            try:
                await self.client.rm(cid)
            except ContainerError:
                pass


class DockerContainerFactoryProvider:
    """ContainerFactoryProvider SPI binding
    (CONFIG_whisk_spi_ContainerFactoryProvider=
     openwhisk_tpu.containerpool.docker_factory:DockerContainerFactoryProvider)."""

    @staticmethod
    def instance(invoker_name: str = "invoker0", logger=None,
                 **kwargs) -> DockerContainerFactory:
        return DockerContainerFactory(invoker_name, **kwargs)

"""Batched invoker placement on device.

The TPU-native reformulation of the reference's scheduling inner loop
(ShardingContainerPoolBalancer.scala:398-436). The reference probes invokers
one-by-one per activation (home + k*step mod n, step coprime to n). Key
observation: because gcd(step, n) = 1, the probe ORDER is a permutation with
closed-form rank

    rank(i) = (i - home) * step^{-1}  (mod n)

so "first invoker with capacity along the probe sequence" becomes
"argmin(rank) over eligible invokers" — one vectorized reduction over the
fleet instead of a sequential walk. A micro-batch of B activations is then a
`lax.scan` of B such reductions with the capacity state carried through,
which preserves the reference's sequential read-modify-write semantics
exactly (intra-batch contention resolves identically to processing the
requests one at a time).

State (static shapes; fleets grow into padding, SURVEY §7 risk list):
  free_mb   int32[N]     free memory permits per invoker (this controller's
                         shard; may go negative under forced placement, the
                         ForcibleSemaphore over-commit semantics)
  conc_free int32[N, A]  spare intra-container concurrency permits per
                         (invoker, action-slot) — the NestedSemaphore inner
                         level. Slot ids are assigned host-side (collision-
                         free up to A live actions).
  health    bool[N]      usable mask (Healthy; flips fold in from the
                         supervision feed)

Request batch (int32[B] each): partition offset/size (managed vs blackbox
fleet slice), home, step_inv (modular inverse of the coprime step), need_mb,
conc_slot, max_conc, rand (forced-placement choice), valid.

Returns (new_state, chosen int32[B] — global invoker index or -1, forced
bool[B]). Overload forces a random usable invoker (over-commit); no usable
invokers -> -1.
"""
from __future__ import annotations

from functools import partial
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


def _mulmod(a, b, m):
    """(a % m) * b % m without int32 overflow, for b < m <= 2**17.

    The naive product overflows int32 once partition sizes pass ~46k (e.g.
    the 64k-invoker configuration with a large step inverse), corrupting
    probe ranks. Splitting b = hi*512 + lo keeps every intermediate under
    2**26: a' < 2**17, hi < 2**8, lo < 2**9.
    """
    a = jnp.mod(a, m)
    hi = b // 512
    lo = b - hi * 512
    t = jnp.mod(a * hi, m)
    t = jnp.mod(t * 512, m)
    return jnp.mod(t + a * lo, m)


class PlacementState(NamedTuple):
    free_mb: jax.Array    # int32[N]
    conc_free: jax.Array  # int32[N, A]
    health: jax.Array     # bool[N]


class RequestBatch(NamedTuple):
    offset: jax.Array     # int32[B] partition start
    size: jax.Array       # int32[B] partition length
    home: jax.Array       # int32[B] hash % size
    step_inv: jax.Array   # int32[B] inverse of step mod size
    need_mb: jax.Array    # int32[B]
    conc_slot: jax.Array  # int32[B]
    max_conc: jax.Array   # int32[B]
    rand: jax.Array       # int32[B] randomness for forced placement
    valid: jax.Array      # bool[B]


def init_state(n_invokers: int, slot_mb, n_pad: int = 0, action_slots: int = 512
               ) -> PlacementState:
    """Build device state; `slot_mb` is scalar or per-invoker list. Padding
    rows are unhealthy with zero capacity."""
    n_pad = n_pad or n_invokers
    assert n_pad >= n_invokers
    free = jnp.zeros((n_pad,), jnp.int32)
    slot_arr = jnp.broadcast_to(jnp.asarray(slot_mb, jnp.int32), (n_invokers,))
    free = free.at[:n_invokers].set(slot_arr)
    health = jnp.zeros((n_pad,), bool).at[:n_invokers].set(True)
    conc = jnp.zeros((n_pad, action_slots), jnp.int32)
    return PlacementState(free, conc, health)


def set_health(state: PlacementState, idx, usable) -> PlacementState:
    return state._replace(health=state.health.at[jnp.asarray(idx)].set(
        jnp.asarray(usable)))


def _schedule_one(state: PlacementState, req) -> Tuple[PlacementState, Tuple]:
    """One activation: vectorized probe + capacity update (scan body)."""
    offset, size, home, step_inv, need, slot, max_conc, rand, valid = req
    n = state.free_mb.shape[0]
    big = jnp.int32(n + 2)

    idx = jnp.arange(n, dtype=jnp.int32)
    local = idx - offset
    in_part = (local >= 0) & (local < size)
    size_safe = jnp.maximum(size, 1)
    # probe-order rank via modular inverse of the coprime step
    rank = _mulmod(local - home, step_inv, size_safe)

    conc_col = jax.lax.dynamic_index_in_dim(state.conc_free, slot, axis=1,
                                            keepdims=False)
    has_conc = conc_col > 0
    has_mem = state.free_mb >= need
    eligible = in_part & state.health & (has_conc | has_mem)
    key = jnp.where(eligible, rank, big)
    choice = jnp.argmin(key)
    found = key[choice] < big

    # overload: force a usable invoker chosen by a random rotation
    usable = in_part & state.health
    fkey = jnp.where(usable, jnp.mod(local - rand, size_safe), big)
    fchoice = jnp.argmin(fkey)
    have_usable = fkey[fchoice] < big

    sel = jnp.where(found, choice, fchoice)
    placed = valid & (found | have_usable)
    forced = valid & ~found & have_usable

    # capacity update (NestedSemaphore.tryAcquireConcurrent semantics)
    use_conc = placed & (conc_col[sel] > 0)
    take_mem = placed & ~use_conc
    free_mb = state.free_mb.at[sel].add(
        jnp.where(take_mem, -need, 0).astype(jnp.int32))
    conc_delta = jnp.where(use_conc, -1,
                           jnp.where(take_mem & (max_conc > 1), max_conc - 1, 0))
    conc_free = state.conc_free.at[sel, slot].add(conc_delta.astype(jnp.int32))

    out_choice = jnp.where(placed, sel, -1)
    return PlacementState(free_mb, conc_free, state.health), (out_choice, forced)


@jax.jit
def schedule_batch(state: PlacementState, batch: RequestBatch
                   ) -> Tuple[PlacementState, jax.Array, jax.Array]:
    """Place a micro-batch sequentially (lax.scan) with vectorized probes."""
    reqs = (batch.offset, batch.size, batch.home, batch.step_inv,
            batch.need_mb, batch.conc_slot, batch.max_conc, batch.rand,
            batch.valid)
    new_state, (chosen, forced) = jax.lax.scan(
        lambda s, r: _schedule_one(s, r), state, reqs)
    return new_state, chosen, forced


def _release_one(state: PlacementState, rel) -> Tuple[PlacementState, Tuple]:
    inv, slot, need, max_conc, valid = rel
    simple = valid & (max_conc <= 1)
    conc_val = state.conc_free[inv, slot] + 1
    reduced = valid & (max_conc > 1) & (conc_val >= max_conc)
    # concurrency release: +1 permit; a full container's worth free ->
    # reduce by max_conc and return the container's memory
    conc_delta = jnp.where(valid & (max_conc > 1),
                           jnp.where(reduced, 1 - max_conc, 1), 0)
    free_delta = jnp.where(simple | reduced, need, 0)
    return PlacementState(
        state.free_mb.at[inv].add(free_delta.astype(jnp.int32)),
        state.conc_free.at[inv, slot].add(conc_delta.astype(jnp.int32)),
        state.health), ()


@jax.jit
def release_batch(state: PlacementState, inv, slot, need_mb, max_conc, valid
                  ) -> PlacementState:
    """Fold a batch of completion releases into the state (ref
    releaseInvoker / NestedSemaphore.releaseConcurrent)."""
    new_state, _ = jax.lax.scan(
        lambda s, r: _release_one(s, r),
        state, (inv, slot, need_mb, max_conc, valid))
    return new_state


def make_fused_step(release_fn=None, schedule_fn=None):
    """One jitted device program for the balancer's whole step:
    fold releases -> fold health flips -> schedule the micro-batch.

    The three phases as separate calls cost three dispatches per batch
    (dominant at small fleet sizes, where each kernel is ~microseconds);
    fused, XLA compiles them into a single program. Works over any
    (release_fn, schedule_fn) pair — the XLA kernels (default), the
    shard_map'd variants, or the pallas schedule.
    """
    release_fn = release_fn or release_batch
    schedule_fn = schedule_fn or schedule_batch

    @jax.jit
    def fused(state: PlacementState, rel_inv, rel_slot, rel_mem, rel_maxc,
              rel_valid, health_idx, health_val, health_valid,
              batch: RequestBatch):
        state = release_fn(state, rel_inv, rel_slot, rel_mem, rel_maxc,
                           rel_valid)
        # masked health fold: padded rows keep their current value
        cur = state.health[health_idx]
        state = state._replace(health=state.health.at[health_idx].set(
            jnp.where(health_valid, health_val, cur)))
        return schedule_fn(state, batch)

    return fused


def make_release_packed(release_fn=None):
    """Release-only fold over the packed int32[5,R] matrix (inv, slot, mem,
    maxc, valid) — the idle-drain counterpart of make_fused_step_packed."""
    release_fn = release_fn or release_batch

    @jax.jit
    def packed(state: PlacementState, rel):
        return release_fn(state, rel[0], rel[1], rel[2], rel[3],
                          rel[4].astype(bool))

    return packed


def make_fused_step_packed(release_fn=None, schedule_fn=None):
    """Transfer-packed variant of make_fused_step for the balancer's host
    path. The unpacked signature costs 16 host->device transfers per step
    (8 request columns + 5 release arrays + 3 health arrays) and 2 reads
    back; on a tunneled device every transfer is a round trip, so the
    TRANSFER COUNT — not the kernel — dominates the step. Packing collapses
    the inputs to ONE flat int32 buffer (rel [5*R] ++ health [3*H] ++ req
    [9*B] here, [10*B] in the admit variant; split by static shape inside
    the program) and the outputs to ONE int32 vector
    (((chosen+1)<<2) | throttled<<1 | forced — always 0 for throttled here;
    callers decode with `unpack_chosen`). R/H/B are static per compile; the
    balancer's power-of-two bucketing bounds the cache-key count.
    """
    fused = make_fused_step(release_fn, schedule_fn)

    @partial(jax.jit, static_argnums=(2, 3, 4))
    def packed(state: PlacementState, buf, R: int, H: int, B: int):
        # buf int32[5R+3H+9B]:
        #   rel    [5,R]: inv, slot, mem, maxc, valid
        #   health [3,H]: idx, val, mask
        #   req    [9,B]: offset, size, home, step_inv, need_mb,
        #                 conc_slot, max_conc, rand, valid
        rel = buf[:5 * R].reshape(5, R)
        health = buf[5 * R:5 * R + 3 * H].reshape(3, H)
        req = buf[5 * R + 3 * H:].reshape(9, B)
        batch = RequestBatch(req[0], req[1], req[2], req[3], req[4], req[5],
                             req[6], req[7], req[8].astype(bool))
        state, chosen, forced = fused(
            state, rel[0], rel[1], rel[2], rel[3], rel[4].astype(bool),
            health[0], health[1].astype(bool), health[2].astype(bool), batch)
        return state, ((chosen + 1) << 2) | forced.astype(jnp.int32)

    return packed


def make_fused_admit_step_packed(release_fn=None, schedule_fn=None):
    """make_fused_step_packed + device token-bucket admission (ops.throttle):
    the fused program folds releases and health, ADMITS the batch against
    per-namespace buckets (Entitlement.scala:86-153 / RateThrottler.scala as
    a vectorized segmented count — see ops/throttle.py), then schedules only
    the admitted requests. Over-rate requests come back flagged in bit 1 of
    the packed output and never consume placement capacity.

    req grows a 10th row: ns_slot (the balancer's namespace->bucket index).
    """
    from .throttle import admit_batch

    fused = make_fused_step(release_fn, schedule_fn)

    @partial(jax.jit, static_argnums=(3, 4, 5))
    def packed(carry, buf, now, R: int, H: int, B: int):
        state, buckets = carry
        rel = buf[:5 * R].reshape(5, R)
        health = buf[5 * R:5 * R + 3 * H].reshape(3, H)
        req = buf[5 * R + 3 * H:].reshape(10, B)
        valid = req[8].astype(bool)
        buckets, admitted = admit_batch(buckets, now, req[9], valid)
        throttled = valid & ~admitted
        batch = RequestBatch(req[0], req[1], req[2], req[3], req[4], req[5],
                             req[6], req[7], admitted)
        state, chosen, forced = fused(
            state, rel[0], rel[1], rel[2], rel[3], rel[4].astype(bool),
            health[0], health[1].astype(bool), health[2].astype(bool), batch)
        out = (((chosen + 1) << 2) | (throttled.astype(jnp.int32) << 1)
               | forced.astype(jnp.int32))
        return (state, buckets), out

    return packed


def unpack_chosen(out):
    """Decode the packed step output vector (host numpy or device jnp):
    -> (chosen int32, forced bool, throttled bool). Throttled requests
    carry chosen == -1 (they were never scheduled)."""
    return (out >> 2) - 1, (out & 1).astype(bool), ((out >> 1) & 1).astype(bool)

"""Dynamic controller membership: live cluster size over the bus.

The reference re-shards every invoker's memory between controllers using
Akka Cluster membership events — MemberUp/MemberRemoved drive
`updateCluster(availableMembers.size)`
(ShardingContainerPoolBalancer.scala:217-250,561-584). This is the
framework-native replacement: each controller heartbeats on a
`controllers` topic; every controller folds the live set from heartbeat
recency and calls `balancer.update_cluster(n_live)` whenever it changes,
so capacity re-shards within a bounded window of a join or a crash. A
graceful shutdown sends a `leave` so planned departures re-shard
immediately instead of waiting out the timeout.

The deploy-time `--cluster-size` remains the initial value (the
reference's seed-node list); membership converges from there.
"""
from __future__ import annotations

import json
import time
from typing import Dict, Optional

from ...messaging.connector import MessageFeed
from ...utils.scheduler import Scheduler
from ...utils.transaction import TransactionId

CONTROLLERS_TOPIC = "controllers"
#: heartbeats are ephemeral like health pings — keep only a small tail
CONTROLLERS_RETENTION_BYTES = 256 * 1024
HEARTBEAT_S = 1.0
#: a controller is gone after this much heartbeat silence (the reference's
#: Akka failure detector defaults are in the same few-second range)
MEMBER_TIMEOUT_S = 5.0


class ControllerMembership:
    def __init__(self, messaging_provider, instance, balancer, logger=None,
                 heartbeat_s: float = HEARTBEAT_S,
                 member_timeout_s: float = MEMBER_TIMEOUT_S):
        self.provider = messaging_provider
        self.instance = instance
        self.balancer = balancer
        self.logger = logger
        self.heartbeat_s = heartbeat_s
        self.member_timeout_s = member_timeout_s
        #: instance -> local receive time of the last heartbeat
        self._last_seen: Dict[int, float] = {}
        self._producer = None
        self._feed: Optional[MessageFeed] = None
        self._ticker: Optional[Scheduler] = None
        self._current_size = 0
        self._seed_size = 1
        self._started = 0.0
        self._last_tick = 0.0

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        # the deploy-time size seeds a grace window: until peers have had a
        # full timeout to heartbeat, never fold BELOW the seed — otherwise a
        # fresh controller booted as 1-of-2 would briefly claim the whole
        # fleet's capacity and overcommit
        self._seed_size = max(self.balancer.cluster_size, 1)
        self._current_size = self._seed_size  # update only on real change
        self._started = time.monotonic()
        self.provider.ensure_topic(CONTROLLERS_TOPIC,
                                   retention_bytes=CONTROLLERS_RETENTION_BYTES)
        self._producer = self.provider.get_producer()
        consumer = self.provider.get_consumer(
            CONTROLLERS_TOPIC, f"membership{self.instance.instance}",
            max_peek=128, from_latest=True)
        box = {}

        async def handle(payload: bytes):
            self._on_message(payload)
            box["feed"].processed()

        self._feed = MessageFeed("controllers", consumer, 128, handle,
                                 logger=self.logger)
        box["feed"] = self._feed
        self._feed.start()
        self._ticker = Scheduler(self.heartbeat_s, self._tick,
                                 name="membership-heartbeat",
                                 logger=self.logger).start()

    async def stop(self) -> None:
        if self._ticker:
            await self._ticker.stop()
        if self._producer is not None:
            try:  # planned departure: peers re-shard without the timeout
                await self._producer.send(CONTROLLERS_TOPIC, json.dumps(
                    {"kind": "leave",
                     "instance": self.instance.instance}).encode())
            except Exception:  # noqa: BLE001 — bus may already be gone
                pass
        if self._feed:
            await self._feed.stop()

    # -- protocol ----------------------------------------------------------
    def _on_message(self, payload: bytes) -> None:
        try:
            msg = json.loads(payload)
            inst = int(msg["instance"])
            kind = msg.get("kind", "heartbeat")
        except (ValueError, KeyError, TypeError):
            return
        if inst == self.instance.instance:
            return
        if kind == "leave":
            self._last_seen.pop(inst, None)
            self._refold()
        else:
            joined = inst not in self._last_seen
            self._last_seen[inst] = time.monotonic()
            if joined:
                self._refold()

    async def _tick(self) -> None:
        await self._producer.send(CONTROLLERS_TOPIC, json.dumps(
            {"kind": "heartbeat", "instance": self.instance.instance}).encode())
        now = time.monotonic()
        # Stall guard: if OUR OWN ticks gapped (event loop blocked — e.g. a
        # long jit compile — or host pause), peer silence is our fault, not
        # theirs. Give every peer (and the boot grace window) a fresh
        # heartbeat interval before judging, the same reason Akka's failure
        # detector forgives process pauses.
        if self._last_tick and now - self._last_tick > self.member_timeout_s:
            stall = now - self._last_tick
            self._started += stall
            floor = now - self.heartbeat_s
            self._last_seen = {i: max(ts, floor)
                               for i, ts in self._last_seen.items()}
        self._last_tick = now
        dead = [i for i, ts in self._last_seen.items()
                if now - ts > self.member_timeout_s]
        for i in dead:
            del self._last_seen[i]
        # refold every tick: it no-ops when the size is unchanged, and also
        # converges the case where a seeded peer never appeared at all once
        # the boot grace window lapses
        self._refold()

    def _refold(self) -> None:
        n = 1 + len(self._last_seen)  # self + live peers
        if time.monotonic() - self._started < self.member_timeout_s:
            n = max(n, self._seed_size)
        if n != self._current_size:
            old = self._current_size
            self._current_size = n
            if self.logger:
                self.logger.info(
                    TransactionId.LOADBALANCER,
                    f"cluster membership {old or '?'} -> {n} "
                    f"(peers: {sorted(self._last_seen)})", "Membership")
            self.balancer.update_cluster(n)
            metrics = getattr(self.balancer, "metrics", None)
            if metrics is not None:
                metrics.gauge("loadbalancer_cluster_size", n)

    # -- views -------------------------------------------------------------
    @property
    def cluster_size(self) -> int:
        return self._current_size or 1

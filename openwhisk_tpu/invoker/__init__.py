from .reactive import InvokerReactive, InvokerReactiveProvider

__all__ = ["InvokerReactive", "InvokerReactiveProvider"]

"""Open-loop load generator: coordinated-omission-correct e2e measurement.

The reference repo benchmarks its controller with wrk
(`tests/performance/wrk_tests/post.lua`) — an open-loop generator. Our own
`bench.py:_balancer_bench` is CLOSED-loop: 64 workers behind an
`asyncio.Semaphore`, each waiting for its previous completion before
issuing the next request. Under saturation a closed loop self-throttles —
the system sets the arrival rate, queueing delay hides from the
percentiles, and the reported p99 suffers textbook coordinated omission
(Tene, "How NOT to Measure Latency"; wrk2's raison d'être; see PAPERS.md).

This module is the open-loop half of ISSUE 7's observatory:

  * `make_schedule` — Poisson (or constant-rate) arrival offsets, fixed
    up front so the offered rate is independent of the system under test.
  * `open_loop` — fire each request AT its scheduled time (never waiting
    on earlier completions) and measure latency FROM the scheduled
    arrival, so time a request spends queued behind a stalled system is
    charged to the system, not silently dropped from the sample set.
  * `sweep_balancer` — double the offered rate against a live TpuBalancer
    + echo-invoker fleet until the run stops being sustainable (p99 bound
    exceeded, completions lost, or the generator itself falling behind
    schedule), then re-measure the last sustainable rate and read the
    per-stage latency budget out of the waterfall plane
    (utils/waterfall.py) — the number pair bench.py's `e2e_open_loop`
    rider reports: a sustained activations/s headline plus WHERE the
    per-activation time goes.

CLI (one JSON line on stdout, like bench.py):

    python tools/loadgen.py --rate0 32 --duration 2.5
    python tools/loadgen.py --rate 200        # single fixed-rate run
"""
from __future__ import annotations

import argparse
import asyncio
import json
import os
import random
import sys
import time
from typing import Awaitable, Callable, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

#: a measured step is sustainable iff ALL hold
DEFAULT_P99_BOUND_MS = 1000.0    #: e2e p99 from scheduled arrival
MIN_COMPLETION_RATIO = 0.98      #: completions / offered within the drain
MAX_FIRE_LAG_MS = 50.0           #: generator max lateness vs its schedule
DRAIN_TIMEOUT_S = 15.0


def parse_stragglers(spec) -> dict:
    """`--stragglers` SPEC -> {invoker_index: ack_delay_seconds}.

    SPEC is `IDX:DELAY_S[,IDX:DELAY_S...]` (e.g. `3:0.25` delays invoker
    3's acks by 250 ms — the PR 4 acceptance scenario's numbers); a bare
    `IDX` defaults to 0.25 s. Dicts pass through normalized, None/empty
    means no injection."""
    if not spec:
        return {}
    if isinstance(spec, dict):
        return {int(k): float(v) for k, v in spec.items()}
    out = {}
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        idx, _, delay = part.partition(":")
        out[int(idx)] = float(delay) if delay else 0.25
    return out


def apply_stragglers(invokers, spec) -> dict:
    """PR 4's straggler injection, extracted to ONE helper: set `.delay`
    on the indexed invoker stand-ins. The test SimInvokers and bench.py's
    echo feeds expose the same mutable attribute, so the anomaly e2e
    tests, the `placement_quality` bench rider and manual loadgen drives
    all inject through this path. Returns the applied {index: delay_s}
    map (out-of-range indexes are dropped) — report it next to the
    numbers it skews."""
    applied = {}
    for idx, delay in sorted(parse_stragglers(spec).items()):
        if 0 <= idx < len(invokers):
            invokers[idx].delay = delay
            applied[idx] = delay
    return applied


def make_schedule(rate: float, n: int, dist: str = "poisson",
                  seed: int = 1) -> List[float]:
    """Arrival offsets (seconds from t0) for `n` requests at `rate`/s.
    Poisson: exponential inter-arrivals (the memoryless open-loop
    default); constant: a deterministic 1/rate grid."""
    if rate <= 0 or n <= 0:
        return []
    if dist == "constant":
        return [i / rate for i in range(n)]
    rng = random.Random(seed)
    t, out = 0.0, []
    for _ in range(n):
        t += rng.expovariate(rate)
        out.append(t)
    return out


async def open_loop(one: Callable[[int, int], Awaitable[bool]],
                    offsets: List[float],
                    drain_timeout: float = DRAIN_TIMEOUT_S) -> dict:
    """Drive `one(i, sched_ns)` open-loop: each request fires at its
    scheduled offset regardless of earlier completions; `sched_ns`
    (time.monotonic_ns at the scheduled arrival) is the latency base —
    `one` returns True on success. Returns samples measured FROM the
    schedule plus the generator's own health: fire lag, the generator
    process's OWN GC pauses during the window, and an attribution of the
    worst fire lag (gc_pause vs event_loop_stall) so a failed verdict can
    blame the generator or the system instead of silently blaming the
    balancer."""
    import gc

    samples_ms: List[float] = []
    errors = 0
    fire_lag_max = 0.0
    worst_lag_window = (0.0, 0.0)  # (sched, fire) monotonic seconds
    tasks: List[asyncio.Task] = []
    loop = asyncio.get_event_loop()

    # generator self-check: GC pauses in THIS process during the window.
    # A 100 ms collection between two scheduled fires reads exactly like a
    # system stall in the fire-lag number — record the pauses so the
    # verdict can tell them apart.
    gc_stat = {"pauses": 0, "total_ms": 0.0, "max_ms": 0.0}
    gc_recent: List[tuple] = []  # (start_mono, end_mono, dur_ms)
    gc_t0 = {}

    def _gc_cb(phase, info):
        if phase == "start":
            gc_t0["t"] = time.perf_counter()
            return
        t = gc_t0.pop("t", None)
        if t is None:
            return
        dur_ms = (time.perf_counter() - t) * 1e3
        gc_stat["pauses"] += 1
        gc_stat["total_ms"] += dur_ms
        gc_stat["max_ms"] = max(gc_stat["max_ms"], dur_ms)
        end = time.monotonic()
        gc_recent.append((end - dur_ms / 1e3, end, dur_ms))
        if len(gc_recent) > 256:
            del gc_recent[:128]

    gc.callbacks.append(_gc_cb)
    t0 = time.monotonic()
    t0_ns = time.monotonic_ns()

    async def timed(i: int, sched_ns: int) -> None:
        nonlocal errors
        try:
            ok = await one(i, sched_ns)
        except Exception:  # noqa: BLE001 — an error is a sample, not an abort
            ok = False
        if ok:
            samples_ms.append((time.monotonic_ns() - sched_ns) / 1e6)
        else:
            errors += 1

    try:
        i, n = 0, len(offsets)
        while i < n:
            now = time.monotonic() - t0
            while i < n and offsets[i] <= now:
                sched_ns = t0_ns + int(offsets[i] * 1e9)
                # lateness of the FIRE vs the schedule: the generator's own
                # health — a saturated event loop shows up here, and the
                # latency sample already charges the lag to the system
                lag = (time.monotonic_ns() - sched_ns) / 1e6
                if lag > fire_lag_max:
                    fire_lag_max = lag
                    worst_lag_window = (t0 + offsets[i],
                                        time.monotonic())
                tasks.append(loop.create_task(timed(i, sched_ns)))
                i += 1
            if i < n:
                await asyncio.sleep(offsets[i] - (time.monotonic() - t0))
        fired_wall = time.monotonic() - t0
    finally:
        try:
            gc.callbacks.remove(_gc_cb)
        except ValueError:
            pass
    done, pending = await asyncio.wait(tasks, timeout=drain_timeout) \
        if tasks else (set(), set())
    for p in pending:
        p.cancel()
    if pending:
        await asyncio.gather(*pending, return_exceptions=True)
    wall = time.monotonic() - t0
    samples_ms.sort()

    def pctl(q: float) -> Optional[float]:
        if not samples_ms:
            return None
        return round(samples_ms[min(len(samples_ms) - 1,
                                    int(q * len(samples_ms)))], 3)

    # attribute the WORST fire lag: a GC pause overlapping the
    # [scheduled, fired] window makes the generator the culprit; otherwise
    # something else held the loop (a system callback, the scheduler)
    lag_cause = None
    if fire_lag_max > 0.0:
        w0, w1 = worst_lag_window
        overlapped = any(s <= w1 and e >= w0 for s, e, _d in gc_recent)
        lag_cause = "gc_pause" if overlapped else "event_loop_stall"

    return {
        "offered": n,
        "completed": len(samples_ms),
        "errors": errors,
        "unfinished": len(pending),
        "generator": {
            "gc_pauses": gc_stat["pauses"],
            "gc_pause_total_ms": round(gc_stat["total_ms"], 3),
            "gc_pause_max_ms": round(gc_stat["max_ms"], 3),
            "max_fire_lag_ms": round(fire_lag_max, 3),
            "max_fire_lag_cause": lag_cause,
        },
        "wall_s": round(wall, 3),
        "fired_wall_s": round(fired_wall, 3),
        "throughput_per_sec": (round(len(samples_ms) / wall, 1)
                               if wall else 0.0),
        "p50_ms": pctl(0.50),
        "p90_ms": pctl(0.90),
        "p99_ms": pctl(0.99),
        "mean_ms": (round(sum(samples_ms) / len(samples_ms), 3)
                    if samples_ms else None),
        "fire_lag_max_ms": round(fire_lag_max, 3),
        "samples_ms": samples_ms,
    }


def verdict(row: dict, p99_bound_ms: float = DEFAULT_P99_BOUND_MS) -> dict:
    """The sweep's step verdict with ATTRIBUTION: which checks failed, and
    — when the generator fell behind its own schedule — whether the
    generator's own GC (the open_loop self-check) or a loop stall caused
    it. A rung failed by generator stalls is a harness problem; one failed
    by p99/completions is the system's."""
    failed: List[str] = []
    total = row["completed"] + row["errors"] + row["unfinished"]
    if not row["completed"]:
        failed.append("no_completions")
    else:
        ratio = row["completed"] / max(1, total)
        if ratio < MIN_COMPLETION_RATIO:
            failed.append(f"completion_ratio {round(ratio, 3)} < "
                          f"{MIN_COMPLETION_RATIO}")
        if row["errors"] != 0:
            failed.append(f"errors {row['errors']}")
        if row["p99_ms"] is None or row["p99_ms"] > p99_bound_ms:
            failed.append(f"p99 {row['p99_ms']}ms > {p99_bound_ms}ms")
    if row["fire_lag_max_ms"] > MAX_FIRE_LAG_MS:
        gen = row.get("generator") or {}
        cause = gen.get("max_fire_lag_cause")
        failed.append(
            f"generator_fire_lag {row['fire_lag_max_ms']}ms"
            + (f" (cause: {cause}, gc_pauses: {gen.get('gc_pauses')}, "
               f"gc_max: {gen.get('gc_pause_max_ms')}ms)" if cause else ""))
    out = {"sustainable": not failed, "failed": failed}
    blame = "none"
    if failed:
        gen_only = all(f.startswith("generator_fire_lag") for f in failed)
        blame = "generator" if gen_only else "system"
    out["blames"] = blame
    return out


def sustainable(row: dict, p99_bound_ms: float = DEFAULT_P99_BOUND_MS) -> bool:
    """The sweep's step verdict: latency bounded, nothing lost, and the
    generator itself kept to its schedule (a lagging generator means the
    offered rate was not actually offered). `verdict()` is the explained
    variant; this stays the boolean every older call site uses."""
    return verdict(row, p99_bound_ms)["sustainable"]


# -- the balancer target ---------------------------------------------------

class _BalancerTarget:
    """A live TpuBalancer + echo-invoker fleet (bench.py's stand-ins) with
    a publish-and-await-completion `one()` that anchors each activation's
    waterfall context at its SCHEDULED arrival — so the first stage delta
    carries the open-loop send lag and the per-stage budget telescopes to
    the same e2e the generator measures."""

    def __init__(self, n_invokers: int = 16, kernel: str = "auto",
                 waterfall: bool = True, prewarm: bool = False,
                 fleet_mesh: bool = False, stragglers=None):
        self.n_invokers = n_invokers
        self.kernel = kernel
        self.waterfall = waterfall
        self.prewarm = prewarm
        self.fleet_mesh = fleet_mesh
        self.stragglers = stragglers
        self.stragglers_applied: dict = {}
        self.bal = None
        self._fleet_stop = None
        self._feeds = None
        self._actions = None
        self._ident = None
        self._publish = None

    async def start(self) -> None:
        import bench
        from openwhisk_tpu.controller.loadbalancer import TpuBalancer
        from openwhisk_tpu.controller.loadbalancer.base import (
            HEALTHY, maybe_batch_publish)
        from openwhisk_tpu.core.entity import ControllerInstanceId, Identity
        from openwhisk_tpu.messaging import MemoryMessagingProvider
        from openwhisk_tpu.utils.waterfall import GLOBAL_WATERFALL

        GLOBAL_WATERFALL.enabled = self.waterfall
        GLOBAL_WATERFALL.reset()
        provider = MemoryMessagingProvider()
        # prewarm off by default: background XLA compiles are pure GIL
        # contention inside a latency-measurement window (the PR-5 lesson)
        self.bal = TpuBalancer(provider, ControllerInstanceId("0"),
                               managed_fraction=1.0, blackbox_fraction=0.0,
                               kernel=self.kernel, prewarm=self.prewarm,
                               fleet_mesh=self.fleet_mesh)
        # batch-shaped publish (ISSUE 14): the generator rides the same
        # front-door coalescer the controller's invoke path uses, so the
        # headline measures the shipped publish SPI (None when the knob
        # is off — the serial publish path, bit-exact)
        self._publish = maybe_batch_publish(self.bal)
        await self.bal.start()
        self._feeds, self._fleet_stop = await bench._echo_fleet(
            provider, self.n_invokers)
        # straggler injection (shared PR 4 idiom): delay the indexed echo
        # feeds' acks — the run's numbers then carry the skew they came
        # from in the JSON line (`stragglers`)
        self.stragglers_applied = apply_stragglers(self._feeds,
                                                   self.stragglers)
        for _ in range(120):
            health = await self.bal.invoker_health()
            if sum(h.status == HEALTHY for h in health) >= self.n_invokers:
                break
            await asyncio.sleep(0.25)
        else:
            raise RuntimeError("loadgen: fleet never became healthy")
        self._actions = [bench._bench_action(f"ol{i}", memory=128)
                         for i in range(8)]
        self._ident = Identity.generate("guest")

    async def one(self, i: int, sched_ns: int) -> bool:
        import bench  # noqa: F401 — path bootstrap already done at start()
        from openwhisk_tpu.core.entity import (ActivationId,
                                               ControllerInstanceId)
        from openwhisk_tpu.messaging import ActivationMessage
        from openwhisk_tpu.utils.transaction import TransactionId
        from openwhisk_tpu.utils.waterfall import GLOBAL_WATERFALL
        action = self._actions[i % len(self._actions)]
        msg = ActivationMessage(
            TransactionId(), action.fully_qualified_name, action.rev.rev,
            self._ident, ActivationId.generate(), ControllerInstanceId("0"),
            True, {})
        aid = msg.activation_id.asString
        # anchor at the SCHEDULED arrival: the publish_enqueue delta then
        # carries the open-loop send lag (coordinated-omission-correct)
        GLOBAL_WATERFALL.begin(aid, t0_ns=sched_ns)
        try:
            if self._publish is not None:
                promise = await self._publish.publish(action, msg)
            else:
                promise = await self.bal.publish(action, msg)
            await promise
            return True
        except Exception:  # noqa: BLE001 — the row counts it as an error
            GLOBAL_WATERFALL.discard(aid)
            return False

    async def stop(self) -> None:
        if self._fleet_stop is not None:
            await self._fleet_stop()
        if self.bal is not None:
            await self.bal.close()
        if self._feeds:
            for f in self._feeds:
                await f.stop()


# -- the shared funnel deployment (ISSUE 20) -------------------------------

FUNNEL_READY_PREFIX = "FUNNELREADY:"


def _free_port() -> int:
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class _FunnelTarget:
    """Worker-side target for the SHARED deployment (`--funnel H:P`):
    a `FunnelBalancer` front end forwarding each admission wave as ONE
    fence-stamped columnar frame over the TCP bus to the device-owning
    balancer process (`--serve-funnel`). Same `one()` contract as
    `_BalancerTarget`, but the placement/completion stages live in the
    OTHER process — so no waterfall anchor here (the worker measures the
    e2e the client sees; the balancer process owns the stage budget)."""

    def __init__(self, endpoint: str, worker_ident: Optional[int] = None):
        host, _, port = str(endpoint).rpartition(":")
        self.host, self.port = host or "127.0.0.1", int(port)
        # origins 100+ keep the front-end instance ids clear of the
        # balancer's own controller id space
        self.origin = 100 + (worker_ident or 0)
        self.bal = None
        self._publish = None
        self._actions = None
        self._ident = None
        self.stragglers_applied: dict = {}

    async def start(self) -> None:
        import bench
        from openwhisk_tpu.controller.loadbalancer.base import \
            maybe_batch_publish
        from openwhisk_tpu.controller.loadbalancer.funnel import \
            FunnelBalancer
        from openwhisk_tpu.core.entity import ControllerInstanceId, Identity
        from openwhisk_tpu.messaging.tcp import TcpMessagingProvider

        from openwhisk_tpu.utils.waterfall import GLOBAL_WATERFALL
        # the placement stages live in the balancer process: this
        # worker never stamps a waterfall, so keep the plane off here
        GLOBAL_WATERFALL.enabled = False
        provider = TcpMessagingProvider(self.host, self.port)
        self.bal = FunnelBalancer(provider,
                                  ControllerInstanceId(str(self.origin)),
                                  target=0)
        # the same front-door coalescer the controller's invoke path
        # uses: one API wave -> one publish_many -> one wire frame
        self._publish = maybe_batch_publish(self.bal)
        await self.bal.start()
        self._actions = [bench._bench_action(f"ol{i}", memory=128)
                         for i in range(8)]
        self._ident = Identity.generate("guest")

    async def one(self, i: int, sched_ns: int) -> bool:
        from openwhisk_tpu.core.entity import (ActivationId,
                                               ControllerInstanceId)
        from openwhisk_tpu.messaging import ActivationMessage
        from openwhisk_tpu.utils.transaction import TransactionId
        action = self._actions[i % len(self._actions)]
        msg = ActivationMessage(
            TransactionId(), action.fully_qualified_name, action.rev.rev,
            self._ident, ActivationId.generate(), ControllerInstanceId("0"),
            True, {})
        try:
            if self._publish is not None:
                promise = await self._publish.publish(action, msg)
            else:
                promise = await self.bal.publish(action, msg)
            await promise
            return True
        except Exception:  # noqa: BLE001 — a 429/503 is an error sample
            return False

    async def stop(self) -> None:
        if self.bal is not None:
            await self.bal.close()


def serve_funnel(n_invokers: int = 16, kernel: str = "auto",
                 port: Optional[int] = None) -> None:
    """The balancer-role process of the shared deployment: boots the TCP
    bus broker on a free port, the ONE TpuBalancer owning the (simulated)
    device fleet, the echo-invoker fleet, and a `FunnelReceiver` draining
    `ctrlfunnel0`. Prints `FUNNELREADY:{"port": P}` once the fleet is
    healthy, then serves until stdin closes (the parent's shutdown
    signal) or SIGTERM."""

    async def go() -> None:
        import bench
        import signal
        import threading
        from openwhisk_tpu.controller.loadbalancer import TpuBalancer
        from openwhisk_tpu.controller.loadbalancer.base import HEALTHY
        from openwhisk_tpu.controller.loadbalancer.funnel import \
            FunnelReceiver
        from openwhisk_tpu.core.entity import ControllerInstanceId
        from openwhisk_tpu.messaging.tcp import (TcpBusServer,
                                                 TcpMessagingProvider)

        p = port or _free_port()
        server = TcpBusServer("127.0.0.1", p)
        await server.start()
        provider = TcpMessagingProvider("127.0.0.1", p)
        bal = TpuBalancer(provider, ControllerInstanceId("0"),
                          managed_fraction=1.0, blackbox_fraction=0.0,
                          kernel=kernel, prewarm=False)
        await bal.start()
        feeds, fleet_stop = await bench._echo_fleet(provider, n_invokers)
        for _ in range(120):
            health = await bal.invoker_health()
            if sum(h.status == HEALTHY for h in health) >= n_invokers:
                break
            await asyncio.sleep(0.25)
        else:
            raise RuntimeError("serve-funnel: fleet never became healthy")
        # no entity store in the harness: resolve the workers' fixed
        # action set from a dict (same 8 actions every worker mints)
        by_name = {}
        for i in range(8):
            a = bench._bench_action(f"ol{i}", memory=128)
            by_name[str(a.fully_qualified_name)] = a

        async def resolver(name: str, rev: str):
            return by_name[name]

        recv = FunnelReceiver(provider, ControllerInstanceId("0"), bal,
                              resolver=resolver)
        recv.start()
        print(FUNNEL_READY_PREFIX + json.dumps({"port": p}), flush=True)

        stop_ev = asyncio.Event()
        loop = asyncio.get_event_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            loop.add_signal_handler(sig, stop_ev.set)

        def watch_stdin() -> None:
            try:
                sys.stdin.read()
            except Exception:  # noqa: BLE001 — EOF either way
                pass
            loop.call_soon_threadsafe(stop_ev.set)

        threading.Thread(target=watch_stdin, daemon=True).start()
        await stop_ev.wait()
        await recv.stop()
        await fleet_stop()
        for f in feeds:
            await f.stop()
        await bal.close()
        await server.stop()

    asyncio.run(go())


async def _measure_step(target: _BalancerTarget, rate: float,
                        duration: float, dist: str, seed: int,
                        reset_waterfall: bool = True,
                        keep_samples: bool = False) -> dict:
    from openwhisk_tpu.utils.waterfall import GLOBAL_WATERFALL
    if reset_waterfall and GLOBAL_WATERFALL.enabled:
        GLOBAL_WATERFALL.reset()
    n = max(1, int(rate * duration))
    offsets = make_schedule(rate, n, dist=dist, seed=seed)
    row = await open_loop(target.one, offsets)
    samples = row.pop("samples_ms")
    if keep_samples:
        # the multi-process merge needs the raw samples (rounded): merged
        # percentiles must come from the union, not from per-worker
        # quantiles (which do not compose)
        row["samples_ms"] = [round(s, 3) for s in samples]
    row["offered_rate"] = rate
    return row


def sweep_balancer(rate0: float = 32.0, duration: float = 2.5,
                   max_doublings: int = 8,
                   p99_bound_ms: float = DEFAULT_P99_BOUND_MS,
                   dist: str = "poisson", n_invokers: int = 16,
                   kernel: str = "auto", waterfall: bool = True,
                   fixed_rate: Optional[float] = None, seed: int = 1,
                   host_observatory: Optional[bool] = None,
                   gc_tune: bool = True, fleet_mesh: bool = False,
                   keep_samples: bool = False,
                   worker_ident: Optional[int] = None,
                   stragglers=None, trace_keep_all: bool = False,
                   trace_export: Optional[str] = None,
                   funnel: Optional[str] = None) -> dict:
    """The observatory: sweep offered rate (doubling from `rate0`) to the
    max sustainable throughput, then re-measure that rate for the headline
    row + the waterfall's per-stage budget. `fixed_rate` skips the sweep
    and measures one rate. Returns the `e2e_open_loop` block.

    `host_observatory`: True arms the host hot-loop observatory
    (utils/hostprof.py) on the generator/balancer loop for the run and
    attaches its snapshot as `host` — the bench riders' measured target
    list; False forces it (and its always-on serde accounting) off for the
    overhead rider's OFF half; None (default) leaves the process-global
    state alone.

    `gc_tune` (default True, reported as `gc_tuned` in the block): after
    the target boots, freeze the permanent heap out of the collector and
    raise the GC thresholds (utils/hostprof.py tune_gc) — the same knob a
    production controller gets via CONFIG_whisk_host_gc_enabled. Without
    it, CPython's default full-heap gen-2 collections stall the loop
    100-250 ms mid-window and the fire-lag verdict blames the generator;
    the open_loop GC self-check still measures and reports whatever
    pauses remain, so the tuning is a measured choice, not a blind one."""

    async def go() -> dict:
        from openwhisk_tpu.utils.hostprof import GLOBAL_HOST_OBSERVATORY
        from openwhisk_tpu.utils.waterfall import GLOBAL_WATERFALL
        if worker_ident is not None:
            # --procs worker: stamp the fleet-observatory identity block so
            # the parent's merged snapshot carries per-member provenance
            from openwhisk_tpu.utils.eventlog import set_identity
            set_identity(instance=worker_ident, role="loadgen")
        obs_installed = False
        if host_observatory is not None:
            GLOBAL_HOST_OBSERVATORY.enabled = bool(host_observatory)
            if host_observatory:
                GLOBAL_HOST_OBSERVATORY.reset()
                obs_installed = GLOBAL_HOST_OBSERVATORY.install()
        # trace observatory riders (ISSUE 18): `--trace-keep-all` forces
        # the tail-sampling floor to 1.0 (every completion keeps) and
        # widens the kept ring to hold a whole run; `--trace-export`
        # dumps the kept traces as NDJSON after the run. Both arm the
        # plane BEFORE the balancer boots (the balancer hook attaches the
        # reporter tee at construction).
        trace_armed = bool(trace_keep_all or trace_export)
        if trace_armed:
            import dataclasses
            from openwhisk_tpu.utils.tracestore import GLOBAL_TRACE_STORE
            GLOBAL_TRACE_STORE.enabled = True
            if trace_keep_all:
                GLOBAL_TRACE_STORE.config = dataclasses.replace(
                    GLOBAL_TRACE_STORE.config, keep_floor=1.0,
                    keep_ring=65536)
                GLOBAL_TRACE_STORE._floor_every = 1
            GLOBAL_TRACE_STORE.reset()
        if funnel:
            # shared deployment worker: the system under test lives in
            # the --serve-funnel process; this process is front end only
            target = _FunnelTarget(funnel, worker_ident)
        else:
            target = _BalancerTarget(n_invokers=n_invokers, kernel=kernel,
                                     waterfall=waterfall,
                                     fleet_mesh=fleet_mesh,
                                     stragglers=stragglers)
        await target.start()
        gc_tuned = None
        if gc_tune:
            from openwhisk_tpu.utils.hostprof import tune_gc
            gc_tuned = tune_gc(force=True)
        try:
            # warm long enough to actually FINISH the first-sight compiles
            # a rate's batch/release buckets trigger (ISSUE 8's coalescing
            # forms bigger micro-batches, so a rate now exercises more
            # bucket shapes than the eager path did — a short warm leaked
            # those compiles into the measured window, where a ~1 s stall
            # reads exactly like saturation)
            warm_t = max(1.0, duration / 2)

            ladder_done = False

            async def warm(rate: float, passes: int = 1) -> None:
                # per-rate warmup: a higher rate fills bigger micro-batch
                # buckets whose fused program jit-compiles on first sight —
                # inside a measured window that compile stall would read as
                # a (false) saturation verdict
                nonlocal ladder_done
                if not ladder_done:
                    ladder_done = True
                    # deterministic bucket-ladder warm, ONCE: a saturating
                    # rate warm jumps straight to the biggest (R, B)
                    # bucket, so the middle power-of-two shapes (a
                    # draining tail passes through 64, 128...) would
                    # first-sight-compile INSIDE a measured window. One
                    # same-sweep burst per bucket touches each fused +
                    # release-only program here instead (~6 shapes total
                    # under the shared-bucket rule).
                    cap = getattr(target.bal, "max_batch", 256)
                    k = 8
                    while k <= cap:
                        await open_loop(target.one, [0.0] * k,
                                        drain_timeout=30.0)
                        k *= 2
                for p in range(passes):
                    await _measure_step(target, rate, warm_t, dist,
                                        seed + 97 + p)

            def judge(r: dict) -> bool:
                r["verdict"] = verdict(r, p99_bound_ms)
                r["sustainable"] = r["verdict"]["sustainable"]
                return r["sustainable"]

            steps = []
            swept_ok = False
            if fixed_rate is not None:
                sustained_rate = fixed_rate
                # no ramp precedes a fixed-rate run, so it must absorb ALL
                # its bucket compiles here — two full passes
                await warm(fixed_rate, passes=2)
            else:
                rate, sustained_rate = rate0, None
                for _ in range(max_doublings):
                    await warm(rate)
                    row = await _measure_step(target, rate, duration, dist,
                                              seed)
                    judge(row)
                    if not row["sustainable"]:
                        # one retry: a first-sight bucket-shape compile is
                        # a ONE-TIME stall that reads exactly like
                        # saturation (fire lag + a p99 spike); genuine
                        # saturation fails the retry too
                        retry = await _measure_step(target, rate, duration,
                                                    dist, seed + 31)
                        judge(retry)
                        retry["retried"] = True
                        if retry["sustainable"]:
                            row = retry
                        else:
                            steps.append(row)
                            row = retry
                    steps.append(row)
                    if not row["sustainable"]:
                        break
                    sustained_rate = rate
                    rate *= 2
                swept_ok = sustained_rate is not None
                if sustained_rate is None:
                    # even rate0 failed: measure it anyway so the block
                    # still carries numbers — but say so (sustained=false)
                    sustained_rate = rate0
            # confirmation run at the sustained rate: its percentiles and
            # per-stage budget are the headline (the sweep rows above only
            # bracketed it) — re-judged, so the top-level `sustained` flag
            # never launders an unsustainable rate into a headline
            if obs_installed:
                # scope the host observatory to the HEADLINE window:
                # warmup's first-sight jit compiles would otherwise own
                # the lag histogram and the self-time census
                GLOBAL_HOST_OBSERVATORY.reset()
            head = await _measure_step(target, sustained_rate, duration,
                                       dist, seed + 1,
                                       keep_samples=keep_samples)
            judge(head)
            if not head["sustainable"]:
                # same one-retry rule as the sweep steps: a stray stall
                # (GC, background compile) must not flip the headline
                if obs_installed:
                    # the snapshot scopes to the REPORTED window: without
                    # this, a retry leaves the failed attempt's tasks in
                    # the counters while `completed` counts only the
                    # retry — tasks/activation read ~2x
                    GLOBAL_HOST_OBSERVATORY.reset()
                head = await _measure_step(target, sustained_rate, duration,
                                           dist, seed + 61,
                                           keep_samples=keep_samples)
                judge(head)
                head["retried"] = True
            # a borderline TOP rung that passed the sweep once but fails
            # its confirmation must not wipe the whole headline: fall back
            # one rung at a time and confirm there (recorded — the
            # reported rate is then genuinely sustained, just lower)
            fb_seed = 211
            while (not head["sustainable"] and fixed_rate is None
                   and sustained_rate / 2 >= rate0):
                sustained_rate /= 2
                if obs_installed:
                    GLOBAL_HOST_OBSERVATORY.reset()
                head = await _measure_step(target, sustained_rate, duration,
                                           dist, seed + fb_seed,
                                           keep_samples=keep_samples)
                judge(head)
                if not head["sustainable"]:
                    if obs_installed:
                        GLOBAL_HOST_OBSERVATORY.reset()
                    head = await _measure_step(target, sustained_rate,
                                               duration, dist,
                                               seed + fb_seed + 17,
                                               keep_samples=keep_samples)
                    judge(head)
                    head["retried"] = True
                head["fell_back"] = True
                fb_seed += 41
            budget = (GLOBAL_WATERFALL.budget() if GLOBAL_WATERFALL.enabled
                      else None)
            tail = (GLOBAL_WATERFALL.tail_attribution()
                    if GLOBAL_WATERFALL.enabled else None)
            if budget and head["p50_ms"] and \
                    budget.get("p50_decomposition_sum_ms") is not None:
                # the EXTERNAL accounting check: the waterfall's stage
                # budget vs the generator's own independently measured
                # e2e median (both anchored at scheduled arrival) — this
                # crosses instrumentation boundaries, so ~1 here means
                # the per-stage budget really explains the measured e2e
                budget["budget_vs_measured_p50"] = round(
                    budget["p50_decomposition_sum_ms"] / head["p50_ms"], 3)
            host = (GLOBAL_HOST_OBSERVATORY.snapshot() if obs_installed
                    else None)
            # exact-merge export for the --procs parent (ISSUE 16): raw
            # integer bucket counts merge bucket-wise bit-exactly; the
            # rendered snapshot's percentiles do not compose
            host_raw = (GLOBAL_HOST_OBSERVATORY.raw_counts()
                        if obs_installed and worker_ident is not None
                        else None)
            trace_stats = None
            traces_exported = None
            if trace_armed:
                from openwhisk_tpu.utils.tracestore import (
                    GLOBAL_TRACE_STORE, assemble_trace)
                trace_stats = GLOBAL_TRACE_STORE.stats()
                if trace_export:
                    # NDJSON: one assembled trace tree per kept entry —
                    # the one-JSON-line stdout contract stays untouched
                    n_exp = 0
                    with open(trace_export, "w") as f:
                        for e in GLOBAL_TRACE_STORE.entries():
                            f.write(json.dumps(assemble_trace(
                                e.get("trace_id") or "", [e])) + "\n")
                            n_exp += 1
                    traces_exported = n_exp
            return {
                "mode": "open_loop",
                "funnel_endpoint": funnel,
                "dist": dist,
                "gc_tuned": gc_tuned,
                "stragglers": {str(k): v for k, v
                               in target.stragglers_applied.items()},
                "fleet_mesh": bool(fleet_mesh),
                "fleet_shards": getattr(target.bal, "n_shards", 1),
                "sustained": bool(head["sustainable"]
                                  and (fixed_rate is not None or swept_ok)),
                "sustained_activations_per_sec": head["throughput_per_sec"],
                "sustained_offered_rate": sustained_rate,
                "p50_ms": head["p50_ms"],
                "p99_ms": head["p99_ms"],
                "p99_bound_ms": p99_bound_ms,
                "latency_base": "scheduled_arrival",
                "headline": head,
                "sweep": steps,
                "stage_budget": budget,
                "tail_attribution": tail,
                "host": host,
                "host_raw": host_raw,
                "n_invokers": n_invokers,
                "trace_keep_all": bool(trace_keep_all),
                "trace_export": trace_export,
                "traces_exported": traces_exported,
                "trace_stats": trace_stats,
            }
        finally:
            await target.stop()
            if obs_installed:
                GLOBAL_HOST_OBSERVATORY.uninstall()

    return asyncio.run(go())


def multiproc_fixed_rate(rate: float, procs: int, duration: float = 2.5,
                         p99_bound_ms: float = DEFAULT_P99_BOUND_MS,
                         dist: str = "poisson", n_invokers: int = 16,
                         kernel: str = "auto", seed: int = 1,
                         fleet_mesh: bool = False, gc_tune: bool = True,
                         waterfall: bool = True,
                         host_observatory: bool = False,
                         timeout_s: float = 600.0,
                         shared: bool = False) -> dict:
    """`--procs N`: the multi-process generator (ROADMAP item 1's "keep
    the verdict honest" note). At 4k+ offered/s ONE Python generator loop
    is itself a measurable fraction of the box: its task churn and GC
    share the core with the system under test, and fire-lag verdicts
    start blaming the harness. This mode forks N worker generators, each
    firing an INDEPENDENT Poisson schedule at rate/N (independent Poisson
    processes superpose to a Poisson process at the full rate, so the
    offered process is exactly the single-generator one), and merges the
    per-worker SAMPLES into the headline percentiles — merged from the
    union, because quantiles do not compose across workers. Each worker
    keeps its own open_loop self-check, so a failed verdict still blames
    the specific worker (gc_pause vs event_loop_stall) instead of the
    fleet.

    Honesty note, by design (`topology: "twins"`): each worker drives
    its OWN balancer + echo fleet twin (the in-process publish entry
    point cannot be shared across processes). The merged number is
    therefore N generator-honest twins at rate/N each, the right verdict
    when the question is "is the GENERATOR the bottleneck", and says so
    in `targets`.

    `shared=True` (`topology: "shared"`, ISSUE 20) removes that caveat:
    ONE `--serve-funnel` balancer process owns the device fleet, and the
    N workers are front-end processes forwarding their admission waves
    over the TCP bus funnel. The merged-schedule sustained rate is then
    the SYSTEM-under-test headline — one shared balancer really placed
    every row — which is exactly the number the twins mode must not
    claim."""
    import subprocess

    procs = max(1, int(procs))
    share = rate / procs
    serve = None
    funnel_endpoint = None
    balancer_note = None
    serve_err = None
    if shared:
        import tempfile
        serve_cmd = [sys.executable, os.path.abspath(__file__),
                     "--serve-funnel", "--invokers", str(n_invokers),
                     "--kernel", kernel]
        # stderr to a spool file: the balancer process outlives the
        # workers and logs freely — a PIPE would fill and wedge it
        serve_err = tempfile.TemporaryFile(mode="w+")
        serve = subprocess.Popen(serve_cmd, stdin=subprocess.PIPE,
                                 stdout=subprocess.PIPE,
                                 stderr=serve_err, text=True)
        ready_by = time.monotonic() + 120.0
        while time.monotonic() < ready_by:
            line = serve.stdout.readline()
            if not line:
                break  # balancer process died before becoming ready
            if line.startswith(FUNNEL_READY_PREFIX):
                p = json.loads(line[len(FUNNEL_READY_PREFIX):])["port"]
                funnel_endpoint = f"127.0.0.1:{p}"
                break
        if funnel_endpoint is None:
            serve.kill()
            try:
                serve.wait(timeout=10.0)
            except Exception:  # noqa: BLE001 — diagnostics only
                pass
            serve_err.seek(0)
            err = serve_err.read()
            serve_err.close()
            raise RuntimeError(
                "shared deployment: balancer process never became ready"
                + (f"; stderr tail: {err[-400:]}" if err else ""))
    try:
        workers = []
        for i in range(procs):
            cmd = [sys.executable, os.path.abspath(__file__),
                   "--rate", str(share), "--duration", str(duration),
                   "--dist", dist, "--invokers", str(n_invokers),
                   "--kernel", kernel, "--seed",
                   str(seed + 1009 * (i + 1)),
                   "--p99-bound-ms", str(p99_bound_ms), "--emit-samples"]
            if shared:
                # funnel worker: front end only — the waterfall stages
                # live in the balancer process, and the worker ident
                # keys its funnel origin instance
                cmd += ["--funnel", funnel_endpoint, "--no-waterfall",
                        "--worker-ident", str(i)]
            if fleet_mesh:
                cmd.append("--fleet-mesh")
            if not gc_tune:
                cmd.append("--no-gc-tune")
            if not waterfall and not shared:
                cmd.append("--no-waterfall")
            if host_observatory:
                # each worker stamps its fleet identity and emits raw
                # integer bucket counts; the parent merges them into ONE
                # fleet snapshot (ISSUE 16) instead of N per-worker blobs
                cmd.append("--host-observatory")
                if not shared:
                    cmd += ["--worker-ident", str(i)]
            workers.append(subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                            stderr=subprocess.PIPE,
                                            text=True))
        rows: List[Optional[dict]] = []
        stderr_tails: List[Optional[str]] = []
        # one shared deadline for the whole fleet: the workers run
        # CONCURRENTLY, so the sequential reap hands each communicate() the
        # time REMAINING, not a fresh full budget (procs wedged workers must
        # cost ~timeout_s total, not procs * timeout_s)
        deadline = time.monotonic() + timeout_s
        for p in workers:
            try:
                out, err = p.communicate(
                    timeout=max(0.0, deadline - time.monotonic()))
                row = None
                for line in reversed(out.splitlines()):
                    line = line.strip()
                    if line.startswith("{"):
                        try:
                            row = json.loads(line)
                        except ValueError:
                            # a partial flush from a dying worker (or a
                            # '{'-prefixed log line) must not crash the
                            # parent and discard every OTHER worker's row
                            continue
                        break
                rows.append(row)
                # keep a diagnostic tail so a dead worker's traceback (or its
                # own error-fallback JSON) survives into the per_worker row
                stderr_tails.append(err[-500:] if err else None)
            except subprocess.TimeoutExpired:
                p.kill()
                # reap the killed child (no zombie, no Popen ResourceWarning)
                # and drain its pipes so partial diagnostics survive
                try:
                    _out, err = p.communicate(timeout=10.0)
                except Exception:  # noqa: BLE001 — diagnostics only
                    err = ""
                rows.append(None)
                tail = f"worker timed out after {timeout_s:.0f}s"
                if err:
                    tail += f"; stderr tail: {err[-400:]}"
                stderr_tails.append(tail)
    finally:
        if serve is not None:
            # shutdown signal is stdin EOF; fall back to kill on a wedge
            try:
                serve.stdin.close()
            except Exception:  # noqa: BLE001
                pass
            try:
                serve.wait(timeout=30.0)
            except Exception:  # noqa: BLE001 — includes TimeoutExpired
                serve.kill()
                try:
                    serve.wait(timeout=10.0)
                except Exception:  # noqa: BLE001
                    pass
            err = ""
            try:
                serve_err.seek(0)
                err = serve_err.read()
                serve_err.close()
            except Exception:  # noqa: BLE001 — diagnostics only
                pass
            balancer_note = err[-500:] if err else None
    ok_rows = [r for r in rows if r and (r.get("headline") or {})]
    samples = sorted(s for r in ok_rows
                     for s in (r.get("headline") or {}).get("samples_ms")
                     or [])

    def pctl(q: float) -> Optional[float]:
        if not samples:
            return None
        return round(samples[min(len(samples) - 1, int(q * len(samples)))],
                     3)

    per_worker = []
    for i, r in enumerate(rows):
        if r is None or r.get("error"):
            row = {"worker": i,
                   "error": (r or {}).get("error") or "no JSON line "
                   "(crashed or timed out)"}
            if stderr_tails[i]:
                row["stderr_tail"] = stderr_tails[i]
            per_worker.append(row)
            continue
        head = r.get("headline") or {}
        gen = head.get("generator") or {}
        row = {
            "worker": i,
            "offered_rate": share,
            "sustained": r.get("sustained"),
            "throughput_per_sec": head.get("throughput_per_sec"),
            "p99_ms": head.get("p99_ms"),
            "verdict": head.get("verdict"),
            "blames": (head.get("verdict") or {}).get("blames"),
            "max_fire_lag_ms": gen.get("max_fire_lag_ms"),
            "gc_pauses": gen.get("gc_pauses"),
        }
        per_worker.append(row)
    # ONE fleet-merged host snapshot (ISSUE 16): the workers export raw
    # integer bucket counts (host_raw), which merge bucket-wise
    # bit-exactly — the federation's merge math, reused verbatim —
    # replacing the N per-worker blobs this mode used to emit
    host_fleet = None
    if host_observatory:
        host_raws = [r.get("host_raw") for r in ok_rows
                     if r.get("host_raw")]
        if host_raws:
            from openwhisk_tpu.controller.monitoring import \
                merged_host_report
            host_fleet = merged_host_report(host_raws)
    merged_p99 = pctl(0.99)
    all_sustained = (len(ok_rows) == procs
                     and all(r.get("sustained") for r in ok_rows))
    fleet_sustained_per_sec = round(
        sum(w.get("throughput_per_sec") or 0.0
            for w in per_worker if "error" not in w), 1)
    if shared:
        targets = ("one shared balancer+fleet process behind the " +
                   str(procs) + "-worker admission funnel; the merged-"
                   "schedule sustained rate IS the system-under-test "
                   "headline")
    else:
        targets = ("one balancer+fleet twin per worker (generator-"
                   "honesty mode; the single-process headline remains "
                   "the system-under-test number)")
    return {
        "mode": "open_loop_multiproc",
        "topology": "shared" if shared else "twins",
        "procs": procs,
        "dist": dist,
        "offered_rate": rate,
        "per_worker_rate": share,
        "targets": targets,
        "funnel_endpoint": funnel_endpoint,
        "balancer_stderr_tail": balancer_note,
        "sustained": bool(all_sustained
                          and merged_p99 is not None
                          and merged_p99 <= p99_bound_ms),
        "sustained_activations_per_sec": fleet_sustained_per_sec,
        "fleet_merged_sustained_per_sec": fleet_sustained_per_sec,
        "completed": len(samples),
        "p50_ms": pctl(0.50),
        "p90_ms": pctl(0.90),
        "p99_ms": merged_p99,
        "p99_bound_ms": p99_bound_ms,
        "latency_base": "scheduled_arrival",
        "host_fleet": host_fleet,
        "per_worker": per_worker,
    }


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rate0", type=float, default=32.0,
                    help="sweep starting offered rate (doubles upward)")
    ap.add_argument("--rate", type=float, default=None,
                    help="skip the sweep: measure this fixed rate")
    ap.add_argument("--duration", type=float, default=2.5,
                    help="seconds per measured step")
    ap.add_argument("--dist", choices=("poisson", "constant"),
                    default="poisson")
    ap.add_argument("--p99-bound-ms", type=float,
                    default=DEFAULT_P99_BOUND_MS)
    ap.add_argument("--invokers", type=int, default=16)
    ap.add_argument("--kernel", default="auto")
    ap.add_argument("--no-waterfall", action="store_true")
    ap.add_argument("--host-observatory", action="store_true",
                    help="arm the host hot-loop observatory "
                         "(utils/hostprof.py) for the run and attach its "
                         "snapshot as `host` in the JSON line")
    ap.add_argument("--no-gc-tune", action="store_true",
                    help="skip the harness GC tuning (freeze + raised "
                         "thresholds); default is tuned, reported in "
                         "`gc_tuned`")
    ap.add_argument("--serve-funnel", action="store_true",
                    help="run the SHARED deployment's balancer-role "
                         "process: TCP bus broker + the one device-"
                         "owning balancer + echo fleet + FunnelReceiver; "
                         "prints FUNNELREADY:{\"port\": P} when healthy "
                         "and serves until stdin closes")
    ap.add_argument("--serve-port", type=int, default=None,
                    help="fixed port for --serve-funnel (default: pick "
                         "a free one)")
    ap.add_argument("--funnel", default=None, metavar="HOST:PORT",
                    help="worker mode for the shared deployment: drive a "
                         "FunnelBalancer front end against the "
                         "--serve-funnel process at HOST:PORT instead of "
                         "an in-process balancer twin")
    ap.add_argument("--shared", action="store_true",
                    help="with --procs N: ONE shared balancer process "
                         "(auto-spawned --serve-funnel) fed by N funnel "
                         "front-end workers — topology 'shared' — "
                         "instead of N independent balancer twins")
    ap.add_argument("--procs", type=int, default=1,
                    help="fork N worker generators with partitioned "
                         "Poisson schedules at rate/N each and merge the "
                         "per-worker sample sets (requires --rate; keeps "
                         "generator churn off the verdict at 4k+/s)")
    ap.add_argument("--seed", type=int, default=1,
                    help="schedule seed (workers get derived seeds)")
    ap.add_argument("--emit-samples", action="store_true",
                    help="keep the headline run's raw latency samples in "
                         "the JSON line (the --procs parent merges them)")
    ap.add_argument("--worker-ident", type=int, default=None,
                    help="(set by the --procs parent) this worker's fleet "
                         "identity instance; stamps identity blocks and "
                         "emits host_raw for the parent's exact merge")
    ap.add_argument("--stragglers", default=None,
                    help="inject ack-delay stragglers into the echo fleet: "
                         "'IDX:DELAY_S[,IDX:DELAY_S...]' (bare IDX = "
                         "0.25 s); the applied map is reported in the "
                         "JSON line")
    ap.add_argument("--trace-keep-all", action="store_true",
                    help="force the trace observatory's tail-sampling "
                         "floor to 1.0 for the run: every completion "
                         "keeps its trace (widens the kept ring to hold "
                         "the whole run)")
    ap.add_argument("--trace-export", default=None, metavar="PATH",
                    help="after the run, dump the kept traces as NDJSON "
                         "(one assembled span tree per line) to PATH; "
                         "stdout keeps its one-JSON-line contract")
    ap.add_argument("--fleet-mesh", action="store_true",
                    help="run the target balancer in fleet-mesh mode "
                         "(CONFIG_whisk_loadBalancer_fleetMesh semantics; "
                         "shard count = visible devices pow2-floored)")
    args = ap.parse_args()
    if args.serve_funnel:
        # the balancer-role process never prints a JSON verdict line —
        # its contract is the FUNNELREADY line + serving until EOF
        serve_funnel(n_invokers=args.invokers, kernel=args.kernel,
                     port=args.serve_port)
        return
    try:
        if args.procs > 1 or args.shared:
            if args.rate is None:
                ap.error("--procs/--shared requires --rate (fixed-rate "
                         "measurement; sweeps stay single-process)")
            if args.stragglers:
                ap.error("--stragglers is single-process only (each "
                         "--procs worker drives its own fleet twin, so "
                         "a shared straggler index is meaningless)")
            if args.trace_keep_all or args.trace_export:
                ap.error("--trace-keep-all/--trace-export are "
                         "single-process only (each worker's store is "
                         "its own; export from a single-process run)")
            out = multiproc_fixed_rate(
                rate=args.rate, procs=args.procs, duration=args.duration,
                p99_bound_ms=args.p99_bound_ms, dist=args.dist,
                n_invokers=args.invokers, kernel=args.kernel,
                seed=args.seed, fleet_mesh=args.fleet_mesh,
                gc_tune=not args.no_gc_tune,
                waterfall=not args.no_waterfall,
                host_observatory=args.host_observatory,
                shared=args.shared)
        else:
            out = sweep_balancer(rate0=args.rate0, duration=args.duration,
                                 p99_bound_ms=args.p99_bound_ms,
                                 dist=args.dist,
                                 n_invokers=args.invokers,
                                 kernel=args.kernel,
                                 waterfall=not args.no_waterfall,
                                 fixed_rate=args.rate, seed=args.seed,
                                 host_observatory=(True
                                                   if args.host_observatory
                                                   else None),
                                 gc_tune=not args.no_gc_tune,
                                 fleet_mesh=args.fleet_mesh,
                                 keep_samples=args.emit_samples,
                                 worker_ident=args.worker_ident,
                                 stragglers=args.stragglers,
                                 trace_keep_all=args.trace_keep_all,
                                 trace_export=args.trace_export,
                                 funnel=args.funnel)
    except Exception as e:  # noqa: BLE001 — one parseable line, always
        import traceback
        traceback.print_exc(file=sys.stderr)
        print(json.dumps({"mode": "open_loop", "error": f"{type(e).__name__}: {e}",
                          "sustained_activations_per_sec": None}))
        return
    print(json.dumps(out))


if __name__ == "__main__":
    main()

"""S3 AttachmentStore: large action code in an S3(-compatible) bucket.

Rebuild of common/scala/.../database/s3/S3AttachmentStore.scala — the
reference's production attachment backend. Speaks the S3 REST API directly
(no SDK in this image) with AWS Signature V4 request signing implemented
from the spec over stdlib hmac/hashlib, so it works against AWS S3, MinIO,
Ceph RGW, or any SigV4-compatible object store.

Wire surface used:
  PUT    /{bucket}/{key}                       upload (Content-Type kept)
  GET    /{bucket}/{key}                       download / 404 NoSuchKey
  DELETE /{bucket}/{key}                       delete
  GET    /{bucket}?list-type=2&prefix=...      enumerate a doc's attachments

Key layout mirrors the reference: {prefix}/{url-encoded doc id}/{name}.
Contract-tested against a fake S3 server that RE-VERIFIES every SigV4
signature server-side (tests/test_s3_attachments.py).
"""
from __future__ import annotations

import datetime
import hashlib
import hmac
import xml.etree.ElementTree as ET
from typing import List, Optional, Tuple
from urllib.parse import quote

import aiohttp

from .attachment_store import AttachmentStore
from .store import ArtifactStoreException, NoDocumentException

_ALGO = "AWS4-HMAC-SHA256"


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def sign_v4(method: str, host: str, path: str, query: List[Tuple[str, str]],
            payload: bytes, access_key: str, secret_key: str,
            region: str = "us-east-1",
            now: Optional[datetime.datetime] = None) -> dict:
    """AWS SigV4 headers for one request (docs: 'Signature Version 4
    signing process'). Signed headers: host, x-amz-content-sha256,
    x-amz-date — the minimal set S3 requires."""
    now = now or datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    datestamp = now.strftime("%Y%m%d")
    payload_hash = _sha256(payload)

    canonical_uri = quote(path, safe="/~")
    canonical_qs = "&".join(
        f"{quote(k, safe='~')}={quote(v, safe='~')}"
        for k, v in sorted(query))
    headers = {"host": host, "x-amz-content-sha256": payload_hash,
               "x-amz-date": amz_date}
    signed = ";".join(sorted(headers))
    canonical_headers = "".join(f"{k}:{headers[k]}\n" for k in sorted(headers))
    canonical_request = "\n".join([method, canonical_uri, canonical_qs,
                                   canonical_headers, signed, payload_hash])

    scope = f"{datestamp}/{region}/s3/aws4_request"
    string_to_sign = "\n".join([_ALGO, amz_date, scope,
                                _sha256(canonical_request.encode())])
    k = _hmac(_hmac(_hmac(_hmac(f"AWS4{secret_key}".encode(), datestamp),
                          region), "s3"), "aws4_request")
    signature = hmac.new(k, string_to_sign.encode(), hashlib.sha256).hexdigest()
    return {
        "x-amz-date": amz_date,
        "x-amz-content-sha256": payload_hash,
        "Authorization": (f"{_ALGO} Credential={access_key}/{scope}, "
                          f"SignedHeaders={signed}, Signature={signature}"),
    }


class S3AttachmentStore(AttachmentStore):
    def __init__(self, endpoint: str, bucket: str, access_key: str,
                 secret_key: str, prefix: str = "whisk-attachments",
                 region: str = "us-east-1"):
        self.endpoint = endpoint.rstrip("/")
        self.host = self.endpoint.split("://", 1)[-1]
        self.bucket = bucket
        self.prefix = prefix.strip("/")
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self._session: Optional[aiohttp.ClientSession] = None

    def _http(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession()
        return self._session

    def _key(self, doc_id: str, name: str = "") -> str:
        base = f"{self.prefix}/{quote(doc_id, safe='')}"
        return f"{base}/{name}" if name else base

    async def _request(self, method: str, path: str,
                       query: Optional[List[Tuple[str, str]]] = None,
                       payload: bytes = b"",
                       content_type: Optional[str] = None):
        query = query or []
        headers = sign_v4(method, self.host, path, query, payload,
                          self.access_key, self.secret_key, self.region)
        if content_type:
            headers["Content-Type"] = content_type
        url = self.endpoint + quote(path, safe="/~")
        if query:
            url += "?" + "&".join(f"{k}={quote(v, safe='~')}"
                                  for k, v in sorted(query))
        return self._http().request(method, url, data=payload or None,
                                    headers=headers)

    # -- AttachmentStore contract ------------------------------------------
    async def attach(self, doc_id: str, name: str, content_type: str,
                     data: bytes) -> None:
        path = f"/{self.bucket}/{self._key(doc_id, name)}"
        async with await self._request("PUT", path, payload=data,
                                       content_type=content_type) as resp:
            if resp.status != 200:
                raise ArtifactStoreException(
                    f"s3 put {path} failed ({resp.status}): "
                    f"{(await resp.text())[:256]}")

    async def read_attachment(self, doc_id: str, name: str) -> Tuple[str, bytes]:
        path = f"/{self.bucket}/{self._key(doc_id, name)}"
        async with await self._request("GET", path) as resp:
            if resp.status == 404:
                raise NoDocumentException(f"attachment {doc_id}/{name}")
            if resp.status != 200:
                raise ArtifactStoreException(
                    f"s3 get {path} failed ({resp.status})")
            return (resp.headers.get("Content-Type",
                                     "application/octet-stream"),
                    await resp.read())

    async def _list(self, doc_id: str) -> List[str]:
        path = f"/{self.bucket}"
        query = [("list-type", "2"), ("prefix", self._key(doc_id) + "/")]
        async with await self._request("GET", path, query=query) as resp:
            if resp.status != 200:
                raise ArtifactStoreException(
                    f"s3 list failed ({resp.status})")
            body = await resp.text()
        root = ET.fromstring(body)
        ns = root.tag.partition("}")[0] + "}" if root.tag.startswith("{") else ""
        return [el.text for el in root.iter(f"{ns}Key") if el.text]

    async def delete_attachments(self, doc_id: str,
                                 except_name: Optional[str] = None) -> None:
        keep = self._key(doc_id, except_name) if except_name else None
        for key in await self._list(doc_id):
            if key == keep:
                continue
            async with await self._request(
                    "DELETE", f"/{self.bucket}/{key}") as resp:
                if resp.status not in (200, 204, 404):
                    raise ArtifactStoreException(
                        f"s3 delete {key} failed ({resp.status})")

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()


class S3AttachmentStoreProvider:
    """AttachmentStoreProvider SPI binding
    (CONFIG_whisk_spi_AttachmentStoreProvider=
     openwhisk_tpu.database.s3_attachment_store:S3AttachmentStoreProvider)."""

    @staticmethod
    def make_store(**kwargs) -> S3AttachmentStore:
        return S3AttachmentStore(**kwargs)

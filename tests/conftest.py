"""Test configuration.

Multi-chip sharding is tested on a virtual 8-device CPU mesh: JAX must see
these env vars before its first import, so they are set at conftest import
time (pytest imports conftest before test modules).
"""
import os
import sys

# Force, not setdefault: the driver/judge environment exports
# JAX_PLATFORMS=axon (the TPU tunnel), and subprocesses spawned by tests
# inherit os.environ — a setdefault would leave them contending for the
# one tunneled chip.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Belt and suspenders for the pytest process itself (env var above covers
# spawned subprocesses; this covers the case where jax was imported before
# conftest in an embedding process).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

"""Kernel profiling observatory (ISSUE 3).

Covers: compile-event detection + classification (first call, expect
window, bucketed-shape predicate, shape churn) over plain and real-jit
entry points; the recompile watchdog (structured warning + tagged
counter); the capture window arm/drain cycle (with the real
`jax.profiler` trace marked slow); tail-sampling admission; the guarded
memory-stats read; the disabled-profiler true-no-op contract asserted
with tracemalloc; the balancer integrations (induced shape churn on the
TPU balancer classifying expected=false, CPU twins answering the same
profile shape); and the two /admin/profile/* controller endpoints.
"""
import asyncio
import base64
import tracemalloc

import aiohttp
import numpy as np
import pytest

from openwhisk_tpu.controller.loadbalancer import (LeanBalancer,
                                                   ShardingBalancer,
                                                   TpuBalancer)
from openwhisk_tpu.core.entity import (ControllerInstanceId, Identity,
                                       WhiskAuthRecord)
from openwhisk_tpu.messaging import MemoryMessagingProvider
from openwhisk_tpu.ops.profiler import (KernelProfiler, ProfilingConfig,
                                        pow2_statics)
from openwhisk_tpu.utils.logging import MetricEmitter
from tests.test_balancers import _fleet, _ping_all, make_action, make_msg


class _WarnCatcher:
    def __init__(self):
        self.warns = []

    def warn(self, transid, msg, component=""):
        self.warns.append(msg)

    def info(self, *a, **k):
        pass

    error = info


def _prof(**cfg) -> KernelProfiler:
    p = KernelProfiler(ProfilingConfig(**cfg))
    p.metrics = MetricEmitter()
    p.logger = _WarnCatcher()
    return p


class TestCompileClassification:
    def test_first_call_then_cache_hits(self):
        p = _prof()
        calls = []
        f = p.wrap("entry", lambda x: calls.append(x) or len(calls))
        a = np.zeros((8,), np.int32)
        assert f(a) == 1 and f(a) == 2 and f(np.ones((8,), np.int32)) == 3
        log = p.compile_log()
        assert len(log) == 1  # same (shape, dtype) key: one compile
        assert log[0]["expected"] is True and log[0]["reason"] == "first_call"
        census = p.cache_census()["entry"]
        assert census == {"signatures": 1, "compiles": 1, "calls": 3}

    def test_expect_window_classifies_growth(self):
        p = _prof()
        f = p.wrap("entry", lambda x: x)
        f(np.zeros((8,), np.int32))
        p.expect("fleet_growth")
        f(np.zeros((16,), np.int32))  # new shape inside the window
        log = p.compile_log()
        assert [e["reason"] for e in log] == ["first_call", "fleet_growth"]
        assert all(e["expected"] for e in log)
        assert p.compiles_expected == 2 and p.compiles_unexpected == 0

    def test_bucketed_shape_predicate_vs_churn(self):
        p = _prof(expect_window_s=0.0)  # no grace window
        f = p.wrap("entry", lambda x, b: x, expected=pow2_statics)
        f(np.zeros((8,), np.int32), 8)    # first_call
        f(np.zeros((8,), np.int32), 16)   # pow2 static: bucketed_shape
        f(np.zeros((8,), np.int32), 13)   # non-pow2 static: churn
        log = p.compile_log()
        assert [e["reason"] for e in log] == \
            ["first_call", "bucketed_shape", "shape_churn"]
        assert p.compiles_unexpected == 1
        # the watchdog: structured warning + tagged counter
        assert any("shape churn" in w for w in p.logger.warns)
        assert p.metrics.counter_value("loadbalancer_kernel_recompiles_total",
                                       tags={"expected": "false"}) == 1
        assert p.metrics.counter_value("loadbalancer_kernel_recompiles_total",
                                       tags={"expected": "true"}) == 2

    def test_rewrap_with_new_fn_resets_signature_cache(self):
        p = _prof()
        f1 = p.wrap("entry", lambda x: 1)
        f1(np.zeros((8,), np.int32))
        p.expect("kernel_swap")
        f2 = p.wrap("entry", lambda x: 2)  # rebuilt entry point
        assert f2(np.zeros((8,), np.int32)) == 2
        log = p.compile_log()
        assert [e["reason"] for e in log] == ["first_call", "kernel_swap"]

    def test_real_jit_compiles_are_detected(self):
        import jax
        import jax.numpy as jnp
        p = _prof()
        f = p.wrap("jit", jax.jit(lambda x: jnp.sum(x * 2)))
        out = f(np.arange(8, dtype=np.int32))
        assert int(out) == 56
        f(np.arange(8, dtype=np.int32))       # cache hit
        f(np.arange(16, dtype=np.int32))      # second shape: new compile
        log = p.compile_log()
        assert len(log) == 2
        assert log[0]["wall_ms"] > log[1].get("_never", 0)  # wall recorded
        assert p.cache_census()["jit"]["signatures"] == 2


class TestPhasesAndMemory:
    def test_phase_rollups_and_exposition(self):
        p = _prof(phase_window=64)
        for ms in (1.0, 2.0, 3.0, 100.0):
            p.observe_phase("readback", ms)
        roll = p.phase_rollups()["readback"]
        assert roll["count"] == 4
        assert roll["p50_ms"] in (2.0, 3.0)
        assert roll["p99_ms"] == 100.0
        text = p.prometheus_text()
        assert ("# TYPE openwhisk_loadbalancer_phase_duration_seconds "
                "histogram") in text
        assert 'phase="readback"' in text and 'le="+Inf"' in text
        # cumulative +Inf bucket equals _count
        inf_line = [l for l in text.splitlines() if 'le="+Inf"' in l][0]
        cnt_line = [l for l in text.splitlines() if "_count" in l][0]
        assert inf_line.rsplit(" ", 1)[1] == cnt_line.rsplit(" ", 1)[1] == "4"

    def test_memory_stats_guard_on_cpu(self):
        # CPU backend: memory_stats is absent/None — a guarded no-op dict
        p = _prof()
        stats = p.memory_stats()
        assert isinstance(stats, dict)
        m = MetricEmitter()
        out = p.refresh_memory(m)  # must never raise, whatever the backend
        assert isinstance(out, dict)

    def test_refresh_memory_gauges_and_watermark(self, monkeypatch):
        p = _prof()
        m = MetricEmitter()
        monkeypatch.setattr(p, "memory_stats", lambda: {
            "bytes_in_use": 1000, "peak_bytes_in_use": 1500,
            "bytes_limit": 4000})
        p.refresh_memory(m)
        monkeypatch.setattr(p, "memory_stats", lambda: {
            "bytes_in_use": 500, "bytes_limit": 4000})
        p.refresh_memory(m)
        assert m.gauge_value("loadbalancer_hbm_bytes_in_use") == 500
        # the high watermark survives a backend that stops reporting peak
        assert m.gauge_value("loadbalancer_hbm_peak_bytes_in_use") == 1500
        assert m.gauge_value("loadbalancer_hbm_bytes_limit") == 4000
        assert m.gauge_value("loadbalancer_hbm_utilization_ratio") == 0.125


class TestCaptureAndTailSampling:
    def test_capture_window_arm_and_drain(self):
        p = _prof(capture_limit=8)
        assert p.capture_step({"x": 1}) is False  # not armed
        status = p.arm_capture(3)
        assert status["armed"] and status["steps"] == 3
        assert p.capture_armed
        for i in range(3):
            assert p.capture_step({"step": i}) is True
        assert p.capture_step({"step": 99}) is False  # drained
        assert not p.capture_armed
        cap = p.profile_json("xla")["capture"]
        assert cap["captured"] == 3 and cap["remaining"] == 0
        assert [r["step"] for r in cap["steps"]] == [0, 1, 2]

    def test_capture_steps_capped_at_limit(self):
        p = _prof(capture_limit=4)
        assert p.arm_capture(10_000)["steps"] == 4

    def test_tail_sampling_admission(self):
        p = _prof(tail_threshold_ms=50.0)
        assert p.admit_batch(10.0) is False   # fast batch: row dropped
        assert p.admit_batch(60.0) is True    # slow batch: kept
        assert p.tail_skipped == 1
        p.arm_capture(2)
        assert p.admit_batch(10.0) is True    # capture wants everything
        p2 = _prof()  # threshold 0: everything kept
        assert p2.admit_batch(0.001) is True and p2.tail_skipped == 0

    def test_rearm_retargets_tail_threshold(self):
        p = _prof()
        p.arm_capture(1, tail_threshold_ms=25.0)
        assert p.tail_threshold_ms == 25.0
        p.capture_step({})
        assert p.admit_batch(10.0) is False

    @pytest.mark.slow
    def test_real_jax_profiler_trace(self, tmp_path):
        # the real jax.profiler wrap: arm with a trace_dir, drain, and the
        # trace directory must exist (contents are backend-dependent)
        import jax
        import jax.numpy as jnp
        p = _prof()
        status = p.arm_capture(1, trace_dir=str(tmp_path / "trace"))
        if not status["trace"].get("active"):
            pytest.skip(f"jax.profiler unavailable: {status['trace']}")
        jnp.sum(jnp.arange(16)).block_until_ready()
        p.capture_step({"step": 0})  # drains the window -> stops the trace
        assert p._trace_active is False
        assert (tmp_path / "trace").exists()


class TestDisabledNoOp:
    def test_wrap_is_identity_and_hot_paths_allocate_nothing(self):
        p = KernelProfiler(ProfilingConfig(enabled=False))

        def fn(x):
            return x

        assert p.wrap("entry", fn) is fn  # no wrapper frame at all
        assert p.admit_batch(1.0) is True
        # warm the paths once, then assert zero residual allocations
        p.observe_phase("assembly", 1.0)
        p.capture_step({})
        p.expect("x")
        tracemalloc.start()
        try:
            s1 = tracemalloc.take_snapshot()
            for _ in range(256):
                p.observe_phase("assembly", 1.0)
                p.admit_batch(1.0)
                p.capture_step({})
                p.expect("x")
            s2 = tracemalloc.take_snapshot()
        finally:
            tracemalloc.stop()
        flt = [tracemalloc.Filter(True, "*profiler.py")]
        grown = [d for d in s2.filter_traces(flt).compare_to(
            s1.filter_traces(flt), "lineno") if d.size_diff > 0]
        assert not grown, f"disabled profiler allocated: {grown}"

    def test_env_off_switch_leaves_balancer_unwrapped(self, monkeypatch):
        monkeypatch.setenv("CONFIG_whisk_profiling_enabled", "false")

        async def go():
            provider = MemoryMessagingProvider()
            bal = TpuBalancer(provider, ControllerInstanceId("0"),
                              managed_fraction=1.0, blackbox_fraction=0.0)
            try:
                assert bal.profiler.enabled is False
                # wrap() returned the jitted callables unchanged: the
                # dispatch hot path carries no profiler frame
                assert not hasattr(bal._packed_fn, "_kernel_profiled")
                assert not hasattr(bal._release_packed_fn,
                                   "_kernel_profiled")
                assert bal.profiler.cache_census() == {}
            finally:
                await bal.close()

        asyncio.run(go())

    def test_config_env_overrides(self, monkeypatch):
        monkeypatch.setenv("CONFIG_whisk_profiling_compileLog", "7")
        monkeypatch.setenv("CONFIG_whisk_profiling_tailThresholdMs", "12.5")
        p = KernelProfiler.from_config()
        assert p.config.compile_log == 7
        assert p.tail_threshold_ms == 12.5


class TestBalancerIntegration:
    def test_tpu_dispatch_profiles_and_churn_classifies_unexpected(self):
        async def go():
            provider = MemoryMessagingProvider()
            bal = TpuBalancer(provider, ControllerInstanceId("0"),
                              managed_fraction=1.0, blackbox_fraction=0.0)
            await bal.start()
            invokers, producer = await _fleet(provider, 2)
            await _ping_all(invokers, producer)
            ident = Identity.generate("guest")
            action = make_action("profiled", memory=128)
            msgs = [make_msg(action, ident, True) for _ in range(4)]
            await asyncio.gather(*[await bal.publish(action, m)
                                   for m in msgs])
            prof_before = bal.kernel_profile()
            # induce shape churn: a hand-rolled dispatch with a NON-pow2
            # batch bucket (bp=12) — a shape _bucket() can never produce
            rel = np.zeros((5, 8), np.int32)
            rel[3] = 1
            health = np.zeros((3, 64), np.int32)
            req = np.zeros((9, 12), np.int32)
            req[1] = 1
            req[6] = 1
            buf = np.concatenate([rel.ravel(), health.ravel(), req.ravel()])
            bal.state, _ = bal._packed_fn(bal.state, buf, 8, 64, 12)
            prof_after = bal.kernel_profile()
            churn = bal.metrics.counter_value(
                "loadbalancer_kernel_recompiles_total",
                tags={"expected": "false"})
            await bal.close()
            for inv in invokers:
                await inv.stop()
            return prof_before, prof_after, churn

        before, after, churn = asyncio.run(go())
        assert before["kernel"] in ("xla", "pallas")
        # the dispatch cycle reported every phase
        for phase in ("assembly", "dispatch", "readback", "fanout", "total"):
            assert before["phases"][phase]["count"] >= 1, phase
            assert before["phases"][phase]["p50_ms"] is not None
        # the first fused-program compile is in the log, expected
        assert before["compiles"]["expected"] >= 1
        assert before["compiles"]["unexpected"] == 0
        assert any(e["reason"] == "first_call"
                   for e in before["compiles"]["log"])
        assert "fused_step" in before["cache_census"]
        assert isinstance(before["memory"], dict)
        # the induced churn: classified expected=false, counter bumped
        assert after["compiles"]["unexpected"] == 1
        assert churn == 1
        bad = [e for e in after["compiles"]["log"] if not e["expected"]]
        assert bad and bad[-1]["reason"] == "shape_churn"

    def test_tail_sampling_skips_fast_batches_in_flight_recorder(self):
        async def go():
            provider = MemoryMessagingProvider()
            bal = TpuBalancer(provider, ControllerInstanceId("0"),
                              managed_fraction=1.0, blackbox_fraction=0.0)
            # every CPU-local batch completes far under 10 s: all sampled out
            bal.profiler.tail_threshold_ms = 10_000.0
            await bal.start()
            invokers, producer = await _fleet(provider, 2)
            await _ping_all(invokers, producer)
            ident = Identity.generate("guest")
            action = make_action("tails", memory=128)
            msgs = [make_msg(action, ident, True) for _ in range(3)]
            await asyncio.gather(*[await bal.publish(action, m)
                                   for m in msgs])
            n_records = len(bal.flight_recorder)
            skipped = bal.profiler.tail_skipped
            # gauges still refreshed for the sampled-out batches
            healthy = bal.metrics.gauge_value("loadbalancer_healthy_invokers")
            # a capture window overrides the sampler
            bal.profiler.arm_capture(4)
            more = [make_msg(action, ident, True) for _ in range(2)]
            await asyncio.gather(*[await bal.publish(action, m)
                                   for m in more])
            n_after_capture = len(bal.flight_recorder)
            captured = len(bal.profiler._capture_rows)
            await bal.close()
            for inv in invokers:
                await inv.stop()
            return n_records, skipped, healthy, n_after_capture, captured

        n_records, skipped, healthy, n_after, captured = asyncio.run(go())
        assert n_records == 0 and skipped >= 1
        assert healthy == 2
        assert n_after >= 1          # capture forced full rows back on
        assert captured >= 1
        # captured steps carry full detail
        # (decisions + timings ride the captured row)

    def test_cpu_twins_drain_capture_windows(self):
        """A capture window armed on a CPU twin must drain off its publish
        path (one step per publish) — otherwise POST /admin/profile/capture
        would arm a window that stays armed forever (and would never stop a
        live jax.profiler trace) on sharding/lean deployments."""
        async def go():
            provider = MemoryMessagingProvider()
            bal = ShardingBalancer(provider, ControllerInstanceId("0"),
                                   managed_fraction=1.0,
                                   blackbox_fraction=0.0)
            await bal.start()
            invokers, producer = await _fleet(provider, 2)
            await _ping_all(invokers, producer)
            ident = Identity.generate("guest")
            action = make_action("cpucap", memory=128)
            bal.profiler.arm_capture(2)
            for _ in range(3):
                await (await bal.publish(action,
                                         make_msg(action, ident, True)))
            cap = bal.kernel_profile()["capture"]
            await bal.close()
            for inv in invokers:
                await inv.stop()
            return cap

        cap = asyncio.run(go())
        assert cap["armed"] is False and cap["captured"] == 2
        assert all(r["kernel"] == "cpu" and "total_ms" in r
                   for r in cap["steps"])

    def test_cpu_twins_answer_the_same_profile_shape(self):
        async def go():
            provider = MemoryMessagingProvider()
            bal = ShardingBalancer(provider, ControllerInstanceId("0"),
                                   managed_fraction=1.0,
                                   blackbox_fraction=0.0)
            await bal.start()
            invokers, producer = await _fleet(provider, 2)
            await _ping_all(invokers, producer)
            ident = Identity.generate("guest")
            action = make_action("cpuprof", memory=128)
            msg = make_msg(action, ident, True)
            await (await bal.publish(action, msg))
            sharding = bal.kernel_profile()
            await bal.close()
            for inv in invokers:
                await inv.stop()

            class _DummyInvoker:
                async def stop(self):
                    pass

            async def factory(invoker_id, messaging_provider):
                return _DummyInvoker()

            lean = LeanBalancer(provider, ControllerInstanceId("1"), factory)
            await lean.start()
            msg2 = make_msg(action, ident, False)
            await lean.publish(action, msg2)
            lean_prof = lean.kernel_profile()
            await lean.close()
            return sharding, lean_prof

        sharding, lean_prof = asyncio.run(go())
        for prof, phase in ((sharding, "schedule"), (lean_prof, "dispatch")):
            assert prof["kernel"] == "cpu"
            assert prof["phases"][phase]["count"] >= 1
            assert prof["phases"][phase]["p50_ms"] is not None
            assert prof["compiles"]["log"] == []  # nothing jitted here
            assert prof["capture"]["armed"] is False
            assert isinstance(prof["memory"], dict)


PORT = 13381


class TestAdminEndpoints:
    """GET /admin/profile/kernel + POST /admin/profile/capture on a live
    controller HTTP surface, with a TpuBalancer placing through publish()."""

    def _run(self, scenario):
        from openwhisk_tpu.controller.core import Controller

        async def go():
            provider = MemoryMessagingProvider()
            bal = TpuBalancer(provider, ControllerInstanceId("0"),
                              managed_fraction=1.0, blackbox_fraction=0.0)
            controller = Controller(ControllerInstanceId("0"), provider,
                                    load_balancer=bal)
            ident = Identity.generate("guest")
            await controller.auth_store.put(WhiskAuthRecord(
                ident.subject, [ident.namespace], [ident.authkey]))
            await controller.start(port=PORT)
            invokers, producer = await _fleet(provider, 2)
            await _ping_all(invokers, producer)
            hdrs = {"Authorization": "Basic " + base64.b64encode(
                ident.authkey.compact.encode()).decode()}
            try:
                async with aiohttp.ClientSession() as s:
                    return await scenario(bal, ident, s, hdrs)
            finally:
                await controller.stop()
                for inv in invokers:
                    await inv.stop()

        return asyncio.run(go())

    def test_auth_required(self):
        async def scenario(bal, ident, s, hdrs):
            out = {}
            async with s.get(f"http://127.0.0.1:{PORT}"
                             "/admin/profile/kernel") as r:
                out["get"] = r.status
            async with s.post(f"http://127.0.0.1:{PORT}"
                              "/admin/profile/capture",
                              json={"steps": 2}) as r:
                out["post"] = r.status
            return out

        statuses = self._run(scenario)
        assert statuses == {"get": 401, "post": 401}

    def test_profile_and_capture_round_trip(self):
        async def scenario(bal, ident, s, hdrs):
            base = f"http://127.0.0.1:{PORT}/admin/profile"
            action = make_action("adminprof", memory=128)
            msgs = [make_msg(action, ident, True) for _ in range(3)]
            await asyncio.gather(*[await bal.publish(action, m)
                                   for m in msgs])
            out = {}
            async with s.get(base + "/kernel", headers=hdrs) as r:
                out["profile"] = (r.status, await r.json())
            async with s.post(base + "/capture", headers=hdrs,
                              json={"steps": 2}) as r:
                out["arm"] = (r.status, await r.json())
            more = [make_msg(action, ident, True) for _ in range(2)]
            for m in more:  # separate publishes: >= 2 dispatch steps
                await (await bal.publish(action, m))
            async with s.get(base + "/kernel", headers=hdrs) as r:
                out["after"] = (r.status, await r.json())
            async with s.post(base + "/capture", headers=hdrs,
                              json={"steps": 0}) as r:
                out["bad_steps"] = r.status
            async with s.post(base + "/capture", headers=hdrs,
                              json={"steps": "many"}) as r:
                out["bad_type"] = r.status
            return out

        out = self._run(scenario)
        status, prof = out["profile"]
        assert status == 200
        assert prof["enabled"] is True
        assert prof["kernel"] in ("xla", "pallas")
        assert prof["compiles"]["expected"] >= 1
        for phase in ("assembly", "dispatch", "readback", "fanout"):
            assert prof["phases"][phase]["p50_ms"] is not None
            assert prof["phases"][phase]["p99_ms"] is not None
        assert "fused_step" in prof["cache_census"]
        assert isinstance(prof["memory"], dict)
        status, armed = out["arm"]
        assert status == 200 and armed["armed"] and armed["steps"] == 2
        status, after = out["after"]
        assert status == 200
        assert after["capture"]["captured"] == 2
        assert after["capture"]["armed"] is False
        row = after["capture"]["steps"][0]
        assert "timings" in row and "total_ms" in row and "decisions" in row
        assert out["bad_steps"] == 400
        assert out["bad_type"] == 400

"""Columnar batch wire records: one encoded frame for a whole micro-batch.

ISSUE 12's tentpole: the activation BATCH — not the activation — is the
unit of work on every host hop. The coalescing producer already ships one
`pubN` frame per micro-batch, but each sub-message inside it is still an
independently-JSON-encoded ActivationMessage / ack: at 1,000 activations/s
the host pays ~N `json.dumps` + N `json.loads` per hop, plus N parses of
the SAME identity/action/controller sub-objects (the host observatory
measured the serde plane at ~7.7% of wall per hop at 512/s, before
counting the per-message object construction it feeds).

This module is the wire half of the columnar hot path:

  * `ActivationBatchMessage` — N controller->invoker dispatches packed as
    ONE struct-of-arrays JSON record: per-batch dedup tables for the
    repeated heavy sub-objects (users, (action, revision) pairs,
    controller ids) and packed per-row columns (activation ids, user /
    action indices, transids, blocking bits, arg payloads — the arg
    column is the "one blob" of the packed form: a single `json.dumps`
    writes every row's args in one C-speed pass, and sparse columns
    carry the rarely-present fields). ONE serialize per batch; the
    decode side rebuilds N `ActivationMessage`s parsing each unique
    identity/action exactly once.
  * `AckBatchMessage` — the mirror record for the invoker->controller
    completion fan-in (kinds, transids, ids, invoker dedup, system-error
    bits, response payloads).
  * `is_batch_payload` / `batch_hop_of` — frame sniffing for consumers:
    every batch payload starts with the `{"whiskBatch":` magic, so a
    feed handler can route a frame to the batch decode without parsing
    it (plain per-message frames never start with that key — neither
    ActivationMessage nor the acks serialize a `whiskBatch` field
    first, and json.dumps key order is insertion order).

Off switch: the batch wire rides the coalescing producer
(`CONFIG_whisk_bus_coalesce_batchWire=false` restores one independently
encoded payload per message — the serial wire format, byte-exact).
"""
from __future__ import annotations

import json
import logging
from typing import Dict, List, Optional, Tuple

from ..core.entity import ActivationId, ControllerInstanceId, Identity
from ..core.entity.names import FullyQualifiedEntityName
from ..utils.transaction import TransactionId
from .message import (AcknowledgementMessage, ActivationMessage,
                      CombinedCompletionAndResultMessage, CompletionMessage,
                      Message, ResultMessage)

#: every batch payload leads with this key (json.dumps preserves insertion
#: order, so the magic is a stable byte prefix — the cheap routing test)
BATCH_MAGIC = b'{"whiskBatch":'
#: the lazy ack frame's exact serialized prefix (compact json.dumps puts
#: the magic key first): parse_batch sniffs THIS before paying a
#: full-payload newline scan that plain frames can never satisfy
_LAZY_PREFIX = b'{"whiskBatch":"ackL"'

KIND_ACTIVATION = "act1"
KIND_ACK = "ack1"
#: the LAZY ack frame (ISSUE 14): a JSON header (columns + respLen) then
#: one raw newline then the concatenated per-row response payloads as
#: opaque bytes. json.dumps never emits a raw newline (strings escape
#: theirs), so the first b"\n" in a batch payload is always this frame
#: delimiter and plain frames never contain one.
KIND_ACK_LAZY = "ackL"
#: the admission-funnel frame (ISSUE 20): an activation batch plus a
#: (origin, seq, epoch) routing header — one front-end process's whole
#: admission wave shipped to the device-owning balancer as one record.
KIND_FUNNEL = "fun1"
#: the funnel's per-row outcome frame back to the origin: placement /
#: refusal / completion records, columnar like the ack batch.
KIND_FUNNEL_ACK = "funA"

#: serde hop labels by batch kind (mirrors connector._SERDE_HOPS so the
#: host observatory's per-hop accounting survives the batch wire)
_BATCH_HOPS = {KIND_ACTIVATION: "activation", KIND_ACK: "completion_ack",
               KIND_ACK_LAZY: "completion_ack",
               KIND_FUNNEL: "activation",
               KIND_FUNNEL_ACK: "completion_ack"}

#: the deferred result parse books its cost under its OWN hop, so the
#: "consumer never reads the result" case is visible as a ZERO row here
#: while the frame decode stays under completion_ack
LAZY_RESULT_HOP = "ack_result"


def is_batch_payload(raw) -> bool:
    """True when `raw` is a batch wire record (magic-prefix sniff; no
    parse). Accepts bytes/bytearray/str."""
    if isinstance(raw, str):
        return raw.startswith('{"whiskBatch":')
    return bytes(raw[:len(BATCH_MAGIC)]) == BATCH_MAGIC


def batch_hop_of(kind: str) -> str:
    return _BATCH_HOPS.get(kind, "other")


def batchable_family(msg) -> Optional[str]:
    """The batch family a message coalesces into, or None for messages
    that stay per-frame (pings, events: background chatter whose framing
    is not on the hot path)."""
    if isinstance(msg, ActivationMessage):
        return KIND_ACTIVATION
    if isinstance(msg, AcknowledgementMessage):
        return KIND_ACK
    return None


class _Dedup:
    """Insertion-ordered dedup table: intern() returns the index of the
    (hashable) key, appending `value` on first sight."""

    __slots__ = ("index", "values")

    def __init__(self):
        self.index: Dict[object, int] = {}
        self.values: List[object] = []

    def intern(self, key, value) -> int:
        i = self.index.get(key)
        if i is None:
            i = len(self.values)
            self.index[key] = i
            self.values.append(value)
        return i


class ActivationBatchMessage(Message):
    """N ActivationMessages as one columnar wire record (see module doc).

    The struct-of-arrays layout: `users`/`actions`/`ctrls` are per-batch
    dedup tables (each unique identity / (fqn, revision) / controller
    encoded ONCE); `ids`, `u`, `a`, `c`, `tx`, `bl`, `args` are
    length-N columns; `cause`/`trace`/`init` are sparse {row: value}
    columns present only when some row carries the field. `fence` is the
    batch-level HA epoch (one controller's flush shares one epoch; a
    rare mixed-epoch flush falls back to a sparse per-row column)."""

    def __init__(self, msgs: List[ActivationMessage]):
        self.msgs = msgs

    #: the waterfall produce edge stamps per activation: connector
    #: stamp_produce reads this instead of .activation_id
    @property
    def activation_ids(self) -> List[str]:
        return [m.activation_id.asString for m in self.msgs]

    def to_json(self) -> dict:
        users, actions, ctrls = _Dedup(), _Dedup(), _Dedup()
        ids: List[str] = []
        u_col: List[int] = []
        a_col: List[int] = []
        c_col: List[int] = []
        tx_col: List[object] = []
        bl_col: List[int] = []
        args_col: List[Optional[dict]] = []
        cause: Dict[str, str] = {}
        trace: Dict[str, dict] = {}
        init: Dict[str, dict] = {}
        fences: Dict[str, int] = {}
        fparts: Dict[str, int] = {}
        for row, m in enumerate(self.msgs):
            ids.append(m.activation_id.asString)
            # identity dedup keys on the subject+namespace-uuid pair (the
            # stable identity key); the action table keys on (fqn, rev)
            ident = m.user
            u_col.append(users.intern(
                (ident.subject, ident.namespace.uuid.asString),
                ident.to_json()))
            a_col.append(actions.intern((str(m.action), m.revision),
                                        [str(m.action), m.revision]))
            c_col.append(ctrls.intern(m.root_controller_index.name,
                                      m.root_controller_index.name))
            tx_col.append(m.transid.to_json())
            bl_col.append(1 if m.blocking else 0)
            args_col.append(m.content)
            if m.cause is not None:
                cause[str(row)] = m.cause.to_json()
            if m.trace_context is not None:
                trace[str(row)] = m.trace_context
            if m.init_args:
                init[str(row)] = m.init_args
            if m.fence_epoch is not None:
                fences[str(row)] = m.fence_epoch
            if m.fence_part is not None:
                fparts[str(row)] = m.fence_part
        out = {
            "whiskBatch": KIND_ACTIVATION,
            "users": users.values,
            "actions": actions.values,
            "ctrls": ctrls.values,
            "ids": ids,
            "u": u_col, "a": a_col, "c": c_col,
            "tx": tx_col, "bl": bl_col,
            "args": args_col,
        }
        if cause:
            out["cause"] = cause
        if trace:
            out["trace"] = trace
        if init:
            out["init"] = init
        if fences:
            # the common case is one shared epoch: collapse to a scalar
            vals = set(fences.values())
            if len(vals) == 1 and len(fences) == len(self.msgs):
                out["fence"] = vals.pop()
            else:
                out["fences"] = fences
        if fparts:
            # active/active: per-row partition ids (a batch freely mixes
            # namespaces, so partitions rarely collapse to one scalar)
            vals = set(fparts.values())
            if len(vals) == 1 and len(fparts) == len(self.msgs):
                out["fpart"] = vals.pop()
            else:
                out["fparts"] = fparts
        return out

    @staticmethod
    def parse(raw) -> List[ActivationMessage]:
        """One json.loads + shared-subobject reconstruction: each unique
        identity/action/controller in the batch is parsed exactly once
        and the rebuilt objects are SHARED across the batch's messages
        (read-only on the consume side, like the reference's case
        classes)."""
        j = json.loads(raw)
        return ActivationBatchMessage.from_json(j)

    @staticmethod
    def from_json(j: dict) -> List[ActivationMessage]:
        users = [Identity.from_json(u) for u in j["users"]]
        actions = [(FullyQualifiedEntityName.parse(a), rev)
                   for a, rev in j["actions"]]
        ctrls = [ControllerInstanceId(c) for c in j["ctrls"]]
        cause = j.get("cause") or {}
        trace = j.get("trace") or {}
        init = j.get("init") or {}
        fence = j.get("fence")
        fences = j.get("fences") or {}
        fpart = j.get("fpart")
        fparts = j.get("fparts") or {}
        out: List[ActivationMessage] = []
        for row, (aid, u, a, c, tx, bl, args) in enumerate(zip(
                j["ids"], j["u"], j["a"], j["c"], j["tx"], j["bl"],
                j["args"])):
            key = str(row)
            fqn, rev = actions[a]
            row_cause = cause.get(key)
            out.append(ActivationMessage(
                TransactionId.from_json(tx), fqn, rev, users[u],
                ActivationId(aid), ctrls[c], bool(bl), args,
                init.get(key) or {},
                ActivationId(row_cause) if row_cause else None,
                trace.get(key),
                fence if fence is not None else fences.get(key),
                fpart if fpart is not None else fparts.get(key)))
        return out


#: ack kind -> wire code (one char per row in the kinds column)
_ACK_CODES = {"completion": "c", "result": "r", "combined": "b"}
_ACK_KINDS = {v: k for k, v in _ACK_CODES.items()}


class LazyWhiskActivation:
    """A WhiskActivation that stays raw bytes until somebody reads it.

    The lazy ack frame (ISSUE 14) ships each activation's response
    payload as an opaque bytes column; the completion hot loop
    (`process_acknowledgements`) only needs the ack COLUMNS (id, invoker,
    system-error bit) — the response is dead weight there. This proxy
    carries the raw payload through the promise plumbing and parses it on
    the first attribute access, which for a blocking invoke happens on
    the API handler's own turn and for a fire-and-forget ack happens
    never. The deferred parse books its bytes + wall time under the
    `ack_result` serde hop, so skipped parses are a measurable zero."""

    __slots__ = ("raw", "_obj")

    def __init__(self, raw: bytes):
        self.raw = raw
        self._obj = None

    @property
    def materialized(self) -> bool:
        return self._obj is not None

    def _materialize(self):
        obj = self._obj
        if obj is None:
            from ..core.entity import WhiskActivation
            from ..utils.hostprof import GLOBAL_HOST_OBSERVATORY
            obs = GLOBAL_HOST_OBSERVATORY
            try:
                if obs.serde_active:
                    import time as _time
                    t0 = _time.perf_counter_ns()
                    obj = WhiskActivation.from_json(json.loads(self.raw))
                    obs.serde_observe(LAZY_RESULT_HOP, "deserialize",
                                      len(self.raw),
                                      _time.perf_counter_ns() - t0)
                else:
                    obj = WhiskActivation.from_json(json.loads(self.raw))
            except Exception as e:
                # a corrupt body behind a CONSISTENT lazy frame (header +
                # lengths fine, payload garbled) is by design undetectable
                # until this first read — the eager wire's decode-time
                # "corrupt completion ack" drop can't apply. Surface a
                # well-defined, logged error here instead of letting a
                # JSONDecodeError/KeyError escape deep inside whatever
                # consumer touched the first attribute.
                logging.warning("corrupt lazy ack result (%dB): %r",
                                len(self.raw), e)
                raise ValueError(
                    f"corrupt lazy ack result: {e!r}") from e
            self._obj = obj
        return obj

    def __getattr__(self, name):
        # only reached for names not in __slots__/class dict: every real
        # WhiskActivation attribute (activation_id, response, to_json...)
        # lands here and forces the parse
        return getattr(self._materialize(), name)

    def __repr__(self) -> str:  # no parse for logging
        state = "parsed" if self._obj is not None else f"{len(self.raw)}B raw"
        return f"LazyWhiskActivation({state})"


class AckBatchMessage(Message):
    """N invoker->controller acks as one columnar wire record. The heavy
    per-row payload (the WhiskActivation response) stays per-row — it IS
    the data — but the batch pays ONE json.dumps/loads for all of them,
    and the invoker table dedups the repeated instance id.

    `lazy_results=True` (the ISSUE 14 wire) moves the response payloads
    OUT of the JSON record: the frame becomes a JSON header (columns +
    a `respLen` byte-length column) followed by one raw newline and the
    concatenated response payloads as opaque bytes. The decode side then
    never parses a response the consumer doesn't read — the controller's
    completion loop only touches the columns. False keeps the PR 11
    format byte-exact."""

    def __init__(self, msgs: List[AcknowledgementMessage],
                 lazy_results: bool = False):
        self.msgs = msgs
        self.lazy_results = lazy_results

    @property
    def activation_ids(self) -> List[str]:
        return [m.activation_id.asString for m in self.msgs]

    def _columns(self) -> dict:
        """The shared (response-free) ack columns: the eager record and
        the lazy header carry their responses differently, so each
        caller builds its own resp column. The sparse `trace` column
        (ISSUE 18) mirrors the activation batch's: present only when
        some ack carries a trace context, so untraced batches stay
        byte-exact with the PR 11/14 frames — and because it lives HERE
        it rides both the eager record and the lazy header."""
        invs = _Dedup()
        kinds: List[str] = []
        tx_col: List[object] = []
        ids: List[str] = []
        iv_col: List[int] = []
        err_col: List[int] = []
        trace: Dict[str, dict] = {}
        for row, m in enumerate(self.msgs):
            kinds.append(_ACK_CODES.get(m.kind, "b"))
            tx_col.append(m.transid.to_json())
            ids.append(m.activation_id.asString)
            iv_col.append(-1 if m.invoker is None
                          else invs.intern(m.invoker.as_string,
                                           m.invoker.to_json()))
            err_col.append(1 if m.is_system_error else 0)
            tc = getattr(m, "trace_context", None)
            if tc is not None:
                trace[str(row)] = tc
        out = {"invs": invs.values, "kinds": "".join(kinds),
               "tx": tx_col, "ids": ids, "iv": iv_col, "err": err_col}
        if trace:
            out["trace"] = trace
        return out

    def to_json(self) -> dict:
        out = {"whiskBatch": KIND_ACK}
        out.update(self._columns())
        out["resp"] = [m.activation.to_json()
                       if m.activation is not None else None
                       for m in self.msgs]
        return out

    @staticmethod
    def _resp_bytes(m: AcknowledgementMessage) -> bytes:
        """One row's opaque response payload. A still-raw relay (a
        LazyWhiskActivation nobody parsed) passes its bytes through
        untouched — re-encoding an unread payload would be the exact
        serde cost the lazy column exists to skip."""
        act = m.activation
        if act is None:
            return b""
        if isinstance(act, LazyWhiskActivation) and not act.materialized:
            return act.raw
        return json.dumps(act.to_json(), separators=(",", ":")).encode()

    def serialize(self) -> bytes:
        if not self.lazy_results:
            return super().serialize()
        bodies = [self._resp_bytes(m) for m in self.msgs]
        header = {"whiskBatch": KIND_ACK_LAZY}
        header.update(self._columns())
        header["respLen"] = [len(b) for b in bodies]
        return (json.dumps(header, separators=(",", ":")).encode()
                + b"\n" + b"".join(bodies))

    @staticmethod
    def parse(raw) -> List[AcknowledgementMessage]:
        j = json.loads(raw)
        return AckBatchMessage.from_json(j)

    @staticmethod
    def from_json(j: dict) -> List[AcknowledgementMessage]:
        from ..core.entity import InvokerInstanceId, WhiskActivation
        invs = [InvokerInstanceId.from_json(v) for v in j["invs"]]
        trace = j.get("trace") or {}
        out: List[AcknowledgementMessage] = []
        for row, (code, tx, aid, iv, err, resp) in enumerate(zip(
                j["kinds"], j["tx"], j["ids"], j["iv"], j["err"],
                j["resp"])):
            transid = TransactionId.from_json(tx)
            inv = invs[iv] if iv >= 0 else None
            act = WhiskActivation.from_json(resp) if resp else None
            kind = _ACK_KINDS.get(code, "combined")
            if kind == "completion":
                ack = CompletionMessage(transid, ActivationId(aid),
                                        bool(err), inv)
            elif kind == "result":
                ack = ResultMessage(transid, act)
            else:
                ack = CombinedCompletionAndResultMessage(transid, act, inv)
            # set post-construction: the kind ctors are frozen contracts
            ack.trace_context = trace.get(str(row))
            out.append(ack)
        return out

    @staticmethod
    def from_lazy(header: dict, body: bytes) -> List[AcknowledgementMessage]:
        """Decode the lazy frame WITHOUT touching a single response byte
        beyond slicing: every ack field comes from the columns (the
        `err` bit was computed at encode time from the same response the
        eager path would re-derive it from), and each present response
        becomes a LazyWhiskActivation over its body slice. Building the
        base AcknowledgementMessage directly — instead of the kind
        subclasses — matters: ResultMessage reads activation_id off the
        activation and CombinedCompletionAndResultMessage reads
        response.is_whisk_error, either of which would force the parse
        this frame exists to defer."""
        from ..core.entity import InvokerInstanceId
        invs = [InvokerInstanceId.from_json(v) for v in header["invs"]]
        trace = header.get("trace") or {}
        lens = header["respLen"]
        out: List[AcknowledgementMessage] = []
        off = 0
        for row, (code, tx, aid, iv, err, ln) in enumerate(zip(
                header["kinds"], header["tx"], header["ids"], header["iv"],
                header["err"], lens)):
            raw = body[off:off + ln] if ln else b""
            off += ln
            ack = AcknowledgementMessage(
                TransactionId.from_json(tx), ActivationId(aid),
                invs[iv] if iv >= 0 else None, bool(err),
                LazyWhiskActivation(raw) if raw else None)
            ack.kind = _ACK_KINDS.get(code, "combined")
            ack.trace_context = trace.get(str(row))
            out.append(ack)
        if off != len(body):
            raise ValueError(
                f"lazy ack frame body length {len(body)} != respLen "
                f"sum {off}")
        return out


class FunnelFrame:
    """Decoded `fun1` frame: the rebuilt ActivationMessages plus the
    routing header the receiver fences/dedupes on."""

    __slots__ = ("origin", "seq", "epoch", "msgs")

    def __init__(self, origin: int, seq: int, epoch: int,
                 msgs: List[ActivationMessage]):
        self.origin = origin
        self.seq = seq
        self.epoch = epoch
        self.msgs = msgs


class FunnelBatchMessage(Message):
    """ISSUE 20: one front-end admission wave as ONE wire record — the
    `act1` struct-of-arrays columns (reused verbatim: dedup tables +
    packed per-row columns) plus three routing scalars:

      * `origin` — the front-end controller instance the per-row outcome
        frames route back to (topic `ctrlfunnelack<origin>`);
      * `seq` — the sender's frame counter. Application-level retry
        re-ships the SAME seq, and the receiver dedupes PER ROW (the
        `pubN` discipline one layer up): a replayed frame only places
        rows whose first delivery was lost;
      * `epoch` — the placement-leadership epoch the sender believes
        current. 0 = unfenced (bootstrap; the balancer's own standby /
        partition fences still apply row-by-row); nonzero must equal the
        receiving balancer's live epoch or the whole frame is refused —
        covering both the zombie sender and the demoted (stale-epoch)
        balancer."""

    def __init__(self, msgs: List[ActivationMessage], origin: int,
                 seq: int, epoch: int = 0):
        self.msgs = msgs
        self.origin = int(origin)
        self.seq = int(seq)
        self.epoch = int(epoch)

    @property
    def activation_ids(self) -> List[str]:
        return [m.activation_id.asString for m in self.msgs]

    def to_json(self) -> dict:
        # reuse the act1 columns; overwriting the kind keeps `whiskBatch`
        # in first position (dict order), so the magic-prefix sniff holds
        out = ActivationBatchMessage(self.msgs).to_json()
        out["whiskBatch"] = KIND_FUNNEL
        out["origin"] = self.origin
        out["seq"] = self.seq
        out["epoch"] = self.epoch
        return out

    @staticmethod
    def from_json(j: dict) -> FunnelFrame:
        msgs = ActivationBatchMessage.from_json(j)
        return FunnelFrame(int(j["origin"]), int(j["seq"]),
                           int(j.get("epoch", 0)), msgs)


#: funnel outcome codes (one char per row in the `k` column):
#:   p = placed (the row has a completion promise at the balancer)
#:   r = refused (sparse `exc` row carries [kind-code, exact text])
#:   c = completed (sparse `resp` row carries the activation JSON for
#:       blocking rows; non-blocking completions ship slim)
#:   f = forced completion timeout (the serial path's ActiveAckTimeout)
FUNNEL_PLACED = "p"
FUNNEL_REFUSED = "r"
FUNNEL_COMPLETED = "c"
FUNNEL_FORCED = "f"

#: refusal kind-codes: "T" rebuilds LoadBalancerThrottleException (429
#: at the front door), anything else a plain LoadBalancerException (503)
FUNNEL_EXC_THROTTLE = "T"
FUNNEL_EXC_ERROR = "L"


class FunnelOutcome:
    """One decoded `funA` row."""

    __slots__ = ("code", "aid", "err", "exc", "resp")

    def __init__(self, code: str, aid: str, err: bool = False,
                 exc: Optional[Tuple[str, str]] = None,
                 resp: Optional[dict] = None):
        self.code = code
        self.aid = aid
        self.err = err
        self.exc = exc
        self.resp = resp


class FunnelAckFrame:
    __slots__ = ("origin", "epoch", "rows")

    def __init__(self, origin: int, epoch: int, rows: List[FunnelOutcome]):
        self.origin = origin
        self.epoch = epoch
        self.rows = rows


class FunnelAckMessage(Message):
    """N funnel outcome records as one columnar record. `epoch` is the
    balancer's CURRENT placement epoch — senders adopt it, so a
    bootstrap (epoch-0) sender converges to fenced frames after its
    first outcome wave."""

    def __init__(self, origin: int, epoch: int,
                 rows: List[FunnelOutcome]):
        self.origin = int(origin)
        self.epoch = int(epoch)
        self.rows = rows

    def to_json(self) -> dict:
        exc: Dict[str, list] = {}
        resp: Dict[str, dict] = {}
        for i, r in enumerate(self.rows):
            if r.exc is not None:
                exc[str(i)] = [r.exc[0], r.exc[1]]
            if r.resp is not None:
                resp[str(i)] = r.resp
        out = {
            "whiskBatch": KIND_FUNNEL_ACK,
            "origin": self.origin,
            "epoch": self.epoch,
            "ids": [r.aid for r in self.rows],
            "k": "".join(r.code for r in self.rows),
            "err": [1 if r.err else 0 for r in self.rows],
        }
        if exc:
            out["exc"] = exc
        if resp:
            out["resp"] = resp
        return out

    @staticmethod
    def from_json(j: dict) -> FunnelAckFrame:
        exc = j.get("exc") or {}
        resp = j.get("resp") or {}
        rows = []
        for i, (aid, code, err) in enumerate(zip(j["ids"], j["k"],
                                                 j["err"])):
            key = str(i)
            e = exc.get(key)
            rows.append(FunnelOutcome(
                code, aid, bool(err),
                (e[0], e[1]) if e is not None else None,
                resp.get(key)))
        return FunnelAckFrame(int(j["origin"]), int(j.get("epoch", 0)),
                              rows)


def make_batch(family: str, msgs: list,
               lazy_results: bool = False) -> Message:
    """Wrap same-family messages into their batch record (the
    `serialize_many` entry point the coalescing producer uses).
    `lazy_results` selects the ISSUE 14 lazy ack frame for the ack
    family; activation batches ignore it (their args ARE read by every
    consumer)."""
    if family == KIND_ACTIVATION:
        return ActivationBatchMessage(msgs)
    if family == KIND_ACK:
        return AckBatchMessage(msgs, lazy_results=lazy_results)
    raise ValueError(f"not a batchable family: {family!r}")


def parse_batch(raw) -> Tuple[str, list]:
    """Decode one batch payload -> (kind, [messages]). The caller sniffs
    with is_batch_payload first; an unknown kind raises ValueError (the
    feed's corrupt-message posture). A lazy ack frame splits at its
    first raw newline (plain JSON frames never contain one) and parses
    ONLY the header — the response payloads stay opaque slices."""
    if isinstance(raw, str):
        raw = raw.encode()
    raw = bytes(raw)
    # sniff the fixed lazy prefix BEFORE scanning for the delimiter:
    # plain frames can never contain a raw newline, so the full-payload
    # memchr would be guaranteed-miss work on the completion hot loop's
    # biggest byte streams (eager ack frames carrying whole responses)
    if raw.startswith(_LAZY_PREFIX):
        nl = raw.find(b"\n")
        if nl < 0:
            raise ValueError("lazy ack frame missing its body delimiter")
        header = json.loads(raw[:nl])
        kind = header.get("whiskBatch")
        if kind != KIND_ACK_LAZY:
            raise ValueError(f"framed batch with unknown kind {kind!r}")
        return kind, AckBatchMessage.from_lazy(header, raw[nl + 1:])
    j = json.loads(raw)
    kind = j.get("whiskBatch")
    if kind == KIND_ACTIVATION:
        return kind, ActivationBatchMessage.from_json(j)
    if kind == KIND_ACK:
        return kind, AckBatchMessage.from_json(j)
    if kind == KIND_FUNNEL:
        # the funnel frame decodes to ONE header-carrying object, not a
        # message list — only the funnel receiver consumes this kind
        return kind, FunnelBatchMessage.from_json(j)
    if kind == KIND_FUNNEL_ACK:
        return kind, FunnelAckMessage.from_json(j)
    raise ValueError(f"unknown batch kind {kind!r}")

"""Placement policy models.

`sharding_policy` is the faithful CPU re-implementation of the reference's
ShardingContainerPoolBalancer scheduling math — it is simultaneously (a) a
production CPU policy, (b) the parity oracle the TPU kernel is tested
against, and (c) the CPU baseline bench.py compares to. The batched
TPU-native formulation of the same policy lives in openwhisk_tpu.ops.
"""
from .sharding_policy import (ShardingPolicyState, generate_hash,
                              pairwise_coprimes, schedule)

__all__ = ["ShardingPolicyState", "generate_hash", "pairwise_coprimes", "schedule"]

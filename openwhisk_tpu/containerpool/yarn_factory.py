"""YARN container driver: action containers via the YARN services REST API.

Rebuild of core/invoker/.../containerpool/yarn/ (YARNContainerFactory.scala,
YARNComponentActor.scala, YARNRESTUtil.scala): at init the factory registers
one YARN *service* per invoker whose *components* are the action image kinds,
each starting at 0 instances; creating a container flexes the matching
component +1 and polls the service status until the new container reports
READY with an IP; destroying flexes -1. The reference's actor-per-component
serialization of flex ops becomes one asyncio lock per component here.

Gated: usable wherever a YARN RM with the services API (or the in-process
fake in tests) is reachable.
"""
from __future__ import annotations

import asyncio
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import aiohttp

from ..core.entity import ByteSize
from .container import Container, ContainerError
from .factory import ContainerFactory


@dataclass
class YARNConfig:
    """Ref YARNConfig (application.conf whisk.yarn)."""
    master_url: str = "http://127.0.0.1:8088"
    yarn_link_log_message: bool = True
    service_name: str = "openwhisk-action-service"
    auth: Optional[str] = None          # "simple" user name, appended as ?user.name=
    cpus: int = 1
    memory_fallback_mb: int = 256
    action_port: int = 8080


def _component_name(image: str) -> str:
    """YARN component names: [a-z0-9-], derived from the image kind."""
    return "".join(c if c.isalnum() else "-" for c in image.lower()).strip("-")[:63]


class YARNClient:
    """Async client for the subset of the services API the invoker needs
    (ref YARNRESTUtil.scala)."""

    def __init__(self, config: YARNConfig):
        self.config = config
        self._session: Optional[aiohttp.ClientSession] = None

    def _http(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession()
        return self._session

    def _url(self, path: str) -> str:
        url = f"{self.config.master_url}/app/v1/services{path}"
        if self.config.auth:
            url += f"?user.name={self.config.auth}"
        return url

    async def create_service(self, definition: Dict[str, Any]) -> None:
        async with self._http().post(self._url(""), json=definition) as resp:
            if resp.status not in (200, 202):
                raise ContainerError(
                    f"YARN service create failed ({resp.status}): "
                    f"{(await resp.text())[:512]}")

    async def describe(self, service: str) -> Dict[str, Any]:
        async with self._http().get(self._url(f"/{service}")) as resp:
            if resp.status != 200:
                raise ContainerError(f"YARN describe failed ({resp.status})")
            return await resp.json(content_type=None)

    async def add_component(self, service: str, component: str, image: str,
                            cpus: int, memory_mb: int) -> None:
        """Declare a component with its artifact + resource spec (the
        reference pre-declares every runtime kind at service creation from
        the ExecManifest; we declare lazily on first use)."""
        async with self._http().put(
                self._url(f"/{service}"),
                json={"components": [{
                    "name": component,
                    "number_of_containers": 0,
                    "artifact": {"id": image, "type": "DOCKER"},
                    "resource": {"cpus": cpus, "memory": str(memory_mb)},
                    "launch_command": "",
                    "restart_policy": "NEVER",
                }]}) as resp:
            if resp.status not in (200, 202):
                raise ContainerError(
                    f"YARN add component {component} failed ({resp.status}): "
                    f"{(await resp.text())[:512]}")
            await resp.read()

    async def flex(self, service: str, component: str, count: int,
                   decommission: Optional[List[str]] = None) -> None:
        body: Dict[str, Any] = {"number_of_containers": count}
        if decommission:
            # remove THESE instances, not an arbitrary newest one
            body["decommissioned_instances"] = list(decommission)
        async with self._http().put(
                self._url(f"/{service}/components/{component}"),
                json=body) as resp:
            if resp.status not in (200, 202):
                raise ContainerError(
                    f"YARN flex {component}={count} failed ({resp.status})")
            await resp.read()

    async def delete_service(self, service: str) -> None:
        async with self._http().delete(self._url(f"/{service}")) as resp:
            if resp.status not in (200, 202, 204, 404):
                raise ContainerError(f"YARN service delete failed ({resp.status})")
            await resp.read()

    async def close(self) -> None:
        if self._session:
            await self._session.close()
            self._session = None


class YARNContainer(Container):
    def __init__(self, factory: "YARNContainerFactory", component: str,
                 yarn_container_id: str, ip: str, port: int):
        super().__init__(yarn_container_id, (ip, port))
        self.factory = factory
        self.component = component

    async def suspend(self) -> None:   # YARN cannot freeze a container
        pass

    async def resume(self) -> None:
        pass

    async def destroy(self) -> None:
        await super().destroy()
        await self.factory.release(self)

    async def logs(self, limit_bytes: int = 10 * 1024 * 1024,
                   wait_for_sentinel: bool = True) -> List[str]:
        # ref: YARN log aggregation is out-of-band; emit the pointer line
        # the reference logs (yarn_link_log_message)
        return [f"Logs are in the YARN UI for container {self.container_id}"]


class YARNContainerFactory(ContainerFactory):
    def __init__(self, invoker_name: str = "invoker0",
                 config: Optional[YARNConfig] = None,
                 client: Optional[YARNClient] = None):
        self.config = config or YARNConfig()
        self.client = client or YARNClient(self.config)
        self.service = f"{self.config.service_name}-{invoker_name}".lower()
        self._components: Dict[str, int] = {}          # component -> target count
        self._known: Dict[str, set] = {}               # component -> seen container ids
        self._locks: Dict[str, asyncio.Lock] = {}      # serialize flex per component
        self._poll_s = 0.05
        self._timeout_s = 60.0

    def _lock(self, component: str) -> asyncio.Lock:
        return self._locks.setdefault(component, asyncio.Lock())

    async def init(self) -> None:
        await self.cleanup()
        await self.client.create_service({
            "name": self.service,
            "version": "1.0.0",
            "components": [],
        })

    async def _ensure_component(self, component: str, image: str,
                                memory_mb: int) -> None:
        if component in self._components:
            return
        await self.client.add_component(self.service, component, image,
                                        self.config.cpus,
                                        memory_mb or self.config.memory_fallback_mb)
        self._components[component] = 0
        self._known[component] = set()

    async def create_container(self, transid, name: str, image: str,
                               memory: ByteSize, cpu_shares: int = 0,
                               action=None) -> YARNContainer:
        component = _component_name(image)
        # serialize only the flex (count bump); the slow readiness poll runs
        # unlocked so concurrent cold starts of one kind overlap, and each
        # new container id is claimed under the lock so no two callers can
        # adopt the same instance
        async with self._lock(component):
            await self._ensure_component(component, image, memory.to_mb)
            self._components[component] += 1
            await self.client.flex(self.service, component,
                                   self._components[component])
        return await self._await_new_container(component)

    async def _await_new_container(self, component: str) -> YARNContainer:
        deadline = asyncio.get_event_loop().time() + self._timeout_s
        while True:
            desc = await self.client.describe(self.service)
            for comp in desc.get("components", []):
                if comp.get("name") != component:
                    continue
                for c in comp.get("containers", []):
                    cid = c.get("id")
                    if (cid and c.get("state") == "READY" and c.get("ip")):
                        async with self._lock(component):
                            if cid in self._known[component]:
                                continue  # another caller claimed it
                            self._known[component].add(cid)
                        return YARNContainer(self, component, cid, c["ip"],
                                             self.config.action_port)
            if asyncio.get_event_loop().time() > deadline:
                raise ContainerError(
                    f"YARN container for {component} not READY within "
                    f"{self._timeout_s}s")
            await asyncio.sleep(self._poll_s)

    async def release(self, container: YARNContainer) -> None:
        component = container.component
        async with self._lock(component):
            self._known[component].discard(container.container_id)
            self._components[component] = max(0, self._components[component] - 1)
            # decommission THIS instance: a bare flex-down lets YARN pick an
            # arbitrary (possibly live, in-use) container to kill
            await self.client.flex(self.service, component,
                                   self._components[component],
                                   decommission=[container.container_id])

    async def cleanup(self) -> None:
        try:
            await self.client.delete_service(self.service)
        except ContainerError:
            pass
        self._components.clear()
        self._known.clear()

    async def close(self) -> None:
        await self.cleanup()
        await self.client.close()


class YARNContainerFactoryProvider:
    """ContainerFactoryProvider SPI binding
    (CONFIG_whisk_spi_ContainerFactoryProvider=
     openwhisk_tpu.containerpool.yarn_factory:YARNContainerFactoryProvider)."""

    @staticmethod
    def instance(invoker_name: str = "invoker0", logger=None,
                 **kwargs) -> YARNContainerFactory:
        return YARNContainerFactory(invoker_name, **kwargs)

"""API-gateway route management + edge proxy tests.

Covers the rebuild of core/routemgmt (createApi/getApi/deleteApi JS actions)
and the nginx edge role (ansible/roles/nginx/templates/nginx.conf.j2):
upstream failover, vanity-namespace rewrite, gateway route dispatch, and
/metrics denial.
"""
import asyncio
import base64

import aiohttp
import pytest

from openwhisk_tpu.controller.routemgmt import (ApiManagementException,
                                                ApiRouteManager)
from openwhisk_tpu.database.memory_store import MemoryArtifactStore
from openwhisk_tpu.edge import EdgeProxy, Upstream
from openwhisk_tpu.standalone import GUEST_KEY, GUEST_UUID, make_standalone

AUTH = "Basic " + base64.b64encode(f"{GUEST_UUID}:{GUEST_KEY}".encode()).decode()
HDRS = {"Authorization": AUTH, "Content-Type": "application/json"}

C_PORT = 13321
E_PORT = 13322
CBASE = f"http://127.0.0.1:{C_PORT}"
EBASE = f"http://127.0.0.1:{E_PORT}"

WEB_CODE = """
def main(args):
    return {'greeting': 'Hello ' + args.get('who', 'world') + '!'}
"""


def _apidoc(base="/hello", rel="/greet", verb="get", action="webhello",
            **extra):
    doc = {"gatewayBasePath": base, "gatewayPath": rel, "gatewayMethod": verb,
           "action": {"name": action, "namespace": "guest"},
           "responsetype": "json"}
    doc.update(extra)
    return doc


class TestApiRouteManager:
    def run(self, coro):
        return asyncio.run(coro)

    def test_create_get_delete_cycle(self):
        async def go():
            rm = ApiRouteManager(MemoryArtifactStore())
            view = await rm.create_api("guest", _apidoc())
            assert view["basePath"] == "/hello"
            assert "/greet" in view["swagger"]["paths"]
            # second verb on the same path merges into the same doc
            await rm.create_api("guest", _apidoc(verb="post"))
            # another relPath
            await rm.create_api("guest", _apidoc(rel="/bye", apiName="hello-api"))
            apis = await rm.get_apis("guest")
            assert len(apis) == 1
            paths = apis[0]["swagger"]["paths"]
            assert set(paths["/greet"]) == {"get", "post"}

            # filtered get: one path, one verb
            only = await rm.get_apis("guest", base_path="/hello",
                                     rel_path="/greet", verb="post")
            assert set(only[0]["swagger"]["paths"]) == {"/greet"}
            assert set(only[0]["swagger"]["paths"]["/greet"]) == {"post"}
            # filter by apiName works too (getApi.js matches name or path)
            byname = await rm.get_apis("guest", base_path="hello-api")
            assert byname and byname[0]["basePath"] == "/hello"

            # delete one verb; the other survives
            await rm.delete_api("guest", "/hello", "/greet", "post")
            apis = await rm.get_apis("guest")
            assert set(apis[0]["swagger"]["paths"]["/greet"]) == {"get"}
            # delete whole relPath
            await rm.delete_api("guest", "/hello", "/bye")
            assert "/bye" not in (await rm.get_apis("guest"))[0]["swagger"]["paths"]
            # deleting the last path removes the doc entirely
            await rm.delete_api("guest", "/hello", "/greet")
            assert await rm.get_apis("guest") == []
        self.run(go())

    def test_validation_errors(self):
        async def go():
            rm = ApiRouteManager(MemoryArtifactStore())
            with pytest.raises(ApiManagementException):
                await rm.create_api("guest", {"gatewayBasePath": "/x"})
            with pytest.raises(ApiManagementException):
                await rm.create_api("guest", _apidoc(verb="teapot"))
            with pytest.raises(ApiManagementException):
                await rm.create_api("guest", _apidoc(responsetype="yaml"))
        self.run(go())

    def test_swagger_install_and_match(self):
        async def go():
            rm = ApiRouteManager(MemoryArtifactStore())
            await rm.create_api("guest", _apidoc())
            await rm.create_api("guest", _apidoc(base="/hello/deep", rel="/greet",
                                               action="deep"))
            # longest basePath prefix wins
            op = await rm.match("GET", "/hello/deep/greet")
            assert op["action"] == "deep"
            op = await rm.match("GET", "/hello/greet")
            assert op["action"] == "webhello"
            assert await rm.match("POST", "/hello/greet") is None
            assert await rm.match("GET", "/nothing") is None
            # full swagger install (createApi.js swagger branch)
            await rm.create_api("guest", {"swagger": {
                "swagger": "2.0", "basePath": "/sw", "info": {"title": "sw"},
                "paths": {"/p": {"get": {"x-openwhisk": {
                    "namespace": "guest", "package": "", "action": "webhello",
                    "responsetype": "json",
                    "url": "/api/v1/web/guest/default/webhello.json"}}}}}})
            op = await rm.match("GET", "/sw/p")
            assert op["action"] == "webhello"
        self.run(go())


class TestEdgeProxySystem:
    def run_edge(self, coro_fn, domain="", dead_upstream=False):
        async def go():
            controller = await make_standalone(port=C_PORT)
            urls = ([f"http://127.0.0.1:{C_PORT - 9}"] if dead_upstream else []) \
                + [CBASE]
            edge = EdgeProxy.for_controllers(
                urls, domain=domain,
                route_matcher=controller.route_manager.match)
            await edge.start(host="127.0.0.1", port=E_PORT)
            try:
                async with aiohttp.ClientSession() as s:
                    # create the web action behind everything
                    async with s.put(
                            f"{CBASE}/api/v1/namespaces/_/actions/webhello",
                            headers=HDRS,
                            json={"exec": {"kind": "python:3", "code": WEB_CODE},
                                  "annotations": [{"key": "web-export",
                                                   "value": True}]}) as r:
                        assert r.status == 200
                    return await coro_fn(s)
            finally:
                await edge.stop()
                await controller.stop()
        return asyncio.run(go())

    def test_proxy_api_routes_and_deny_metrics(self):
        async def go(s):
            out = {}
            async with s.get(f"{EBASE}/api/v1") as r:
                out["info"] = r.status
            async with s.get(f"{EBASE}/metrics") as r:
                out["metrics"] = r.status
            # authenticated CRUD through the edge
            async with s.get(f"{EBASE}/api/v1/namespaces/_/actions",
                             headers=HDRS) as r:
                out["list"] = (r.status, [a["name"] for a in await r.json()])
                out["transid"] = r.headers.get("X-Request-ID") is not None
            return out
        out = self.run_edge(go)
        assert out["info"] == 200
        assert out["metrics"] == 403
        assert out["list"] == (200, ["webhello"])
        assert out["transid"]

    def test_gateway_route_dispatch(self):
        async def go(s):
            # register the API route on the controller
            async with s.put(f"{CBASE}/api/v1/namespaces/_/apis",
                             headers=HDRS, json={"apidoc": _apidoc()}) as r:
                assert r.status == 200, await r.text()
            out = {}
            async with s.get(f"{EBASE}/hello/greet?who=Edge") as r:
                out["hit"] = (r.status, await r.json())
            async with s.get(f"{EBASE}/hello/nope") as r:
                out["miss"] = r.status
            # list through the REST surface
            async with s.get(f"{CBASE}/api/v1/namespaces/_/apis",
                             headers=HDRS) as r:
                out["apis"] = [a["basePath"] for a in (await r.json())["apis"]]
            # delete and verify the edge stops serving it
            async with s.delete(
                    f"{CBASE}/api/v1/namespaces/_/apis?basepath=/hello",
                    headers=HDRS) as r:
                out["del"] = r.status
            async with s.get(f"{EBASE}/hello/greet") as r:
                out["after_del"] = r.status
            return out
        out = self.run_edge(go)
        assert out["hit"] == (200, {"greeting": "Hello Edge!"})
        assert out["miss"] == 404
        assert out["apis"] == ["/hello"]
        assert out["del"] == 204
        assert out["after_del"] == 404

    def test_vanity_namespace_rewrite(self):
        async def go(s):
            # Host: guest.example.test → /api/v1/web/guest/... rewrite
            hdrs = {"Host": "guest.example.test"}
            out = {}
            async with s.get(f"{EBASE}/default/webhello.json?who=Vanity",
                             headers=hdrs) as r:
                out["vanity"] = (r.status, await r.json())
            # API paths pass through untouched even with a vanity host
            async with s.get(f"{EBASE}/api/v1", headers=hdrs) as r:
                out["api_untouched"] = r.status
            return out
        out = self.run_edge(go, domain="example.test")
        assert out["vanity"] == (200, {"greeting": "Hello Vanity!"})
        assert out["api_untouched"] == 200

    def test_upstream_failover(self):
        async def go(s):
            # first upstream in the pool is dead; request must still succeed
            out = {}
            for _ in range(3):  # round-robin hits the dead one at least once
                async with s.get(f"{EBASE}/api/v1") as r:
                    out.setdefault("codes", []).append(r.status)
            return out
        out = self.run_edge(go, dead_upstream=True)
        assert out["codes"] == [200, 200, 200]

"""Actions: the deployable unit of compute.

Ref: common/scala/.../core/entity/WhiskAction.scala — WhiskAction carries the
exec (code), parameters, limits; ExecutableWhiskAction is the invoker-side
projection guaranteed to have runnable code (sequences excluded); the
*MetaData variants strip code bodies for the control plane.
"""
from __future__ import annotations

from typing import Optional

from .entity import WhiskEntity
from .exec import CodeExec, Exec, ExecMetaData, SequenceExec
from .limits import ActionLimits
from .names import EntityName, EntityPath
from .parameters import Parameters
from .semver import SemVer


class WhiskAction(WhiskEntity):
    collection = "actions"

    def __init__(self, namespace: EntityPath, name: EntityName, exec: Exec,
                 parameters: Optional[Parameters] = None,
                 limits: Optional[ActionLimits] = None,
                 version: Optional[SemVer] = None, publish: bool = False,
                 annotations: Optional[Parameters] = None,
                 updated: Optional[float] = None):
        super().__init__(namespace, name, version, publish, annotations, updated)
        self.exec = exec
        self.parameters = parameters or Parameters()
        self.limits = limits or ActionLimits()

    @property
    def is_sequence(self) -> bool:
        return isinstance(self.exec, SequenceExec)

    def to_executable(self) -> Optional["ExecutableWhiskAction"]:
        """Project to the invoker-side executable form; None for sequences
        (ref WhiskAction.toExecutableWhiskAction)."""
        if self.is_sequence:
            return None
        return ExecutableWhiskAction(
            self.namespace, self.name, self.exec, self.parameters, self.limits,
            self.version, self.publish, self.annotations, self.updated,
        ).revision(self.rev)

    def exec_metadata(self) -> ExecMetaData:
        return ExecMetaData.of(self.exec)

    def to_json(self) -> dict:
        j = self.base_json()
        j["exec"] = self.exec.to_json()
        j["parameters"] = self.parameters.to_json()
        j["limits"] = self.limits.to_json()
        return j

    @classmethod
    def from_json(cls, j: dict) -> "WhiskAction":
        a = cls(
            EntityPath(j["namespace"]), EntityName(j["name"]),
            Exec.from_json(j["exec"]),
            Parameters.from_json(j.get("parameters")),
            ActionLimits.from_json(j.get("limits")),
            SemVer.from_string(j.get("version", "0.0.1")),
            bool(j.get("publish", False)),
            Parameters.from_json(j.get("annotations")),
            (j.get("updated", 0) / 1000.0) or None,
        )
        return a


class ExecutableWhiskAction(WhiskAction):
    """An action guaranteed to carry runnable (non-sequence) code."""

    def __init__(self, namespace, name, exec, parameters=None, limits=None,
                 version=None, publish=False, annotations=None, updated=None):
        if isinstance(exec, SequenceExec):
            raise ValueError("sequence exec is not executable")
        super().__init__(namespace, name, exec, parameters, limits, version,
                         publish, annotations, updated)

    def container_initializer(self, env: Optional[dict] = None) -> dict:
        """The /init payload for the action container
        (ref WhiskAction.containerInitializer)."""
        e = self.exec
        payload = {
            "name": str(self.name),
            "main": getattr(e, "main", None) or "main",
            "code": getattr(e, "code", "") or "",
            "binary": getattr(e, "binary", False),
        }
        if env:
            payload["env"] = env
        return payload

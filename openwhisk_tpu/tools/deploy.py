"""owdeploy: cluster deployment tool — the ansible playbooks' role.

The reference deploys with ansible (ansible/openwhisk.yml:18-34: zookeeper ->
kafka -> controllers -> invokers -> nginx edge) parameterized by
ansible/group_vars/all. This tool consumes the same shape of inventory (YAML
or JSON; see deploy/cluster.yaml) and either

  up / down / status    run the whole topology as supervised local processes
                        (bus broker -> invokers -> controllers -> edge),
                        pid-tracked under <rundir>;
  render systemd        emit one unit file per service for a systemd host;
  render k8s            emit Deployment/Service manifests for a cluster.

Limits and feature tunables from the inventory's `limits:`/`config:` maps are
exported as CONFIG_whisk_* environment variables, the same override channel
the reference uses (docs/concurrency.md:28-40 convention).
"""
from __future__ import annotations

import argparse
import json
import os
import shlex
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

DEFAULT_INVENTORY = {
    "rundir": "ow-run",
    "db": "whisks.db",
    "bus": {"host": "127.0.0.1", "port": 4222},
    "docstore": {"enabled": False, "host": "127.0.0.1", "port": 4223},
    "controllers": {"count": 1, "base_port": 3233, "balancer": "tpu"},
    "invokers": {"count": 1, "memory_mb": 2048, "prewarm": False},
    "edge": {"enabled": True, "port": 8080, "domain": ""},
    "monitoring": {"enabled": False, "port": 9096},
    "limits": {},   # e.g. invocationsPerMinute: 60  -> CONFIG_whisk_...
    "config": {},   # raw CONFIG_whisk_* overrides
}


def load_inventory(path: Optional[str]) -> dict:
    inv = json.loads(json.dumps(DEFAULT_INVENTORY))  # deep copy
    if path:
        with open(path) as f:
            if path.endswith((".yaml", ".yml")):
                import yaml
                loaded = yaml.safe_load(f) or {}
            else:
                loaded = json.load(f)
        for key, value in loaded.items():
            if isinstance(value, dict) and isinstance(inv.get(key), dict):
                inv[key].update(value)
            else:
                inv[key] = value
    return inv


#: limit keys the controller actually reads (controller/__main__.py); the
#: env channel splits on "_", so only camelCase spellings survive the
#: round-trip through config_from_env
KNOWN_LIMIT_KEYS = ("invocationsPerMinute", "concurrentInvocations",
                    "firesPerMinute")


def _camel(key: str) -> str:
    parts = key.split("_")
    return parts[0] + "".join(p[:1].upper() + p[1:] for p in parts[1:] if p)


def _config_env(inv: dict) -> Dict[str, str]:
    """Only the inventory-derived CONFIG_* keys (what renderers persist)."""
    env: Dict[str, str] = {}
    for k, v in inv.get("limits", {}).items():
        key = _camel(k)  # accept snake_case inventories
        if key not in KNOWN_LIMIT_KEYS:
            raise ValueError(
                f"inventory limits key {k!r} is not a recognized limit "
                f"(expected one of {', '.join(KNOWN_LIMIT_KEYS)})")
        env[f"CONFIG_whisk_limits_{key}"] = str(v)
    for k, v in inv.get("config", {}).items():
        key = k if k.startswith("CONFIG_") else f"CONFIG_whisk_{k}"
        env[key] = str(v)
    return env


def _env(inv: dict) -> Dict[str, str]:
    return {**os.environ, **_config_env(inv)}


def services(inv: dict, python: str = sys.executable,
             net: Optional[Dict[str, str]] = None) -> List[dict]:
    """The topology as an ordered service list (start order = list order).

    `net` overrides how services bind and find each other, for rendered
    targets where loopback is wrong: `bus_bind` (bus listen address),
    `bus_host` (address others dial the bus at), `controller_host` (format
    string with `{i}` for the edge's upstream list)."""
    net = net or {}
    bus = inv["bus"]
    bus_addr = f"{net.get('bus_host', bus['host'])}:{bus['port']}"
    ctrl_host = net.get("controller_host", "127.0.0.1")
    db = inv["db"]
    out = [{
        "name": "bus",
        "argv": [python, "-m", "openwhisk_tpu.messaging",
                 "--host", net.get("bus_bind", bus["host"]),
                 "--port", str(bus["port"])],
    }]
    ds = inv.get("docstore") or {}
    if ds.get("enabled"):
        # the shared persistence service (CouchDB-equivalent): controllers
        # and invokers dial it instead of sharing a sqlite file path, which
        # is what makes genuinely multi-host topologies possible
        out.append({
            "name": "docstore",
            "argv": [python, "-m", "openwhisk_tpu.database.remote_store",
                     "--db", db,
                     "--host", net.get("docstore_bind", ds.get("host", "127.0.0.1")),
                     "--port", str(ds.get("port", 4223))],
        })
        db = f"docstore://{net.get('docstore_host', ds.get('host', '127.0.0.1'))}:{ds.get('port', 4223)}"
    for i in range(inv["invokers"]["count"]):
        argv = [python, "-m", "openwhisk_tpu.invoker", "--bus", bus_addr,
                "--db", db, "--unique-name", f"invoker-{i}",
                "--memory", str(inv["invokers"]["memory_mb"])]
        if inv["invokers"].get("prewarm"):
            argv.append("--prewarm")
        factory = inv["invokers"].get("container_factory")
        if factory:
            from ..containerpool.factory import FACTORY_PROVIDERS
            if factory not in FACTORY_PROVIDERS:
                raise ValueError(
                    f"invokers.container_factory must be one of "
                    f"{'/'.join(FACTORY_PROVIDERS)}, got {factory!r}")
            argv += ["--container-factory", factory]
        out.append({"name": f"invoker{i}", "argv": argv})
    n_ctrl = inv["controllers"]["count"]
    ctrl_urls = []
    for i in range(n_ctrl):
        port = inv["controllers"]["base_port"] + i
        ctrl_urls.append(f"http://{ctrl_host.format(i=i)}:{port}")
        argv = [python, "-m", "openwhisk_tpu.controller", "--bus", bus_addr,
                "--host", net.get("controller_bind", "127.0.0.1"),
                "--db", db, "--port", str(port), "--instance", str(i),
                "--cluster-size", str(n_ctrl),
                "--balancer", inv["controllers"].get("balancer", "tpu")]
        if i == 0 and inv["controllers"].get("seed_guest", True):
            argv.append("--seed-guest")
        # balancer checkpoint/resume (SURVEY §5.4): per-controller snapshot
        # files under the configured directory; restarts skip the warm-up
        # window instead of double-booking in-flight capacity
        snap_dir = inv["controllers"].get("snapshot_dir")
        interval = inv["controllers"].get("snapshot_interval")
        if interval is not None:
            if float(interval) <= 0:
                raise ValueError(
                    f"controllers.snapshot_interval must be > 0, "
                    f"got {interval!r}")
            if not snap_dir:
                raise ValueError(
                    "controllers.snapshot_interval is set but "
                    "controllers.snapshot_dir is not — no snapshots would "
                    "be written")
        if snap_dir:
            argv += ["--balancer-snapshot",
                     os.path.join(snap_dir, f"controller{i}.snap")]
            if interval is not None:
                argv += ["--balancer-snapshot-interval", str(interval)]
        out.append({"name": f"controller{i}", "argv": argv})
    mon = inv.get("monitoring") or {}
    if mon.get("enabled"):
        # the user-events service (ref core/monitoring/user-events): consumes
        # the events topic, serves Prometheus series on /metrics
        out.append({"name": "monitoring",
                    "argv": [python, "-m",
                             "openwhisk_tpu.controller.monitoring",
                             "--bus", bus_addr,
                             "--port", str(mon.get("port", 9096))]})
    if inv["edge"].get("enabled", True):
        argv = [python, "-m", "openwhisk_tpu.edge",
                "--port", str(inv["edge"]["port"]), "--controllers", *ctrl_urls]
        if inv["edge"].get("domain"):
            argv += ["--domain", inv["edge"]["domain"]]
        out.append({"name": "edge", "argv": argv})
    return out


# ------------------------------------------------------------------ local up
def up(inv: dict) -> None:
    rundir = inv["rundir"]
    os.makedirs(rundir, exist_ok=True)
    env = _env(inv)
    env.setdefault("PYTHONPATH", os.getcwd())
    started = []
    for svc in services(inv):
        log = open(os.path.join(rundir, f"{svc['name']}.log"), "ab")
        proc = subprocess.Popen(svc["argv"], stdout=log, stderr=log, env=env,
                                start_new_session=True)
        with open(os.path.join(rundir, f"{svc['name']}.pid"), "w") as f:
            f.write(str(proc.pid))
        started.append((svc["name"], proc.pid))
        print(f"started {svc['name']} (pid {proc.pid})")
        if svc["name"] in ("bus", "docstore"):
            time.sleep(1.0)  # services connect at boot; spine must be up first
    print(f"{len(started)} services up; logs + pids in {rundir}/")


def _pids(inv: dict) -> List[tuple]:
    rundir = inv["rundir"]
    out = []
    if not os.path.isdir(rundir):
        return out
    for fn in sorted(os.listdir(rundir)):
        if fn.endswith(".pid"):
            with open(os.path.join(rundir, fn)) as f:
                out.append((fn[:-4], int(f.read().strip())))
    return out


def _alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except OSError:
        return False


def down(inv: dict) -> None:
    # reverse *start* order (edge -> controllers -> invokers -> bus) so the
    # front stops admitting traffic before the workers go away
    order = {s["name"]: i for i, s in enumerate(services(inv))}
    tracked = sorted(_pids(inv), key=lambda p: order.get(p[0], -1))
    for name, pid in reversed(tracked):
        if _alive(pid):
            try:
                os.killpg(os.getpgid(pid), signal.SIGTERM)
            except OSError:
                try:
                    os.kill(pid, signal.SIGTERM)
                except OSError:
                    pass  # exited between the liveness check and the signal
            print(f"stopped {name} (pid {pid})")
        os.unlink(os.path.join(inv["rundir"], f"{name}.pid"))


def status(inv: dict) -> bool:
    pids = _pids(inv)
    if not pids:
        print("no services running (no pid files)")
        return False
    all_up = True
    for name, pid in pids:
        up_ = _alive(pid)
        all_up &= up_
        print(f"{name}: {'up' if up_ else 'DOWN'} (pid {pid})")
    return all_up


# ------------------------------------------------------------------ renderers
def render_systemd(inv: dict, outdir: str) -> None:
    os.makedirs(outdir, exist_ok=True)
    env_lines = "".join(f"Environment={k}={v}\n"
                        for k, v in _config_env(inv).items())
    for svc in services(inv, python="/usr/bin/python3"):
        after = "network.target" if svc["name"] == "bus" else "ow-bus.service"
        unit = (f"[Unit]\nDescription=openwhisk-tpu {svc['name']}\n"
                f"After={after}\n\n"
                f"[Service]\nExecStart={shlex.join(svc['argv'])}\n"
                f"WorkingDirectory=/opt/openwhisk-tpu\n{env_lines}"
                "Restart=on-failure\nRestartSec=2\n\n"
                "[Install]\nWantedBy=multi-user.target\n")
        path = os.path.join(outdir, f"ow-{svc['name']}.service")
        with open(path, "w") as f:
            f.write(unit)
        print(f"wrote {path}")


def render_k8s(inv: dict, outdir: str) -> None:
    import yaml
    os.makedirs(outdir, exist_ok=True)
    # controller + invoker share one store: a ReadWriteMany PVC mounted at
    # /data (the local-up equivalent of pointing every service at one
    # sqlite path)
    docs = [{"apiVersion": "v1", "kind": "PersistentVolumeClaim",
             "metadata": {"name": "ow-shared-db"},
             "spec": {"accessModes": ["ReadWriteMany"],
                      "resources": {"requests": {"storage": "1Gi"}}}}]
    ports = {"bus": inv["bus"]["port"], "edge": inv["edge"]["port"],
             "docstore": (inv.get("docstore") or {}).get("port", 4223),
             "monitoring": (inv.get("monitoring") or {}).get("port", 9096)}
    # pods find each other via their Service DNS names, not loopback
    net = {"bus_bind": "0.0.0.0", "bus_host": "ow-bus",
           "controller_bind": "0.0.0.0", "controller_host": "ow-controller{i}",
           "docstore_bind": "0.0.0.0", "docstore_host": "ow-docstore"}
    db_file = os.path.basename(inv["db"])
    for svc in services(inv, python="python3", net=net):
        name = f"ow-{svc['name']}"
        argv = list(svc["argv"])
        pod_spec: dict = {}
        # a docstore:// URL needs no volume — only file-backed --db args
        # (every service in file mode; only the docstore pod in URL mode)
        needs_db_file = ("--db" in argv and
                         not argv[argv.index("--db") + 1].startswith("docstore://"))
        if needs_db_file:
            argv[argv.index("--db") + 1] = f"/data/{db_file}"
            pod_spec["volumes"] = [{"name": "shared-db",
                                    "persistentVolumeClaim":
                                        {"claimName": "ow-shared-db"}}]
        container = {"name": name, "image": "openwhisk-tpu:latest",
                     "command": argv,
                     "env": [{"name": k, "value": v}
                             for k, v in _config_env(inv).items()]}
        if needs_db_file:
            container["volumeMounts"] = [{"name": "shared-db",
                                          "mountPath": "/data"}]
        docs.append({"apiVersion": "apps/v1", "kind": "Deployment",
                     "metadata": {"name": name},
                     "spec": {"replicas": 1,
                              "selector": {"matchLabels": {"app": name}},
                              "template": {
                                  "metadata": {"labels": {"app": name}},
                                  "spec": {"containers": [container],
                                           **pod_spec}}}})
        port = ports.get(svc["name"])
        if svc["name"].startswith("controller"):
            port = inv["controllers"]["base_port"] + int(svc["name"][10:])
        if port:
            docs.append({"apiVersion": "v1", "kind": "Service",
                         "metadata": {"name": name},
                         "spec": {"selector": {"app": name},
                                  "ports": [{"port": port,
                                             "targetPort": port}]}})
    path = os.path.join(outdir, "openwhisk-tpu.yaml")
    with open(path, "w") as f:
        yaml.safe_dump_all(docs, f, sort_keys=False)
    print(f"wrote {path} ({len(docs)} manifests)")


def render_monitoring(inv: dict, outdir: str,
                      controller_host: str = "127.0.0.1",
                      monitoring_host: str = "127.0.0.1") -> None:
    """Prometheus scrape config + Grafana dashboard for the deployment
    (ref core/monitoring/user-events/compose: prometheus + the OpenWhisk
    Grafana dashboards). Controllers expose balancer metrics on /metrics;
    the user-events service (inventory `monitoring.enabled`) exposes the
    per-action series. Host args take a `{i}` format for multi-host
    topologies (e.g. "ow-controller{i}" under the k8s renderer's DNS)."""
    os.makedirs(outdir, exist_ok=True)
    n_ctrl = inv["controllers"]["count"]
    base = inv["controllers"]["base_port"]
    targets = [f"{controller_host.format(i=i)}:{base + i}"
               for i in range(n_ctrl)]
    scrapes = [
        "  - job_name: openwhisk-controllers\n"
        "    metrics_path: /metrics\n"
        "    static_configs:\n"
        f"      - targets: {json.dumps(targets)}\n"]
    mon = inv.get("monitoring") or {}
    if mon.get("enabled"):
        scrapes.append(
            "  - job_name: openwhisk-user-events\n"
            "    metrics_path: /metrics\n"
            "    static_configs:\n"
            f"      - targets: [\"{monitoring_host}:{mon.get('port', 9096)}\"]\n")
    prom = "global:\n  scrape_interval: 5s\nscrape_configs:\n" + "".join(scrapes)
    path = os.path.join(outdir, "prometheus.yml")
    with open(path, "w") as f:
        f.write(prom)
    print(f"wrote {path}")

    def panel(pid, title, exprs, y, unit="short", width=12, x=0):
        return {
            "id": pid, "title": title, "type": "timeseries",
            "gridPos": {"h": 8, "w": width, "x": x, "y": y},
            "fieldConfig": {"defaults": {"unit": unit}},
            "targets": [{"expr": e, "legendFormat": l, "refId": chr(65 + i)}
                        for i, (e, l) in enumerate(exprs)],
        }

    dashboard = {
        "title": "OpenWhisk-TPU",
        "uid": "openwhisk-tpu",
        "schemaVersion": 39,
        "refresh": "10s",
        "time": {"from": "now-1h", "to": "now"},
        "panels": [
            panel(1, "Activations/s by action",
                  [("sum by (action) "
                    "(rate(openwhisk_userevents_activations_total[1m]))",
                    "{{action}}")], 0),
            panel(2, "Cold starts/s",
                  [("sum(rate(openwhisk_userevents_cold_starts_total[1m]))",
                    "cold starts")], 0, x=12),
            panel(3, "Mean activation duration (ms)",
                  [("sum by (action) "
                    "(rate(openwhisk_userevents_duration_ms_sum[5m]))"
                    " / sum by (action) "
                    "(rate(openwhisk_userevents_duration_ms_count[5m]))",
                    "{{action}}")], 8, unit="ms"),
            panel(4, "Throttle rejections/s",
                  [("sum by (namespace, metric) "
                    "(rate(openwhisk_userevents_rate_limit_total[1m]))",
                    "{{namespace}} {{metric}}")], 8, x=12),
            panel(5, "Placements/s (TPU balancer)",
                  [("rate(openwhisk_loadbalancer_tpu_scheduled[1m])",
                    "scheduled"),
                   ("rate(openwhisk_loadbalancer_forced_placements[1m])",
                    "forced")], 16),
            panel(6, "Device step mean (ms)",
                  [("rate(openwhisk_loadbalancer_tpu_schedule_batch_ms_sum[5m])"
                    " / rate(openwhisk_loadbalancer_tpu_schedule_batch_ms_count[5m])",
                    "step")], 16, unit="ms", x=12),
        ],
    }
    path = os.path.join(outdir, "grafana-openwhisk.json")
    with open(path, "w") as f:
        json.dump(dashboard, f, indent=2)
    print(f"wrote {path}")


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(description="OpenWhisk-TPU deployer")
    parser.add_argument("-i", "--inventory", default=None,
                        help="inventory file (yaml or json)")
    sub = parser.add_subparsers(dest="cmd", required=True)
    sub.add_parser("up")
    sub.add_parser("down")
    sub.add_parser("status")
    render = sub.add_parser("render")
    render.add_argument("target", choices=("systemd", "k8s", "monitoring"))
    render.add_argument("-o", "--outdir", default="deploy/out")
    args = parser.parse_args(argv)

    inv = load_inventory(args.inventory)
    if args.cmd == "up":
        up(inv)
    elif args.cmd == "down":
        down(inv)
    elif args.cmd == "status":
        return 0 if status(inv) else 1
    elif args.cmd == "render":
        renderer = {"systemd": render_systemd, "k8s": render_k8s,
                    "monitoring": render_monitoring}[args.target]
        renderer(inv, args.outdir)
    return 0


if __name__ == "__main__":
    sys.exit(main())

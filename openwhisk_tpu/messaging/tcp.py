"""TCP bus: the framework's own distributed messaging spine.

The reference's data plane rides Kafka (SURVEY §5.8); this module provides
the framework-native equivalent for multi-process/multi-host deployments
without external brokers: a lightweight asyncio broker (`TcpBusServer`)
serving the same topic/consumer-group semantics as the in-memory bus over
length-prefixed JSON frames, and `TcpMessagingProvider` implementing the
MessagingProvider SPI against it. Kafka itself remains pluggable behind the
same SPI (messaging/kafka.py, gated on client availability).

Protocol (4-byte big-endian length + JSON):
  {"op": "pub",  "topic": t, "payload": <b64>}            -> {"ok": true}
  {"op": "pubN", "msgs": [{"topic": t, "mid": m,
   "payload": <b64>}, ...]}  -> {"ok": true, "results": [{"ok": true,
                                 "dup": bool}, ...]}      (one ack for N)
  {"op": "peek", "topic": t, "group": g, "max": n,
   "timeout": s}   -> {"msgs": [[offset, <b64>], ...]}    (long-poll)
  {"op": "ensure", "topic": t}                            -> {"ok": true}
Delivery is at-most-once per group, exactly like the reference's
commit-after-peek hand-off (MessageConsumer.scala:179-190). `pubN` is the
coalesced produce op (messaging/coalesce.py): N payloads, one round trip,
dedupe keyed PER SUB-MESSAGE so a retried frame replays only the payloads
whose first delivery was lost.
"""
from __future__ import annotations

import asyncio
import base64
import json
import logging
import socket
import struct
import time
import uuid
from typing import List, Optional, Tuple

from .connector import MessageConsumer, MessageProducer, MessagingProvider
from .memory import MemoryBus

_log = logging.getLogger("openwhisk_tpu.messaging.tcp")

#: frames whose b64+JSON encode exceeds this many payload bytes are built on
#: the default executor instead of the event loop (a 1 MB action result
#: costs ~ms of base64 — real loop stall at thousands of sends/s)
OFFLOAD_ENCODE_BYTES = 48 * 1024

#: cap on the RAW payload bytes packed into one pubN frame: b64 inflates by
#: 4/3 and _read_frame rejects frames over 64 MiB, so a coalesced batch of
#: large bodies (64 x ~1 MiB completion acks) must SPLIT into several
#: frames rather than ship one rejected mega-frame that would fail every
#: message in the batch forever (the count-based flush bound alone cannot
#: see bytes)
MAX_PUBN_PAYLOAD_BYTES = 16 * 1024 * 1024

#: process-wide TCP-bus client health counters (export_bus_gauges)
_BUS_STATS = {"consumer_reconnects": 0}


async def _read_frame(reader: asyncio.StreamReader) -> Optional[dict]:
    try:
        header = await reader.readexactly(4)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    (length,) = struct.unpack(">I", header)
    if length > 64 * 1024 * 1024:
        return None
    try:
        body = await reader.readexactly(length)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    return json.loads(body)


def _frame(obj: dict) -> bytes:
    body = json.dumps(obj, separators=(",", ":")).encode()
    return struct.pack(">I", len(body)) + body


class TcpBusServer:
    """The broker: topic queues (a MemoryBus) served over TCP."""

    def __init__(self, host: str = "127.0.0.1", port: int = 4222):
        self.host = host
        self.port = port
        self.bus = MemoryBus()
        self._server: Optional[asyncio.AbstractServer] = None
        self._client_writers: set = set()
        self._seen_mids: dict = {}  # LRU of recent pub message ids (dedupe)

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._handle, self.host, self.port)

    async def stop(self) -> None:
        if self._server:
            self._server.close()
            # sever live client connections: wait_closed() (py3.12) waits for
            # all handlers, which block in reads on long-lived clients
            for w in list(self._client_writers):
                w.close()
            await self._server.wait_closed()

    def _seen(self, mid) -> bool:
        """Record `mid` in the dedupe LRU; True when it was already there
        (a producer retried a frame whose ack was lost — the activation
        must not run twice because of a dropped TCP response)."""
        if mid is None:
            return False
        if mid in self._seen_mids:
            return True
        self._seen_mids[mid] = None
        if len(self._seen_mids) > 8192:
            self._seen_mids.pop(next(iter(self._seen_mids)))
        return False

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        from .memory import MemoryConsumer, MemoryProducer
        producer = MemoryProducer(self.bus)
        consumers = {}
        self._client_writers.add(writer)
        try:
            while True:
                req = await _read_frame(reader)
                if req is None:
                    break
                op = req.get("op")
                if op == "pub":
                    if self._seen(req.get("mid")):
                        writer.write(_frame({"ok": True, "dup": True}))
                    else:
                        payload = base64.b64decode(req["payload"])
                        await producer.send(req["topic"], payload)
                        writer.write(_frame({"ok": True}))
                elif op == "pubN":
                    # coalesced produce: dedupe each sub-message, then one
                    # grouped append for everything fresh — a retried frame
                    # replays only the sub-messages that never landed
                    results = []
                    fresh = []
                    for sub in req.get("msgs", []):
                        if self._seen(sub.get("mid")):
                            results.append({"ok": True, "dup": True})
                        else:
                            fresh.append((sub["topic"],
                                          base64.b64decode(sub["payload"]),
                                          None))
                            results.append({"ok": True})
                    if fresh:
                        await producer.send_many(fresh)
                    writer.write(_frame({"ok": True, "results": results}))
                elif op == "peek":
                    key = (req["topic"], req.get("group", "default"))
                    consumer = consumers.get(key)
                    if consumer is None:
                        consumer = MemoryConsumer(
                            self.bus, key[0], key[1], max_peek=1024,
                            from_latest=bool(req.get("latest")))
                        consumers[key] = consumer
                    batch = await consumer.peek(int(req.get("max", 128)),
                                                float(req.get("timeout", 0.5)))
                    consumer.commit()
                    writer.write(_frame({"msgs": [
                        [off, base64.b64encode(p).decode()]
                        for (_t, _p, off, p) in batch]}))
                elif op == "ensure":
                    t = self.bus.topic(req["topic"])
                    if req.get("retention_bytes") is not None:
                        t.set_retention_bytes(int(req["retention_bytes"]))
                    writer.write(_frame({"ok": True}))
                else:
                    writer.write(_frame({"error": f"unknown op {op!r}"}))
                await writer.drain()
        finally:
            self._client_writers.discard(writer)
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionResetError, OSError):
                pass


class _TcpConnection:
    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self.reader: Optional[asyncio.StreamReader] = None
        self.writer: Optional[asyncio.StreamWriter] = None
        self._lock = asyncio.Lock()

    async def request(self, obj: dict) -> dict:
        return await self.request_frame(_frame(obj))

    async def request_frame(self, frame: bytes) -> dict:
        """One request/response round trip for an already-encoded frame
        (large frames are built off-loop by the producer; the retry loop
        reuses the same bytes, which is what keeps broker-side dedupe by
        mid sound)."""
        async with self._lock:
            for attempt in (1, 2):
                if self.writer is None or self.writer.is_closing():
                    self.reader, self.writer = await asyncio.open_connection(
                        self.host, self.port)
                try:
                    self.writer.write(frame)
                    await self.writer.drain()
                    resp = await _read_frame(self.reader)
                    if resp is not None:
                        return resp
                except (ConnectionResetError, BrokenPipeError, OSError):
                    pass
                # reconnect once; close the dead transport to free its fd
                self.writer.close()
                self.writer = None
            raise ConnectionError(f"bus at {self.host}:{self.port} unreachable")

    async def close(self) -> None:
        if self.writer is not None:
            self.writer.close()
            try:
                await self.writer.wait_closed()
            except (ConnectionResetError, OSError):
                pass
            self.writer = None


def _encode_pub(topic: str, mid: str, payload: bytes) -> bytes:
    return _frame({"op": "pub", "topic": topic, "mid": mid,
                   "payload": base64.b64encode(payload).decode()})


def _encode_pubn(msgs: List[Tuple[str, str, bytes]]) -> bytes:
    return _frame({"op": "pubN", "msgs": [
        {"topic": t, "mid": m, "payload": base64.b64encode(p).decode()}
        for (t, m, p) in msgs]})


class TcpProducer(MessageProducer):
    def __init__(self, host: str, port: int):
        self._conn = _TcpConnection(host, port)
        self._sent = 0
        # cheap unique message ids: one random prefix per producer plus a
        # counter, instead of a uuid4 per send (uuid minting was measurable
        # hot-path work at thousands of sends/s). Dedupe semantics are
        # unchanged: the mid is unique per LOGICAL send and stable across
        # the connection-level retry inside request_frame.
        self._mid_prefix = uuid.uuid4().hex[:12]
        self._mid_seq = 0

    @property
    def sent_count(self) -> int:
        return self._sent

    def _next_mid(self) -> str:
        self._mid_seq += 1
        return f"{self._mid_prefix}-{self._mid_seq}"

    async def _encoded(self, total_payload: int, encode, *args) -> bytes:
        """Build the frame inline for small payloads; push the b64+JSON
        encode of large bodies onto the default executor so it never
        blocks the event loop."""
        if total_payload <= OFFLOAD_ENCODE_BYTES:
            return encode(*args)
        return await asyncio.get_event_loop().run_in_executor(
            None, encode, *args)

    async def send(self, topic: str, msg) -> None:
        payload = bytes(msg) if isinstance(msg, (bytes, bytearray)) \
            else msg.serialize()
        # one mid per logical send: a connection-retry of the same frame is
        # deduped broker-side, keeping pub effectively-once
        frame = await self._encoded(len(payload), _encode_pub, topic,
                                    self._next_mid(), payload)
        await self._conn.request_frame(frame)
        self._sent += 1
        from .connector import stamp_produce
        stamp_produce(msg)  # waterfall produce edge (broker-acknowledged)

    async def send_many(self, items) -> None:
        """Coalesced produce: one `pubN` frame + one ack for the whole
        micro-batch instead of a lock-serialized round trip per message.
        The broker dedupes per sub-message, so a frame retry after a lost
        ack replays only what never landed. Batches whose raw payloads
        exceed MAX_PUBN_PAYLOAD_BYTES split into several frames (in
        order, same connection) so one oversized mega-frame can never be
        rejected broker-side and take the whole batch down with it; a
        single message bigger than the cap ships alone, exactly like the
        serial path would have sent it."""
        from .connector import stamp_produce
        chunk: List[Tuple[str, str, bytes]] = []
        chunk_src: list = []
        chunk_bytes = 0

        async def _ship() -> None:
            nonlocal chunk, chunk_src, chunk_bytes
            frame = await self._encoded(chunk_bytes, _encode_pubn, chunk)
            await self._conn.request_frame(frame)
            self._sent += len(chunk)
            for m in chunk_src:
                if m is not None:
                    stamp_produce(m)  # produce edge per message, one ack
            chunk, chunk_src, chunk_bytes = [], [], 0

        for topic, payload, m in items:
            payload = bytes(payload)
            if chunk and chunk_bytes + len(payload) > MAX_PUBN_PAYLOAD_BYTES:
                await _ship()
            chunk.append((topic, self._next_mid(), payload))
            chunk_src.append(m)
            chunk_bytes += len(payload)
        if chunk:
            await _ship()

    async def close(self) -> None:
        await self._conn.close()


class TcpConsumer(MessageConsumer):
    def __init__(self, host: str, port: int, topic: str, group: str,
                 max_peek: int = 128, from_latest: bool = False):
        self._conn = _TcpConnection(host, port)
        self.topic = topic
        self.group = group
        self.max_peek = max_peek
        self.from_latest = from_latest
        #: connection-loss retries inside peek() (aggregated process-wide
        #: into the bus_consumer_reconnects gauge)
        self.reconnects = 0

    async def peek(self, max_messages: int, timeout: float = 0.5
                   ) -> List[Tuple[str, int, int, bytes]]:
        # On ConnectionError, do NOT sleep out the whole window: the broker
        # may come back mid-sleep (rolling restart), and a feed that naps
        # the full long-poll timeout adds that much delivery delay per
        # blip. Capped exponential backoff with a short first retry keeps
        # reconnection snappy while not hammering a dead endpoint.
        deadline = time.monotonic() + max(0.0, timeout)
        delay = 0.02
        while True:
            remaining = deadline - time.monotonic()
            try:
                resp = await self._conn.request({
                    "op": "peek", "topic": self.topic, "group": self.group,
                    "latest": self.from_latest,
                    "max": min(max_messages, self.max_peek),
                    "timeout": max(remaining, 0.0)})
            except ConnectionError:
                self.reconnects += 1
                _BUS_STATS["consumer_reconnects"] += 1
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return []
                await asyncio.sleep(min(delay, remaining))
                delay = min(delay * 2, 1.0)
                continue
            return [(self.topic, 0, off, base64.b64decode(p))
                    for off, p in resp.get("msgs", [])]

    def commit(self) -> None:
        pass  # the broker commits at peek (at-most-once), like the reference

    async def close(self) -> None:
        await self._conn.close()


def export_bus_gauges(metrics) -> None:
    """TCP-bus client health (ridden by the balancers' supervision tick,
    like export_tracing_gauges): consumer reconnect attempts — a rising
    count means feeds are riding out broker blips via the peek backoff."""
    metrics.gauge("bus_consumer_reconnects", _BUS_STATS["consumer_reconnects"])


class TcpMessagingProvider(MessagingProvider):
    def __init__(self, host: str = "127.0.0.1", port: int = 4222):
        self.host = host
        self.port = port
        self._admin = _TcpConnection(host, port)

    def get_producer(self) -> TcpProducer:
        return TcpProducer(self.host, self.port)

    def get_consumer(self, topic: str, group_id: str, max_peek: int = 128,
                     from_latest: bool = False) -> TcpConsumer:
        return TcpConsumer(self.host, self.port, topic, group_id, max_peek,
                           from_latest=from_latest)

    def ensure_topic(self, topic: str, partitions: int = 1,
                     retention_bytes: Optional[int] = None) -> None:
        req = {"op": "ensure", "topic": topic,
               "retention_bytes": retention_bytes}
        from ..utils.tasks import spawn
        try:
            loop = asyncio.get_event_loop()
            running = loop.is_running()
        except RuntimeError:
            running = False
        if running:
            spawn(self._admin.request(req), name=f"ensure-{topic}")
            return
        # No running loop (service boot, sync tooling): a silent skip here
        # used to leave topics with custom retention_bytes unconfigured
        # until first use reset nothing — log it and fall back to a
        # blocking one-shot connection so the retention override lands.
        _log.warning("ensure_topic(%r): no running event loop; using a "
                     "blocking one-shot connection", topic)
        self._ensure_blocking(req)

    def _ensure_blocking(self, req: dict, timeout: float = 2.0) -> None:
        """Synchronous one-shot `ensure` (only reachable from sync
        contexts). Best-effort: an unreachable broker logs and returns —
        topics still auto-create on first use, only the retention override
        is lost (and now said so, instead of silently)."""
        frame = _frame(req)
        try:
            with socket.create_connection((self.host, self.port),
                                          timeout=timeout) as s:
                s.settimeout(timeout)
                s.sendall(frame)
                header = self._recv_exact(s, 4)
                (length,) = struct.unpack(">I", header)
                self._recv_exact(s, length)
        except OSError as e:
            _log.warning("ensure_topic(%r): blocking fallback failed: %r",
                         req["topic"], e)

    @staticmethod
    def _recv_exact(s: socket.socket, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = s.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("bus closed mid-frame")
            buf += chunk
        return buf

"""Kafka backend contract tests against a fake aiokafka client (ref
connector/kafka/*.scala + KafkaConnectorTests.scala): topic ensure with
retention config, commit-after-peek at-most-once handoff, payload-size
config, from-latest subscription, and the MessageFeed pipeline running on
top. The real `aiokafka` is not in this image, so the fake stands in —
these tests are the first execution this backend gets anywhere.

When no fake is installed the module stays import-gated: constructing any
Kafka class raises the clear RuntimeError instead of an obscure NameError.
"""
import asyncio
import importlib
import sys
import types

import pytest


# ---------------------------------------------------------------- fake broker
class FakeBroker:
    def __init__(self):
        self.topics = {}           # name -> list[bytes]
        self.topic_configs = {}    # name -> dict
        self.committed = {}        # (group, topic) -> offset
        self.create_calls = []

    def append(self, topic, value):
        self.topics.setdefault(topic, []).append(value)
        return len(self.topics[topic]) - 1


def make_fake_aiokafka(broker: FakeBroker):
    mod = types.ModuleType("aiokafka")
    admin_mod = types.ModuleType("aiokafka.admin")

    class AIOKafkaProducer:
        def __init__(self, bootstrap_servers=None, max_request_size=None,
                     acks=None):
            self.bootstrap_servers = bootstrap_servers
            self.max_request_size = max_request_size
            self.acks = acks
            self.started = False
            broker.last_producer = self

        async def start(self):
            self.started = True

        async def stop(self):
            self.started = False

        async def send_and_wait(self, topic, value):
            assert self.started, "send before start()"
            if self.max_request_size and len(value) > self.max_request_size:
                raise RuntimeError("MessageSizeTooLargeError")
            broker.append(topic, value)

    class _Record:
        def __init__(self, topic, partition, offset, value):
            self.topic, self.partition = topic, partition
            self.offset, self.value = offset, value

    class _TP:
        def __init__(self, topic):
            self.topic, self.partition = topic, 0

    class AIOKafkaConsumer:
        def __init__(self, topic, bootstrap_servers=None, group_id=None,
                     enable_auto_commit=None, auto_offset_reset="earliest"):
            assert enable_auto_commit is False, \
                "contract: manual commit only (commit-after-peek)"
            self.topic, self.group = topic, group_id
            self.auto_offset_reset = auto_offset_reset
            self.started = False
            self._pos = None
            self._last_peeked = None

        async def start(self):
            self.started = True
            key = (self.group, self.topic)
            if key in broker.committed:
                self._pos = broker.committed[key]
            elif self.auto_offset_reset == "latest":
                self._pos = len(broker.topics.get(self.topic, []))
            else:
                self._pos = 0

        async def stop(self):
            self.started = False

        async def getmany(self, timeout_ms=0, max_records=None):
            assert self.started
            log = broker.topics.get(self.topic, [])
            records = [
                _Record(self.topic, 0, off, log[off])
                for off in range(self._pos,
                                 min(len(log), self._pos + (max_records or 1)))
            ]
            if not records:
                await asyncio.sleep(min(timeout_ms / 1000.0, 0.01))
                return {}
            self._pos = records[-1].offset + 1
            self._last_peeked = self._pos
            return {_TP(self.topic): records}

        async def commit(self):
            assert self.started
            if self._last_peeked is not None:
                broker.committed[(self.group, self.topic)] = self._last_peeked

    class NewTopic:
        def __init__(self, name, num_partitions, replication_factor,
                     topic_configs=None):
            self.name = name
            self.num_partitions = num_partitions
            self.topic_configs = topic_configs or {}

    class AIOKafkaAdminClient:
        def __init__(self, bootstrap_servers=None):
            self.bootstrap_servers = bootstrap_servers

        async def start(self):
            pass

        async def close(self):
            pass

        async def create_topics(self, new_topics):
            for t in new_topics:
                broker.create_calls.append(t)
                broker.topics.setdefault(t.name, [])
                broker.topic_configs[t.name] = dict(t.topic_configs)

    mod.AIOKafkaProducer = AIOKafkaProducer
    mod.AIOKafkaConsumer = AIOKafkaConsumer
    mod.admin = admin_mod
    admin_mod.AIOKafkaAdminClient = AIOKafkaAdminClient
    admin_mod.NewTopic = NewTopic
    return mod, admin_mod


@pytest.fixture
def kafka_mod():
    """messaging.kafka reloaded against a fresh fake aiokafka."""
    broker = FakeBroker()
    mod, admin_mod = make_fake_aiokafka(broker)
    saved = {k: sys.modules.get(k) for k in ("aiokafka", "aiokafka.admin")}
    sys.modules["aiokafka"] = mod
    sys.modules["aiokafka.admin"] = admin_mod
    import openwhisk_tpu.messaging.kafka as kafka
    kafka = importlib.reload(kafka)
    yield kafka, broker
    for k, v in saved.items():
        if v is None:
            sys.modules.pop(k, None)
        else:
            sys.modules[k] = v
    importlib.reload(kafka)


class TestKafkaContract:
    def test_gated_when_library_absent(self):
        import openwhisk_tpu.messaging.kafka as kafka
        if kafka.HAVE_KAFKA:
            pytest.skip("aiokafka installed: the gate is legitimately open")
        with pytest.raises(RuntimeError, match="no kafka client"):
            kafka.KafkaMessagingProvider()

    def test_producer_payload_size_and_acks_config(self, kafka_mod):
        kafka, broker = kafka_mod

        async def go():
            provider = kafka.KafkaMessagingProvider("broker:9092")
            producer = provider.get_producer()
            await producer.send("t", b"x" * 100)
            assert broker.last_producer.max_request_size == \
                kafka.MAX_REQUEST_SIZE == 1024 * 1024 + 6144
            assert broker.last_producer.acks == "all"
            assert producer.sent_count == 1
            # over the cap: surfaced, not swallowed
            with pytest.raises(RuntimeError, match="TooLarge"):
                await producer.send("t", b"x" * (kafka.MAX_REQUEST_SIZE + 1))
            await producer.close()

        asyncio.run(go())

    def test_message_objects_are_serialized(self, kafka_mod):
        kafka, broker = kafka_mod
        from openwhisk_tpu.core.entity import InvokerInstanceId
        from openwhisk_tpu.messaging import PingMessage

        async def go():
            producer = kafka.KafkaMessagingProvider("b").get_producer()
            await producer.send("health", PingMessage(InvokerInstanceId(3)))
            raw = broker.topics["health"][0]
            parsed = PingMessage.parse(raw)
            assert parsed.instance.instance == 3
            await producer.close()

        asyncio.run(go())

    def test_ensure_topic_creates_with_retention(self, kafka_mod):
        kafka, broker = kafka_mod

        async def go():
            provider = kafka.KafkaMessagingProvider("b")
            provider.ensure_topic("completed0", retention_bytes=1 << 30)
            await asyncio.sleep(0.05)  # ensure runs as a spawned task

        asyncio.run(go())
        assert broker.topic_configs.get("completed0") == \
            {"retention.bytes": str(1 << 30)}
        assert broker.create_calls[0].num_partitions == 1

    def test_peek_commit_ordering_at_most_once(self, kafka_mod):
        """Commit AFTER peek: messages peeked but not committed are
        redelivered to the group's next consumer (at-most-once handoff to
        the handler, ref MessageConsumer.scala:179-190)."""
        kafka, broker = kafka_mod

        async def go():
            provider = kafka.KafkaMessagingProvider("b")
            producer = provider.get_producer()
            for i in range(5):
                await producer.send("invoker0", f"m{i}".encode())

            c1 = provider.get_consumer("invoker0", "invoker0")
            first = await c1.peek(2)
            assert [v for (_, _, _, v) in first] == [b"m0", b"m1"]
            c1.commit()
            await asyncio.sleep(0.02)  # commit is fire-and-forget
            second = await c1.peek(2)
            assert [v for (_, _, _, v) in second] == [b"m2", b"m3"]
            # NOT committed — crash here: the next consumer in the group
            # must see m2 again, not lose it
            await c1.close()

            c2 = provider.get_consumer("invoker0", "invoker0")
            replay = await c2.peek(10)
            assert [v for (_, _, _, v) in replay] == [b"m2", b"m3", b"m4"]
            await c2.close()
            await producer.close()

        asyncio.run(go())

    def test_from_latest_skips_backlog(self, kafka_mod):
        kafka, broker = kafka_mod

        async def go():
            provider = kafka.KafkaMessagingProvider("b")
            producer = provider.get_producer()
            await producer.send("health", b"old-ping")
            c = provider.get_consumer("health", "health-ctrl0",
                                      from_latest=True)
            assert await c.peek(10, timeout=0.01) == []
            await producer.send("health", b"new-ping")
            got = await c.peek(10)
            assert [v for (_, _, _, v) in got] == [b"new-ping"]
            await c.close()
            await producer.close()

        asyncio.run(go())

    def test_message_feed_runs_on_kafka(self, kafka_mod):
        """The MessageFeed double-buffered pull pipeline executes against
        the Kafka consumer exactly as against the in-memory bus."""
        kafka, broker = kafka_mod
        from openwhisk_tpu.messaging import MessageFeed

        async def go():
            provider = kafka.KafkaMessagingProvider("b")
            producer = provider.get_producer()
            for i in range(6):
                await producer.send("invoker1", f"a{i}".encode())
            got = []
            box = {}

            async def handle(payload: bytes):
                got.append(payload)
                box["feed"].processed()

            consumer = provider.get_consumer("invoker1", "invoker1")
            feed = MessageFeed("invoker1", consumer, 4, handle)
            box["feed"] = feed
            feed.start()
            for _ in range(100):
                if len(got) == 6:
                    break
                await asyncio.sleep(0.02)
            await feed.stop()
            await producer.close()
            return got

        got = asyncio.run(go())
        assert got == [f"a{i}".encode() for i in range(6)]

"""Structured logging + in-process metrics.

Rebuilt from the reference's Logging/MetricEmitter
(common/scala/.../common/Logging.scala:37-120,241-258): log lines are prefixed
with the transaction id; MetricEmitter keeps counters/histograms/gauges that a
Prometheus endpoint can scrape (openwhisk_tpu.controller.monitoring).
"""
from __future__ import annotations

import sys
import threading
import time
from collections import defaultdict
from typing import Optional

_LEVELS = {"debug": 10, "info": 20, "warn": 30, "error": 40}


class MetricEmitter:
    """Thread-safe counters / histograms / gauges (ref Logging.scala:241-258).

    Histograms keep (count, sum, min, max) plus a small reservoir for
    percentile estimates — enough for the /metrics endpoint and tests.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, int] = defaultdict(int)
        self._gauges: dict[str, float] = {}
        self._hist: dict[str, list] = {}  # name -> [count, sum, min, max, reservoir]

    def counter(self, name: str, delta: int = 1) -> None:
        with self._lock:
            self._counters[name] += delta

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def histogram(self, name: str, value: float) -> None:
        with self._lock:
            h = self._hist.get(name)
            if h is None:
                h = [0, 0.0, float("inf"), float("-inf"), []]
                self._hist[name] = h
            h[0] += 1
            h[1] += value
            h[2] = min(h[2], value)
            h[3] = max(h[3], value)
            res = h[4]
            if len(res) < 1024:
                res.append(value)
            else:  # reservoir-replace
                res[h[0] % 1024] = value

    # -- read side ---------------------------------------------------------
    def counter_value(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def gauge_value(self, name: str) -> Optional[float]:
        with self._lock:
            return self._gauges.get(name)

    def histogram_stats(self, name: str) -> Optional[dict]:
        with self._lock:
            h = self._hist.get(name)
            if not h or not h[0]:
                return None
            res = sorted(h[4])
            return {
                "count": h[0], "sum": h[1], "min": h[2], "max": h[3],
                "mean": h[1] / h[0],
                "p50": res[len(res) // 2],
                "p99": res[min(len(res) - 1, int(len(res) * 0.99))],
            }

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {k: {"count": v[0], "sum": v[1]} for k, v in self._hist.items()},
            }

    def prometheus_text(self) -> str:
        """Render in Prometheus exposition format (ref core/monitoring)."""
        out = []
        snap = self.snapshot()
        for k, v in sorted(snap["counters"].items()):
            n = _prom_name(k)
            out.append(f"# TYPE {n} counter\n{n} {v}")
        for k, v in sorted(snap["gauges"].items()):
            n = _prom_name(k)
            out.append(f"# TYPE {n} gauge\n{n} {v}")
        for k, v in sorted(snap["histograms"].items()):
            n = _prom_name(k)
            out.append(f"# TYPE {n} summary\n{n}_count {v['count']}\n{n}_sum {v['sum']}")
        return "\n".join(out) + "\n"


def _prom_name(name: str) -> str:
    return "openwhisk_" + "".join(c if c.isalnum() or c == "_" else "_" for c in name)


class Logging:
    """Base logger: level-filtered, transid-prefixed lines + metric sink."""

    def __init__(self, level: str = "info", metrics: Optional[MetricEmitter] = None,
                 stream=None):
        self.level = _LEVELS.get(level, 20)
        self.metrics = metrics or MetricEmitter()
        self.stream = stream or sys.stderr
        self._lock = threading.Lock()

    def emit(self, level: str, transid, message: str, component: str = "") -> None:
        if _LEVELS.get(level, 20) < self.level:
            return
        ts = time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime())
        line = f"[{ts}] [{level.upper()}] [{transid}] [{component}] {message}"
        with self._lock:
            print(line, file=self.stream)

    def debug(self, transid, msg, component=""):
        self.emit("debug", transid, msg, component)

    def info(self, transid, msg, component=""):
        self.emit("info", transid, msg, component)

    def warn(self, transid, msg, component=""):
        self.emit("warn", transid, msg, component)

    def error(self, transid, msg, component=""):
        self.emit("error", transid, msg, component)


class PrintLogging(Logging):
    pass


class NullLogging(Logging):
    def emit(self, level, transid, message, component=""):
        pass

"""openwhisk_tpu — a TPU-native serverless (FaaS) control plane.

A ground-up rebuild of the capabilities of Apache OpenWhisk (reference:
/root/reference, Scala/Akka) designed TPU-first: the controller's activation
placement decisions are computed by a JAX/XLA vectorized bin-packing kernel
over device-resident invoker state (see `openwhisk_tpu.ops.placement` and
`openwhisk_tpu.controller.loadbalancer.tpu_balancer`), shardable over a
`jax.sharding.Mesh` for fleets of up to 64k invokers.

Layer map (mirrors reference SURVEY.md §1):
  controller/   REST API, entitlement, load balancing   (ref: core/controller)
  invoker/      activation execution loop               (ref: core/invoker)
  containerpool container lifecycle + drivers           (ref: core/invoker/containerpool)
  messaging/    bus abstraction + in-memory/kafka-like  (ref: common/.../connector)
  database/     artifact/activation stores + caching    (ref: common/.../database)
  core/entity/  domain model                            (ref: common/.../entity)
  ops/          JAX/Pallas device kernels (placement, throttling)
  parallel/     mesh/sharding for multi-chip balancer state
  models/       placement policy models (sharding-parity, batched bin-pack)
  utils/        logging, transactions, semaphores, scheduling, config
"""

__version__ = "0.1.0"

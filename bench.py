"""Benchmark: activation placement decisions/sec on the TPU placement kernel.

Measures the steady-state rate of the balancer's device step — ONE fused
program (ops.placement.make_fused_step: previous batch's release fold +
health fold + a B=256 schedule) over the fleet size given by `--fleet`
(default 1024; the north-star config is 65536), exactly the program
TpuBalancer._device_step dispatches per micro-batch. Books are held
constant (each step releases the prior step's placements) so the loop runs
indefinitely.

What runs (default, no args):
  1. XLA kernel, median of 5 timed repeats (+ spread) — the headline number.
  2. Pallas kernel (ops/placement_pallas.py), same protocol — on real TPU
     hardware this is the compiled kernel, on CPU it is interpret mode.
  3. On-device parity: both kernels stepped from identical state over the
     same batch; chosen/forced/books compared exactly.
  4. Balancer-level benchmark: TpuBalancer.publish() -> placement future,
     echo invokers on the in-memory bus — activations/s and p50/p99
     publish->placement latency at client concurrencies c=64/8/1, each with
     a phase breakdown (assembly / dispatch / readback / fan-out ms). Two
     runs: the default backend (through the tunnel every device step costs a
     ~70 ms wire round trip), and a CPU-backend subprocess — the HOST-PATH
     row, showing what the host machinery sustains when the device is
     PCIe-local (as on a real TPU host) rather than behind a WAN tunnel.

`--kernel xla|pallas` restricts step 1-2 to one kernel; `--quick` skips the
balancer bench; `--sweep` prints an (N invokers x A slots) xla-vs-pallas
rate table to stderr for kernel-selection docs — sweep mode emits NO JSON
line on stdout and ignores --kernel/--quick (it is a diagnostic, not the
driver contract).

Baseline: BASELINE.json targets >= 50,000 placements/s (reference point: the
CPU ShardingContainerPoolBalancer inner loop, which this kernel replaces).
`vs_baseline` = median XLA rate / 50,000. A CPU-oracle rate is also measured
for context (stderr).

Prints ONE JSON line on stdout; every secondary figure rides along as extra
keys (kernels, parity_ok, balancer, spread) so the driver's BENCH_r{N}.json
captures the whole story.
"""
from __future__ import annotations

import argparse
import asyncio
import json
import os
import statistics
import sys
import time

import numpy as np

N_INVOKERS = 1024
BATCH = 256
WARMUP = 5
ITERS = 40
REPEATS = 5
TARGET = 50_000.0


def _build_fused(kernel: str):
    """The balancer's fused device program with the requested schedule
    kernel — mirrors TpuBalancer._init_device_state's wrapping."""
    import jax

    from openwhisk_tpu.ops.placement import (PlacementState, make_fused_step,
                                             schedule_batch)

    if kernel == "pallas":
        from openwhisk_tpu.ops.placement_pallas import (schedule_batch_pallas,
                                                        to_transposed)
        interpret = jax.default_backend() == "cpu"

        def sched(st, b):
            ts, chosen, forced = schedule_batch_pallas(
                to_transposed(st), b, interpret=interpret)
            return (PlacementState(ts.free_mb, ts.conc_free.T, ts.health),
                    chosen, forced)

        return make_fused_step(None, sched)
    if kernel == "pallas_repair":
        from openwhisk_tpu.ops.placement import release_batch_vector
        from openwhisk_tpu.ops.placement_pallas import (
            schedule_batch_repair_pallas, to_transposed)
        interpret = jax.default_backend() == "cpu"

        def sched(st, b):
            ts, chosen, forced, rounds = schedule_batch_repair_pallas(
                to_transposed(st), b, interpret=interpret)
            return (PlacementState(ts.free_mb, ts.conc_free.T, ts.health),
                    chosen, forced, rounds)

        return make_fused_step(release_batch_vector, sched)
    if kernel == "repair":
        from openwhisk_tpu.ops.placement import (release_batch_vector,
                                                 schedule_batch_repair)
        return make_fused_step(release_batch_vector, schedule_batch_repair)
    return make_fused_step(None, schedule_batch)


def _bench_kernel(kernel: str, n_invokers: int = N_INVOKERS,
                  action_slots: int = 256, repeats: int = REPEATS,
                  iters: int = ITERS, batch_size: int = BATCH,
                  batch=None) -> dict:
    """Median-of-`repeats` steady-state rate for one kernel."""
    import jax
    import jax.numpy as jnp

    from __graft_entry__ import _example_batch
    from openwhisk_tpu.ops.placement import init_state

    state0 = init_state(n_invokers, [2048] * n_invokers,
                        action_slots=action_slots)
    if batch is None:
        batch = _example_batch(n_invokers, batch_size, seed=7)
    else:
        batch_size = int(batch.valid.shape[0])
    fused = _build_fused(kernel)
    hidx = jnp.zeros((8,), jnp.int32)
    hval = jnp.zeros((8,), bool)
    hmask = jnp.zeros((8,), bool)

    def step(carry):
        state, rel_inv, rel_ok = carry
        state, chosen, forced, _rounds = fused(
            state, rel_inv, batch.conc_slot, batch.need_mb, batch.max_conc,
            rel_ok, hidx, hval, hmask, batch)
        return (state, jnp.clip(chosen, 0), chosen >= 0), chosen

    carry = (state0, jnp.zeros((batch_size,), jnp.int32),
             jnp.zeros((batch_size,), bool))
    for _ in range(WARMUP):
        carry, chosen = step(carry)
    jax.block_until_ready(carry)

    rates, p50s = [], []
    for _ in range(repeats):
        lat = []
        t0 = time.perf_counter()
        for _ in range(iters):
            t1 = time.perf_counter()
            carry, chosen = step(carry)
            jax.block_until_ready(chosen)
            lat.append(time.perf_counter() - t1)
        dt = time.perf_counter() - t0
        rates.append(batch_size * iters / dt)
        p50s.append(sorted(lat)[len(lat) // 2] * 1e3)

    med = statistics.median(rates)
    return {
        "rate_median": round(med, 1),
        "rate_min": round(min(rates), 1),
        "rate_max": round(max(rates), 1),
        "spread_pct": round(100.0 * (max(rates) - min(rates)) / med, 1),
        "p50_step_ms": round(statistics.median(p50s), 3),
        "repeats": repeats,
    }


def _parity_check(n_invokers: int = 512, action_slots: int = 128) -> bool:
    """Step the XLA and pallas kernels from identical state over the same
    batch ON DEVICE and compare placements and books exactly."""
    import numpy as np

    from __graft_entry__ import _example_batch
    from openwhisk_tpu.ops.placement import init_state

    batch = _example_batch(n_invokers, BATCH, seed=11)
    import jax.numpy as jnp
    hidx = jnp.zeros((8,), jnp.int32)
    hval = jnp.zeros((8,), bool)
    hmask = jnp.zeros((8,), bool)
    no_rel = jnp.zeros((BATCH,), bool)
    rel_inv = jnp.zeros((BATCH,), jnp.int32)

    outs = {}
    for kernel in ("xla", "pallas"):
        state = init_state(n_invokers, [2048] * n_invokers,
                           action_slots=action_slots)
        fused = _build_fused(kernel)
        # two steps: the second exercises release-fold + scheduling on
        # non-trivial books
        state, chosen1, forced1, _ = fused(
            state, rel_inv, batch.conc_slot, batch.need_mb, batch.max_conc,
            no_rel, hidx, hval, hmask, batch)
        state, chosen2, forced2, _ = fused(
            state, jnp.clip(chosen1, 0), batch.conc_slot, batch.need_mb,
            batch.max_conc, chosen1 >= 0, hidx, hval, hmask, batch)
        outs[kernel] = tuple(np.asarray(x) for x in
                             (chosen1, forced1, chosen2, forced2,
                              state.free_mb, state.conc_free, state.health))

    ok = all(np.array_equal(a, b) for a, b in zip(outs["xla"], outs["pallas"]))
    if not ok:
        for i, name in enumerate(("chosen1", "forced1", "chosen2", "forced2",
                                  "free_mb", "conc_free", "health")):
            if not np.array_equal(outs["xla"][i], outs["pallas"][i]):
                print(f"# PARITY MISMATCH in {name}", file=sys.stderr)
    return ok


def _bench_action(name, memory=256):
    from openwhisk_tpu.core.entity import (ActionLimits, CodeExec, EntityName,
                                           EntityPath, ExecutableWhiskAction,
                                           MB, MemoryLimit, TimeLimit)
    from openwhisk_tpu.core.entity.ids import DocRevision

    a = ExecutableWhiskAction(EntityPath("guest"), EntityName(name),
                              CodeExec(kind="python:3", code="x"),
                              limits=ActionLimits(TimeLimit(5000),
                                                  MemoryLimit(MB(memory))))
    a.rev = DocRevision("1-b")
    return a


async def _echo_invoker(provider, instance, delay=0.0, on_frame=None):
    """An invoker stand-in: consumes its topic, acks every activation
    immediately with a successful record (pure control-plane load). Rides
    the same batch wire as the real InvokerReactive: a columnar dispatch
    frame decodes ONCE, and the whole frame's acks are submitted in one
    sweep so they coalesce into one ack batch frame back.

    `delay` rides as a mutable attribute on the returned feed (the PR 4
    SimInvoker idiom, so tools/loadgen.py's `apply_stragglers` drives
    test stubs and bench feeds through the same knob): a straggler's
    acks sleep `feed.delay` seconds before flushing.

    `on_frame(instance, msgs)` is a synchronous per-frame hook (the
    trace-assembly rider emits invoker-side spans from it, standing in
    for the real InvokerReactive's container span pair)."""
    from openwhisk_tpu.core.entity import (ActivationResponse, EntityPath,
                                           WhiskActivation)
    from openwhisk_tpu.messaging import (ActivationMessage,
                                         CombinedCompletionAndResultMessage,
                                         MessageFeed)
    from openwhisk_tpu.messaging.columnar import is_batch_payload
    from openwhisk_tpu.messaging.connector import (decode_batch,
                                                   decode_message)

    topic = instance.as_string
    provider.ensure_topic(topic)
    consumer = provider.get_consumer(topic, topic)
    # the stand-in rides the same ack coalescing as the real
    # InvokerReactive, so the e2e riders measure the shipped completion path
    from openwhisk_tpu.messaging import maybe_coalesce
    producer = maybe_coalesce(provider.get_producer())
    box = {}

    async def handle(payload: bytes):
        if is_batch_payload(payload):
            _kind, msgs = decode_batch(payload)
        else:
            msgs = [decode_message(ActivationMessage.parse, payload,
                                   "activation")]
        if on_frame is not None:
            on_frame(instance, msgs)
        now = time.time()
        by_topic = {}
        for msg in msgs:
            act = WhiskActivation(
                EntityPath(str(msg.user.namespace.name)), msg.action.name,
                msg.user.subject, msg.activation_id, now, now,
                ActivationResponse.success({"ok": True}), duration=1)
            by_topic.setdefault(
                f"completed{msg.root_controller_index.as_string}",
                []).append(CombinedCompletionAndResultMessage(
                    msg.transid, act, instance))
        # straggler injection: read the live knob each frame (riders and
        # tests retune it mid-run, like the PR 4 SimInvoker scenario)
        d = getattr(box["feed"], "delay", 0.0)
        if d:
            await asyncio.sleep(d)
        # send_batch: every ack submits in THIS sweep (one dispatch
        # frame's acks flush as one ack batch frame) with no task per
        # message — asyncio.gather over N send() coroutines minted a
        # Task each, measurable loop churn at thousands of acks/s
        for topic, acks in by_topic.items():
            await producer.send_batch(topic, acks)
        box["feed"].processed()

    feed = MessageFeed(topic, consumer, 256, handle)
    feed.delay = delay
    box["feed"] = feed
    feed.start()
    return feed


async def _echo_fleet(provider, n_invokers, stragglers=None, on_frame=None):
    """Start `n_invokers` echo invokers + a 1 Hz pinger (supervision marks a
    fleet Offline after 10 s of silence, which a cold first compile easily
    outlasts). Returns (feeds, stop) — await stop() to end the pinger.
    `stragglers`: a {index: delay_s} map (or the loadgen SPEC string) —
    those invokers' acks are delayed from the first frame."""
    from openwhisk_tpu.core.entity import MB, InvokerInstanceId
    from openwhisk_tpu.messaging import PingMessage
    from tools.loadgen import parse_stragglers

    slow = parse_stragglers(stragglers)
    producer = provider.get_producer()
    provider.ensure_topic("health")
    feeds, instances = [], []
    for i in range(n_invokers):
        inst = InvokerInstanceId(i, user_memory=MB(8192))
        instances.append(inst)
        feeds.append(await _echo_invoker(provider, inst,
                                         delay=slow.get(i, 0.0),
                                         on_frame=on_frame))
        await producer.send("health", PingMessage(inst))
    stop_ping = asyncio.Event()

    async def pinger():
        while not stop_ping.is_set():
            for inst in instances:
                await producer.send("health", PingMessage(inst))
            try:
                await asyncio.wait_for(stop_ping.wait(), 1.0)
            except asyncio.TimeoutError:
                pass

    ping_task = asyncio.ensure_future(pinger())

    async def stop():
        stop_ping.set()
        await ping_task

    return feeds, stop


def _balancer_bench(n_invokers: int = 16, total: int = 2000,
                    concurrency: int = 64, kernel: str = "auto",
                    flight_recorder: bool = True,
                    telemetry: bool = True,
                    profiling: bool = True,
                    anomaly: bool = True,
                    waterfall: bool = True,
                    fleet_observatory: bool = True,
                    **host_path) -> dict:
    """TpuBalancer.publish() end-to-end on the in-memory bus with echo
    invokers: the full host path (slot alloc, micro-batch assembly, device
    step, promise fan-out, bus send) that the raw kernel number omits.
    `host_path` forwards hot-path knobs (placement_kernel, pipeline_depth,
    donate_state, ring_assembly) straight to the TpuBalancer constructor —
    the pipeline_speedup rider toggles them.

    CLOSED-loop by construction (`concurrency` workers behind a
    semaphore): the system sets the arrival rate, so the percentiles
    suffer coordinated omission under saturation — the row says so
    (`mode: "closed_loop"`) and rides as a comparison beside the
    `e2e_open_loop` headline (tools/loadgen.py), which measures from
    scheduled arrival instead."""
    from openwhisk_tpu.controller.loadbalancer import TpuBalancer
    from openwhisk_tpu.core.entity import (ActivationId, ControllerInstanceId,
                                           Identity)
    from openwhisk_tpu.messaging import (ActivationMessage,
                                         MemoryMessagingProvider)
    from openwhisk_tpu.ops.profiler import KernelProfiler, ProfilingConfig
    from openwhisk_tpu.utils.transaction import TransactionId
    from openwhisk_tpu.utils.waterfall import GLOBAL_WATERFALL

    make_action = _bench_action

    async def go() -> dict:
        provider = MemoryMessagingProvider()
        # the profiler wraps the jitted entry points at construction, so
        # the OFF run must disable it BEFORE the balancer builds them
        prof = KernelProfiler(ProfilingConfig(enabled=profiling))
        bal = TpuBalancer(provider, ControllerInstanceId("0"),
                          managed_fraction=1.0, blackbox_fraction=0.0,
                          kernel=kernel, profiler=prof, **host_path)
        bal.flight_recorder.enabled = flight_recorder
        bal.telemetry.enabled = telemetry
        bal.anomaly.enabled = anomaly
        # the waterfall plane is process-global (its stages span layers):
        # toggle + reset it per run so the overhead rider's OFF half is a
        # true no-op and the ON half starts from clean aggregates
        GLOBAL_WATERFALL.enabled = waterfall
        GLOBAL_WATERFALL.reset()
        # the event log is process-global like the waterfall; structural
        # events are rare by design, so the ON half measures the ambient
        # cost of the armed plane (the `enabled` branch at call sites)
        from openwhisk_tpu.utils.eventlog import GLOBAL_EVENT_LOG
        GLOBAL_EVENT_LOG.enabled = fleet_observatory
        GLOBAL_EVENT_LOG.reset()
        await bal.start()
        feeds, stop_fleet = await _echo_fleet(provider, n_invokers)
        # wait until supervision has actually registered the fleet (a fixed
        # sleep races the first device-program compile on slow channels)
        from openwhisk_tpu.controller.loadbalancer.base import HEALTHY
        for _ in range(120):
            health = await bal.invoker_health()
            if sum(h.status == HEALTHY for h in health) >= n_invokers:
                break
            await asyncio.sleep(0.25)
        else:
            raise RuntimeError("balancer bench: fleet never became healthy")

        actions = [make_action(f"bench{i}", memory=128) for i in range(8)]
        ident = Identity.generate("guest")
        lat: list = []
        e2e: list = []
        sem = asyncio.Semaphore(concurrency)

        async def one(i):
            action = actions[i % len(actions)]
            msg = ActivationMessage(
                TransactionId(), action.fully_qualified_name, action.rev.rev,
                ident, ActivationId.generate(), ControllerInstanceId("0"),
                True, {})
            async with sem:
                if waterfall:
                    GLOBAL_WATERFALL.begin(msg.activation_id.asString)
                t0 = time.perf_counter()
                promise = await bal.publish(action, msg)
                lat.append(time.perf_counter() - t0)
                await promise
                # completion-based e2e beside the publish()-only number:
                # publish() resolves at PLACEMENT, so its percentiles miss
                # the produce/pickup/ack half of the path entirely
                e2e.append(time.perf_counter() - t0)

        # warmup: two rounds so the power-of-two schedule/release bucket
        # shapes the measured run will hit are already compiled
        for _ in range(2):
            await asyncio.gather(*[one(i) for i in range(min(128, total))])
        lat.clear()
        e2e.clear()
        if waterfall:
            GLOBAL_WATERFALL.reset()  # drop warmup compile outliers
        # fresh metrics: the warmup rounds polluted the phase histograms
        # with first-call jit-compile outliers (hundreds of ms dispatches)
        bal.metrics = type(bal.metrics)()
        t0 = time.perf_counter()
        await asyncio.gather(*[one(i) for i in range(total)])
        wall = time.perf_counter() - t0
        await stop_fleet()
        await bal.close()
        for f in feeds:
            await f.stop()

        lat.sort()
        e2e.sort()
        phases = {}
        for ph in ("assembly", "dispatch", "readback", "fanout"):
            st = bal.metrics.histogram_stats(f"loadbalancer_tpu_{ph}_ms")
            if st:
                phases[ph] = {"p50_ms": round(st["p50"], 3),
                              "mean_ms": round(st["mean"], 3)}
        bs = bal.metrics.histogram_stats("loadbalancer_tpu_batch_size")
        rounds = bal.metrics.histogram_stats("loadbalancer_repair_rounds")
        return {
            # closed loop: arrivals are gated on completions, so these
            # percentiles under-report queueing delay at saturation
            # (coordinated omission) — the open-loop rider is the headline
            "mode": "closed_loop",
            "activations_per_sec": round(total / wall, 1),
            "publish_p50_ms": round(lat[len(lat) // 2] * 1e3, 3),
            "publish_p99_ms": round(lat[int(len(lat) * 0.99)] * 1e3, 3),
            "e2e_p50_ms": round(e2e[len(e2e) // 2] * 1e3, 3),
            "e2e_p99_ms": round(e2e[int(len(e2e) * 0.99)] * 1e3, 3),
            "concurrency": concurrency,
            "n_invokers": n_invokers,
            "phases": phases,
            "batch_size_mean": round(bs["mean"], 1) if bs else None,
            "repair_rounds_mean": round(rounds["mean"], 2) if rounds else None,
            # the PR-5 acceptance gate: the hot-path overhaul must add ZERO
            # unexpected recompiles (PR-3 watchdog clean)
            "recompiles_unexpected": prof.compiles_unexpected,
        }

    return asyncio.run(go())


def _mc_worker(instance: int, cluster_size: int, port: int, total: int,
               concurrency: int, n_invokers: int) -> None:
    """Subprocess entry for the multi-controller stage: ONE TpuBalancer
    (cluster-sharded capacity: each controller gets user_memory/cluster_size
    per invoker, the reference's getInvokerSlot) publishing over the TCP bus
    against the parent's shared echo fleet. Protocol: print READY after
    warmup, wait for GO on stdin, run, print one JSON line."""
    from openwhisk_tpu.controller.loadbalancer import TpuBalancer
    from openwhisk_tpu.controller.loadbalancer.base import HEALTHY
    from openwhisk_tpu.core.entity import (ActivationId, ControllerInstanceId,
                                           Identity)
    from openwhisk_tpu.messaging import ActivationMessage
    from openwhisk_tpu.messaging.tcp import TcpMessagingProvider
    from openwhisk_tpu.utils.transaction import TransactionId

    async def go():
        provider = TcpMessagingProvider(port=port)
        bal = TpuBalancer(provider, ControllerInstanceId(str(instance)),
                          cluster_size=cluster_size,
                          managed_fraction=1.0, blackbox_fraction=0.0)
        await bal.start()
        for _ in range(240):
            health = await bal.invoker_health()
            if sum(h.status == HEALTHY for h in health) >= n_invokers:
                break
            await asyncio.sleep(0.25)
        else:
            raise RuntimeError(f"worker {instance}: fleet never healthy")
        actions = [_bench_action(f"mc{instance}_{i}", memory=128)
                   for i in range(8)]
        ident = Identity.generate("guest")
        sem = asyncio.Semaphore(concurrency)

        async def one(i):
            action = actions[i % len(actions)]
            msg = ActivationMessage(
                TransactionId(), action.fully_qualified_name, action.rev.rev,
                ident, ActivationId.generate(),
                ControllerInstanceId(str(instance)), True, {})
            async with sem:
                promise = await bal.publish(action, msg)
                await promise

        for _ in range(2):
            await asyncio.gather(*[one(i) for i in range(min(128, total))])
        print("READY", flush=True)
        await asyncio.to_thread(sys.stdin.readline)  # GO
        t0 = time.time()
        await asyncio.gather(*[one(i) for i in range(total)])
        t1 = time.time()
        await bal.close()
        print(json.dumps({"instance": instance, "total": total,
                          "t0": t0, "t1": t1,
                          "rate": round(total / (t1 - t0), 1)}), flush=True)

    asyncio.run(go())


def _multi_controller_bench(n_controllers: int, total_per: int = 1500,
                            concurrency: int = 64, n_invokers: int = 16
                            ) -> dict:
    """Control-plane scale-out: N controller processes (cluster-sharded
    capacity over one shared echo fleet) publishing concurrently over the
    TCP bus; reports per-controller and AGGREGATE activations/s. On this
    one-core box extra controllers can only convert device wire-wait into
    useful work, so scaling is a lower bound for real multi-host."""
    import os
    import socket

    from openwhisk_tpu.messaging.tcp import TcpBusServer, TcpMessagingProvider

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    async def go() -> dict:
        server = TcpBusServer(port=port)
        await server.start()
        # the echo fleet is co-located with the broker: attach it to the
        # broker's in-process MemoryBus directly (same queues the TCP
        # workers see) instead of round-tripping localhost TCP into our own
        # process — co-located components take the in-process fast path
        from openwhisk_tpu.messaging import MemoryMessagingProvider
        provider = MemoryMessagingProvider()
        provider.bus = server.bus
        feeds, stop_fleet = await _echo_fleet(provider, n_invokers)
        procs = []

        async def read_line(p):
            line = await p.stdout.readline()
            return line.decode().strip()

        try:
            for i in range(n_controllers):
                code = (f"import bench; bench._mc_worker({i}, "
                        f"{n_controllers}, {port}, {total_per}, "
                        f"{concurrency}, {n_invokers})")
                procs.append(await asyncio.create_subprocess_exec(
                    sys.executable, "-c", code,
                    stdin=asyncio.subprocess.PIPE,
                    stdout=asyncio.subprocess.PIPE,
                    stderr=asyncio.subprocess.DEVNULL,
                    cwd=os.path.dirname(os.path.abspath(__file__))))
            ready = await asyncio.wait_for(
                asyncio.gather(*[read_line(p) for p in procs]), timeout=600)
            if any(r != "READY" for r in ready):
                raise RuntimeError(f"workers not ready: {ready}")
            for p in procs:
                p.stdin.write(b"GO\n")
                await p.stdin.drain()
            results = [json.loads(await asyncio.wait_for(read_line(p), 600))
                       for p in procs]
        finally:
            for p in procs:
                if p.returncode is None:
                    p.kill()
                await p.wait()
            await stop_fleet()
            for f in feeds:
                await f.stop()
            await server.stop()

        wall = max(r["t1"] for r in results) - min(r["t0"] for r in results)
        return {
            "n_controllers": n_controllers,
            "aggregate_activations_per_sec": round(
                sum(r["total"] for r in results) / wall, 1),
            "per_controller": [r["rate"] for r in results],
            "concurrency_per_controller": concurrency,
            "n_invokers": n_invokers,
        }

    return asyncio.run(go())


def _balancer_rows() -> dict:
    """The balancer stage at three client concurrencies: c=64 is the
    throughput row, c=8 the mid point, c=1 isolates the batching window's
    idle-latency cost (SURVEY §7's batching-vs-latency tension as a
    measured number)."""
    return {
        "c64": _balancer_bench(total=2000, concurrency=64),
        "c8": _balancer_bench(total=600, concurrency=8),
        "c1": _balancer_bench(total=150, concurrency=1),
    }


def _subprocess_json(expr: str, marker: str, label: str,
                     pin_cpu: bool = False, force_devices: bool = False,
                     timeout_s: int = 1200) -> Optional[dict]:
    """Evaluate one `bench.*` expression in a FRESH subprocess and parse
    its marker-prefixed JSON stdout line. Two uses share this runner:
    `pin_cpu` pins the subprocess to the CPU backend (the only clean path
    once the in-process backend registry has cached a device failure;
    `force_devices` adds the 8-virtual-device XLA flag for runs needing
    the full CPU mesh), while the default INHERITS the current backend
    env — process isolation for riders whose measurement a lived-in
    process skews (a prior kernel bench leaves dead executables and GC
    pressure behind, and in an open-loop window those stalls read exactly
    like saturation; measured). The timeout doubles as the dead-tunnel
    guard: a hang is killed and reported instead of wedging the round."""
    import os
    import subprocess
    env_lines = ["import os, json"]
    if pin_cpu:
        env_lines.append("os.environ['JAX_PLATFORMS'] = 'cpu'")
        if force_devices:
            env_lines.append(
                "os.environ['XLA_FLAGS'] = os.environ.get('XLA_FLAGS', '') + "
                "' --xla_force_host_platform_device_count=8'")
        env_lines += ["import jax",
                      "jax.config.update('jax_platforms', 'cpu')"]
    code = "\n".join(env_lines + [
        "import bench",
        f"print('{marker}:' + json.dumps({expr}))",
    ]) + "\n"
    try:
        out = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            timeout=timeout_s)
        for line in out.stdout.splitlines():
            if line.startswith(marker + ":"):
                return json.loads(line[len(marker) + 1:])
        print(f"# {label} failed: {out.stderr[-400:]}", file=sys.stderr)
    except Exception as e:  # noqa: BLE001 — auxiliary measure
        print(f"# {label} failed: {e!r}", file=sys.stderr)
    return None


def _cpu_subprocess_json(expr: str, marker: str, label: str,
                         force_devices: bool = False) -> Optional[dict]:
    """CPU-pinned variant of _subprocess_json (kept as the name every
    fallback call site uses)."""
    return _subprocess_json(expr, marker, label, pin_cpu=True,
                            force_devices=force_devices)


def _balancer_host_rows() -> Optional[dict]:
    """The same balancer rows forced onto the CPU backend in a subprocess:
    the HOST-PATH measure. Through a tunneled chip every device step costs a
    wire round trip (~70 ms here) that does not exist on a real TPU host
    (PCIe-local chips); the CPU-backend run shows what the host machinery
    itself sustains with the device round trip out of the picture."""
    return _cpu_subprocess_json("bench._balancer_rows()", "BENCHJSON",
                                "balancer host-path run",
                                force_devices=True)


def _plane_overhead(flag: str, key: str, repeats: int = 3, total: int = 1000,
                    concurrency: int = 64) -> Optional[dict]:
    """The observability tax, shared rider body: best XLA-kernel
    placement rate through the full balancer path with one plane ON vs
    OFF. Every plane lives somewhere on the dispatch/completion path, so
    the balancer-level rate — not the raw kernel step — is where its cost
    can show. `flag` is the _balancer_bench kwarg that toggles the plane,
    `key` names the result fields (`rate_{key}_on/off`). Acceptance gate
    for each plane: overhead_pct <= 5 (ISSUEs 1-4, 16).

    Each arm is judged by its BEST repeat after one discarded warmup run:
    throughput noise on a shared host is one-sided (GC, scheduling and
    first-compile hiccups only ever slow a run down, never speed it up),
    so best-of-N converges on the true marginal cost where a median of 3
    can report a double-digit phantom overhead for a plane that provably
    records nothing on the measured path."""
    try:
        _balancer_bench(total=total, concurrency=concurrency,
                        kernel="xla", **{flag: False})  # warmup, discarded
        on_rates, off_rates = [], []
        for _ in range(repeats):
            on_rates.append(_balancer_bench(
                total=total, concurrency=concurrency, kernel="xla",
                **{flag: True})["activations_per_sec"])
            off_rates.append(_balancer_bench(
                total=total, concurrency=concurrency, kernel="xla",
                **{flag: False})["activations_per_sec"])
        on = max(on_rates)
        off = max(off_rates)
        return {
            f"rate_{key}_on": round(on, 1),
            f"rate_{key}_off": round(off, 1),
            "overhead_pct": round(100.0 * (off - on) / off, 2) if off else None,
            "repeats": repeats,
            "agg": "best_of_n_after_warmup",
        }
    except Exception as e:  # noqa: BLE001 — rider is auxiliary
        if _backend_unavailable(e):
            raise  # the fallback runner re-runs this rider on CPU
        print(f"# {key}_overhead failed: {e!r}", file=sys.stderr)
        return None


# Named wrappers: _rider_subprocess_cpu re-invokes riders by attribute
# name in a fresh CPU-pinned process, so each plane keeps a module-level
# entry point.

def _flight_recorder_overhead(**kw) -> Optional[dict]:
    return _plane_overhead("flight_recorder", "recorder", **kw)


def _telemetry_overhead(**kw) -> Optional[dict]:
    return _plane_overhead("telemetry", "telemetry", **kw)


def _profiling_overhead(**kw) -> Optional[dict]:
    return _plane_overhead("profiling", "profiling", **kw)


def _anomaly_overhead(**kw) -> Optional[dict]:
    return _plane_overhead("anomaly", "anomaly", **kw)


def _waterfall_overhead(**kw) -> Optional[dict]:
    """ISSUE 7 gate: per-activation stage stamping must cost <= 5% through
    the full balancer path (same protocol as the other four planes)."""
    return _plane_overhead("waterfall", "waterfall", **kw)


def _fleet_observatory_overhead(repeats: int = 20, total: int = 1000,
                                concurrency: int = 64) -> Optional[dict]:
    """ISSUE 16 gate: the fleet observatory is scrape-pull-only — with no
    scraper attached its steady-state cost is the armed EventLog (one
    bool branch at structural call sites, which a placement-only bench
    never even takes), so the expected overhead is ~0.

    That makes the shared `_plane_overhead` protocol (fresh fixture per
    arm per repeat) the wrong instrument: on a shared host the balancer
    rate swings 4x run-to-run, and a between-run comparison of a ~0%
    effect reports pure noise with either sign. This rider instead builds
    the fixture ONCE and alternates armed/disarmed measured segments
    back-to-back inside the same process — each pair shares the host's
    momentary throughput mode, so the paired ratio isolates the plane's
    marginal cost. Segment order flips every repeat to cancel drift.
    Individual pairs still carry tens-of-percent host jitter at ~0.5 s
    segment lengths, so the verdict is a 20%-trimmed mean over many
    pairs — per-pair noise is zero-mean once paired, and the trim guards
    the tails a mean can't."""
    from openwhisk_tpu.controller.loadbalancer import TpuBalancer
    from openwhisk_tpu.core.entity import (ActivationId, ControllerInstanceId,
                                           Identity)
    from openwhisk_tpu.messaging import (ActivationMessage,
                                         MemoryMessagingProvider)
    from openwhisk_tpu.utils.eventlog import GLOBAL_EVENT_LOG
    from openwhisk_tpu.utils.transaction import TransactionId

    async def go() -> dict:
        provider = MemoryMessagingProvider()
        bal = TpuBalancer(provider, ControllerInstanceId("0"),
                          managed_fraction=1.0, blackbox_fraction=0.0,
                          kernel="xla")
        await bal.start()
        feeds, stop_fleet = await _echo_fleet(provider, 16)
        from openwhisk_tpu.controller.loadbalancer.base import HEALTHY
        for _ in range(120):
            health = await bal.invoker_health()
            if sum(h.status == HEALTHY for h in health) >= 16:
                break
            await asyncio.sleep(0.25)
        else:
            raise RuntimeError("fleet observatory rider: fleet unhealthy")

        actions = [_bench_action(f"fo{i}", memory=128) for i in range(8)]
        ident = Identity.generate("guest")
        sem = asyncio.Semaphore(concurrency)

        async def one(i):
            action = actions[i % len(actions)]
            msg = ActivationMessage(
                TransactionId(), action.fully_qualified_name, action.rev.rev,
                ident, ActivationId.generate(), ControllerInstanceId("0"),
                True, {})
            async with sem:
                promise = await bal.publish(action, msg)
                await promise

        async def segment() -> float:
            t0 = time.perf_counter()
            await asyncio.gather(*[one(i) for i in range(total)])
            return total / (time.perf_counter() - t0)

        try:
            # warmup: compile + settle before any measured segment
            await segment()
            was = GLOBAL_EVENT_LOG.enabled
            pairs = []
            on_rates, off_rates = [], []
            for k in range(repeats):
                order = (True, False) if k % 2 == 0 else (False, True)
                rate = {}
                for armed in order:
                    GLOBAL_EVENT_LOG.enabled = armed
                    GLOBAL_EVENT_LOG.reset()
                    rate[armed] = await segment()
                GLOBAL_EVENT_LOG.enabled = was
                on_rates.append(rate[True])
                off_rates.append(rate[False])
                pairs.append(100.0 * (rate[False] - rate[True])
                             / rate[False])
        finally:
            await stop_fleet()
            await bal.close()
            for f in feeds:
                await f.stop()
        trim = max(1, len(pairs) // 5)
        kept = sorted(pairs)[trim:-trim] if len(pairs) > 2 * trim else pairs
        return {
            "rate_fleet_observatory_on": round(max(on_rates), 1),
            "rate_fleet_observatory_off": round(max(off_rates), 1),
            "overhead_pct": round(statistics.mean(kept), 2),
            "pair_overheads_pct": [round(p, 2) for p in pairs],
            "repeats": repeats,
            "agg": "trimmed_mean_paired_segments",
        }

    try:
        return asyncio.run(go())
    except Exception as e:  # noqa: BLE001 — rider is auxiliary
        if _backend_unavailable(e):
            raise  # the fallback runner re-runs this rider on CPU
        print(f"# fleet_observatory_overhead failed: {e!r}", file=sys.stderr)
        return None


def _trace_assembly(clean: int = 192, stragglers_n: int = 12,
                    n_invokers: int = 8) -> Optional[dict]:
    """ISSUE 18 acceptance: a spillover burst with injected stragglers
    through the tail-sampled trace observatory, four legs in one fixture:

      clean bulk   reason-free traffic keeps at the deterministic floor
                   (keep_floor=0.05 -> every 20th completion);
      stragglers   a delayed-fleet salvo lands above the live tail
                   threshold -> 100% kept with reason `slow`;
      spillover    non-blocking overflow diverts b0 -> b1; every spilled
                   trace is kept, and at least one assembles into a tree
                   spanning >= 3 processes whose origin stage spans
                   telescope to the waterfall total;
      dead peer    GET /admin/trace/{id} through a real Controller with
                   a dead member answers 200 + members_missing (never a
                   500), and every OpenMetrics exemplar rendered during
                   the run resolves to a kept trace.
    """
    import base64
    import dataclasses
    import re

    import aiohttp
    from aiohttp import web as aioweb

    from openwhisk_tpu.controller.core import Controller
    from openwhisk_tpu.controller.loadbalancer import TpuBalancer
    from openwhisk_tpu.controller.loadbalancer.base import HEALTHY
    from openwhisk_tpu.controller.loadbalancer.lean import LeanBalancer
    from openwhisk_tpu.controller.loadbalancer.partitions import PartitionRing
    from openwhisk_tpu.controller.loadbalancer.spillover import (
        SpilloverReceiver, SpilloverSender)
    from openwhisk_tpu.core.entity import (MB, ActivationId,
                                           ControllerInstanceId, Identity,
                                           WhiskAuthRecord)
    from openwhisk_tpu.messaging import (ActivationMessage,
                                         MemoryMessagingProvider)
    from openwhisk_tpu.utils.logging import NullLogging
    from openwhisk_tpu.utils.tracestore import (GLOBAL_TRACE_STORE,
                                                assemble_trace,
                                                synthetic_span)
    from openwhisk_tpu.utils.tracing import GLOBAL_TRACER, trace_id_of
    from openwhisk_tpu.utils.transaction import TransactionId
    from openwhisk_tpu.utils.waterfall import GLOBAL_WATERFALL, N_STAGES

    store = GLOBAL_TRACE_STORE
    CTL_PORT, PEER_PORT = 13981, 13982

    async def go() -> dict:
        was_enabled, was_cfg = store.enabled, store.config
        was_floor = store._floor_every
        wf_was = GLOBAL_WATERFALL.enabled
        # arm the plane with a floor crisp enough to assert exactly
        store.enabled = True
        store.config = dataclasses.replace(store.config, keep_floor=0.05,
                                           keep_ring=1024)
        store._floor_every = 20
        store.reset()
        store.attach()
        GLOBAL_WATERFALL.enabled = True
        GLOBAL_WATERFALL.reset()

        provider = MemoryMessagingProvider()
        ring = PartitionRing(8)
        b0 = TpuBalancer(provider, ControllerInstanceId("0"),
                         managed_fraction=1.0, blackbox_fraction=0.0,
                         kernel="xla")
        b1 = TpuBalancer(provider, ControllerInstanceId("1"),
                         managed_fraction=1.0, blackbox_fraction=0.0,
                         kernel="xla")
        for b in (b0, b1):
            b.set_partition_mode(ring)
            await b.start()
        for pid in range(8):
            b0.set_partition_leadership(pid, 2, True)
            b1.partition_epochs[pid] = 2  # peer knowledge, not ownership

        # invoker-side spans: the echo stand-in emits one per message
        # (the real InvokerReactive's container span pair rides the same
        # store.active gate)
        def invoker_spans(instance, msgs):
            if not store.active:
                return
            now = time.time()
            for m in msgs:
                tid = trace_id_of(getattr(m, "trace_context", None))
                if tid:
                    store.emit(synthetic_span(
                        tid, "invoker_run", now, now,
                        tags={"proc": f"invoker{instance.instance}"}))

        feeds, stop_fleet = await _echo_fleet(provider, n_invokers,
                                              on_frame=invoker_spans)
        for bal in (b0, b1):
            for _ in range(120):
                health = await bal.invoker_health()
                if sum(h.status == HEALTHY for h in health) >= n_invokers:
                    break
                await asyncio.sleep(0.25)
            else:
                raise RuntimeError("trace assembly rider: fleet unhealthy")

        hot_action = _bench_action("ta_hot", memory=128)

        class _Membership:
            instance = ControllerInstanceId("0")

            @staticmethod
            def least_loaded_peer():
                return 1

        class _Store:
            @staticmethod
            async def get_action(name, rev=None):
                class Doc:
                    @staticmethod
                    def to_executable():
                        return hot_action

                return Doc()

        b0.spillover_sink = SpilloverSender(provider, _Membership())
        receiver = SpilloverReceiver(provider, ControllerInstanceId("1"),
                                     b1, _Store())
        receiver.start()

        actions = [_bench_action(f"ta{i}", memory=128) for i in range(4)]
        ident = Identity.generate("guest")
        sem = asyncio.Semaphore(24)

        async def one(action):
            # the invoke.py driver shape: controller span -> trace
            # context on the message -> waterfall adoption. Everything
            # opens INSIDE the semaphore: a context anchored at burst
            # submit would fold the whole gather's queue wait into the
            # row total and drag the live p99 to the leg duration.
            async with sem:
                transid = TransactionId()
                span = GLOBAL_TRACER.start_span("controller_activation",
                                                transid)
                msg = ActivationMessage(
                    transid, action.fully_qualified_name, action.rev.rev,
                    ident, ActivationId.generate(),
                    ControllerInstanceId("0"), True, {},
                    trace_context=GLOBAL_TRACER.get_trace_context(transid))
                tid = trace_id_of(msg.trace_context)
                GLOBAL_WATERFALL.adopt(msg.activation_id.asString,
                                       GLOBAL_WATERFALL.open(),
                                       trace_id=tid)
                promise = await b0.publish(action, msg)
                GLOBAL_TRACER.finish_span(
                    transid, {"activationId": msg.activation_id.asString,
                              "proc": "controller0"}, span=span)
                await promise
            return tid

        async def settle(target):
            for _ in range(300):
                if store.stats()["seen"] >= target:
                    return
                await asyncio.sleep(0.05)

        out = {}
        try:
            # warmup: the first batches pay kernel compile (seconds) —
            # folded into the live histogram they'd drag the p99 bucket
            # above the straggler salvo. Drive a burst, then zero both
            # planes so the measured legs see steady-state latencies only.
            await asyncio.gather(*[one(actions[i % 4]) for i in range(64)])
            GLOBAL_WATERFALL.reset()
            store.reset()
            # exemplars pinned during warmup reference traces the reset
            # just purged — drop the phase aggregates with them, so the
            # every-rendered-exemplar-resolves gate only sees pins made
            # after the store went clean
            for bal in (b0, b1):
                with bal.profiler._phase_lock:
                    bal.profiler._phases.clear()

            # -- leg 1: the clean bulk keeps at the floor exactly ---------
            clean_tids = await asyncio.gather(
                *[one(actions[i % 4]) for i in range(clean)])
            await settle(clean)
            floor_kept = [t for t in clean_tids
                          if (store.get(t) or {}).get("reason") == "floor"]
            expected = clean // 20
            assert expected // 2 <= len(floor_kept) <= expected + 1, \
                f"floor keeps {len(floor_kept)} vs expected ~{expected}"

            # -- leg 2: stragglers keep 100% with reason `slow` -----------
            # the live threshold is whatever the clean leg's p99 bucket
            # settled at (XLA recompiles for fresh batch geometries can
            # legitimately push it to ~1s): the salvo's injected delay
            # scales to sit clearly above it, like a real straggler does
            threshold = store.tail_threshold_ms()
            assert threshold < 2500.0, \
                f"tail threshold {threshold}ms never settled"
            delay_s = min(3.0, threshold / 1000.0 * 1.5 + 0.1)
            for f in feeds:
                f.delay = delay_s
            straggler_tids = await asyncio.gather(
                *[one(actions[0]) for _ in range(stragglers_n)])
            for f in feeds:
                f.delay = 0.0
            await settle(clean + stragglers_n)
            slow_kept = [t for t in straggler_tids
                         if "slow" in (store.get(t) or {}).get("reasons",
                                                               ())]
            straggler_keep_pct = 100.0 * len(slow_kept) / stragglers_n
            assert straggler_keep_pct == 100.0, \
                f"straggler keep {straggler_keep_pct}%"

            # -- leg 3: spillover -> >= 3-process assembled tree ----------
            i = 0
            while ring.partition_of(f"sp{i}") != 4:
                i += 1
            spill_ident = Identity.generate(f"sp{i}")
            depth_was = b0.spillover_depth
            b0.spillover_depth = 2
            pairs, spill_tids = [], []
            for _ in range(8):
                transid = TransactionId()
                span = GLOBAL_TRACER.start_span("controller_activation",
                                                transid)
                msg = ActivationMessage(
                    transid, hot_action.fully_qualified_name,
                    hot_action.rev.rev, spill_ident,
                    ActivationId.generate(), ControllerInstanceId("0"),
                    False, {},
                    trace_context=GLOBAL_TRACER.get_trace_context(transid))
                GLOBAL_WATERFALL.adopt(
                    msg.activation_id.asString, GLOBAL_WATERFALL.open(),
                    trace_id=trace_id_of(msg.trace_context))
                GLOBAL_TRACER.finish_span(
                    transid, {"activationId": msg.activation_id.asString,
                              "proc": "controller0"}, span=span)
                spill_tids.append(trace_id_of(msg.trace_context))
                pairs.append((hot_action, msg))
            outs = b0.publish_many(pairs)
            await asyncio.gather(*outs)
            b0.spillover_depth = depth_was

            # both halves of a spilled trace land in the SAME ring here
            # (one process, one global store) — scan entries() for them,
            # the way two processes' /admin/trace/local answers would
            def halves_of():
                by_tid = {}
                for e in store.entries():
                    by_tid.setdefault(e.get("trace_id"), []).append(e)
                return by_tid

            kept_spilled = []
            for _ in range(300):
                by_tid = halves_of()
                kept_spilled = [
                    t for t in spill_tids
                    if any("spilled" in e["reasons"]
                           for e in by_tid.get(t, ()))]
                if b0.spilled_rows and len(kept_spilled) >= b0.spilled_rows:
                    break
                await asyncio.sleep(0.05)
            assert b0.spilled_rows >= 1, "no rows spilled past the depth"
            assert len(kept_spilled) >= b0.spilled_rows, \
                f"{b0.spilled_rows} spilled, {len(kept_spilled)} kept"

            await asyncio.sleep(0.5)  # let the peer halves complete too
            by_tid = halves_of()
            assembled, stage_sum, wf_total = None, None, None
            for t in kept_spilled:
                halves = by_tid.get(t, [])
                rows = [e["waterfall"] for e in halves
                        if e.get("waterfall")]
                if not rows:
                    continue
                a = assemble_trace(t, halves)
                if len(a["processes"]) < 3 or len(halves) < 2:
                    continue
                # telescoping: each half's present deltas sum back to its
                # own measured total (each delta floors to µs
                # independently, so the bound is one µs per stage)
                ok = all(abs(sum(d for d in r["deltas_us"] if d >= 0)
                             - r["total_us"]) <= N_STAGES for r in rows)
                assert ok, f"stage deltas do not telescope for {t}"
                wf_total = max(r["total_us"] for r in rows)
                stage_sum = sum(d for r in rows
                                for d in r["deltas_us"] if d >= 0)
                assembled = a
                break
            assert assembled is not None, \
                "no spilled trace assembled to >= 3 processes (2 halves)"

            # -- leg 4a: every rendered OM exemplar resolves --------------
            ex_tids = set()
            for bal in (b0, b1):
                text = bal.profiler.prometheus_text(openmetrics=True)
                ex_tids.update(re.findall(r'trace_id="([0-9a-f]+)"', text))
            if b0.profiler.enabled:
                assert ex_tids, "profiler on but no exemplars rendered"
            unresolved = [t for t in ex_tids if store.get(t) is None]
            assert not unresolved, \
                f"{len(unresolved)} rendered exemplars not kept"

            # -- leg 4b: dead-peer assembly over real HTTP ----------------
            async def noop_factory(invoker_id, prov):
                class _S:
                    async def stop(self):
                        pass

                return _S()

            logger = NullLogging()
            cprov = MemoryMessagingProvider()
            lb = LeanBalancer(cprov, ControllerInstanceId("0"),
                              noop_factory, logger=logger,
                              metrics=logger.metrics, user_memory=MB(512))
            ctl = Controller(ControllerInstanceId("0"), cprov,
                             logger=logger, load_balancer=lb)
            admin = Identity.generate("guest")
            await ctl.auth_store.put(WhiskAuthRecord(
                admin.subject, [admin.namespace], [admin.authkey]))

            async def peer_local(request):
                # a live peer that never kept the trace: found=false,
                # which must NOT read as a missing member
                return aioweb.json_response(
                    {"trace_id": request.match_info["trace_id"],
                     "found": False, "entry": None})

            papp = aioweb.Application()
            papp.router.add_get("/admin/trace/local/{trace_id}",
                                peer_local)
            prunner = aioweb.AppRunner(papp)
            await prunner.setup()
            await aioweb.TCPSite(prunner, "127.0.0.1", PEER_PORT).start()

            class _FleetStub:
                def peer_directory(self):
                    return {1: f"http://127.0.0.1:{PEER_PORT}",
                            2: "http://127.0.0.1:9"}  # dead peer

                async def stop(self):
                    pass

            await ctl.start(port=CTL_PORT)
            ctl.membership = _FleetStub()
            hdrs = {"Authorization": "Basic " + base64.b64encode(
                admin.authkey.compact.encode()).decode()}
            target = assembled["trace_id"]
            try:
                base = f"http://127.0.0.1:{CTL_PORT}"
                async with aiohttp.ClientSession() as s:
                    async with s.get(f"{base}/admin/trace/{target}",
                                     headers=hdrs) as r:
                        http_status = r.status
                        http_body = await r.json()
                    async with s.get(f"{base}/admin/traces?reason=slow",
                                     headers=hdrs) as r:
                        list_status = r.status
                        list_body = await r.json()
            finally:
                await prunner.cleanup()
                await ctl.stop()
            assert http_status == 200, f"assembly answered {http_status}"
            assert http_body["found"] is True
            assert http_body["members_missing"] == [2], \
                f"members_missing {http_body.get('members_missing')}"
            assert list_status == 200 and len(list_body["traces"]) \
                >= stragglers_n

            stats = store.stats()
            out = {
                "clean": clean,
                "keep_floor": 0.05,
                "floor_kept": len(floor_kept),
                "floor_expected": expected,
                "tail_threshold_ms": round(threshold, 3),
                "straggler_delay_s": round(delay_s, 3),
                "straggler_keep_pct": round(straggler_keep_pct, 1),
                "spilled_rows": int(b0.spilled_rows),
                "spilled_kept": len(kept_spilled),
                "assembled_processes": assembled["processes"],
                "stage_sum_us": int(stage_sum),
                "waterfall_total_us": int(wf_total),
                "dead_peer_status": http_status,
                "members_missing": http_body["members_missing"],
                "exemplars_rendered": len(ex_tids),
                "exemplars_resolved": True,
                "kept_total": stats["kept_total"],
                "dropped_total": stats["dropped_total"],
            }
        finally:
            await stop_fleet()
            await receiver.stop()
            await b0.close()
            await b1.close()
            for f in feeds:
                await f.stop()
            store.detach()
            store.enabled = was_enabled
            store.config = was_cfg
            store._floor_every = was_floor
            store.reset()
            GLOBAL_WATERFALL.enabled = wf_was
            GLOBAL_WATERFALL.reset()
        return out

    try:
        return asyncio.run(go())
    except Exception as e:  # noqa: BLE001 — rider is auxiliary
        if _backend_unavailable(e):
            raise  # the fallback runner re-runs this rider on CPU
        print(f"# trace_assembly failed: {e!r}", file=sys.stderr)
        return None


def _trace_plane_overhead(repeats: int = 20, total: int = 2000,
                          concurrency: int = 64) -> Optional[dict]:
    """ISSUE 18 gate: the armed trace observatory's marginal cost on the
    traced blocking-publish path, <= 5% by acceptance. Same paired-segment
    protocol as `_fleet_observatory_overhead` (fixture built ONCE,
    armed/disarmed segments back-to-back, order flipped per repeat,
    20%-trimmed mean over the pairs): the driver makes real spans + trace
    contexts + waterfall adoptions in BOTH arms (that cost is the tracing
    spine's, paid since PR 2), so the pair isolates exactly what this PR
    added — the reporter tee, the completion-time verdict, and the floor
    keeps' serialization."""
    from openwhisk_tpu.controller.loadbalancer import TpuBalancer
    from openwhisk_tpu.core.entity import (ActivationId, ControllerInstanceId,
                                           Identity)
    from openwhisk_tpu.messaging import (ActivationMessage,
                                         MemoryMessagingProvider)
    from openwhisk_tpu.utils.tracestore import GLOBAL_TRACE_STORE
    from openwhisk_tpu.utils.tracing import GLOBAL_TRACER, trace_id_of
    from openwhisk_tpu.utils.transaction import TransactionId
    from openwhisk_tpu.utils.waterfall import GLOBAL_WATERFALL

    store = GLOBAL_TRACE_STORE

    async def go() -> dict:
        was_enabled = store.enabled
        wf_was = GLOBAL_WATERFALL.enabled
        GLOBAL_WATERFALL.enabled = True
        GLOBAL_WATERFALL.reset()
        store.enabled = True
        store.reset()
        store.attach()
        provider = MemoryMessagingProvider()
        bal = TpuBalancer(provider, ControllerInstanceId("0"),
                          managed_fraction=1.0, blackbox_fraction=0.0,
                          kernel="xla")
        await bal.start()
        feeds, stop_fleet = await _echo_fleet(provider, 16)
        from openwhisk_tpu.controller.loadbalancer.base import HEALTHY
        for _ in range(120):
            health = await bal.invoker_health()
            if sum(h.status == HEALTHY for h in health) >= 16:
                break
            await asyncio.sleep(0.25)
        else:
            raise RuntimeError("trace plane rider: fleet unhealthy")

        actions = [_bench_action(f"tp{i}", memory=128) for i in range(8)]
        ident = Identity.generate("guest")
        sem = asyncio.Semaphore(concurrency)

        async def one(i):
            action = actions[i % len(actions)]
            transid = TransactionId()
            span = GLOBAL_TRACER.start_span("controller_activation",
                                            transid)
            msg = ActivationMessage(
                transid, action.fully_qualified_name, action.rev.rev,
                ident, ActivationId.generate(), ControllerInstanceId("0"),
                True, {},
                trace_context=GLOBAL_TRACER.get_trace_context(transid))
            GLOBAL_WATERFALL.adopt(msg.activation_id.asString,
                                   GLOBAL_WATERFALL.open(),
                                   trace_id=trace_id_of(msg.trace_context))
            async with sem:
                promise = await bal.publish(action, msg)
                GLOBAL_TRACER.finish_span(
                    transid, {"proc": "controller0"}, span=span)
                await promise

        async def segment() -> float:
            t0 = time.perf_counter()
            await asyncio.gather(*[one(i) for i in range(total)])
            return total / (time.perf_counter() - t0)

        try:
            await segment()  # warmup: compile + settle
            pairs = []
            on_rates, off_rates = [], []
            for k in range(repeats):
                order = (True, False) if k % 2 == 0 else (False, True)
                rate = {}
                for armed in order:
                    if armed:
                        store.enabled = True
                        store.reset()
                        store.attach()
                    else:
                        store.detach()
                        store.enabled = False
                    rate[armed] = await segment()
                on_rates.append(rate[True])
                off_rates.append(rate[False])
                pairs.append(100.0 * (rate[False] - rate[True])
                             / rate[False])
        finally:
            await stop_fleet()
            await bal.close()
            for f in feeds:
                await f.stop()
            store.detach()
            store.enabled = was_enabled
            store.reset()
            GLOBAL_WATERFALL.enabled = wf_was
            GLOBAL_WATERFALL.reset()
        trim = max(1, len(pairs) // 5)
        kept = sorted(pairs)[trim:-trim] if len(pairs) > 2 * trim else pairs
        return {
            "rate_trace_plane_on": round(max(on_rates), 1),
            "rate_trace_plane_off": round(max(off_rates), 1),
            "overhead_pct": round(statistics.mean(kept), 2),
            "target_pct": 5.0,
            "pair_overheads_pct": [round(p, 2) for p in pairs],
            "repeats": repeats,
            "agg": "trimmed_mean_paired_segments",
        }

    try:
        return asyncio.run(go())
    except Exception as e:  # noqa: BLE001 — rider is auxiliary
        if _backend_unavailable(e):
            raise  # the fallback runner re-runs this rider on CPU
        print(f"# trace_plane_overhead failed: {e!r}", file=sys.stderr)
        return None


def _incident_capture(clean: int = 240, straggler_salvo: int = 64,
                      n_invokers: int = 8) -> Optional[dict]:
    """ISSUE 19 acceptance: an injected straggler (the loadgen
    `--stragglers` helper) drives the straggler alert to firing against a
    journaled balancer with the incident recorder armed, and the FIRING
    transition must auto-freeze exactly ONE forensic bundle (debounce)
    joining >= 5 planes. Four legs in one fixture:

      capture      straggler alert fires -> one bundle on disk with the
                   alert context, anomaly score matrix, waterfall, >= 1
                   kept trace and the journal window, written off-loop;
      debounce     a second straggler invoker's own FIRING transition
                   inside the window coalesces into the same bundle;
      time-travel  the bundle's journal window replays through
                   JournalDebugger: break-on-activation-id stops at the
                   placing batch, run_to_end re-derives the books with 0
                   parity mismatches, diff_books matches the captured
                   books bit-exact;
      fleet        GET /admin/fleet/incidents through a real Controller
                   with a live + a dead peer answers 200 with member
                   provenance and members_missing (never a 500).
    """
    import base64
    import tempfile

    import aiohttp
    from aiohttp import web as aioweb

    from openwhisk_tpu.controller.core import Controller
    from openwhisk_tpu.controller.loadbalancer import TpuBalancer
    from openwhisk_tpu.controller.loadbalancer.base import HEALTHY
    from openwhisk_tpu.controller.loadbalancer.journal import PlacementJournal
    from openwhisk_tpu.controller.loadbalancer.lean import LeanBalancer
    from openwhisk_tpu.controller.loadbalancer.timetravel import \
        JournalDebugger
    from openwhisk_tpu.core.entity import (MB, ActivationId,
                                           ControllerInstanceId, Identity,
                                           WhiskAuthRecord)
    from openwhisk_tpu.messaging import (ActivationMessage,
                                         MemoryMessagingProvider)
    from openwhisk_tpu.utils.blackbox import GLOBAL_INCIDENTS, read_bundle
    from openwhisk_tpu.utils.logging import NullLogging
    from openwhisk_tpu.utils.tracestore import GLOBAL_TRACE_STORE
    from openwhisk_tpu.utils.tracing import GLOBAL_TRACER, trace_id_of
    from openwhisk_tpu.utils.transaction import TransactionId
    from openwhisk_tpu.utils.waterfall import GLOBAL_WATERFALL
    from tools.loadgen import apply_stragglers

    store = GLOBAL_TRACE_STORE
    CTL_PORT, PEER_PORT = 13983, 13984
    inc_dir = tempfile.mkdtemp(prefix="bench-incidents-")
    jdir = tempfile.mkdtemp(prefix="bench-incidents-wal-")
    # the recorder + a fast-firing straggler rule are armed via env
    # BEFORE the balancer exists (plane wiring reads config at
    # construction); everything is restored in the finally
    env_overrides = {
        "CONFIG_whisk_incidents_enabled": "true",
        "CONFIG_whisk_incidents_directory": inc_dir,
        # one incident -> ONE bundle across the whole rider (camelCase:
        # the env parser splits on _, so debounce_s would nest wrong)
        "CONFIG_whisk_incidents_debounceS": "600",
        # the built-in straggler rule holds for 30 s before firing — an
        # operator tightening it for a drill is exactly this override
        "CONFIG_whisk_alerts_rules":
            '{"straggler": {"threshold": 2.0, "for_s": 0}}',
    }
    env_was = {k: os.environ.get(k) for k in env_overrides}
    os.environ.update(env_overrides)

    async def go() -> dict:
        was_enabled, was_floor = store.enabled, store._floor_every
        wf_was = GLOBAL_WATERFALL.enabled
        store.enabled = True
        store._floor_every = 20
        store.reset()
        store.attach()
        GLOBAL_WATERFALL.enabled = True
        GLOBAL_WATERFALL.reset()

        provider = MemoryMessagingProvider()
        bal = TpuBalancer(provider, ControllerInstanceId("0"),
                          managed_fraction=1.0, blackbox_fraction=0.0,
                          kernel="xla")
        assert GLOBAL_INCIDENTS.stats()["installed"], \
            "recorder must arm at balancer construction"
        bal.attach_journal(PlacementJournal(jdir))
        await bal.start()
        feeds, stop_fleet = await _echo_fleet(provider, n_invokers)
        for _ in range(120):
            health = await bal.invoker_health()
            if sum(h.status == HEALTHY for h in health) >= n_invokers:
                break
            await asyncio.sleep(0.25)
        else:
            raise RuntimeError("incident rider: fleet unhealthy")

        actions = [_bench_action(f"ic{i}", memory=128) for i in range(4)]
        ident = Identity.generate("guest")
        sem = asyncio.Semaphore(32)

        async def one(i):
            # the traced invoke.py driver shape, so completions feed the
            # tail sampler and the bundle gets real kept traces
            async with sem:
                action = actions[i % len(actions)]
                transid = TransactionId()
                span = GLOBAL_TRACER.start_span("controller_activation",
                                                transid)
                msg = ActivationMessage(
                    transid, action.fully_qualified_name, action.rev.rev,
                    ident, ActivationId.generate(),
                    ControllerInstanceId("0"), True, {},
                    trace_context=GLOBAL_TRACER.get_trace_context(transid))
                GLOBAL_WATERFALL.adopt(
                    msg.activation_id.asString, GLOBAL_WATERFALL.open(),
                    trace_id=trace_id_of(msg.trace_context))
                promise = await bal.publish(action, msg)
                GLOBAL_TRACER.finish_span(
                    transid, {"activationId": msg.activation_id.asString,
                              "proc": "controller0"}, span=span)
                await promise

        out = {}
        try:
            # -- leg 1: drive to firing, capture one bundle ---------------
            # clean bulk first: per-invoker latency estimates must be warm
            # (min_samples) before a straggler can z-score against them
            await asyncio.gather(*[one(i) for i in range(clean)])
            # two delayed invokers: each (rule, invoker) instance fires on
            # its own -> the SECOND transition proves the debounce
            applied = apply_stragglers(feeds, {0: 0.6, 1: 0.6})
            assert len(applied) == 2
            salvo = 0
            for _ in range(20):  # keep driving until the alert lands
                await asyncio.gather(*[one(i) for i in range(
                    straggler_salvo)])
                salvo += straggler_salvo
                if GLOBAL_INCIDENTS.stats()["captured"] >= 1:
                    break
            apply_stragglers(feeds, {0: 0.0, 1: 0.0})
            for _ in range(200):  # the capture worker writes off-loop
                st = GLOBAL_INCIDENTS.stats()
                if st["captured"] >= 1 and st["bundles"] >= 1:
                    break
                await asyncio.sleep(0.1)
            stats = GLOBAL_INCIDENTS.stats()
            assert stats["captured"] >= 1, f"no capture: {stats}"
            # let any queued coalesced triggers settle, then the debounce
            # verdict: ONE bundle, everything else folded into it
            await asyncio.sleep(1.0)
            bundles = sorted(
                n for n in os.listdir(inc_dir) if n.endswith(".wbb"))
            assert len(bundles) == 1, f"debounce leak: {bundles}"
            bundle_path = os.path.join(inc_dir, bundles[0])
            payload = read_bundle(bundle_path)
            assert payload is not None, "bundle unreadable"
            assert payload["reason"].startswith("alert:straggler"), payload[
                "reason"]
            planes = {k: v for k, v in payload["planes"].items()
                      if v is not None}
            assert len(planes) >= 5, f"planes: {sorted(planes)}"
            for need in ("alerts", "anomaly_scores", "waterfall",
                         "traces", "journal", "books"):
                assert need in planes, f"missing plane {need}"
            assert planes["traces"], "no kept trace overlapped the window"
            recs = planes["journal"]["records"]
            assert recs, "journal window empty"

            # -- leg 2: time-travel replay of the bundle's window ---------
            batch_aids = [a for r in recs if r.get("t") == "batch"
                          for a in (r.get("aids") or [])]
            assert batch_aids, "no batch records in the window"
            dbg = JournalDebugger.from_bundle(payload)
            try:
                stop = dbg.run_to_activation(batch_aids[0])
                assert stop is not None, "break-on-activation-id missed"
                assert batch_aids[0] in stop["aids"]
                replay_stats = dbg.run_to_end()
                diff = dbg.diff_books()
            finally:
                await dbg.aclose()
            assert replay_stats["parity_mismatches"] == 0, replay_stats
            assert diff["match"], diff

            # -- leg 3: federated serving with a dead peer ----------------
            async def noop_factory(invoker_id, prov):
                class _S:
                    async def stop(self):
                        pass

                return _S()

            logger = NullLogging()
            cprov = MemoryMessagingProvider()
            lb = LeanBalancer(cprov, ControllerInstanceId("0"),
                              noop_factory, logger=logger,
                              metrics=logger.metrics, user_memory=MB(512))
            ctl = Controller(ControllerInstanceId("0"), cprov,
                             logger=logger, load_balancer=lb)
            admin = Identity.generate("guest")
            await ctl.auth_store.put(WhiskAuthRecord(
                admin.subject, [admin.namespace], [admin.authkey]))

            async def peer_incidents(request):
                return aioweb.json_response(
                    {"incidents": [{"id": "inc-peer-0001", "ts": 1.0,
                                    "reason": "alert:straggler"}],
                     "stats": {}})

            papp = aioweb.Application()
            papp.router.add_get("/admin/incidents", peer_incidents)
            prunner = aioweb.AppRunner(papp)
            await prunner.setup()
            await aioweb.TCPSite(prunner, "127.0.0.1", PEER_PORT).start()

            class _FleetStub:
                def peer_directory(self):
                    return {1: f"http://127.0.0.1:{PEER_PORT}",
                            2: "http://127.0.0.1:9"}  # dead peer

                async def stop(self):
                    pass

            await ctl.start(port=CTL_PORT)
            ctl.membership = _FleetStub()
            hdrs = {"Authorization": "Basic " + base64.b64encode(
                admin.authkey.compact.encode()).decode()}
            try:
                base = f"http://127.0.0.1:{CTL_PORT}"
                async with aiohttp.ClientSession() as s:
                    async with s.get(f"{base}/admin/fleet/incidents",
                                     headers=hdrs) as r:
                        fleet_status = r.status
                        fleet_body = await r.json()
                    async with s.get(
                            f"{base}/admin/incident/{payload['id']}",
                            headers=hdrs) as r:
                        get_status = r.status
                        get_body = await r.json()
            finally:
                await prunner.cleanup()
                await ctl.stop()
            assert fleet_status == 200, f"fleet answered {fleet_status}"
            members = {row["member"] for row in fleet_body["incidents"]}
            assert 0 in members and 1 in members, members
            assert fleet_body["members_missing"] == [2], fleet_body
            assert get_status == 200 and get_body["member"] == "local"

            out = {
                "straggler_invokers": 2,
                "straggler_delay_s": 0.6,
                "salvo_activations": salvo,
                "trigger_reason": payload["reason"],
                "bundles_written": len(bundles),
                "coalesced": stats["coalesced"],
                "planes_captured": len(planes),
                "planes": sorted(planes),
                "plane_errors": payload["plane_errors"],
                "journal_window": [planes["journal"]["from_seq"],
                                   planes["journal"]["to_seq"]],
                "journal_records": len(recs),
                "break_aid_found": True,
                "replay_parity_mismatches":
                    replay_stats["parity_mismatches"],
                "replay_books_match": diff["match"],
                "fleet_status": fleet_status,
                "fleet_members": sorted(members),
                "members_missing": fleet_body["members_missing"],
            }
        finally:
            await stop_fleet()
            await bal.close()
            for f in feeds:
                await f.stop()
            store.detach()
            store.enabled = was_enabled
            store._floor_every = was_floor
            store.reset()
            GLOBAL_WATERFALL.enabled = wf_was
            GLOBAL_WATERFALL.reset()
        return out

    try:
        return asyncio.run(go())
    except Exception as e:  # noqa: BLE001 — rider is auxiliary
        if _backend_unavailable(e):
            raise  # the fallback runner re-runs this rider on CPU
        print(f"# incident_capture failed: {e!r}", file=sys.stderr)
        return None
    finally:
        for k, v in env_was.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _incident_overhead(repeats: int = 20, total: int = 1000,
                       concurrency: int = 64) -> Optional[dict]:
    """ISSUE 19 gate: the ARMED-but-idle incident recorder's marginal
    cost on the blocking-publish path, <= 5% by acceptance (expected ~0:
    arming costs one forced EventLog bool plus an alert-transition
    listener that a healthy run never invokes — nothing per placement).
    Same paired-segment protocol as `_fleet_observatory_overhead`
    (fixture built ONCE, armed/disarmed segments back-to-back, order
    flipped per repeat, 20%-trimmed mean over the pairs); install/
    uninstall runs BETWEEN segments so thread start/join never lands in
    a measured window."""
    from openwhisk_tpu.controller.loadbalancer import TpuBalancer
    from openwhisk_tpu.core.entity import (ActivationId, ControllerInstanceId,
                                           Identity)
    from openwhisk_tpu.messaging import (ActivationMessage,
                                         MemoryMessagingProvider)
    from openwhisk_tpu.utils.blackbox import GLOBAL_INCIDENTS
    from openwhisk_tpu.utils.transaction import TransactionId

    import tempfile
    inc_dir = tempfile.mkdtemp(prefix="bench-incover-")
    env_overrides = {
        "CONFIG_whisk_incidents_enabled": "true",
        "CONFIG_whisk_incidents_directory": inc_dir,
    }
    env_was = {k: os.environ.get(k) for k in env_overrides}

    async def go() -> dict:
        provider = MemoryMessagingProvider()
        # env not yet flipped: the balancer must NOT auto-own the
        # recorder — the rider arms/disarms it per segment
        bal = TpuBalancer(provider, ControllerInstanceId("0"),
                          managed_fraction=1.0, blackbox_fraction=0.0,
                          kernel="xla")
        os.environ.update(env_overrides)
        await bal.start()
        feeds, stop_fleet = await _echo_fleet(provider, 16)
        from openwhisk_tpu.controller.loadbalancer.base import HEALTHY
        for _ in range(120):
            health = await bal.invoker_health()
            if sum(h.status == HEALTHY for h in health) >= 16:
                break
            await asyncio.sleep(0.25)
        else:
            raise RuntimeError("incident overhead rider: fleet unhealthy")

        actions = [_bench_action(f"io{i}", memory=128) for i in range(8)]
        ident = Identity.generate("guest")
        sem = asyncio.Semaphore(concurrency)

        async def one(i):
            action = actions[i % len(actions)]
            msg = ActivationMessage(
                TransactionId(), action.fully_qualified_name, action.rev.rev,
                ident, ActivationId.generate(), ControllerInstanceId("0"),
                True, {})
            async with sem:
                promise = await bal.publish(action, msg)
                await promise

        async def segment() -> float:
            t0 = time.perf_counter()
            await asyncio.gather(*[one(i) for i in range(total)])
            return total / (time.perf_counter() - t0)

        token = object()
        try:
            await segment()  # warmup: compile + settle
            pairs = []
            on_rates, off_rates = [], []
            for k in range(repeats):
                order = (True, False) if k % 2 == 0 else (False, True)
                rate = {}
                for armed in order:
                    if armed:
                        assert GLOBAL_INCIDENTS.install(balancer=bal,
                                                        owner=token)
                    else:
                        GLOBAL_INCIDENTS.uninstall(owner=token)
                    rate[armed] = await segment()
                GLOBAL_INCIDENTS.uninstall(owner=token)
                on_rates.append(rate[True])
                off_rates.append(rate[False])
                pairs.append(100.0 * (rate[False] - rate[True])
                             / rate[False])
        finally:
            GLOBAL_INCIDENTS.uninstall(owner=token)
            await stop_fleet()
            await bal.close()
            for f in feeds:
                await f.stop()
        trim = max(1, len(pairs) // 5)
        kept = sorted(pairs)[trim:-trim] if len(pairs) > 2 * trim else pairs
        return {
            "rate_incidents_on": round(max(on_rates), 1),
            "rate_incidents_off": round(max(off_rates), 1),
            "overhead_pct": round(statistics.mean(kept), 2),
            "target_pct": 5.0,
            "pair_overheads_pct": [round(p, 2) for p in pairs],
            "repeats": repeats,
            "agg": "trimmed_mean_paired_segments",
        }

    try:
        return asyncio.run(go())
    except Exception as e:  # noqa: BLE001 — rider is auxiliary
        if _backend_unavailable(e):
            raise  # the fallback runner re-runs this rider on CPU
        print(f"# incident_overhead failed: {e!r}", file=sys.stderr)
        return None
    finally:
        for k, v in env_was.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _placement_quality(total: int = 400, concurrency: int = 32,
                       n_invokers: int = 8,
                       stragglers: str = "3:0.25") -> Optional[dict]:
    """ISSUE 17 A/B: the placement-quality plane under a straggler.

    Two arms over the same workload shape, fresh fixture each (EWMAs
    must not leak between arms): `straggler` injects ack delay on one
    invoker via the shared PR 4 helper (tools/loadgen.apply_stragglers),
    so the anomaly plane flags it and the shadow counterfactual runs the
    penalty-demoted probe geometry; `clean` runs the identical drive
    with no injection, where the penalty vector stays zero and the
    shadow MUST be bit-identical to production (divergent_rows == 0 is
    the end-to-end restatement of the parity property the tier-1 fuzz
    asserts). The pair is the plane's payoff evidence: regret +
    divergence with the shadow penalty effectively on vs off."""
    from openwhisk_tpu.controller.loadbalancer import TpuBalancer
    from openwhisk_tpu.controller.loadbalancer.base import HEALTHY
    from openwhisk_tpu.controller.loadbalancer.quality import (QualityConfig,
                                                               QualityPlane)
    from openwhisk_tpu.core.entity import (ActivationId, ControllerInstanceId,
                                           Identity)
    from openwhisk_tpu.messaging import (ActivationMessage,
                                         MemoryMessagingProvider)
    from openwhisk_tpu.utils.transaction import TransactionId
    from tools.loadgen import apply_stragglers

    async def arm(spec) -> dict:
        provider = MemoryMessagingProvider()
        qp = QualityPlane(QualityConfig(enabled=True, shadow_every_n=4))
        # prewarm off: background compiles are pure GIL contention inside
        # the measured window (the PR-5 lesson, same as the anomaly e2e)
        bal = TpuBalancer(provider, ControllerInstanceId("0"),
                          managed_fraction=1.0, blackbox_fraction=0.0,
                          kernel="xla", quality=qp, prewarm=False)
        await bal.start()
        feeds, stop_fleet = await _echo_fleet(provider, n_invokers)
        for _ in range(120):
            health = await bal.invoker_health()
            if sum(h.status == HEALTHY for h in health) >= n_invokers:
                break
            await asyncio.sleep(0.25)
        else:
            raise RuntimeError("placement quality rider: fleet unhealthy")
        applied = apply_stragglers(feeds, spec)

        actions = [_bench_action(f"pq{i}", memory=128) for i in range(8)]
        ident = Identity.generate("guest")
        sem = asyncio.Semaphore(concurrency)

        async def one(i):
            action = actions[i % len(actions)]
            msg = ActivationMessage(
                TransactionId(), action.fully_qualified_name, action.rev.rev,
                ident, ActivationId.generate(), ControllerInstanceId("0"),
                True, {})
            async with sem:
                promise = await bal.publish(action, msg)
                await promise

        try:
            # warmup compiles (production + shadow + scorer shapes)
            await asyncio.gather(*[one(i) for i in range(min(64, total))])
            # drive in rounds with supervision ticks between them: the
            # anomaly detector harvests one tick late, and the straggler
            # flags become the shadow penalty only on the NEXT refresh
            rounds = 5
            per = max(1, total // rounds)
            for _ in range(rounds):
                await asyncio.gather(*[one(i) for i in range(per)])
                bal._telemetry_tick()
                await asyncio.sleep(0.1)
            # two settle ticks + one more driven round so shadow batches
            # actually run WITH the refreshed penalty in effect
            for _ in range(2):
                bal._telemetry_tick()
                await asyncio.sleep(0.1)
            await asyncio.gather(*[one(i) for i in range(per)])
            report = await asyncio.to_thread(
                qp.quality_report, bal._telemetry_invoker_names())
        finally:
            await stop_fleet()
            await bal.close()
            for f in feeds:
                await f.stop()
        return {
            "stragglers": {str(k): v for k, v in applied.items()},
            "penalized_invokers": int((bal._shadow_penalty_np > 0).sum()),
            "regret_sum_ms": report.get("regret_sum_ms"),
            "regret_p99_le_ms": report.get("regret_p99_le_ms"),
            "fleet_imbalance_cov": report.get("fleet_imbalance_cov"),
            "shadow_batches": report.get("shadow_batches"),
            "shadow_rows": report.get("shadow_rows"),
            "divergent_rows": report.get("divergent_rows"),
            "divergence_ratio": report.get("divergence_ratio"),
            "counters": report.get("counters"),
            "per_invoker": report.get("invokers"),
        }

    try:
        with_straggler = asyncio.run(arm(stragglers))
        clean = asyncio.run(arm(None))
        return {
            "straggler": with_straggler,
            "clean": clean,
            # the pair's headline: how differently the penalized geometry
            # places under a real straggler vs the zero-penalty identity
            "shadow_divergence_ratio": with_straggler["divergence_ratio"],
            "clean_divergent_rows": clean["divergent_rows"],
        }
    except Exception as e:  # noqa: BLE001 — rider is auxiliary
        if _backend_unavailable(e):
            raise  # the fallback runner re-runs this rider on CPU
        print(f"# placement_quality failed: {e!r}", file=sys.stderr)
        return None


def _placement_quality_overhead(repeats: int = 20, total: int = 1000,
                                concurrency: int = 64) -> Optional[dict]:
    """ISSUE 17 gate (<= 5%): the quality plane's marginal cost through
    the full balancer path — the per-batch scorer dispatch plus one
    shadow pass every N batches. Same paired-segment protocol as
    `_fleet_observatory_overhead` (fixture ONCE, armed/disarmed segments
    back-to-back, order flipped per repeat, 20%-trimmed mean of paired
    ratios): the effect is small and between-run host jitter is 4x, so
    only a paired design measures it. The disarmed half parks the shadow
    fn and flips `enabled`, which is exactly what the off-switch does on
    the dispatch path — production decisions are bit-exact either way
    (tier-1-asserted), so the pair measures pure observability tax."""
    from openwhisk_tpu.controller.loadbalancer import TpuBalancer
    from openwhisk_tpu.controller.loadbalancer.base import HEALTHY
    from openwhisk_tpu.controller.loadbalancer.quality import (QualityConfig,
                                                               QualityPlane)
    from openwhisk_tpu.core.entity import (ActivationId, ControllerInstanceId,
                                           Identity)
    from openwhisk_tpu.messaging import (ActivationMessage,
                                         MemoryMessagingProvider)
    from openwhisk_tpu.utils.transaction import TransactionId

    async def go() -> dict:
        provider = MemoryMessagingProvider()
        qp = QualityPlane(QualityConfig(enabled=True, shadow_every_n=16))
        bal = TpuBalancer(provider, ControllerInstanceId("0"),
                          managed_fraction=1.0, blackbox_fraction=0.0,
                          kernel="xla", quality=qp)
        await bal.start()
        feeds, stop_fleet = await _echo_fleet(provider, 16)
        for _ in range(120):
            health = await bal.invoker_health()
            if sum(h.status == HEALTHY for h in health) >= 16:
                break
            await asyncio.sleep(0.25)
        else:
            raise RuntimeError("placement quality overhead: fleet unhealthy")

        actions = [_bench_action(f"pqo{i}", memory=128) for i in range(8)]
        ident = Identity.generate("guest")
        sem = asyncio.Semaphore(concurrency)

        async def one(i):
            action = actions[i % len(actions)]
            msg = ActivationMessage(
                TransactionId(), action.fully_qualified_name, action.rev.rev,
                ident, ActivationId.generate(), ControllerInstanceId("0"),
                True, {})
            async with sem:
                promise = await bal.publish(action, msg)
                await promise

        async def segment() -> float:
            t0 = time.perf_counter()
            await asyncio.gather(*[one(i) for i in range(total)])
            return total / (time.perf_counter() - t0)

        shadow_fn = bal._shadow_fn

        def set_armed(armed: bool) -> None:
            # the off-switch's dispatch-path effect, minus a rebuild:
            # enabled=False skips the scorer, a parked shadow fn skips
            # the counterfactual
            qp.enabled = armed
            bal._shadow_fn = shadow_fn if armed else None

        try:
            await segment()  # warmup: production + shadow + scorer compiles
            pairs = []
            on_rates, off_rates = [], []
            for k in range(repeats):
                order = (True, False) if k % 2 == 0 else (False, True)
                rate = {}
                for armed in order:
                    set_armed(armed)
                    rate[armed] = await segment()
                set_armed(True)
                on_rates.append(rate[True])
                off_rates.append(rate[False])
                pairs.append(100.0 * (rate[False] - rate[True])
                             / rate[False])
        finally:
            await stop_fleet()
            await bal.close()
            for f in feeds:
                await f.stop()
        trim = max(1, len(pairs) // 5)
        kept = sorted(pairs)[trim:-trim] if len(pairs) > 2 * trim else pairs
        return {
            "rate_placement_quality_on": round(max(on_rates), 1),
            "rate_placement_quality_off": round(max(off_rates), 1),
            "overhead_pct": round(statistics.mean(kept), 2),
            "pair_overheads_pct": [round(p, 2) for p in pairs],
            "repeats": repeats,
            "shadow_every_n": qp.shadow_every_n,
            "agg": "trimmed_mean_paired_segments",
        }

    try:
        return asyncio.run(go())
    except Exception as e:  # noqa: BLE001 — rider is auxiliary
        if _backend_unavailable(e):
            raise  # the fallback runner re-runs this rider on CPU
        print(f"# placement_quality_overhead failed: {e!r}", file=sys.stderr)
        return None


def _e2e_open_loop_measure(rate0: float = 32.0, duration: float = 2.5,
                           max_doublings: int = 9) -> Optional[dict]:
    """The in-process body of the e2e_open_loop rider (run it in a fresh
    subprocess via _e2e_open_loop — see _subprocess_json for why)."""
    from tools.loadgen import sweep_balancer
    return sweep_balancer(rate0=rate0, duration=duration,
                          max_doublings=max_doublings)


def _latest_bench_round() -> Optional[tuple]:
    """(filename, unwrapped round dict) of the newest BENCH_*.json beside
    this script, or None. "Newest" is the name sort — the driver numbers
    rounds r01, r02, ... monotonically."""
    import glob
    import os
    here = os.path.dirname(os.path.abspath(__file__))
    rounds = sorted(glob.glob(os.path.join(here, "BENCH_r*.json")))
    if not rounds:
        return None
    path = rounds[-1]
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    from tools.bench_compare import unwrap_round
    return os.path.basename(path), unwrap_round(doc)


def _compared_to(rider_key: str, new_block: dict,
                 latest: Optional[tuple] = None) -> Optional[dict]:
    """The `compared_to` satellite (ISSUE 12): diff one rider's fresh
    block against the same rider in the newest prior BENCH_*.json via
    tools/bench_compare's headline rules. ADVISORY by contract — the
    block reports regressions, it never fails the rider (the judgment
    tool for a round stays the bench_compare CLI). `latest` lets a
    caller that already loaded the baseline pass it in (one read, one
    consistent baseline)."""
    try:
        if latest is None:
            latest = _latest_bench_round()
        if latest is None:
            return None
        fname, old_round = latest
        old_block = old_round.get(rider_key)
        if not isinstance(old_block, dict):
            return {"baseline": fname, "skipped": f"no {rider_key} block "
                    "in the baseline round"}
        from tools.bench_compare import compare
        out = compare({rider_key: old_block}, {rider_key: new_block})
        headlines = [r for r in out["headlines"]
                     if not r["verdict"].startswith("skipped")]
        return {
            "baseline": fname,
            "advisory": True,
            "headlines": headlines,
            "regressions": out["regressions"],
        }
    except Exception as e:  # noqa: BLE001 — advisory must stay advisory
        print(f"# compared_to({rider_key}) failed: {e!r}", file=sys.stderr)
        return None


def _e2e_fleet_mesh_measure(rate0: float = 32.0, duration: float = 2.0,
                            max_doublings: int = 6) -> Optional[dict]:
    """The fleet-mesh comparison point (ROADMAP item 2c): the SAME
    coordinated-omission-correct open-loop sweep, against a balancer in
    fleet-mesh mode (invoker state sharded over the ('fleet',) mesh).
    Runs in a CPU-pinned 8-virtual-device subprocess — the honest
    virtual mesh, same posture as the sharded_fleet_sweep rider; a clean
    DEVICE round of this row stays on the ROADMAP item 2 list."""
    from tools.loadgen import sweep_balancer
    row = sweep_balancer(rate0=rate0, duration=duration,
                         max_doublings=max_doublings, fleet_mesh=True)
    keep = {k: row.get(k) for k in (
        "sustained", "sustained_activations_per_sec",
        "sustained_offered_rate", "p50_ms", "p99_ms", "fleet_shards",
        "gc_tuned")}
    keep["mode"] = "open_loop"
    keep["fleet_mesh"] = True
    return keep


def _e2e_multiproc_measure(rate: float = 128.0, procs: int = 2,
                           duration: float = 1.5) -> Optional[dict]:
    """The --procs fleet-merged point (ISSUE 16): N worker generators at
    rate/N each, the parent reaping ONE fleet-merged host snapshot (raw
    integer bucket counts merged bucket-wise, the federation's own merge
    math) instead of N per-worker blobs. The kept headline is
    fleet_merged_sustained_per_sec — gated in tools/bench_compare.py."""
    from tools.loadgen import multiproc_fixed_rate
    row = multiproc_fixed_rate(rate=rate, procs=procs, duration=duration,
                               host_observatory=True)
    keep = {k: row.get(k) for k in (
        "mode", "procs", "sustained", "sustained_activations_per_sec",
        "fleet_merged_sustained_per_sec", "offered_rate", "p50_ms",
        "p99_ms")}
    hf = row.get("host_fleet") or {}
    keep["host_fleet_members"] = hf.get("members")
    keep["host_fleet_lag_p99_le_ms"] = (hf.get("loop_lag")
                                        or {}).get("p99_le_ms")
    return keep


def _funnel_10k_measure(duration: float = 2.0) -> Optional[dict]:
    """ISSUE 20 rider body: the SHARED multi-process deployment — N
    front-end worker processes funneling one device-owning balancer
    process over the TCP bus — swept over front-end process count at
    4k/8k/12k offered/s. Each point is a merged-schedule verdict
    (topology "shared": one balancer really placed every row, so the
    merged rate IS the system number, unlike the twins-mode sum). The
    funnel's depth bound surfaces as 429s at the front door, which the
    per-worker verdicts count as errors — an over-driven point fails
    honestly instead of queueing unboundedly. The 12k rung doubles as
    the recorded 10k/s attempt, sustained or not."""
    import os
    from tools.loadgen import multiproc_fixed_rate
    cpus = os.cpu_count() or 1
    # front-end process ladder: 2 always (the minimum real multi-process
    # point, timesliced honestly on a small box), 4 when the box has the
    # cores to give each front end one
    proc_ladder = [2] if cpus < 6 else [2, 4]
    rates = (4096.0, 8192.0, 12288.0)
    points = []
    best = None
    attempt_10k = None
    for procs in proc_ladder:
        skip_rest = False
        for rate in rates:
            if skip_rest and not (rate >= 10000.0 and attempt_10k is None):
                continue
            row = multiproc_fixed_rate(rate=rate, procs=procs,
                                       duration=duration, shared=True)
            point = {k: row.get(k) for k in (
                "topology", "procs", "offered_rate", "sustained",
                "sustained_activations_per_sec",
                "fleet_merged_sustained_per_sec", "completed", "p50_ms",
                "p99_ms")}
            point["worker_verdicts"] = [
                {"worker": w.get("worker"),
                 "sustained": w.get("sustained"),
                 "blames": w.get("blames"),
                 "error": w.get("error"),
                 "failed": (w.get("verdict") or {}).get("failed")}
                for w in row.get("per_worker") or []]
            points.append(point)
            if rate >= 10000.0:
                attempt_10k = point
            if point["sustained"]:
                if (best is None or
                        (point["fleet_merged_sustained_per_sec"] or 0) >
                        (best["fleet_merged_sustained_per_sec"] or 0)):
                    best = point
            else:
                # higher rates at this proc count fail harder — skip
                # them, EXCEPT the >=10k rung runs once regardless so
                # the 10k/s attempt is on the record either way
                skip_rest = True
    # headline honesty: a sustained point's merged rate, else the best
    # observed merged rate explicitly flagged unsustained
    if best is not None:
        head, sustained = best, True
    else:
        head = max(points,
                   key=lambda p: p["fleet_merged_sustained_per_sec"] or 0)
        sustained = False
    return {
        "mode": "funnel_10k",
        "topology": "shared",
        "single_process_baseline_per_sec": 4043.0,
        "funnel_sustained_per_sec": head["fleet_merged_sustained_per_sec"],
        "funnel_frontend_procs": head["procs"],
        "sustained": sustained,
        "offered_rates_swept": list(rates),
        "frontend_proc_ladder": proc_ladder,
        "cpus": cpus,
        "attempt_10k": attempt_10k,
        "points": points,
    }


def _funnel_10k() -> Optional[dict]:
    """The ISSUE 20 rider: real multi-process 10k/s attempt through the
    front-end->balancer admission funnel. Pure control-plane/host work —
    always CPU-pinned (and tagged so), like the host-path rows."""
    out = _cpu_subprocess_json("bench._funnel_10k_measure()", "RIDERJSON",
                               "funnel_10k", force_devices=True)
    if out is not None:
        out["backend"] = "cpu"
        cmp_block = _compared_to("funnel_10k", out)
        if cmp_block is not None:
            out["compared_to"] = cmp_block
    return out


def _e2e_open_loop(rate0: float = 32.0, duration: float = 2.5,
                   max_doublings: int = 9) -> Optional[dict]:
    """The ISSUE 7 headline rider: open-loop offered-rate sweep against the
    live balancer path (tools/loadgen.py) — max sustainable activations/s
    with e2e p50/p99 measured from SCHEDULED arrival time (coordinated-
    omission-correct, unlike the closed-loop `balancer` rows) plus the
    waterfall's per-stage budget saying where the per-activation time
    goes. Acceptance: the stage medians sum to ~the e2e median (no
    unaccounted gap) and the budget names the stage to attack next.
    Runs in a fresh backend-inheriting subprocess; falls back to a
    CPU-pinned subprocess when the device is unavailable. The
    `compared_to` block (ISSUE 12) diffs this run against the newest
    prior BENCH_*.json round — advisory, never fails the rider."""
    expr = (f"bench._e2e_open_loop_measure({rate0}, {duration}, "
            f"{max_doublings})")
    out = _subprocess_json(expr, "RIDERJSON", "e2e_open_loop")
    if out is None:
        out = _cpu_subprocess_json(expr, "RIDERJSON",
                                   "e2e_open_loop cpu re-run")
        if out is not None:
            out["backend"] = "cpu_fallback"
    if out is not None:
        # fleet-mesh comparison row (ROADMAP item 2c): same open-loop
        # judge, sharded balancer, 8-way virtual CPU mesh (tagged cpu —
        # never mistakable for a device number)
        mesh = _cpu_subprocess_json("bench._e2e_fleet_mesh_measure()",
                                    "RIDERJSON", "e2e fleet-mesh point",
                                    force_devices=True)
        if mesh is not None:
            mesh["backend"] = "cpu"
            out["fleet_mesh_point"] = mesh
        # --procs fleet-merged point (ISSUE 16): the parent reaps ONE
        # merged snapshot across its worker generators; headline gates
        # as fleet_merged_sustained_per_sec in bench_compare
        mp = _cpu_subprocess_json("bench._e2e_multiproc_measure()",
                                  "RIDERJSON", "e2e multiproc point")
        if mp is not None:
            mp["backend"] = "cpu"
            out["multiproc_point"] = mp
        cmp_block = _compared_to("e2e_open_loop", out)
        if cmp_block is not None:
            out["compared_to"] = cmp_block
    return out


def _bus_coalesce_speedup(n_messages: int = 2048, wave: int = 64,
                          e2e_rates: tuple = (256.0, 512.0),
                          e2e_duration: float = 2.0) -> Optional[dict]:
    """ISSUE 8 rider, two halves:

    1. BUS MICRO: `n_messages` concurrent produces over a live TCP bus
       (waves of `wave`, the shape of a readback fan-out), serial
       per-message `pub` vs the CoalescingProducer's `pubN` frames —
       msgs/s both ways and the speedup.
    2. E2E SCOREBOARD: fixed-rate open-loop runs with the ISSUE 8 knobs ON
       (defaults) vs OFF at each of `e2e_rates` — the waterfall's
       `produce` stage p50/p99 and the generator throughput side by side.
       256/s is the PR 6 baseline's sustained rate (both paths sustain:
       the produce p99 comparison is apples to apples); 512/s is past the
       serial ceiling (the coalesced path holds throughput and the serial
       produce stage absorbs the backlog)."""
    from openwhisk_tpu.messaging.coalesce import CoalescingProducer
    from openwhisk_tpu.messaging.tcp import TcpBusServer, TcpMessagingProvider

    async def _produce_half(coalesced: bool) -> float:
        server = TcpBusServer("127.0.0.1", 0)
        await server.start()
        port = server._server.sockets[0].getsockname()[1]
        provider = TcpMessagingProvider("127.0.0.1", port)
        # bound broker-side retention so the un-consumed backlog stays small
        server.bus.topic("t").set_retention_bytes(128 * 1024)
        producer = provider.get_producer()
        if coalesced:
            producer = CoalescingProducer(producer, max_batch=wave,
                                          window_ms=0.0)
        payload = b"x" * 256
        t0 = time.monotonic()
        for _ in range(n_messages // wave):
            await asyncio.gather(*[producer.send("t", payload)
                                   for _ in range(wave)])
        rate = n_messages / (time.monotonic() - t0)
        await producer.close()
        await server.stop()
        return rate

    try:
        serial = asyncio.run(_produce_half(False))
        coalesced = asyncio.run(_produce_half(True))
        e2e = []
        for rate in e2e_rates:
            # one fresh subprocess per point: a sweep leaves dead jit
            # executables and GC pressure behind, and a later in-process
            # run inherits stalls that read as saturation (measured)
            on = _cpu_subprocess_json(
                f"bench._bus_e2e_point(True, {rate}, {e2e_duration})",
                "RIDERJSON", f"bus e2e knobs-on @{rate}")
            off = _cpu_subprocess_json(
                f"bench._bus_e2e_point(False, {rate}, {e2e_duration})",
                "RIDERJSON", f"bus e2e knobs-off @{rate}")
            if on is None or off is None:
                continue
            row = {"rate": rate, "knobs_on": on, "knobs_off": off}
            if on["produce_p99_ms"] and off["produce_p99_ms"]:
                row["produce_p99_ratio_off_over_on"] = round(
                    off["produce_p99_ms"] / on["produce_p99_ms"], 2)
            e2e.append(row)
        return {
            "n_messages": n_messages,
            "wave": wave,
            "serial_msgs_per_sec": round(serial, 1),
            "coalesced_msgs_per_sec": round(coalesced, 1),
            "speedup": round(coalesced / serial, 2) if serial else None,
            "e2e": e2e,
        }
    except Exception as e:  # noqa: BLE001 — rider is auxiliary
        if _backend_unavailable(e):
            raise  # the fallback runner re-runs this rider on CPU
        print(f"# bus_coalesce_speedup failed: {e!r}", file=sys.stderr)
        return None


def _host_obs_point(enabled: bool, rate: float, duration: float) -> dict:
    """One fixed-rate open-loop measurement with the host hot-loop
    observatory ON or OFF (run in a fresh CPU-pinned subprocess via
    _cpu_subprocess_json: the observatory knobs are env-driven and its
    planes are process-global, so each half must own its process). ON
    attaches the observatory snapshot — loop lag, GC shares, serde shares,
    self-time census — as `host`."""
    import os
    v = "true" if enabled else "false"
    os.environ["CONFIG_whisk_hostProfiling_enabled"] = v
    from tools.loadgen import sweep_balancer
    row = sweep_balancer(fixed_rate=rate, duration=duration,
                         host_observatory=enabled)
    out = {
        # CPU-twin by construction (CPU-pinned subprocess): say so, per
        # the "never mistake a CPU number for a device number" rule
        "backend": "cpu",
        "offered_rate": rate,
        "sustained": row.get("sustained"),
        "activations_per_sec": row.get("sustained_activations_per_sec"),
        "p50_ms": row.get("p50_ms"),
        "p99_ms": row.get("p99_ms"),
        "completed": (row.get("headline") or {}).get("completed"),
    }
    if enabled:
        out["host"] = row.get("host")
    return out


def _host_profiling_overhead(rate: float = 1024.0, duration: float = 2.5,
                             repeats: int = 2) -> Optional[dict]:
    """ISSUE 11 gate: ALL FOUR host-observatory planes (lag probe, gc
    callbacks, task-factory interposer + serde accounting, sampler) must
    cost <= 5% at the PR 7 open-loop sustained rate (~1000/s on the CPU
    twin). Unlike the closed-loop plane riders, this one measures at the
    open-loop saturation edge — where added per-activation host work shows
    up as lost completions, not hidden queueing."""
    try:
        on_rates, off_rates = [], []
        p99_on, p99_off = [], []
        for _ in range(repeats):
            on = _cpu_subprocess_json(
                f"bench._host_obs_point(True, {rate}, {duration})",
                "RIDERJSON", "host profiling on")
            off = _cpu_subprocess_json(
                f"bench._host_obs_point(False, {rate}, {duration})",
                "RIDERJSON", "host profiling off")
            if on and off and on.get("activations_per_sec") \
                    and off.get("activations_per_sec"):
                on_rates.append(on["activations_per_sec"])
                off_rates.append(off["activations_per_sec"])
                if on.get("p99_ms") is not None:
                    p99_on.append(on["p99_ms"])
                if off.get("p99_ms") is not None:
                    p99_off.append(off["p99_ms"])
        if not on_rates:
            return None
        on_med = statistics.median(on_rates)
        off_med = statistics.median(off_rates)
        return {
            "rate_host_profiling_on": round(on_med, 1),
            "rate_host_profiling_off": round(off_med, 1),
            "overhead_pct": (round(100.0 * (off_med - on_med) / off_med, 2)
                             if off_med else None),
            # medians like the rates: one repeat's GC spike must not read
            # as the observatory's latency cost
            "p99_on_ms": statistics.median(p99_on) if p99_on else None,
            "p99_off_ms": statistics.median(p99_off) if p99_off else None,
            "offered_rate": rate,
            "mode": "open_loop",
            "repeats": len(on_rates),
        }
    except Exception as e:  # noqa: BLE001 — rider is auxiliary
        if _backend_unavailable(e):
            raise  # the fallback runner re-runs this rider on CPU
        print(f"# host_profiling_overhead failed: {e!r}", file=sys.stderr)
        return None


def _host_observatory(rate: float = 4096.0, duration: float = 3.0
                      ) -> Optional[dict]:
    """ISSUE 11 payoff rider: the open-loop generator at the columnar
    hot path's sustained offered rate (ISSUE 12: 4096 offered / ~3.3k
    sustained on the 1-core twin, up from PR 7's 1024) with the
    observatory ON — one JSON block with loop-lag p50/p99, the GC pause
    share, per-hop serde shares, the top-5 self-time frames, and the
    `stage_shares` table the ROADMAP "no dominant host stage" claim is
    judged against (compared_to diffs the prior round's table in)."""
    try:
        point = _cpu_subprocess_json(
            f"bench._host_obs_point(True, {rate}, {duration})",
            "RIDERJSON", "host_observatory")
        if point is None:
            return None
        host = point.get("host") or {}
        lag = host.get("loop_lag") or {}
        gc_block = host.get("gc") or {}
        sampler = host.get("sampler") or {}
        top = (sampler.get("top") or [])[:5]
        serde_share = {
            f"{row['hop']}/{row['direction']}": row["share_pct"]
            for row in (host.get("serde") or [])}
        tasks = host.get("tasks") or {}
        completed = point.get("completed") or 0
        # the ISSUE 12 stage-share table: the per-plane shares the
        # "no dominant host stage" ROADMAP claim is judged against —
        # recorded as a measured artifact next to the headline, with the
        # prior round's table diffed in via compared_to below
        worst_serde = max(serde_share.values(), default=0.0)
        gc_share = gc_block.get("pause_share_pct") or 0.0
        stage_shares = {
            "serde_worst_hop_pct": worst_serde,
            "serde_by_hop_pct": serde_share,
            "gc_pause_pct": gc_share,
            "loop_lag_p50_ms": lag.get("p50_ms"),
            "loop_lag_p99_ms": lag.get("p99_ms"),
            "tasks_per_activation": (round(tasks.get("created", 0)
                                           / completed, 2)
                                     if completed else None),
            "no_plane_above_25pct": bool(worst_serde <= 25.0
                                         and gc_share <= 25.0),
        }
        out = {
            "backend": "cpu",
            "offered_rate": rate,
            "sustained": point.get("sustained"),
            "sustained_activations_per_sec": point.get(
                "activations_per_sec"),
            "e2e_p99_ms": point.get("p99_ms"),
            "loop_lag_p50_ms": lag.get("p50_ms"),
            "loop_lag_p99_ms": lag.get("p99_ms"),
            "loop_lag_max_ms": lag.get("max_ms"),
            "gc_pause_share_pct": gc_block.get("pause_share_pct"),
            "gc_pauses_in_dispatch": gc_block.get("overlapping_dispatch"),
            "serde_share_pct": serde_share,
            "stage_shares": stage_shares,
            "top_self_time": top,
            "distinct_hot_frames": len(sampler.get("top") or []),
            "worst_stalls": (host.get("stalls") or {}).get("worst", [])[:5],
            "tasks": host.get("tasks"),
        }
        # before/after: the prior round's stage-share table beside this
        # one (advisory, like the e2e compared_to) — ONE baseline read
        # shared with the headline diff, so both halves describe the
        # same round
        latest = _latest_bench_round()
        cmp_block = _compared_to("host_observatory", out, latest=latest)
        if cmp_block is not None:
            if latest is not None:
                prior = (latest[1].get("host_observatory") or {})
                cmp_block["before_stage_shares"] = (
                    prior.get("stage_shares")
                    or {"serde_by_hop_pct": prior.get("serde_share_pct"),
                        "gc_pause_pct": prior.get("gc_pause_share_pct"),
                        "loop_lag_p99_ms": prior.get("loop_lag_p99_ms")})
            out["compared_to"] = cmp_block
        return out
    except Exception as e:  # noqa: BLE001 — rider is auxiliary
        if _backend_unavailable(e):
            raise  # the fallback runner re-runs this rider on CPU
        print(f"# host_observatory failed: {e!r}", file=sys.stderr)
        return None


def _bus_e2e_point(knobs_on: bool, rate: float, duration: float) -> dict:
    """One fixed-rate open-loop measurement for the bus_coalesce_speedup
    scoreboard (run in a fresh subprocess via _cpu_subprocess_json — the
    ISSUE 8 knobs are env-driven, read at balancer/producer construction,
    so setting them here before the sweep builds its target is enough).
    The toggles cover bus coalescing + the adaptive dispatch window ONLY:
    loadgen enters at balancer.publish, so the admission plane is not on
    this measured path (it is exercised by the HTTP burst drive in the
    verify recipe and tests/test_admission.py instead)."""
    import os
    # set BOTH branches explicitly: a knobs-off env inherited from the
    # operator's shell would otherwise silently turn the on-vs-off
    # scoreboard into serial-vs-serial
    v = "true" if knobs_on else "false"
    os.environ.update({
        "CONFIG_whisk_bus_coalesce_enabled": v,
        "CONFIG_whisk_loadBalancer_adaptiveWindow": v})
    from tools.loadgen import sweep_balancer
    row = sweep_balancer(fixed_rate=rate, duration=duration)
    budget = row.get("stage_budget") or {}
    return {
        # this scoreboard is CPU-twin by construction (CPU-pinned
        # subprocess): say so, per the "never mistake a CPU number for a
        # device number" rule
        "backend": "cpu",
        "offered_rate": rate,
        "sustained": row.get("sustained"),
        "activations_per_sec": row.get("sustained_activations_per_sec"),
        "e2e_p99_ms": row.get("p99_ms"),
        "produce_p50_ms": (budget.get("stage_medians_ms") or {}
                           ).get("produce"),
        "produce_p99_ms": (budget.get("p99_decomposition_ms") or {}
                           ).get("produce"),
    }


def _rider_batch(n_invokers: int, b: int, seed: int = 23):
    """`_example_batch` with the ACTION POOL scaled to the batch: the
    headline protocol (B=256 over 64 actions) holds the per-action burst
    at 4, so the repair_vs_scan sweep keeps that ratio as B grows — B
    sweeps batch WIDTH, not convoy depth. (The convoy shape — many
    requests of one action, deliberately overflowing invokers in a
    sequential chain — is measured separately as the `convoy` row: it is
    the repair kernel's worst case and the reason the `auto` knob
    exists.)"""
    import jax.numpy as jnp

    from openwhisk_tpu.models.sharding_policy import (generate_hash,
                                                      pairwise_coprimes)
    from openwhisk_tpu.ops.placement import RequestBatch

    n_actions = max(1, b // 4)
    rng = np.random.RandomState(seed)
    managed = max(int(0.9 * n_invokers), 1)
    steps = pairwise_coprimes(managed)
    cols = {k: np.zeros((b,), np.int32) for k in
            ("offset", "size", "home", "step_inv", "need_mb", "conc_slot",
             "max_conc", "rand")}
    for i in range(b):
        # EXACT bursts of b/n_actions consecutive requests per action —
        # how a real arrival burst convoys through the FIFO queue (random
        # draws would Poisson-spread the bursts: a 6-request 512 MB action
        # self-overflows its home invoker, turning the row into a chain
        # benchmark — that shape is the `convoy` row's job)
        a = i * n_actions // b
        h = generate_hash(f"ns{a % 8}", f"action{a}")
        step = steps[h % len(steps)]
        cols["offset"][i] = 0
        cols["size"][i] = managed
        cols["home"][i] = h % managed
        cols["step_inv"][i] = pow(step, -1, managed) if managed > 1 else 0
        cols["need_mb"][i] = [128, 256, 512][a % 3]
        cols["conc_slot"][i] = a % 256
        cols["max_conc"][i] = 1
        cols["rand"][i] = rng.randint(0, managed)
    return RequestBatch(*(jnp.asarray(cols[k]) for k in
                          ("offset", "size", "home", "step_inv", "need_mb",
                           "conc_slot", "max_conc", "rand")),
                        valid=jnp.ones((b,), bool))


def _repair_parity_rounds(batch_size: int, n_invokers: int = 1024,
                          action_slots: int = 256, steps: int = 4,
                          batch=None, kernel: str = "repair") -> tuple:
    """Chained-step parity of a repair pair (`kernel`: "repair" or
    "pallas_repair") against the scan oracle over the SAME batch (each
    step releases the prior step's placements, so later steps run on books
    the earlier ones dirtied) + the per-step repair-round counts. Returns
    (parity_ok, rounds)."""
    import jax.numpy as jnp

    from __graft_entry__ import _example_batch
    from openwhisk_tpu.ops.placement import init_state

    batch = batch if batch is not None else _example_batch(
        n_invokers, batch_size, seed=17)
    hidx = jnp.zeros((8,), jnp.int32)
    hval = jnp.zeros((8,), bool)
    hmask = jnp.zeros((8,), bool)
    outs, rounds = {}, []
    for k in ("xla", kernel):
        state = init_state(n_invokers, [2048] * n_invokers,
                           action_slots=action_slots)
        fused = _build_fused(k)
        rel_inv = jnp.zeros((batch_size,), jnp.int32)
        rel_ok = jnp.zeros((batch_size,), bool)
        acc = []
        for _ in range(steps):
            state, chosen, forced, r = fused(
                state, rel_inv, batch.conc_slot, batch.need_mb,
                batch.max_conc, rel_ok, hidx, hval, hmask, batch)
            acc.append((np.asarray(chosen), np.asarray(forced)))
            if k != "xla":
                rounds.append(int(r))
            rel_inv, rel_ok = jnp.clip(chosen, 0), chosen >= 0
        outs[k] = (acc, np.asarray(state.free_mb),
                   np.asarray(state.conc_free))
    parity = (
        all(np.array_equal(sc, rc) and np.array_equal(sf, rf)
            for (sc, sf), (rc, rf) in zip(outs["xla"][0], outs[kernel][0]))
        and np.array_equal(outs["xla"][1], outs[kernel][1])
        and np.array_equal(outs["xla"][2], outs[kernel][2]))
    return parity, rounds


def _repair_compile_census(batch_sizes, n_invokers: int = 256) -> dict:
    """The PR-3 watchdog contract over the repair pair's PACKED entry point
    (the same wrapper the balancer dispatches): one compile per (R, H, B)
    bucket signature across repeated calls, zero unexpected recompiles."""
    import jax.numpy as jnp

    from __graft_entry__ import _example_batch
    from openwhisk_tpu.ops.placement import (init_state,
                                             make_fused_step_packed,
                                             release_batch_vector,
                                             schedule_batch_repair)
    from openwhisk_tpu.ops.profiler import (KernelProfiler, ProfilingConfig,
                                            pow2_statics)

    prof = KernelProfiler(ProfilingConfig(enabled=True))
    fn = prof.wrap("repair_step",
                   make_fused_step_packed(release_batch_vector,
                                          schedule_batch_repair),
                   expected=pow2_statics)
    h = 8
    health = np.zeros((3, h), np.int32)
    state = init_state(n_invokers, [2048] * n_invokers, action_slots=64)
    for _ in range(2):
        st = state
        for b in batch_sizes:
            batch = _example_batch(n_invokers, b, seed=19)
            req = np.stack([np.asarray(x, np.int32) for x in
                            (batch.offset, batch.size, batch.home,
                             batch.step_inv, batch.need_mb, batch.conc_slot,
                             batch.max_conc, batch.rand, batch.valid)])
            rel = np.zeros((5, b), np.int32)
            rel[3] = 1
            buf = jnp.asarray(np.concatenate(
                [rel.ravel(), health.ravel(), req.ravel()]))
            st, _ = fn(st, buf, b, h, b)
    census = prof.cache_census()["repair_step"]
    return {"compiles": census["compiles"],
            "signatures": census["signatures"],
            "calls": census["calls"],
            "recompiles_unexpected": prof.compiles_unexpected}


def _auto_pick_row(n_invokers: int, b: int) -> dict:
    """The kernel="auto" calibration, run exactly as the balancer's prewarm
    drainer runs it (same `calibrate_backend_rates`, same cache): which
    backend the measured rate picks at the headline geometry, plus the
    cached per-backend numbers."""
    import jax

    from openwhisk_tpu.controller.loadbalancer.tpu_balancer import (
        _next_pow2, calibrate_backend_rates)
    from openwhisk_tpu.ops.placement_pallas import (HAS_PALLAS,
                                                    fits_vmem_repair)

    n_pad = _next_pow2(n_invokers)
    on_cpu = jax.default_backend() == "cpu"
    include = HAS_PALLAS and fits_vmem_repair(n_pad, 256, b)
    cal = calibrate_backend_rates(
        n_pad, 256, b, b, b, include_pallas=include,
        iters=2 if on_cpu else 5)
    out = dict(cal)
    out["backend"] = jax.default_backend()
    if on_cpu:
        # the CPU twin can only measure interpret-mode pallas — an honest
        # relative number for the CACHE mechanics, not a device verdict
        out["note"] = "cpu twin: pallas rate is interpret mode"
    return out


def _repair_vs_scan(batch_sizes=(64, 256, 1024), n_invokers: int = 1024,
                    repeats: int = 3, iters: int = 12) -> Optional[dict]:
    """The PR-5/PR-10 tentpole rider: speculate-and-repair vs the reference
    scan at the kernel level, per batch size — median steady-state rates
    through the SAME fused-step protocol as the headline number (action
    pool scaled with B, see _rider_batch), chained-step parity against the
    scan oracle, repair-round stats, and the packed entry point's compile
    census (speculation must not reintroduce shape churn). Each row also
    carries the FUSED PALLAS repair kernel (`pallas_repair_*`): on real
    TPU hardware that is the production candidate (acceptance: >= the XLA
    repair rate); on the CPU twin it is interpret mode — tagged
    `pallas_backend: "interpret"` and EXCLUDED from any headline reading,
    parity still asserted. A `convoy` row measures the documented worst
    case — the largest B over the headline's FIXED 64-action pool, i.e.
    deep same-action overflow chains — where the scan is expected to win.
    An `auto_pick` row reports which backend the kernel="auto" calibration
    chose and the cached measured rates. Acceptance: repair >= scan at
    B=64 and >= 2x at B=1024, parity true (pallas included),
    recompiles_unexpected == 0."""
    try:
        import jax
        on_cpu = jax.default_backend() == "cpu"
        rows = {}
        parity_all = True

        def measure(tag, b, n, batch, reps, its):
            nonlocal parity_all
            scan = _bench_kernel("xla", n, 256, reps, its, batch=batch)
            repair = _bench_kernel("repair", n, 256, reps, its, batch=batch)
            parity, rounds = _repair_parity_rounds(b, n, batch=batch)
            parity_all = parity_all and parity
            rows[tag] = {
                "batch": b,
                "n_invokers": n,
                "scan_rate_median": scan["rate_median"],
                "repair_rate_median": repair["rate_median"],
                "speedup": round(
                    repair["rate_median"] / scan["rate_median"], 2)
                if scan["rate_median"] else None,
                "scan_p50_step_ms": scan["p50_step_ms"],
                "repair_p50_step_ms": repair["p50_step_ms"],
                "repair_rounds_mean": round(sum(rounds) / len(rounds), 2),
                "repair_rounds_max": max(rounds),
                "parity": parity,
            }
            # the fused pallas repair kernel rides every row; interpret
            # mode (CPU twin) gets one fast-ish repeat — the number is
            # tagged and never a headline, the PARITY is the contract
            from openwhisk_tpu.ops.placement_pallas import (HAS_PALLAS,
                                                            fits_vmem_repair)
            if HAS_PALLAS and fits_vmem_repair(_next_pow2_local(n), 256, b):
                p_reps, p_its = (1, max(2, its // 4)) if on_cpu else (reps,
                                                                      its)
                pall = _bench_kernel("pallas_repair", n, 256, p_reps, p_its,
                                     batch=batch)
                p_parity, p_rounds = _repair_parity_rounds(
                    b, n, batch=batch, kernel="pallas_repair")
                parity_all = parity_all and p_parity
                rows[tag].update({
                    "pallas_repair_rate_median": pall["rate_median"],
                    "pallas_repair_p50_step_ms": pall["p50_step_ms"],
                    "pallas_repair_rounds_max": max(p_rounds),
                    "pallas_parity": p_parity,
                    "pallas_vs_xla_repair": round(
                        pall["rate_median"] / repair["rate_median"], 2)
                    if repair["rate_median"] else None,
                })

        def _next_pow2_local(n):
            p = 1
            while p < n:
                p *= 2
            return p

        for b in batch_sizes:
            # fleet >> batch is the shape the kernel targets (and the
            # production shape: the north star is 65536 invokers) — hold
            # fleet/batch >= 4 as B grows, reported per row
            n = max(n_invokers, 4 * b)
            iters_b = max(4, min(iters, (256 * iters) // b))
            measure(f"b{b}", b, n, _rider_batch(n, b), repeats, iters_b)
        from __graft_entry__ import _example_batch
        b_max = max(batch_sizes)
        n_max = max(n_invokers, 4 * b_max)
        measure("convoy", b_max, n_max,
                _example_batch(n_max, b_max, seed=7), 1, 3)
        try:
            auto_pick = _auto_pick_row(n_invokers, min(256, b_max))
        except Exception as e:  # noqa: BLE001 — the row is advisory
            auto_pick = {"error": repr(e)}
        return {"rows": rows, "parity": parity_all,
                "repeats": repeats,
                "pallas_backend": "interpret" if on_cpu else "device",
                "auto_pick": auto_pick,
                "protocol": "per-action burst held at 4 (the headline "
                            "protocol's B=256/64-action ratio) with "
                            "fleet/batch >= 4; the convoy row is the "
                            "fixed-64-action worst case where deep "
                            "same-action overflow chains serialize the "
                            "repair loop (the scan is expected to win it); "
                            "pallas_repair_* numbers on the CPU twin are "
                            "interpret mode and excluded from headlines",
                "compile_census": _repair_compile_census(batch_sizes)}
    except Exception as e:  # noqa: BLE001 — rider is auxiliary
        if _backend_unavailable(e):
            raise  # the fallback runner re-runs this rider on CPU
        print(f"# repair_vs_scan failed: {e!r}", file=sys.stderr)
        return None


def _pipeline_speedup(repeats: int = 3, total: int = 1200,
                      concurrency: int = 64) -> Optional[dict]:
    """The PR-5 end-to-end rider: the full balancer path with the host-path
    overhaul ON (auto placement kernel, pipelined dispatch, buffer
    donation where the backend supports it, ring assembly — the defaults)
    vs OFF (scan kernel, single in-flight step, no donation,
    list-of-tuples assembly — the bit-exact legacy path). Prewarm is off
    in BOTH configs: the compile-ahead ladder is a cold-start feature, and
    in a short measured window where every bucket is already compiled its
    background compiles are pure 2-core contention noise. Acceptance:
    speedup >= 2x on the same box, zero unexpected recompiles either
    way."""
    try:
        on_rates, off_rates, recompiles = [], [], 0
        for _ in range(repeats):
            on = _balancer_bench(total=total, concurrency=concurrency,
                                 kernel="xla", prewarm=False)
            off = _balancer_bench(total=total, concurrency=concurrency,
                                  kernel="xla", placement_kernel="scan",
                                  pipeline_depth=1, donate_state=False,
                                  ring_assembly=False, prewarm=False)
            on_rates.append(on["activations_per_sec"])
            off_rates.append(off["activations_per_sec"])
            recompiles += (on["recompiles_unexpected"]
                           + off["recompiles_unexpected"])
        on_med = statistics.median(on_rates)
        off_med = statistics.median(off_rates)
        return {
            "rate_pipelined": round(on_med, 1),
            "rate_single_inflight": round(off_med, 1),
            "speedup": round(on_med / off_med, 2) if off_med else None,
            "repeats": repeats,
            "recompiles_unexpected": recompiles,
        }
    except Exception as e:  # noqa: BLE001 — rider is auxiliary
        if _backend_unavailable(e):
            raise  # the fallback runner re-runs this rider on CPU
        print(f"# pipeline_speedup failed: {e!r}", file=sys.stderr)
        return None


def _fleet_sweep_row(mesh, fleet: int, batch_size: int, iters: int,
                     repeats: int, action_slots: int = 64) -> dict:
    """One fleet size of the sharded_fleet_sweep: steady-state rate of the
    SHARDED fused step (fleet repair pair over the mesh, previous step's
    placements released each step — the _bench_kernel protocol), exact
    parity vs the SINGLE-DEVICE repair kernel on the same chained steps
    (decisions, forced bits, books, round counts), the packed entry
    point's compile census (one compile per bucket signature, zero
    unexpected — the balancer's watchdog contract), and the MULTICHIP
    dryrun's heal check folded in (releasing every placement must restore
    full capacity)."""
    import jax
    import jax.numpy as jnp

    from openwhisk_tpu.ops.placement import (init_state,
                                             make_fused_step,
                                             make_fused_step_packed,
                                             release_batch_vector,
                                             schedule_batch_repair,
                                             unpack_step_output)
    from openwhisk_tpu.ops.profiler import (KernelProfiler, ProfilingConfig,
                                            pow2_statics)
    from openwhisk_tpu.parallel.fleet_mesh import (fleet_pair, mesh_shards,
                                                   shard_state)

    n_shards = mesh_shards(mesh)
    batch = _rider_batch(fleet, batch_size, seed=29)
    hidx = jnp.zeros((8,), jnp.int32)
    hval = jnp.zeros((8,), bool)
    hmask = jnp.zeros((8,), bool)
    sched, rel, _ = fleet_pair(mesh, "repair")
    fused_sh = make_fused_step(rel, sched)
    fused_1d = _build_fused("repair")

    def init(shard: bool):
        st = init_state(fleet, [2048] * fleet, n_pad=fleet,
                        action_slots=action_slots)
        return shard_state(st, mesh) if shard else st

    # chained-step parity: sharded vs single-device repair over the same
    # dirtied books (2 steps: speculation + release fold both covered)
    outs = {}
    for tag, fused, shard in (("one", fused_1d, False), ("sh", fused_sh,
                                                         True)):
        st = init(shard)
        rel_inv = jnp.zeros((batch_size,), jnp.int32)
        rel_ok = jnp.zeros((batch_size,), bool)
        acc = []
        for _ in range(2):
            st, chosen, forced, r = fused(
                st, rel_inv, batch.conc_slot, batch.need_mb,
                batch.max_conc, rel_ok, hidx, hval, hmask, batch)
            acc.append((np.asarray(chosen), np.asarray(forced), int(r)))
            rel_inv, rel_ok = jnp.clip(chosen, 0), chosen >= 0
        outs[tag] = (acc, np.asarray(st.free_mb), np.asarray(st.conc_free))
    parity = (
        all(np.array_equal(a, d) and np.array_equal(b, e) and c == f
            for (a, b, c), (d, e, f) in zip(outs["one"][0], outs["sh"][0]))
        and np.array_equal(outs["one"][1], outs["sh"][1])
        and np.array_equal(outs["one"][2], outs["sh"][2]))
    rounds = [r for _, _, r in outs["sh"][0]]

    # steady-state rate of the sharded step (releases chained like
    # _bench_kernel: books stay constant, the loop runs indefinitely)
    state0 = init(True)
    carry = (state0, jnp.zeros((batch_size,), jnp.int32),
             jnp.zeros((batch_size,), bool))

    def step(carry):
        st, rel_inv, rel_ok = carry
        st, chosen, forced, _r = fused_sh(
            st, rel_inv, batch.conc_slot, batch.need_mb, batch.max_conc,
            rel_ok, hidx, hval, hmask, batch)
        return (st, jnp.clip(chosen, 0), chosen >= 0), chosen

    for _ in range(2):
        carry, chosen = step(carry)
    jax.block_until_ready(chosen)
    rates = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(iters):
            carry, chosen = step(carry)
            jax.block_until_ready(chosen)
        rates.append(batch_size * iters / (time.perf_counter() - t0))

    # the MULTICHIP dryrun, folded in: release the final outstanding
    # placements and assert the books heal to full capacity
    st, rel_inv, rel_ok = carry
    st = rel(st, rel_inv, batch.conc_slot, batch.need_mb, batch.max_conc,
             rel_ok)
    heal = int(np.asarray(st.free_mb).sum()) == 2048 * fleet

    # compile census over the PACKED entry point (the wrapper the
    # balancer actually dispatches): repeated calls, one compile per
    # signature, zero unexpected recompiles
    prof = KernelProfiler(ProfilingConfig(enabled=True))
    packed = prof.wrap("fleet_step", make_fused_step_packed(rel, sched),
                       expected=pow2_statics)
    req = np.stack([np.asarray(x, np.int32) for x in
                    (batch.offset, batch.size, batch.home, batch.step_inv,
                     batch.need_mb, batch.conc_slot, batch.max_conc,
                     batch.rand, batch.valid)])
    rel_np = np.zeros((5, batch_size), np.int32)
    rel_np[3] = 1
    health = np.zeros((3, 8), np.int32)
    buf = jnp.asarray(np.concatenate(
        [rel_np.ravel(), health.ravel(), req.ravel()]))
    pstate = init(True)
    out = None
    for _ in range(2):
        pstate, out = packed(pstate, buf, batch_size, 8, batch_size)
    jax.block_until_ready(out)
    rounds_packed = unpack_step_output(np.asarray(out))[3]

    med = statistics.median(rates)
    return {
        "fleet": fleet,
        "shard_rows": fleet // n_shards,
        "rate_median": round(med, 1),
        "rate_min": round(min(rates), 1),
        "rate_max": round(max(rates), 1),
        "p50_step_ms": round(batch_size / med * 1e3, 3) if med else None,
        "rounds": rounds,
        "rounds_packed": rounds_packed,
        "parity_vs_single_device": parity,
        "books_heal": heal,
        "recompiles_unexpected": prof.compiles_unexpected,
        "repeats": repeats,
    }


def _sharded_fleet_sweep_measure(fleet_sizes=(1024, 4096, 16384),
                                 n_devices: int = 8, batch_size: int = 256,
                                 iters: int = 6, repeats: int = 3) -> dict:
    """In-process body of the sharded_fleet_sweep rider (ROADMAP item 2):
    placement rate of the PRODUCTION fleet-mesh pair per fleet size,
    sweeping 1k upward until the device runs out of memory (the HBM
    limit) or the size list ends. On a meshless container the 8-way
    virtual CPU mesh (--xla_force_host_platform_device_count) is the
    honest fallback — the caller tags the line cpu_fallback. The
    MULTICHIP_r0* standalone dryrun is folded into each row's heal
    check; `n_devices`/`mesh_axis` ride the block so BENCH rounds stay
    comparable to those dryruns."""
    import jax

    from openwhisk_tpu.parallel.fleet_mesh import (make_fleet_mesh,
                                                   mesh_axis, mesh_shards)

    # pow2 shard count (the invoker pads must divide evenly): a probe
    # reporting e.g. 6 devices meshes the largest pow2 subset
    shards = 1
    while shards * 2 <= max(1, n_devices):
        shards *= 2
    mesh = make_fleet_mesh(shards)
    out = {
        "n_devices": mesh_shards(mesh),
        "mesh_axis": mesh_axis(mesh),
        "device_platform": mesh.devices.flat[0].platform,
        "backend": jax.default_backend(),
        "batch_size": batch_size,
        "rows": [],
    }
    for fleet in fleet_sizes:
        try:
            out["rows"].append(_fleet_sweep_row(mesh, fleet, batch_size,
                                                iters, repeats))
        except Exception as e:  # noqa: BLE001 — the HBM ceiling is a
            # RESULT, not a failure: record where the sweep stopped
            out["hbm_limit"] = {"stopped_at_fleet": fleet,
                                "error": f"{type(e).__name__}: {e}"[:300]}
            break
    out["parity_all"] = all(r.get("parity_vs_single_device")
                            for r in out["rows"]) if out["rows"] else None
    out["recompiles_unexpected"] = sum(
        r.get("recompiles_unexpected", 0) for r in out["rows"])
    return out


def _probe_mesh(timeout_s: float = 90.0) -> tuple:
    """Device-count probe in a SUBPROCESS with a kill timeout — the
    dead-tunnel guard pattern (_probe_backend): a dead TPU tunnel HANGS
    jax.devices() rather than raising, so the probe needs a kill. Returns
    (n_devices, backend, None) or (None, None, error_string)."""
    import os
    import subprocess
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices(); "
             "print(len(d), jax.default_backend())"],
            capture_output=True, text=True, timeout=timeout_s,
            env=os.environ.copy())
    except subprocess.TimeoutExpired:
        return None, None, f"mesh probe hung > {timeout_s:.0f}s"
    except Exception as e:  # noqa: BLE001 — the probe must never raise
        return None, None, repr(e)
    if r.returncode != 0:
        return None, None, (r.stderr.strip().splitlines()
                            or ["no stderr"])[-1]
    try:
        # LAST stdout line: device-runtime banners may precede the print
        n, backend = r.stdout.strip().splitlines()[-1].split()
        return int(n), backend, None
    except (ValueError, IndexError):
        return None, None, f"unparseable probe output: {r.stdout[-200:]!r}"


def _sharded_fleet_sweep() -> Optional[dict]:
    """ROADMAP item 2 rider: probe mesh availability in a subprocess
    (dead-tunnel guard), then run the sweep in a FRESH process — on the
    real device mesh when the probe sees >= 2 devices, else on the 8-way
    virtual CPU mesh, honestly tagged `backend: "cpu_fallback"`. One JSON
    block through _run_rider; advisory `compared_to` vs the newest prior
    round."""
    n_dev, backend, err = _probe_mesh()
    if err is None and backend != "cpu" and (n_dev or 0) >= 2:
        out = _subprocess_json(
            f"bench._sharded_fleet_sweep_measure(n_devices={n_dev})",
            "FLEETJSON", "sharded fleet sweep")
        if out is None:  # device run died mid-sweep: fall back honestly
            err = "device-mesh sweep subprocess failed"
    else:
        out = None
    if out is None:
        out = _cpu_subprocess_json(
            "bench._sharded_fleet_sweep_measure()", "FLEETJSON",
            "sharded fleet sweep (cpu mesh)", force_devices=True)
        if out is not None:
            out["backend"] = "cpu_fallback"
            if err:
                out["probe_error"] = err
    if out is not None:
        cmp = _compared_to("sharded_fleet_sweep", out)
        if cmp is not None:
            out["compared_to"] = cmp
    return out


def _failover_downtime(rate: float = 128.0, duration: float = 2.0,
                       n_invokers: int = 8) -> Optional[dict]:
    """ISSUE 9 rider: the HA plane's headline number. Drive an open-loop
    burst at a journaled active balancer, snapshot mid-burst, then
    hard-kill it (journaling stops dead, crash semantics: only what the
    fsync batches made durable survives) and promote a standby:
    snapshot restore + deterministic journal-tail replay + first
    successful placement. Reports the restore-path downtime — failure
    DETECTION is deployment config (membership member_timeout_s, default
    5 s) and is excluded, and said so, rather than baked into a number
    that would just echo the timeout knob."""
    import os
    import tempfile

    from openwhisk_tpu.controller.loadbalancer import TpuBalancer
    from openwhisk_tpu.controller.loadbalancer.checkpoint import \
        write_snapshot
    from openwhisk_tpu.controller.loadbalancer.journal import PlacementJournal
    from openwhisk_tpu.controller.loadbalancer.membership import \
        MEMBER_TIMEOUT_S
    from openwhisk_tpu.core.entity import (ActivationId, ControllerInstanceId,
                                           Identity)
    from openwhisk_tpu.messaging import (ActivationMessage,
                                         MemoryMessagingProvider)
    from openwhisk_tpu.utils.transaction import TransactionId
    from tools.loadgen import make_schedule

    async def go() -> dict:
        tmp = tempfile.mkdtemp(prefix="failover-bench-")
        snap_path = os.path.join(tmp, "bal.snap")
        jdir = os.path.join(tmp, "wal")
        provider = MemoryMessagingProvider()
        active = TpuBalancer(provider, ControllerInstanceId("0"),
                             managed_fraction=1.0, blackbox_fraction=0.0,
                             kernel="xla", prewarm=False)
        active.attach_journal(PlacementJournal(jdir))
        await active.start()
        feeds, fleet_stop = await _echo_fleet(provider, n_invokers)
        for _ in range(100):
            if sum(active._healthy) >= n_invokers:
                break
            await asyncio.sleep(0.05)
        actions = [_bench_action(f"fo{i}", memory=128) for i in range(4)]
        ident = Identity.generate("guest")

        def msg_for(a, instance="0"):
            return ActivationMessage(
                TransactionId(), a.fully_qualified_name, a.rev.rev, ident,
                ActivationId.generate(), ControllerInstanceId(instance),
                True, {})

        async def one(bal, i, instance="0"):
            a = actions[i % len(actions)]
            try:
                promise = await bal.publish(a, msg_for(a, instance))
                await promise
                return True
            except Exception:  # noqa: BLE001 — a failed send is a sample
                return False

        # open-loop burst; snapshot at the halfway mark so the journal
        # tail carries real post-snapshot work to replay
        offsets = make_schedule(rate, max(1, int(rate * duration)), seed=5)
        t0 = time.monotonic()
        tasks = []
        snapped = False
        for i, off in enumerate(offsets):
            now = time.monotonic() - t0
            if off > now:
                await asyncio.sleep(off - now)
            if not snapped and off >= duration / 2:
                write_snapshot(active, snap_path)
                snapped = True
            tasks.append(asyncio.ensure_future(one(active, i)))
        if not snapped:
            write_snapshot(active, snap_path)
        done = await asyncio.gather(*tasks)
        snapshot_age_ms = (time.monotonic() - t0 - duration / 2) * 1e3
        # HARD KILL: journaling stops here; anything past the last durable
        # fsync batch is lost, exactly as a SIGKILL would lose it
        await asyncio.sleep(0.05)  # let the tail fsync land (linger_s)
        lag_at_kill = active.journal.lag_batches
        active.journal = None
        t_kill = time.monotonic()

        # standby promotion: restore + replay + first placement
        standby = TpuBalancer(provider, ControllerInstanceId("1"),
                              managed_fraction=1.0, blackbox_fraction=0.0,
                              kernel="xla", prewarm=False)
        journal = PlacementJournal(jdir)
        t_r0 = time.monotonic()
        import json as _json
        with open(snap_path) as f:
            snap_doc = _json.load(f)
        standby.restore(snap_doc)
        t_restored = time.monotonic()
        stats = standby.replay_journal(
            journal.records(int(snap_doc.get("journal_seq", 0))),
            from_seq=int(snap_doc.get("journal_seq", 0)))
        t_replayed = time.monotonic()
        standby.set_leadership(2, True)
        await standby.start()
        first_ok = await one(standby, 0, instance="1")
        t_first = time.monotonic()
        await active.close()
        await standby.close()
        await fleet_stop()
        for f in feeds:
            await f.stop()
        journal.close()
        return {
            "downtime_ms": round((t_first - t_kill) * 1e3, 1),
            "restore_ms": round((t_restored - t_r0) * 1e3, 1),
            "replay_ms": round((t_replayed - t_restored) * 1e3, 1),
            "first_placement_ms": round((t_first - t_replayed) * 1e3, 1),
            "replayed_records": stats["replayed"],
            "replayed_batches": stats["batches"],
            "replay_parity_mismatches": stats["parity_mismatches"],
            "journal_lag_at_kill": lag_at_kill,
            "snapshot_age_ms": round(snapshot_age_ms, 1),
            "burst_completed": int(sum(done)),
            "burst_offered": len(offsets),
            "first_standby_placement_ok": bool(first_ok),
            "offered_rate": rate,
            "n_invokers": n_invokers,
            "excludes_detection_window": True,
            "detection_timeout_s_default": MEMBER_TIMEOUT_S,
        }

    try:
        return asyncio.run(go())
    except Exception as e:  # noqa: BLE001 — rider is auxiliary
        if _backend_unavailable(e):
            raise  # the fallback runner re-runs this rider on CPU
        print(f"# failover_downtime failed: {e!r}", file=sys.stderr)
        return None


def _partition_chaos(rate: float = 64.0, duration: float = 3.0,
                     n_invokers: int = 8, n_partitions: int = 8
                     ) -> Optional[dict]:
    """ISSUE 15 rider: active/active partitioned control under a kill.
    THREE active journaled controllers share a MemoryMessagingProvider
    bus + a fenced echo fleet; the partition ring spreads 8 namespaces
    over them and an open-loop NO-RETRY burst drives all three through
    an edge-like owner-first router (bounded retry on refusal only —
    exactly the 503-safe retry, so a retry can never double-execute).
    Mid-burst one active is killed (membership silenced, its queue
    dropped, its journal detached mid-flight — crash semantics); the
    survivors must detect the silence, claim its partitions at bumped
    epochs, absorb its journal tail filtered to those partitions, and
    keep serving every namespace. A post-kill ZOMBIE salvo is then
    driven at the dead controller's still-live object: its dispatches
    carry superseded epochs — invokers that already heard the bumped
    epoch for the partition discard them, ones that haven't yet run the
    fresh row once (the per-invoker fence is eventually-consistent;
    fenced + executed must cover the whole salvo). Reports downtime
    (detection excluded and reported separately, as in the PR 8
    failover rider), double-executions (duplicate side effects — must
    be 0), zombie salvo accounting, absorbed-partition rate, journal
    seq integrity (zero lost/duplicated per journal), and the retry
    bound."""
    import os
    import shutil
    import tempfile

    from openwhisk_tpu.controller.loadbalancer import TpuBalancer
    from openwhisk_tpu.controller.loadbalancer.journal import PlacementJournal
    from openwhisk_tpu.controller.loadbalancer.membership import (
        ControllerMembership)
    from openwhisk_tpu.controller.loadbalancer.partitions import PartitionRing
    from openwhisk_tpu.core.entity import (ActivationId, ActivationResponse,
                                           ControllerInstanceId, EntityPath,
                                           Identity, InvokerInstanceId, MB,
                                           WhiskActivation)
    from openwhisk_tpu.messaging import (ActivationMessage,
                                         CombinedCompletionAndResultMessage,
                                         MemoryMessagingProvider, MessageFeed,
                                         PingMessage, maybe_coalesce)
    from openwhisk_tpu.messaging.columnar import is_batch_payload
    from openwhisk_tpu.messaging.connector import (decode_batch,
                                                   decode_message)
    from openwhisk_tpu.utils.transaction import TransactionId
    from tools.loadgen import make_schedule

    ring = PartitionRing(n_partitions)

    def ns_for(pid):
        i = 0
        while ring.partition_of(f"ns{i}") != pid:
            i += 1
        return f"ns{i}"

    async def fenced_echo_fleet(provider, n):
        """Echo invokers honoring the per-partition fence — the invoker
        half of the zero-double-execution contract, mirrored from
        invoker/reactive.py's discard rule."""
        executed: list = []        # (activation id, partition)
        fenced = {"discards": 0}
        feeds, instances = [], []
        producer = maybe_coalesce(provider.get_producer())

        async def start_one(inst):
            topic = inst.as_string
            provider.ensure_topic(topic)
            consumer = provider.get_consumer(topic, topic)
            seen_epochs: dict = {}
            box = {}

            async def handle(payload: bytes):
                if is_batch_payload(payload):
                    _kind, msgs = decode_batch(payload)
                else:
                    msgs = [decode_message(ActivationMessage.parse,
                                           payload, "activation")]
                now = time.time()
                by_topic = {}
                for msg in msgs:
                    if msg.fence_epoch is not None \
                            and msg.fence_part is not None:
                        cur = seen_epochs.get(msg.fence_part, -1)
                        if msg.fence_epoch < cur:
                            fenced["discards"] += 1
                            continue  # zombie epoch: no side effect
                        seen_epochs[msg.fence_part] = msg.fence_epoch
                    executed.append((msg.activation_id.asString,
                                     msg.fence_part))
                    act = WhiskActivation(
                        EntityPath(str(msg.user.namespace.name)),
                        msg.action.name, msg.user.subject,
                        msg.activation_id, now, now,
                        ActivationResponse.success({"ok": True}),
                        duration=1)
                    by_topic.setdefault(
                        f"completed{msg.root_controller_index.as_string}",
                        []).append(CombinedCompletionAndResultMessage(
                            msg.transid, act, inst))
                for topic2, acks in by_topic.items():
                    await producer.send_batch(topic2, acks)
                box["feed"].processed()

            feed = MessageFeed(topic, consumer, 256, handle)
            box["feed"] = feed
            feed.start()
            return feed

        provider.ensure_topic("health")
        ping_producer = provider.get_producer()
        for i in range(n):
            inst = InvokerInstanceId(i, user_memory=MB(8192))
            instances.append(inst)
            feeds.append(await start_one(inst))
            await ping_producer.send("health", PingMessage(inst))
        stop_ping = asyncio.Event()

        async def pinger():
            while not stop_ping.is_set():
                for inst in instances:
                    await ping_producer.send("health", PingMessage(inst))
                try:
                    await asyncio.wait_for(stop_ping.wait(), 1.0)
                except asyncio.TimeoutError:
                    pass

        ping_task = asyncio.ensure_future(pinger())

        async def stop():
            stop_ping.set()
            await ping_task
            for f in feeds:
                await f.stop()

        return executed, fenced, stop

    async def go() -> dict:
        tmp = tempfile.mkdtemp(prefix="partition-chaos-")
        provider = MemoryMessagingProvider()
        # fleet observatory (ISSUE 16): all three in-process controllers
        # record into the shared process-global event log (call sites
        # stamp their own instance=), so the kill->silence->claim->
        # absorb->first-placement timeline reconstructs from ONE mono
        # clock and its phase durations telescope exactly
        from openwhisk_tpu.utils.eventlog import GLOBAL_EVENT_LOG
        event_log_was = GLOBAL_EVENT_LOG.enabled
        GLOBAL_EVENT_LOG.enabled = True
        GLOBAL_EVENT_LOG.reset()
        executed, fenced, fleet_stop = await fenced_echo_fleet(
            provider, n_invokers)

        balancers, memberships, journals = {}, {}, {}
        absorb_stats: list = []

        def wire(i):
            bal = TpuBalancer(provider, ControllerInstanceId(str(i)),
                              managed_fraction=1.0, blackbox_fraction=0.0,
                              kernel="xla", prewarm=False, cluster_size=3)
            bal.set_partition_mode(ring)
            journal = PlacementJournal(os.path.join(tmp, f"ctrl{i}"))
            bal.attach_journal(journal)

            def on_partitions(gained, lost, bal=bal, me=i):
                for pid, epoch, *_r in lost:
                    bal.set_partition_leadership(pid, epoch, False)
                by_prev: dict = {}
                for pid, epoch, prev in gained:
                    by_prev.setdefault(prev, []).append((pid, epoch))
                for prev, items in by_prev.items():
                    pids = [p for p, _ in items]
                    if prev is not None:
                        t0 = time.monotonic()
                        st = bal.absorb_partitions(
                            pids, PlacementJournal(
                                os.path.join(tmp, f"ctrl{prev}")))
                        st["absorb_ms"] = round(
                            (time.monotonic() - t0) * 1e3, 1)
                        st["by"] = me
                        absorb_stats.append(st)
                    for pid, epoch in items:
                        bal.set_partition_leadership(pid, epoch, True)

            m = ControllerMembership(
                provider, ControllerInstanceId(str(i)), bal,
                heartbeat_s=0.05, member_timeout_s=0.4, ring=ring,
                on_partitions=on_partitions,
                load_hint=lambda b=bal: float(b.total_active_activations))
            balancers[i], memberships[i], journals[i] = bal, m, journal
            return bal, m

        for i in range(3):
            wire(i)
        for bal in balancers.values():
            await bal.start()
        for m in memberships.values():
            m.start()
        for _ in range(200):
            if sum(len(m.owned_partitions)
                   for m in memberships.values()) == n_partitions \
                    and all(sum(b._healthy) >= n_invokers
                            for b in balancers.values()):
                break
            await asyncio.sleep(0.05)
        else:
            raise RuntimeError("ownership/fleet never converged")

        actions = [_bench_action(f"pc{i}", memory=128) for i in range(4)]
        idents = {pid: Identity.generate(ns_for(pid))
                  for pid in range(n_partitions)}
        dead = set()
        retries = {"refused": 0}
        success_t: dict = {pid: [] for pid in range(n_partitions)}

        def msg_for(a, ident, instance):
            return ActivationMessage(
                TransactionId(), a.fully_qualified_name, a.rev.rev, ident,
                ActivationId.generate(), ControllerInstanceId(instance),
                True, {})

        async def one(i):
            """Edge-emulating driver: owner-first rank order, bounded
            retry on REFUSAL ONLY (the 503-safe class); a timeout or a
            dead upstream mid-flight is a failed sample, never a
            retry."""
            pid = i % n_partitions
            a = actions[i % len(actions)]
            candidates = [c for c in ring.rank(pid, [0, 1, 2])
                          if c not in dead] or [0]
            for attempt, c in enumerate(candidates * 2):
                if c in dead:
                    continue
                bal = balancers[c]
                try:
                    promise = await bal.publish(
                        a, msg_for(a, idents[pid], str(c)))
                except Exception:  # noqa: BLE001 — refusal (standby /
                    # unowned partition): pre-state-change, retry-safe
                    retries["refused"] += 1
                    await asyncio.sleep(0.02 * (attempt + 1))
                    continue
                try:
                    await asyncio.wait_for(promise, 10)
                    success_t[pid].append(time.monotonic())
                    return True
                except Exception:  # noqa: BLE001 — placed-but-lost: the
                    return False   # no-retry rule (could double-execute)
            return False

        offsets = make_schedule(rate, max(1, int(rate * duration)), seed=7)
        kill_at = duration / 3.0
        victim = 0
        t0 = time.monotonic()
        t_kill = None
        tasks = []
        for i, off in enumerate(offsets):
            now = time.monotonic() - t0
            if off > now:
                await asyncio.sleep(off - now)
            if t_kill is None and off >= kill_at:
                # SIGKILL semantics, in-process: membership silenced (no
                # leave), queued-but-undispatched work dropped (futures
                # never resolve), journal detached with its buffered
                # tail lost, and the router sees a dead upstream
                m = memberships[victim]
                await m._ticker.stop()
                await m._feed.stop()
                vb = balancers[victim]
                if vb._flush_task:
                    vb._flush_task.cancel()
                vb._pending.clear()
                vb._req_ring.clear()
                vb.journal = None
                dead.add(victim)
                t_kill = time.monotonic()
                GLOBAL_EVENT_LOG.record("chaos_kill", instance=victim,
                                        parts=sorted(
                                            memberships[victim]
                                            .owned_partitions))
            tasks.append(asyncio.ensure_future(one(i)))
        done = await asyncio.gather(*tasks)

        victim_parts = {pid for pid, o
                        in ring.ownership([0, 1, 2]).items()
                        if o == victim}
        survivors_owned = set()
        t_claimed = None
        for _ in range(400):
            survivors_owned = (memberships[1].owned_partitions
                               | memberships[2].owned_partitions)
            if survivors_owned >= victim_parts:
                t_claimed = time.monotonic()
                break
            await asyncio.sleep(0.02)

        # post-claim service proof per absorbed partition + downtime
        t_post = {}
        for pid in sorted(victim_parts):
            idx = 10_000 + pid
            for _ in range(50):
                if await one(idx):
                    t_post[pid] = time.monotonic()
                    break
                await asyncio.sleep(0.05)

        # zombie salvo: the dead object dispatches with superseded
        # epochs; the fleet fence must discard every one
        zombie_aids = []
        vb = balancers[victim]
        for pid in sorted(victim_parts)[:4]:
            a = actions[0]
            msg = msg_for(a, idents[pid], str(victim))
            zombie_aids.append(msg.activation_id.asString)
            try:
                promise = await vb.publish(a, msg)
                await asyncio.wait_for(promise, 2)
            except Exception:  # noqa: BLE001 — expected: fenced acks
                pass           # never come back
        await asyncio.sleep(0.3)

        executed_ids = [aid for aid, _pid in executed]
        # double executions = the SAME activation's side effect landing
        # twice (duplicate aids). Zombie-salvo rows are FRESH aids the
        # dead controller dispatched at a superseded epoch: an invoker
        # that already heard the new epoch for that partition discards
        # them (fenced), one that hasn't yet runs them ONCE — the
        # per-invoker fence is eventually-consistent by design, and a
        # single execution is not a double. Both outcomes are reported;
        # fenced + executed must account for the whole salvo.
        dup_execs = len(executed_ids) - len(set(executed_ids))
        zombie_execs = sum(1 for aid in zombie_aids
                           if aid in set(executed_ids))

        # journal seq integrity: zero lost / duplicated per journal
        lost_seqs = dup_seqs = 0
        journals_checked = 0
        for i in range(3):
            d = os.path.join(tmp, f"ctrl{i}")
            seqs = [int(r["seq"])
                    for r in PlacementJournal(d).records(0)]
            if not seqs:
                continue
            journals_checked += 1
            dup_seqs += len(seqs) - len(set(seqs))
            lost_seqs += (max(seqs) - min(seqs) + 1) - len(set(seqs))

        detection_s = (round(t_claimed - t_kill, 3)
                       if t_claimed and t_kill else None)
        downtime_s = None
        if t_post and t_claimed:
            downtime_s = round(max(t_post.values()) - t_claimed, 3)

        # reconstructed causal timeline (ISSUE 16): decompose the outage
        # into named phases from the recorded structural events. All
        # marks share one process's monotonic clock, so detect + claim +
        # absorb + first_placement sums to the timeline's own
        # (first_placement - kill) downtime EXACTLY; it is reported
        # beside the service-probe downtime above, which measures with
        # probe-loop granularity.
        from openwhisk_tpu.controller.monitoring import reconstruct_phases
        chaos_events = GLOBAL_EVENT_LOG.recent()
        timeline = reconstruct_phases(chaos_events)
        kill_mono = next((e["mono"] for e in chaos_events
                          if e["kind"] == "chaos_kill"), None)
        timeline["events"] = [
            {"kind": e["kind"], "instance": e.get("instance"),
             "t_s": round(e["mono"] - kill_mono, 4)}
            for e in chaos_events
            if kill_mono is not None and e["mono"] >= kill_mono
            and e["kind"] in ("chaos_kill", "member_silent", "part_claim",
                              "part_ownership", "absorb_start",
                              "absorb_end", "first_placement",
                              "fence_discard")]
        GLOBAL_EVENT_LOG.enabled = event_log_was

        for i, m in memberships.items():
            if i != victim:
                await m.stop()
        for b in balancers.values():
            await b.close()
        await fleet_stop()
        for j in journals.values():
            if j is not None:
                j.close()
        shutil.rmtree(tmp, ignore_errors=True)

        return {
            "downtime_s": downtime_s,
            "detection_s": detection_s,
            "timeline": timeline,
            "double_executions": dup_execs,
            "absorbed_rate": round(
                len(survivors_owned & victim_parts)
                / max(1, len(victim_parts)), 3),
            "victim_partitions": sorted(victim_parts),
            "absorbs": absorb_stats,
            "zombie_salvo": len(zombie_aids),
            "zombie_executions": zombie_execs,
            "zombie_fenced_discards": fenced["discards"],
            "journal_lost_seqs": lost_seqs,
            "journal_duplicated_seqs": dup_seqs,
            "journals_checked": journals_checked,
            "edge_retry_refused": retries["refused"],
            "burst_completed": int(sum(bool(x) for x in done)),
            "burst_offered": len(offsets),
            "offered_rate": rate,
            "n_partitions": n_partitions,
            "n_invokers": n_invokers,
            "excludes_detection_window": True,
        }

    try:
        return asyncio.run(go())
    except Exception as e:  # noqa: BLE001 — rider is auxiliary
        if _backend_unavailable(e):
            raise  # the fallback runner re-runs this rider on CPU
        print(f"# partition_chaos failed: {e!r}", file=sys.stderr)
        return None


def _backend_unavailable(e: BaseException) -> bool:
    """True for the LAZY backend-init failure mode: the subprocess probe
    passed but the first dispatched op inside the measured run raised
    (BENCH_r05 — the tunnel died between probe and run). jax surfaces it
    as RuntimeError('Unable to initialize backend ...')."""
    return isinstance(e, RuntimeError) and \
        "nable to initialize backend" in str(e)


def _rider_subprocess_cpu(fn_name: str) -> Optional[dict]:
    """Re-run one overhead rider in a subprocess pinned to the CPU backend
    (the in-process backend registry already cached the failure, so the
    clean re-run needs a fresh process, like _balancer_host_rows)."""
    return _cpu_subprocess_json(f"bench.{fn_name}()", "RIDERJSON",
                                f"{fn_name} cpu re-run")


def _run_rider(fn_name: str, fn) -> Optional[dict]:
    """Run an overhead rider; when the backend dies LAZILY inside the
    measured run (past the subprocess probe), re-run the rider under
    JAX_PLATFORMS=cpu and tag the result `"backend": "cpu_fallback"` so
    the emitted JSON line stays parseable and honest."""
    try:
        return fn()
    except RuntimeError as e:
        if not _backend_unavailable(e):
            raise
        print(f"# {fn_name}: backend died mid-run ({e}); re-running under "
              "JAX_PLATFORMS=cpu", file=sys.stderr)
        out = _rider_subprocess_cpu(fn_name)
        if out is not None:
            out["backend"] = "cpu_fallback"
        return out


def _cpu_oracle_rate(n: int = N_INVOKERS, reqs: int = 2048) -> float:
    from openwhisk_tpu.models.sharding_policy import (ShardingPolicyState,
                                                      release, schedule)
    st = ShardingPolicyState.build([2048] * n)
    rng = np.random.RandomState(3)
    actions = [(f"ns{a % 8}", f"action{a}", [128, 256, 512][a % 3])
               for a in range(64)]
    t0 = time.perf_counter()
    placed = []
    for _ in range(reqs):
        ns, act, mem = actions[rng.randint(0, 64)]
        c, _ = schedule(st, ns, act, mem)
        placed.append((c, act, mem))
        if len(placed) >= BATCH:
            for c, act, mem in placed:
                if c is not None:
                    release(st, c, act, mem)
            placed.clear()
    return reqs / (time.perf_counter() - t0)


def _sweep() -> None:
    """xla-vs-pallas rate table across fleet/slot configs (stderr)."""
    from openwhisk_tpu.ops.placement_pallas import fits_vmem
    print("# N_invokers  action_slots  xla/s      pallas/s   winner",
          file=sys.stderr)
    for n in (128, 512, 1024, 4096):
        for a in (64, 256):
            x = _bench_kernel("xla", n, a, repeats=3, iters=20)
            if not fits_vmem(n, a):
                print(f"# {n:<11} {a:<13} {x['rate_median']:<10.0f} "
                      f"{'(>VMEM)':<10} xla", file=sys.stderr)
                continue
            p = _bench_kernel("pallas", n, a, repeats=3, iters=20)
            win = "pallas" if p["rate_median"] > x["rate_median"] else "xla"
            print(f"# {n:<11} {a:<13} {x['rate_median']:<10.0f} "
                  f"{p['rate_median']:<10.0f} {win}", file=sys.stderr)


def _probe_backend(timeout_s: float) -> tuple:
    """`jax.devices()` in a SUBPROCESS with a kill timeout. A dead TPU
    tunnel doesn't raise — init HANGS waiting on the wire — so the probe
    needs a kill, not a try/except. Returns (backend_name, None) on
    success, (None, error_string) on failure/timeout."""
    import os
    import subprocess
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.devices(); print(jax.default_backend())"],
            capture_output=True, text=True, timeout=timeout_s,
            env=os.environ.copy())
    except subprocess.TimeoutExpired:
        return None, f"backend init hung > {timeout_s:.0f}s"
    except Exception as e:  # noqa: BLE001 — the probe must never raise
        return None, repr(e)
    if r.returncode != 0:
        return None, (r.stderr.strip().splitlines() or ["no stderr"])[-1]
    return r.stdout.strip(), None


def _ensure_backend(retries: int = 3, delay: float = 2.0,
                    probe_timeout_s: float = 60.0) -> dict:
    """Initialize the JAX backend with retry + backoff (the tunneled TPU
    channel flaps: round 5 shipped an EMPTY BENCH json because a single
    failed init took the whole run down). Each attempt probes in a
    subprocess — a dead tunnel makes `jax.devices()` hang forever, which
    no in-process try/except can rescue. If the configured device never
    comes up, fall back to the CPU backend so every stage still produces a
    number — the result carries `backend_fallback` so readers know."""
    import os
    last = None
    for attempt in range(max(1, retries)):
        backend, err = _probe_backend(probe_timeout_s)
        if backend is not None:
            return {"backend": backend, "fallback": False}
        last = err
        print(f"# backend init failed (attempt {attempt + 1}/{retries}):"
              f" {err}; retrying in {delay:.0f}s", file=sys.stderr)
        time.sleep(delay)
        delay *= 2
    print(f"# backend never came up ({last}); falling back to CPU",
          file=sys.stderr)
    # the in-process backend is still uninitialized (only probe subprocesses
    # touched it): flip BOTH the env (inherited by host-path subprocess
    # stages) and the live config before anything initializes it here
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.devices()  # raises only if even CPU is broken — caught by main()
    return {"backend": jax.default_backend(), "fallback": True,
            "error": last}


def _host_info() -> dict:
    """Box identity for the one-line JSON (ISSUE 11 satellite): BENCH_r0*
    rounds land on a noisy shared machine — python/cpu/loadavg make rounds
    comparable (a 4x loadavg delta explains a slow round better than any
    code diff does)."""
    import platform
    import subprocess
    la = os.getloadavg()[0] if hasattr(os, "getloadavg") else None
    # which code produced this round (ISSUE 19 satellite): a BENCH json
    # on disk outlives branch switches, so the line must carry its own
    # provenance — bench_compare prints it in the diff header
    commit = None
    try:
        r = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], capture_output=True,
            text=True, timeout=5,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        commit = r.stdout.strip() or None
    except Exception:  # noqa: BLE001 — no git is not an error
        commit = None
    return {
        "python": platform.python_version(),
        "cpu_count": os.cpu_count(),
        "loadavg_1m_start": round(la, 2) if la is not None else None,
        "git_commit": commit,
        "round": os.environ.get("BENCH_ROUND") or None,
    }


def _reap_leaked_processes() -> list:
    """ISSUE 20 satellite: a killed prior session can leave controller,
    invoker, serve-funnel or loadgen worker processes holding ports and
    stealing CPU — which silently skews every number this round reports
    (and a leaked TcpBusServer can collide with a fresh one's port).
    Scan /proc for this repo's long-running process signatures, SIGTERM
    (then SIGKILL after a 5 s grace) everything that is not this process
    or one of its ancestors, and log exactly what was reaped."""
    import os
    import signal
    signatures = ("-m openwhisk_tpu.controller", "-m openwhisk_tpu.invoker",
                  "-m openwhisk_tpu.messaging", "-m openwhisk_tpu.standalone",
                  "openwhisk_tpu/controller/__main__",
                  "openwhisk_tpu/invoker/__main__",
                  "containerpool/actionproxy.py",
                  "--serve-funnel", "tools/loadgen.py")
    keep = set()
    pid = os.getpid()
    while pid > 1:  # never kill ourselves or the driver chain above us
        keep.add(pid)
        try:
            with open(f"/proc/{pid}/stat") as f:
                # field 4 (after the parenthesized comm, which may itself
                # contain spaces) is the ppid
                pid = int(f.read().rsplit(")", 1)[1].split()[1])
        except (OSError, ValueError, IndexError):
            break
    try:
        pids = [int(d) for d in os.listdir("/proc") if d.isdigit()]
    except OSError:
        return []
    reaped = []
    for p in pids:
        if p in keep:
            continue
        try:
            with open(f"/proc/{p}/cmdline", "rb") as f:
                cmd = f.read().replace(b"\0", b" ").decode(
                    "utf-8", "replace").strip()
        except OSError:
            continue
        if not any(s in cmd for s in signatures):
            continue
        try:
            os.kill(p, signal.SIGTERM)
        except OSError:
            continue
        reaped.append({"pid": p, "cmd": cmd[:160]})
    if reaped:
        deadline = time.monotonic() + 5.0
        live = {r["pid"] for r in reaped}
        while live and time.monotonic() < deadline:
            time.sleep(0.1)
            live = {p for p in live if os.path.exists(f"/proc/{p}")}
        for p in live:
            try:
                os.kill(p, signal.SIGKILL)
            except OSError:
                pass
        for r in reaped:
            print(f"# reaped leaked process {r['pid']}: {r['cmd']}",
                  file=sys.stderr)
    return reaped


def _run(args) -> Optional[dict]:
    import jax

    if args.sweep:
        _sweep()
        return None

    host_info = _host_info()
    rider_wall_s: dict = {}

    def timed_rider(fn_name: str, fn) -> Optional[dict]:
        """_run_rider + per-rider wall-time into the `host` block, so a
        slow round names the stage that ate it."""
        t0 = time.monotonic()
        try:
            return _run_rider(fn_name, fn)
        finally:
            rider_wall_s[fn_name.lstrip("_")] = round(
                time.monotonic() - t0, 1)

    backend = _ensure_backend()

    kernels = {}
    if args.kernel in ("xla", "both"):
        kernels["xla"] = _bench_kernel("xla", n_invokers=args.fleet)
    if args.kernel in ("pallas", "both"):
        from openwhisk_tpu.ops.placement_pallas import fits_vmem
        if fits_vmem(args.fleet, 256):
            kernels["pallas"] = _bench_kernel("pallas",
                                              n_invokers=args.fleet)
        else:
            print(f"# pallas skipped: {args.fleet}x256 exceeds the VMEM "
                  "budget (XLA path covers large fleets)", file=sys.stderr)
            if args.kernel == "pallas":
                kernels["xla"] = _bench_kernel("xla", n_invokers=args.fleet)

    parity_ok = _parity_check() if args.kernel == "both" else None

    balancer = None
    balancer_host = None
    host_profiling_overhead = None
    host_observatory = None
    recorder_overhead = None
    telemetry_overhead = None
    profiling_overhead = None
    anomaly_overhead = None
    waterfall_overhead = None
    fleet_observatory_overhead = None
    placement_quality = None
    placement_quality_overhead = None
    e2e_open_loop = None
    funnel_10k = None
    repair_vs_scan = None
    pipeline_speedup = None
    bus_coalesce_speedup = None
    failover_downtime = None
    partition_chaos = None
    sharded_fleet_sweep = None
    trace_assembly = None
    trace_plane_overhead = None
    incident_capture = None
    incident_overhead = None
    if not args.quick:
        # the new headline first: the open-loop observatory (sustained
        # activations/s + the per-stage budget the next PR attacks)
        e2e_open_loop = timed_rider("_e2e_open_loop", _e2e_open_loop)
        # ISSUE 20: the real multi-process deployment — front-end worker
        # processes funneling ONE balancer process over the TCP bus,
        # swept to the 10k/s attempt (always CPU-pinned host work)
        funnel_10k = timed_rider("_funnel_10k", _funnel_10k)
        # the host hot-loop observatory (ISSUE 11): its payoff block is
        # the measured target list the 10k/s vectorization PR attacks,
        # and its overhead gate keeps all four planes under the house 5%
        host_observatory = timed_rider("_host_observatory",
                                       _host_observatory)
        host_profiling_overhead = timed_rider("_host_profiling_overhead",
                                              _host_profiling_overhead)
        bus_coalesce_speedup = timed_rider("_bus_coalesce_speedup",
                                           _bus_coalesce_speedup)
        failover_downtime = timed_rider("_failover_downtime",
                                        _failover_downtime)
        # ISSUE 15: active/active partitioned control under a mid-burst
        # kill — downtime, double-executions (must stay 0), absorption
        partition_chaos = timed_rider("_partition_chaos",
                                      _partition_chaos)
        waterfall_overhead = timed_rider("_waterfall_overhead",
                                         _waterfall_overhead)
        # ISSUE 16: the armed-EventLog ambient cost (scrape-pull-only
        # federation, so steady state should measure ~0)
        fleet_observatory_overhead = timed_rider(
            "_fleet_observatory_overhead", _fleet_observatory_overhead)
        # ISSUE 17: the placement quality plane — straggler A/B payoff
        # (regret + shadow divergence with the penalty on vs off) and its
        # <= 5% paired-overhead gate
        placement_quality = timed_rider("_placement_quality",
                                        _placement_quality)
        placement_quality_overhead = timed_rider(
            "_placement_quality_overhead", _placement_quality_overhead)
        # ISSUE 18: the tail-sampled trace observatory — the acceptance
        # legs (floor-exact clean keep, 100% straggler keep, >= 3-process
        # assembly, dead-peer degradation, exemplar resolution) and the
        # paired <= 5% overhead gate on the traced publish path
        trace_assembly = timed_rider("_trace_assembly", _trace_assembly)
        trace_plane_overhead = timed_rider("_trace_plane_overhead",
                                           _trace_plane_overhead)
        # ISSUE 19: the incident forensics observatory — a straggler-
        # driven alert must freeze exactly one >= 5-plane bundle whose
        # journal window time-travel-replays with zero mismatches, and
        # the armed-idle recorder stays under the house 5% gate
        incident_capture = timed_rider("_incident_capture",
                                       _incident_capture)
        incident_overhead = timed_rider("_incident_overhead",
                                        _incident_overhead)
        repair_vs_scan = timed_rider("_repair_vs_scan", _repair_vs_scan)
        # ROADMAP item 2: placement rate per fleet size over the
        # ('fleet',) mesh (the MULTICHIP dryrun folded into the bench)
        sharded_fleet_sweep = timed_rider("_sharded_fleet_sweep",
                                          _sharded_fleet_sweep)
        pipeline_speedup = timed_rider("_pipeline_speedup",
                                       _pipeline_speedup)
        recorder_overhead = timed_rider("_flight_recorder_overhead",
                                        _flight_recorder_overhead)
        telemetry_overhead = timed_rider("_telemetry_overhead",
                                         _telemetry_overhead)
        profiling_overhead = timed_rider("_profiling_overhead",
                                         _profiling_overhead)
        anomaly_overhead = timed_rider("_anomaly_overhead",
                                       _anomaly_overhead)
        rows = _balancer_rows()
        # c64 stays flattened at the top level (older readers); the rows
        # dict carries the per-concurrency detail + phase breakdowns
        balancer = {"backend": jax.default_backend(), **rows["c64"],
                    "rows": rows}
        if jax.default_backend() != "cpu":
            host_rows = _balancer_host_rows()
            if host_rows:
                balancer_host = {"backend": "cpu", **host_rows["c64"],
                                 "rows": host_rows}

    multi = None
    if not args.quick:
        multi = {}
        # the n=1 baseline runs BEFORE AND AFTER the scale-out runs: the
        # tunnel channel drifts minute to minute (r01-r05 history), so a
        # ratio against a single baseline sample is a coin flip — the
        # scaling factor divides by the mean of the two brackets
        for key, n in (("n1", 1), ("n2", 2), ("n4", 4), ("n1_b", 1)):
            try:
                multi[key] = _multi_controller_bench(n, total_per=2500)
            except Exception as e:  # noqa: BLE001 — stage is auxiliary
                print(f"# multi-controller {key} failed: {e!r}",
                      file=sys.stderr)
        r1s = [multi[k]["aggregate_activations_per_sec"]
               for k in ("n1", "n1_b") if k in multi]
        if r1s and "n2" in multi:
            r1 = sum(r1s) / len(r1s)
            r2 = multi["n2"]["aggregate_activations_per_sec"]
            multi["baseline_n1_mean"] = round(r1, 1)
            multi["baseline_n1_samples"] = len(r1s)  # 1 = a bracket failed
            multi["scaling_1_to_2"] = round(r2 / r1, 2) if r1 else None
            multi["note"] = (
                "all controllers + bus + echo fleet share ONE core: "
                "scale-out can only convert device wire-wait into work, so "
                "the factor falls as the host path gets faster (r04's "
                "slower host measured 2.4x here); real deployments give "
                "each controller its own cores")

    cpu_rate = _cpu_oracle_rate()
    # the headline is what the product's kernel="auto" policy resolves to
    # at THIS stage's geometry (fleet padded to a power of two, 256 action
    # slots) — the same resolver TpuBalancer uses, not a re-implementation;
    # both kernel rows ride along in `kernels`
    from openwhisk_tpu.controller.loadbalancer.tpu_balancer import (
        _next_pow2, resolve_auto_kernel)
    default_kernel = resolve_auto_kernel(_next_pow2(args.fleet), 256)
    if default_kernel not in kernels:
        default_kernel = "xla" if "xla" in kernels else "pallas"
    headline = kernels.get(default_kernel) or next(iter(kernels.values()))
    print(f"# device={jax.devices()[0]} backend={jax.default_backend()} "
          f"kernel={default_kernel} "
          f"p50_step={headline['p50_step_ms']:.2f}ms "
          f"cpu_oracle={cpu_rate:.0f}/s parity={parity_ok}", file=sys.stderr)

    # ALWAYS tag the round's backend: bench_compare's advisory
    # backend-mismatch rule needs both sides tagged, and rounds before
    # r06 only carried tags on fallback — an untagged device round
    # diffed against a CPU round read as a 99% regression
    try:
        import jax
        round_backend = jax.default_backend()
    except Exception:  # noqa: BLE001 — a dead backend must not kill the line
        round_backend = "unknown"
    out = {
        "metric": "placements_per_sec",
        "backend": round_backend,
        "value": headline["rate_median"],
        "unit": "placements/s",
        "vs_baseline": round(headline["rate_median"] / TARGET, 3),
        "median_of": headline["repeats"],
        "spread_pct": headline["spread_pct"],
        "kernel_selection": {
            "default": default_kernel,
            "policy": "kernel='auto' (TpuBalancer.resolve_auto_kernel): "
                      "pallas on TPU while the state fits VMEM, else xla "
                      "(large fleets swap to xla on growth)",
            "geometry": {"n_pad": _next_pow2(args.fleet),
                         "action_slots": 256},
            "rationale": "equal median rate at bit-exact parity; pallas "
                         "spread 12-18% vs xla 58-69% across r04-r05 runs",
        },
        "kernels": kernels,
        "parity_ok": parity_ok,
        "cpu_oracle_per_sec": round(cpu_rate, 1),
    }
    if backend["fallback"]:
        out["backend_fallback"] = backend
    if balancer is not None:
        out["balancer"] = balancer
    if balancer_host is not None:
        out["balancer_host_path"] = balancer_host
    if recorder_overhead is not None:
        out["flight_recorder_overhead"] = recorder_overhead
    if telemetry_overhead is not None:
        out["telemetry_overhead"] = telemetry_overhead
    if profiling_overhead is not None:
        out["profiling_overhead"] = profiling_overhead
    if anomaly_overhead is not None:
        out["anomaly_overhead"] = anomaly_overhead
    if waterfall_overhead is not None:
        out["waterfall_overhead"] = waterfall_overhead
    if fleet_observatory_overhead is not None:
        out["fleet_observatory_overhead"] = fleet_observatory_overhead
    if placement_quality is not None:
        out["placement_quality"] = placement_quality
    if placement_quality_overhead is not None:
        out["placement_quality_overhead"] = placement_quality_overhead
    if host_profiling_overhead is not None:
        out["host_profiling_overhead"] = host_profiling_overhead
    if host_observatory is not None:
        out["host_observatory"] = host_observatory
    if e2e_open_loop is not None:
        out["e2e_open_loop"] = e2e_open_loop
    if funnel_10k is not None:
        out["funnel_10k"] = funnel_10k
    if bus_coalesce_speedup is not None:
        out["bus_coalesce_speedup"] = bus_coalesce_speedup
    if failover_downtime is not None:
        out["failover_downtime"] = failover_downtime
    if partition_chaos is not None:
        out["partition_chaos"] = partition_chaos
    if repair_vs_scan is not None:
        out["repair_vs_scan"] = repair_vs_scan
    if sharded_fleet_sweep is not None:
        out["sharded_fleet_sweep"] = sharded_fleet_sweep
    if pipeline_speedup is not None:
        out["pipeline_speedup"] = pipeline_speedup
    if trace_assembly is not None:
        out["trace_assembly"] = trace_assembly
    if trace_plane_overhead is not None:
        out["trace_plane_overhead"] = trace_plane_overhead
    if incident_capture is not None:
        out["incident_capture"] = incident_capture
    if incident_overhead is not None:
        out["incident_overhead"] = incident_overhead
    if any(isinstance(r, dict) and r.get("backend") == "cpu_fallback"
           for r in (recorder_overhead, telemetry_overhead,
                     profiling_overhead, anomaly_overhead,
                     waterfall_overhead, fleet_observatory_overhead,
                     e2e_open_loop,
                     repair_vs_scan, pipeline_speedup,
                     bus_coalesce_speedup, failover_downtime,
                     partition_chaos, sharded_fleet_sweep,
                     trace_assembly, trace_plane_overhead,
                     incident_capture, incident_overhead,
                     host_profiling_overhead, host_observatory)):
        # a rider lost the device mid-run and re-ran on CPU: say so at the
        # top level so trajectory readers never mistake a CPU number for a
        # device number
        out["backend"] = "cpu_fallback"
    if multi:
        out["multi_controller"] = multi
    # the `host` block (ISSUE 11 satellite): box identity + load brackets
    # + per-rider wall-time, so BENCH rounds on the noisy box compare
    la_end = None
    import os as _os
    if hasattr(_os, "getloadavg"):
        la_end = round(_os.getloadavg()[0], 2)
    host_info["loadavg_1m_end"] = la_end
    host_info["rider_wall_s"] = rider_wall_s
    out["host"] = host_info
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--kernel", choices=("xla", "pallas", "both"),
                    default="both")
    ap.add_argument("--fleet", type=int, default=N_INVOKERS,
                    help="invoker count for the kernel stages (the "
                         "north-star config is 65536)")
    ap.add_argument("--quick", action="store_true",
                    help="skip the balancer-level benchmark")
    ap.add_argument("--sweep", action="store_true",
                    help="print an (N x A) xla-vs-pallas table to stderr")
    args = ap.parse_args()

    # preamble (ISSUE 20): reap leaked prior-session service processes
    # BEFORE any round measures — a survivor controller/invoker/loadgen
    # fleet skews every number and can hold the bus ports
    try:
        reaped = _reap_leaked_processes()
    except Exception as e:  # noqa: BLE001 — the reaper must never kill a run
        print(f"# leaked-process reap failed: {e!r}", file=sys.stderr)
        reaped = []

    # the driver contract: ONE parseable JSON line on stdout, ALWAYS — a
    # dead device/tunnel produces {"error": ...} with value null instead of
    # an rc=1 traceback and an empty BENCH_rNN.json (round-5 verdict)
    try:
        out = _run(args)
    except Exception as e:  # noqa: BLE001 — every failure becomes JSON
        import traceback
        traceback.print_exc(file=sys.stderr)
        print(json.dumps({
            "metric": "placements_per_sec",
            "value": None,
            "unit": "placements/s",
            "error": f"{type(e).__name__}: {e}",
        }))
        return
    if out is not None:
        if reaped:
            out["reaped_leaked_processes"] = reaped
        print(json.dumps(out))


if __name__ == "__main__":
    main()

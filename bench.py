"""Benchmark: activation placement decisions/sec on the TPU placement kernel.

Measures the steady-state rate of the balancer's device step — ONE fused
program (ops.placement.make_fused_step: previous batch's release fold +
health fold + a B=256 schedule) over a 1024-invoker fleet, exactly the
program TpuBalancer._device_step dispatches per micro-batch. Books are held
constant (each step releases the prior step's placements) so the loop runs
indefinitely.

Baseline: BASELINE.json targets >= 50,000 placements/s (reference point: the
CPU ShardingContainerPoolBalancer inner loop, which this kernel replaces).
`vs_baseline` = measured rate / 50,000. A CPU-oracle rate is also measured
for context (stderr).

Prints ONE JSON line on stdout.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np

N_INVOKERS = 1024
BATCH = 256
WARMUP = 5
ITERS = 40
TARGET = 50_000.0


def main() -> None:
    import jax
    import jax.numpy as jnp

    from __graft_entry__ import _example_batch
    from openwhisk_tpu.ops.placement import init_state, make_fused_step

    state0 = init_state(N_INVOKERS, [2048] * N_INVOKERS, action_slots=256)
    batch = _example_batch(N_INVOKERS, BATCH, seed=7)

    # the balancer's actual device program: fold releases + health flips +
    # schedule, compiled as ONE call (ops.placement.make_fused_step). The
    # releases fed in are the previous batch's placements, books constant.
    fused = make_fused_step()
    hidx = jnp.zeros((8,), jnp.int32)
    hval = jnp.zeros((8,), bool)
    hmask = jnp.zeros((8,), bool)

    def step(carry):
        state, rel_inv, rel_ok = carry
        state, chosen, forced = fused(
            state, rel_inv, batch.conc_slot, batch.need_mb, batch.max_conc,
            rel_ok, hidx, hval, hmask, batch)
        return (state, jnp.clip(chosen, 0), chosen >= 0), chosen

    carry = (state0, jnp.zeros((BATCH,), jnp.int32), jnp.zeros((BATCH,), bool))
    for _ in range(WARMUP):
        carry, chosen = step(carry)
    jax.block_until_ready(carry)

    lat = []
    t0 = time.perf_counter()
    for _ in range(ITERS):
        t1 = time.perf_counter()
        carry, chosen = step(carry)
        jax.block_until_ready(chosen)
        lat.append(time.perf_counter() - t1)
    dt = time.perf_counter() - t0
    rate = BATCH * ITERS / dt
    p50_ms = sorted(lat)[len(lat) // 2] * 1e3

    # CPU oracle context (the reference scheduling loop, same trace shape)
    cpu_rate = _cpu_oracle_rate()
    print(f"# device={jax.devices()[0]} p50_step={p50_ms:.2f}ms "
          f"cpu_oracle={cpu_rate:.0f}/s", file=sys.stderr)

    print(json.dumps({
        "metric": "placements_per_sec",
        "value": round(rate, 1),
        "unit": "placements/s",
        "vs_baseline": round(rate / TARGET, 3),
    }))


def _cpu_oracle_rate(n: int = N_INVOKERS, reqs: int = 2048) -> float:
    from openwhisk_tpu.models.sharding_policy import (ShardingPolicyState,
                                                      release, schedule)
    st = ShardingPolicyState.build([2048] * n)
    rng = np.random.RandomState(3)
    actions = [(f"ns{a % 8}", f"action{a}", [128, 256, 512][a % 3])
               for a in range(64)]
    t0 = time.perf_counter()
    placed = []
    for i in range(reqs):
        ns, act, mem = actions[rng.randint(0, 64)]
        c, _ = schedule(st, ns, act, mem)
        placed.append((c, act, mem))
        if len(placed) >= BATCH:
            for c, act, mem in placed:
                if c is not None:
                    release(st, c, act, mem)
            placed.clear()
    return reqs / (time.perf_counter() - t0)


if __name__ == "__main__":
    main()

"""Semantic versions (ref common/scala/.../core/entity/SemVer.scala)."""
from __future__ import annotations

from functools import total_ordering


@total_ordering
class SemVer:
    __slots__ = ("major", "minor", "patch")

    def __init__(self, major: int = 0, minor: int = 0, patch: int = 1):
        if major < 0 or minor < 0 or patch < 0 or (major, minor, patch) == (0, 0, 0):
            raise ValueError(f"bad semantic version {major}.{minor}.{patch}")
        self.major, self.minor, self.patch = major, minor, patch

    @classmethod
    def from_string(cls, s: str) -> "SemVer":
        parts = (s.split(".") + ["0", "0"])[:3]
        return cls(int(parts[0]), int(parts[1] or 0), int(parts[2] or 0))

    def up_major(self) -> "SemVer":
        return SemVer(self.major + 1, 0, 0)

    def up_minor(self) -> "SemVer":
        return SemVer(self.major, self.minor + 1, 0)

    def up_patch(self) -> "SemVer":
        return SemVer(self.major, self.minor, self.patch + 1)

    def _key(self):
        return (self.major, self.minor, self.patch)

    def __eq__(self, other):
        return isinstance(other, SemVer) and self._key() == other._key()

    def __lt__(self, other):
        return self._key() < other._key()

    def __hash__(self):
        return hash(self._key())

    def __repr__(self):
        return f"{self.major}.{self.minor}.{self.patch}"

    def to_json(self) -> str:
        return repr(self)

    @classmethod
    def from_json(cls, j) -> "SemVer":
        return cls.from_string(str(j))

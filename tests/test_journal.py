"""The HA plane, tier-1 half (ISSUE 9): write-ahead placement journal
framing/corruption posture, deterministic snapshot+journal replay parity
(the NumPy/CPU-twin re-execution of the recorded packed steps must
re-derive bit-identical books AND the journaled decisions), epoch-fenced
leadership, the invoker's zombie-batch fence, and the standby refusal
path. The kill-mid-burst chaos proof lives in tests/test_ha_chaos.py
(slow); everything here is in-process and fast."""
import asyncio
import json
import os
import time

import numpy as np
import pytest

from openwhisk_tpu.controller.loadbalancer import (LoadBalancerException,
                                                   TpuBalancer)
from openwhisk_tpu.controller.loadbalancer.checkpoint import (
    BalancerSnapshotter, load_snapshot, write_snapshot)
from openwhisk_tpu.controller.loadbalancer.journal import (PlacementJournal,
                                                           journal_from_config)
from openwhisk_tpu.controller.loadbalancer.membership import \
    ControllerMembership
from openwhisk_tpu.core.entity import ControllerInstanceId, Identity
from openwhisk_tpu.messaging import MemoryMessagingProvider

from tests.test_balancers import _fleet, _ping_all, make_action, make_msg


def _balancer(provider, instance="0", **kw):
    return TpuBalancer(provider, ControllerInstanceId(instance),
                       managed_fraction=1.0, blackbox_fraction=0.0, **kw)


class TestJournalFraming:
    def test_roundtrip_rotation_prune_and_lag(self, tmp_path):
        j = PlacementJournal(str(tmp_path), segment_bytes=256, fsync_batch=2)
        for s in range(1, 40):
            j.append({"t": "x", "seq": s})
        assert j.flush()
        assert j.lag_batches == 0
        assert [r["seq"] for r in j.records(0)] == list(range(1, 40))
        assert [r["seq"] for r in j.records(30)] == list(range(31, 40))
        assert j.last_seq() == 39
        segs = j._segments()
        assert len(segs) > 3, "segment rotation must split the log"
        # prune everything a seq-20 snapshot covers; the tail must survive
        assert j.prune(20) >= 1
        assert [r["seq"] for r in j.records(20)] == list(range(21, 40))
        j.close()

    def test_fsync_p99_and_gauges(self, tmp_path):
        from openwhisk_tpu.utils.logging import MetricEmitter
        j = PlacementJournal(str(tmp_path))
        j.append({"t": "x", "seq": 1})
        assert j.flush()
        m = MetricEmitter()
        j.export_gauges(m)
        assert m.gauge_value("loadbalancer_journal_lag_batches") == 0
        assert m.gauge_value("loadbalancer_journal_bytes") > 0
        assert m.gauge_value("loadbalancer_journal_fsync_p99_ms") is not None
        j.close()

    def test_config_off_switch(self, tmp_path, monkeypatch):
        monkeypatch.setenv("CONFIG_whisk_ha_journal_enabled", "false")
        assert journal_from_config(str(tmp_path)) is None
        monkeypatch.setenv("CONFIG_whisk_ha_journal_enabled", "true")
        j = journal_from_config(str(tmp_path))
        assert j is not None
        j.close()


class TestJournalCorruption:
    """Satellite: a CRC-failing or half-written tail record truncates the
    journal at the last good frame and logs — never aborts boot."""

    def _write(self, tmp_path, n=10):
        j = PlacementJournal(str(tmp_path), fsync_batch=1)
        for s in range(1, n + 1):
            j.append({"t": "x", "seq": s})
        assert j.flush()
        j.close()
        segs = sorted(p for p in os.listdir(tmp_path) if p.endswith(".seg"))
        return os.path.join(str(tmp_path), segs[-1])

    def test_torn_tail_truncates_at_last_good_frame(self, tmp_path):
        path = self._write(tmp_path)
        with open(path, "r+b") as f:
            f.truncate(os.path.getsize(path) - 3)  # half-written record
        j = PlacementJournal(str(tmp_path))
        assert [r["seq"] for r in j.records(0)] == list(range(1, 10))
        # appending resumes after the torn frame is cut, seqs stay unique
        j.append({"t": "x", "seq": 10})
        assert j.flush()
        assert j.last_seq() == 10
        j.close()

    def test_crc_flip_truncates(self, tmp_path):
        path = self._write(tmp_path)
        data = bytearray(open(path, "rb").read())
        data[-2] ^= 0xFF  # corrupt the last record's payload
        open(path, "wb").write(bytes(data))
        j = PlacementJournal(str(tmp_path))
        recs = list(j.records(0))
        assert [r["seq"] for r in recs] == list(range(1, 10))
        j.close()

    def test_mid_log_corruption_stops_replay_there(self, tmp_path):
        path = self._write(tmp_path)
        data = bytearray(open(path, "rb").read())
        data[len(data) // 2] ^= 0xFF
        open(path, "wb").write(bytes(data))
        j = PlacementJournal(str(tmp_path))
        recs = list(j.records(0))
        # a prefix replays; nothing after the corruption is trusted
        assert recs and recs[-1]["seq"] < 10
        j.close()

    def test_zombie_flush_lands_in_own_segment_and_replay_drops_it(
            self, tmp_path):
        """Review regression: a paused-then-resumed zombie active may
        flush an already-buffered batch AFTER a standby claimed the next
        epoch. The promoted active always appends into a FRESH segment,
        so the late write cannot interleave with (CRC-corrupt) the new
        epoch's frames — and replay drops the stale-epoch records."""
        zombie = PlacementJournal(str(tmp_path), fsync_batch=1)
        for s in range(1, 11):
            zombie.append({"t": "x", "seq": s, "epoch": 1})
        assert zombie.flush()
        # promotion: the new active read 1..10 and continues under epoch 2
        active = PlacementJournal(str(tmp_path), fsync_batch=1)
        assert active.last_seq() == 10
        for s in range(11, 16):
            active.append({"t": "x", "seq": s, "epoch": 2})
        assert active.flush()
        # the zombie resumes and flushes overlapping-seq stale frames
        for s in range(11, 14):
            zombie.append({"t": "x", "seq": s, "epoch": 1})
        assert zombie.flush()
        zombie.close()
        active.close()
        # every frame of BOTH epochs is still intact on disk (no corrupt
        # interleave), and the new epoch's full tail is readable
        recs = list(PlacementJournal(str(tmp_path)).records(0))
        epoch2 = [r["seq"] for r in recs if r.get("epoch") == 2]
        assert epoch2 == [11, 12, 13, 14, 15]

        # the balancer's replay drops the zombie's stale-epoch records
        async def go():
            provider = MemoryMessagingProvider()
            bal = _balancer(provider)
            stats = bal.replay_journal(recs)
            await bal.close()
            return stats

        stats = asyncio.run(go())
        assert stats["stale_epoch_dropped"] == 3

    def test_torn_old_epoch_segment_does_not_hide_newer_epoch(
            self, tmp_path):
        """A tear at the end of the dead epoch's segment (its crash) must
        not swallow the NEW epoch's later segment: replay continues across
        the gap exactly when the next segment opens a higher epoch."""
        old = PlacementJournal(str(tmp_path), fsync_batch=1)
        for s in range(1, 6):
            old.append({"t": "x", "seq": s, "epoch": 1})
        assert old.flush()
        old.close()
        new = PlacementJournal(str(tmp_path), fsync_batch=1)
        for s in range(6, 9):
            new.append({"t": "x", "seq": s, "epoch": 2})
        assert new.flush()
        new.close()
        segs = sorted(p for p in os.listdir(tmp_path) if p.endswith(".seg"))
        assert len(segs) == 2, "each writer must own its own segment"
        first = os.path.join(str(tmp_path), segs[0])
        with open(first, "r+b") as f:
            f.truncate(os.path.getsize(first) - 3)  # zombie died mid-write
        recs = list(PlacementJournal(str(tmp_path)).records(0))
        assert [r["seq"] for r in recs] == [1, 2, 3, 4, 6, 7, 8]

    def test_unknown_record_type_skipped_not_fatal(self, tmp_path):
        async def go():
            provider = MemoryMessagingProvider()
            bal = _balancer(provider)
            stats = bal.replay_journal(
                [{"t": "from_the_future", "seq": 1}])
            await bal.close()
            return stats

        stats = asyncio.run(go())
        assert stats["replayed"] == 1 and stats["last_seq"] == 1


class TestSnapshotHardening:
    """Satellite: version + CRC32 on the snapshot envelope; torn or
    tampered files are rejected cheaply (cold start, never an abort)."""

    def test_snapshot_carries_version_and_crc(self, tmp_path):
        path = str(tmp_path / "bal.snap")

        async def go():
            provider = MemoryMessagingProvider()
            bal = _balancer(provider)
            write_snapshot(bal, path)
            await bal.close()

        asyncio.run(go())
        doc = json.load(open(path))
        assert doc["version"] >= 2 and isinstance(doc["crc32"], int)

    def test_tampered_payload_rejected(self, tmp_path):
        path = str(tmp_path / "bal.snap")

        async def go():
            provider = MemoryMessagingProvider()
            bal = _balancer(provider)
            write_snapshot(bal, path)
            doc = json.load(open(path))
            doc["n_pad"] = doc["n_pad"] * 2  # bit rot with intact JSON
            json.dump(doc, open(path, "w"))
            cold = _balancer(provider, "1")
            ok = load_snapshot(cold, path)
            await bal.close()
            await cold.close()
            return ok

        assert asyncio.run(go()) is False


class TestReplayParity:
    """Tentpole acceptance, fast half: snapshot + journal-tail replay
    re-derives bit-identical books on the CPU twin (deterministic kernel
    re-execution), and the re-derived decisions match the journaled
    readbacks (parity_mismatches == 0)."""

    def test_snapshot_plus_tail_replay_rebuilds_books_bit_exact(
            self, tmp_path):
        snap = str(tmp_path / "bal.snap")
        jdir = str(tmp_path / "wal")

        async def go():
            provider = MemoryMessagingProvider()
            bal = _balancer(provider)
            bal.attach_journal(PlacementJournal(jdir))
            await bal.start()
            invokers, producer = await _fleet(provider, 4, delay=0.4)
            await _ping_all(invokers, producer)
            ident = Identity.generate("guest")
            actions = [make_action(f"jr{i}", memory=128 + 128 * (i % 2))
                       for i in range(3)]
            # wave 1 holds, snapshot mid-life, wave 2 holds + completions
            # (so the journal tail carries batch, ack AND fold records)
            p1 = [await bal.publish(a, make_msg(a, ident, True))
                  for a in actions for _ in range(3)]
            write_snapshot(bal, snap)
            p2 = [await bal.publish(a, make_msg(a, ident, True))
                  for a in actions for _ in range(2)]
            await asyncio.gather(*[asyncio.wait_for(p, 10) for p in p1 + p2])
            for _ in range(50):  # quiesce: all releases folded
                if not (bal._pending or bal._releases
                        or bal._inflight_steps):
                    break
                await asyncio.sleep(0.1)
            await asyncio.sleep(0.3)
            assert bal.journal.flush()

            cold = _balancer(provider, "1")
            reader = PlacementJournal(jdir)
            snap_doc = json.load(open(snap))
            cold.restore(snap_doc)
            stats = cold.replay_journal(
                reader.records(snap_doc["journal_seq"]),
                from_seq=snap_doc["journal_seq"])
            same_free = np.array_equal(np.asarray(cold.state.free_mb),
                                       np.asarray(bal.state.free_mb))
            same_conc = np.array_equal(np.asarray(cold.state.conc_free),
                                       np.asarray(bal.state.conc_free))
            regs = [i.instance for i in cold._registry]
            await bal.close()
            await cold.close()
            for inv in invokers:
                await inv.stop()
            return same_free, same_conc, stats, regs

        same_free, same_conc, stats, regs = asyncio.run(go())
        assert same_free, "memory books must replay bit-exact"
        assert same_conc, "concurrency books must replay bit-exact"
        assert stats["batches"] >= 1, "the tail must contain real batches"
        assert stats["parity_mismatches"] == 0, \
            "re-derived decisions must equal the journaled readback"
        assert regs == [0, 1, 2, 3]

    def test_full_history_replay_without_snapshot(self, tmp_path):
        """A journal whose first record is seq 1 can rebuild the books
        from nothing (registration records included)."""
        jdir = str(tmp_path / "wal")

        async def go():
            provider = MemoryMessagingProvider()
            bal = _balancer(provider)
            bal.attach_journal(PlacementJournal(jdir))
            await bal.start()
            invokers, producer = await _fleet(provider, 2, delay=0.3)
            await _ping_all(invokers, producer)
            ident = Identity.generate("guest")
            action = make_action("jfull", memory=256)
            ps = [await bal.publish(action, make_msg(action, ident, True))
                  for _ in range(4)]
            await asyncio.gather(*[asyncio.wait_for(p, 10) for p in ps])
            for _ in range(50):
                if not (bal._pending or bal._releases
                        or bal._inflight_steps):
                    break
                await asyncio.sleep(0.1)
            await asyncio.sleep(0.3)
            assert bal.journal.flush()
            cold = _balancer(provider, "1")
            ok = load_snapshot(cold, str(tmp_path / "missing.snap"),
                               journal=PlacementJournal(jdir))
            same = np.array_equal(np.asarray(cold.state.free_mb),
                                  np.asarray(bal.state.free_mb))
            regs = [i.instance for i in cold._registry]
            await bal.close()
            await cold.close()
            for inv in invokers:
                await inv.stop()
            return ok, same, regs

        ok, same, regs = asyncio.run(go())
        assert ok is False, "no snapshot file: load reports a cold start"
        assert same, "…but the full-history journal rebuilt the books"
        assert regs == [0, 1]

    def test_journal_off_is_bitexact_noop(self, tmp_path):
        """Acceptance: the off path (no attached journal) behaves exactly
        like today — no records, no seq movement, snapshot unchanged
        modulo the version/crc envelope."""

        async def go():
            provider = MemoryMessagingProvider()
            bal = _balancer(provider)
            await bal.start()
            invokers, producer = await _fleet(provider, 2)
            await _ping_all(invokers, producer)
            ident = Identity.generate("guest")
            action = make_action("joff", memory=256)
            ps = [await bal.publish(action, make_msg(action, ident, True))
                  for _ in range(4)]
            await asyncio.gather(*[asyncio.wait_for(p, 10) for p in ps])
            seq = bal._journal_seq
            snap = bal.snapshot()
            await bal.close()
            for inv in invokers:
                await inv.stop()
            return seq, snap

        seq, snap = asyncio.run(go())
        assert seq == 0 and snap["journal_seq"] == 0


class TestLeadership:
    """Epoch-fenced active/standby over the bus (membership.py)."""

    def _membership(self, provider, i, events, heartbeat=0.05, timeout=0.25):
        class BalancerStub:
            cluster_size = 2
            metrics = None

            def update_cluster(self, n):
                self.cluster_size = n

        async def cb(epoch, active):
            events[i].append((epoch, active))

        m = ControllerMembership(provider, ControllerInstanceId(str(i)),
                                 BalancerStub(), heartbeat_s=heartbeat,
                                 member_timeout_s=timeout, ha=True,
                                 on_leadership=cb)
        m.start()
        return m

    def test_lowest_live_claims_then_standby_takes_over_with_higher_epoch(
            self):
        async def go():
            provider = MemoryMessagingProvider()
            events = {0: [], 1: []}
            m0 = self._membership(provider, 0, events)
            m1 = self._membership(provider, 1, events)
            await asyncio.sleep(1.0)
            assert m0.is_active and not m1.is_active
            assert m0.leadership_epoch == 1 == m1.leadership_epoch
            # hard death: no leave, just silence
            await m0._ticker.stop()
            await m0._feed.stop()
            for _ in range(100):
                if m1.is_active:
                    break
                await asyncio.sleep(0.05)
            assert m1.is_active and m1.leadership_epoch == 2
            assert events[0] == [(1, True)]
            assert events[1] == [(2, True)]
            await m1.stop()
            return True

        assert asyncio.run(go())

    def test_rejoined_old_active_stays_standby_and_zombie_demotes(self):
        async def go():
            provider = MemoryMessagingProvider()
            events = {0: [], 1: []}
            m1 = self._membership(provider, 1, events)
            await asyncio.sleep(0.8)
            assert m1.is_active and m1.leadership_epoch == 1
            # instance 0 joins late: lower instance, but epoch 1 is already
            # claimed and alive — it must NOT steal the leadership
            m0 = self._membership(provider, 0, events)
            await asyncio.sleep(0.8)
            assert m1.is_active and not m0.is_active
            assert m0.leadership_epoch == 1
            # zombie demotion: a forged higher-epoch claim supersedes
            m1._observe_claim(5, 0)
            assert not m1.is_active and m1.leadership_epoch == 5
            await asyncio.sleep(0.1)  # the demotion callback is spawned
            assert events[1][-1] == (5, False)
            await m0.stop()
            await m1.stop()
            return True

        assert asyncio.run(go())


class TestPerPartitionZombieDemotion:
    """ISSUE 15 satellite: a rejoined old owner with a stale epoch stays
    demoted for exactly the partitions it lost while keeping the ones it
    still owns; replay drops stale-epoch records per partition. (The
    full active/active matrix lives in tests/test_partitions.py — this
    is the journal-facing half.)"""

    def test_balancer_keeps_placing_owned_partitions_after_losing_one(
            self):
        from openwhisk_tpu.controller.loadbalancer.partitions import \
            PartitionRing

        async def go():
            provider = MemoryMessagingProvider()
            ring = PartitionRing(8)
            bal = _balancer(provider)
            bal.set_partition_mode(ring)
            await bal.start()
            invokers, producer = await _fleet(provider, 2)
            await _ping_all(invokers, producer)
            action = make_action("zd", memory=128)

            def ns_for(pid, tag):
                i = 0
                while ring.partition_of(f"{tag}{i}") != pid:
                    i += 1
                return f"{tag}{i}"

            bal.set_partition_leadership(1, 2, True)
            bal.set_partition_leadership(5, 2, True)
            # partition 1 superseded elsewhere (epoch 3): demoted for 1,
            # still the active for 5
            bal.set_partition_leadership(1, 3, False)
            with pytest.raises(LoadBalancerException):
                await bal.publish(action, make_msg(
                    action, Identity.generate(ns_for(1, "x")), True))
            p = await bal.publish(action, make_msg(
                action, Identity.generate(ns_for(5, "y")), True))
            await asyncio.wait_for(p, 10)
            await asyncio.sleep(0.1)
            stamps = [(m.fence_part, m.fence_epoch)
                      for inv in invokers for m in inv.handled]
            await bal.close()
            for inv in invokers:
                await inv.stop()
            return stamps

        stamps = asyncio.run(go())
        assert stamps and all(s == (5, 2) for s in stamps), \
            "only the still-owned partition may dispatch, at its epoch"

    def test_replay_drops_stale_partition_epochs_only(self, tmp_path):
        """Per-partition freshness bound over REAL records: with a higher
        epoch for partition A opening the stream, A's older-epoch batches
        drop at replay while partition B's (same journal, same epochs)
        replay untouched."""
        from openwhisk_tpu.controller.loadbalancer.partitions import \
            PartitionRing

        jdir = str(tmp_path / "walp")

        async def go():
            provider = MemoryMessagingProvider()
            ring = PartitionRing(8)
            bal = _balancer(provider)
            bal.set_partition_mode(ring)
            bal.attach_journal(PlacementJournal(jdir))
            await bal.start()
            invokers, producer = await _fleet(provider, 2)
            await _ping_all(invokers, producer)
            action = make_action("zr", memory=128)

            def ns_for(pid, tag):
                i = 0
                while ring.partition_of(f"{tag}{i}") != pid:
                    i += 1
                return f"{tag}{i}"

            bal.set_partition_leadership(1, 2, True)
            bal.set_partition_leadership(5, 2, True)
            for ns in (ns_for(1, "a"), ns_for(5, "b")):
                ident = Identity.generate(ns)
                for _ in range(2):
                    p = await bal.publish(action,
                                          make_msg(action, ident, True))
                    await asyncio.wait_for(p, 10)
            await asyncio.sleep(0.2)
            assert bal.journal.flush()
            recs = list(PlacementJournal(jdir).records(0))
            a_real = [r for r in recs if r.get("t") == "batch"
                      and r.get("parts") == [1]]
            # forge the supersession bound AT THE FRONT of the stream: a
            # first record carrying partition 1 at epoch 3 (what the new
            # owner's opening record would stamp) makes every epoch-2
            # partition-1 batch after it a zombie's late flush
            bound = dict(a_real[0], seq=0)
            bound["pe"] = {"1": 3}

            class Stream:
                @staticmethod
                def records(after_seq=0):
                    return iter([bound] + recs)

            surv = _balancer(provider, "1")
            surv.set_partition_mode(ring)
            await surv.start()
            stats = surv.absorb_partitions([1, 5], Stream())
            b_real = [r for r in recs if r.get("t") == "batch"
                      and r.get("parts") == [5]]
            await bal.close()
            await surv.close()
            for inv in invokers:
                await inv.stop()
            return stats, len(a_real), len(b_real)

        stats, n_a, n_b = asyncio.run(go())
        assert n_a >= 1 and n_b >= 1
        assert stats["stale_epoch_dropped"] >= n_a, \
            "the superseded partition's older-epoch batches must drop"
        assert stats["replayed"] >= n_b, \
            "the untouched partition's batches must replay"


class TestStandbyAndFencing:
    def test_standby_refuses_publish_until_promoted(self):
        async def go():
            provider = MemoryMessagingProvider()
            bal = _balancer(provider)
            await bal.start()
            invokers, producer = await _fleet(provider, 2)
            await _ping_all(invokers, producer)
            ident = Identity.generate("guest")
            action = make_action("stby", memory=256)
            bal.set_leadership(0, False)
            with pytest.raises(LoadBalancerException):
                await bal.publish(action, make_msg(action, ident, True))
            bal.set_leadership(3, True)
            p = await bal.publish(action, make_msg(action, ident, True))
            await asyncio.wait_for(p, 10)
            await asyncio.sleep(0.1)
            # the dispatched message carries the fencing epoch
            fences = [m.fence_epoch for inv in invokers
                      for m in inv.handled]
            await bal.close()
            for inv in invokers:
                await inv.stop()
            return fences

        fences = asyncio.run(go())
        assert fences and all(f == 3 for f in fences)

    def test_standby_snapshotter_never_dumps(self, tmp_path):
        path = str(tmp_path / "standby.snap")

        async def go():
            provider = MemoryMessagingProvider()
            bal = _balancer(provider)
            bal.set_leadership(0, False)
            snap = BalancerSnapshotter(bal, path, interval=0.03).start()
            await asyncio.sleep(0.2)
            await snap.stop(final_dump=True)
            exists_standby = os.path.exists(path)
            # promoted: the same snapshotter wiring dumps again
            bal.set_leadership(1, True)
            snap2 = BalancerSnapshotter(bal, path, interval=0.03).start()
            for _ in range(100):
                if os.path.exists(path):
                    break
                await asyncio.sleep(0.02)
            await snap2.stop()
            exists_active = os.path.exists(path)
            await bal.close()
            return exists_standby, exists_active

        exists_standby, exists_active = asyncio.run(go())
        assert not exists_standby, \
            "a standby must never clobber the active's snapshot"
        assert exists_active

    def test_invoker_discards_fenced_epoch_messages(self, tmp_path):
        """The no-double-placement half of failover: an invoker that has
        seen epoch N discards activations stamped with an older epoch (a
        zombie active's late batch)."""
        from openwhisk_tpu.containerpool import ContainerPoolConfig
        from openwhisk_tpu.core.entity import (ActivationId, ExecManifest,
                                               InvokerInstanceId, MB)
        from openwhisk_tpu.database import (ArtifactActivationStore,
                                            EntityStore, MemoryArtifactStore)
        from openwhisk_tpu.invoker.reactive import InvokerReactive
        from openwhisk_tpu.messaging import ActivationMessage
        from openwhisk_tpu.utils.transaction import TransactionId

        async def go():
            ExecManifest.initialize()
            provider = MemoryMessagingProvider()
            store = MemoryArtifactStore()

            class FactoryStub:
                async def cleanup(self):
                    pass

            inv = InvokerReactive(
                InvokerInstanceId(0, user_memory=MB(1024)), provider,
                EntityStore(store), ArtifactActivationStore(store),
                FactoryStub(),
                pool_config=ContainerPoolConfig(user_memory=MB(1024)))

            released = []

            class FeedStub:
                def processed(self):
                    released.append(1)

            ident = Identity.generate("guest")
            action = make_action("fence", memory=128)

            def payload(epoch):
                return ActivationMessage(
                    TransactionId(), action.fully_qualified_name, None,
                    ident, ActivationId.generate(),
                    ControllerInstanceId("0"), False, {},
                    fence_epoch=epoch).serialize()

            # adopt epoch 4, then a zombie epoch-2 batch arrives: discarded
            # without ever reaching the action-fetch path
            await inv._process(payload(4), FeedStub())
            assert inv._max_fence_epoch == 4
            before = len(released)
            await inv._process(payload(2), FeedStub())
            discarded = inv.fenced_discards
            assert len(released) == before + 1, \
                "a discarded message must still release feed capacity"
            # unfenced traffic (non-HA) is untouched by the fence
            await inv._process(ActivationMessage(
                TransactionId(), action.fully_qualified_name, None, ident,
                ActivationId.generate(), ControllerInstanceId("0"), False,
                {}).serialize(), FeedStub())
            assert inv._max_fence_epoch == 4
            return discarded

        assert asyncio.run(go()) == 1

    def test_standalone_shutdown_writes_final_dump(self, tmp_path):
        """Satellite: the standalone assembly wires snapshot + journal
        through Controller.owned_resources, so a clean shutdown (the
        SIGTERM path ends in controller.stop()) always writes the final
        dump — a restart then replays no journal at all."""
        import socket

        from openwhisk_tpu.standalone import make_standalone

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        snap = str(tmp_path / "sa.snap")
        jdir = str(tmp_path / "wal")

        async def go():
            controller = await make_standalone(
                port=port, balancer="tpu", ui=False,
                snapshot_path=snap, snapshot_interval=60.0,
                journal_dir=jdir)
            bal = controller.load_balancer
            assert bal.journal is not None
            # interval is 60 s: only the shutdown path can write this file
            assert not os.path.exists(snap)
            await controller.stop()
            return os.path.exists(snap)

        assert asyncio.run(go()), "controller.stop() must write the dump"
        doc = json.load(open(snap))
        assert doc["registry"], "final dump carries the live fleet"
        assert doc["version"] >= 2

    def test_fence_epoch_wire_roundtrip_and_absent_by_default(self):
        from openwhisk_tpu.core.entity import ActivationId
        from openwhisk_tpu.messaging import ActivationMessage
        from openwhisk_tpu.utils.transaction import TransactionId
        ident = Identity.generate("guest")
        action = make_action("wire", memory=128)
        plain = ActivationMessage(
            TransactionId(), action.fully_qualified_name, None, ident,
            ActivationId.generate(), ControllerInstanceId("0"), False, {})
        assert "fenceEpoch" not in plain.to_json(), \
            "the non-HA wire format must stay byte-identical"
        assert ActivationMessage.parse(plain.serialize()).fence_epoch is None
        fenced = ActivationMessage(
            TransactionId(), action.fully_qualified_name, None, ident,
            ActivationId.generate(), ControllerInstanceId("0"), False, {},
            fence_epoch=7)
        assert ActivationMessage.parse(fenced.serialize()).fence_epoch == 7


@pytest.mark.mesh
class TestMeshTopologyReplay:
    """ISSUE 13 satellite: the journal records the mesh topology (a
    `mesh` record alongside reg/cluster, plus a shard count on every
    batch record). A promoted standby on the SAME topology reshards at
    restore and replays the tail bit-exactly; replay on a DIFFERENT
    device count cold-starts with a logged reason instead of silently
    mis-sharding."""

    N_SHARDS = 8

    def _mesh_balancer(self, provider, instance="0", **kw):
        kw.setdefault("prewarm", False)
        kw.setdefault("initial_pad", 16)
        kw.setdefault("max_batch", 32)
        return _balancer(provider, instance, fleet_mesh=True,
                         fleet_shards=self.N_SHARDS, **kw)

    async def _journal_some_traffic(self, bal, n_invokers=12, total=24):
        from openwhisk_tpu.core.entity import InvokerInstanceId, MB
        from openwhisk_tpu.controller.loadbalancer import HEALTHY

        async def fake_send(msg, invoker):
            return None

        bal.send_activation_to_invoker = fake_send
        for i in range(n_invokers):
            bal._status_change(InvokerInstanceId(i, user_memory=MB(2048)),
                               HEALTHY)
        ident = Identity.generate("guest")
        actions = [make_action(f"mt{i}", memory=[128, 256][i % 2])
                   for i in range(4)]
        await asyncio.gather(*[
            bal.publish(actions[i % 4], make_msg(actions[i % 4], ident))
            for i in range(total)])
        assert bal.journal.flush()

    def test_mesh_record_stamped_and_same_topology_replays_bit_exact(
            self, tmp_path):
        jdir = str(tmp_path / "wal")

        async def go():
            provider = MemoryMessagingProvider()
            bal = self._mesh_balancer(provider)
            bal.attach_journal(PlacementJournal(jdir))
            await self._journal_some_traffic(bal)
            live_free = np.asarray(bal.state.free_mb)
            live_conc = np.asarray(bal.state.conc_free)
            await bal.close()

            recs = list(PlacementJournal(jdir).records(0))
            # the topology header precedes the first record, and every
            # batch record carries the shard count
            assert recs[0]["t"] == "mesh"
            assert recs[0]["n_shards"] == self.N_SHARDS
            assert recs[0]["axis"] == "fleet"
            assert all(r.get("S") == self.N_SHARDS
                       for r in recs if r.get("t") == "batch")

            # a promoted standby with the SAME device count replays the
            # full history through the sharded kernels, bit-exactly
            cold = self._mesh_balancer(provider, "1")
            stats = cold.replay_journal(PlacementJournal(jdir).records(0))
            same = (np.array_equal(np.asarray(cold.state.free_mb),
                                   live_free)
                    and np.array_equal(np.asarray(cold.state.conc_free),
                                       live_conc))
            await cold.close()
            return stats, same

        stats, same = asyncio.run(go())
        assert "skipped" not in stats
        assert stats["batches"] >= 1
        assert stats["parity_mismatches"] == 0
        assert same, "same-topology mesh replay must be bit-exact"

    def test_replay_on_different_device_count_cold_starts(self, tmp_path):
        jdir = str(tmp_path / "wal")

        async def go():
            provider = MemoryMessagingProvider()
            bal = self._mesh_balancer(provider)
            bal.attach_journal(PlacementJournal(jdir))
            await self._journal_some_traffic(bal)
            last = bal._journal_seq
            await bal.close()

            # a single-device balancer (n_shards=1 != 8) must refuse the
            # tail: cold start, logged reason, every seq still claimed
            single = _balancer(provider, "1", prewarm=False,
                               initial_pad=16, max_batch=32)
            stats = single.replay_journal(PlacementJournal(jdir).records(0))
            full = np.asarray(single.state.free_mb)
            await single.close()
            return stats, full, last

        stats, free, last = asyncio.run(go())
        assert stats["skipped"] == "mesh_topology"
        assert stats["journal_shards"] == self.N_SHARDS
        assert stats["balancer_shards"] == 1
        assert stats["last_seq"] >= last, \
            "a cold start must still claim the tail's seqs"
        # cold start: NO mis-sharded replay landed — the books are the
        # re-initialized state (the fleet re-registers from live pings,
        # exactly the pruned-tail-without-snapshot posture)
        assert int(free.sum()) == 0 and len(free) == 16

    def test_single_device_tail_refused_on_mesh(self, tmp_path):
        """The reverse direction: a journal written by a single-device
        balancer (no mesh records, no S on batches) must not replay on a
        mesh balancer — its batch records imply n_shards=1."""
        jdir = str(tmp_path / "wal")

        async def go():
            provider = MemoryMessagingProvider()
            bal = _balancer(provider, prewarm=False, initial_pad=16,
                            max_batch=32)
            bal.attach_journal(PlacementJournal(jdir))
            await self._journal_some_traffic(bal)
            await bal.close()
            recs = list(PlacementJournal(jdir).records(0))
            assert not any(r.get("t") == "mesh" for r in recs), \
                "single-device journals stay byte-compatible (no mesh recs)"
            assert not any("S" in r for r in recs if r.get("t") == "batch")

            meshy = self._mesh_balancer(provider, "1")
            stats = meshy.replay_journal(PlacementJournal(jdir).records(0))
            await meshy.close()
            return stats

        stats = asyncio.run(go())
        assert stats["skipped"] == "mesh_topology"
        assert stats["journal_shards"] == 1
        assert stats["balancer_shards"] == self.N_SHARDS

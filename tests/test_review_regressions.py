"""Regression tests for review findings (sqlite :memory: threading, LIKE
wildcard escaping, ack shrink aliasing, feed capacity double-credit)."""
import asyncio

import pytest

from openwhisk_tpu.core.entity import (ActivationId, ActivationResponse,
                                       EntityName, EntityPath, Subject,
                                       WhiskActivation)
from openwhisk_tpu.database import SqliteArtifactStore
from openwhisk_tpu.database.cache import EntityCache, RemoteCacheInvalidation
from openwhisk_tpu.messaging import MemoryMessagingProvider, ResultMessage, parse_ack
from openwhisk_tpu.utils.transaction import TransactionId


def run(coro):
    return asyncio.run(coro)


def test_sqlite_memory_store_works_across_executor_threads():
    async def go():
        st = SqliteArtifactStore()  # :memory:
        await st.put("ns/a", {"entityType": "actions", "namespace": "ns",
                              "name": "a", "updated": 1})
        return await st.get("ns/a")
    assert run(go())["name"] == "a"


def test_sqlite_namespace_underscore_not_wildcard():
    async def go():
        st = SqliteArtifactStore()
        await st.put("my_ns/a", {"entityType": "actions", "namespace": "my_ns",
                                 "name": "a", "updated": 1})
        await st.put("myxns/pkg/b", {"entityType": "actions", "namespace": "myxns/pkg",
                                     "name": "b", "updated": 2})
        docs = await st.query("actions", "my_ns")
        count = await st.count("actions", "my_ns")
        return [d["name"] for d in docs], count
    names, count = run(go())
    assert names == ["a"]
    assert count == 1


def test_ack_shrink_does_not_mutate_stored_activation():
    act = WhiskActivation(EntityPath("guest"), EntityName("big"),
                          Subject("guest-user"), ActivationId.generate(),
                          1.0, 2.0, ActivationResponse.success({"blob": "x" * 100}))
    msg = ResultMessage(TransactionId(), act)
    shrunk = msg.shrink(10)
    assert act.response.result == {"blob": "x" * 100}  # original intact
    parsed = parse_ack(shrunk.serialize())
    assert parsed.activation.response.result is None
    assert parsed.activation.response.size is not None


def test_invalidation_feed_capacity_not_inflated_by_bad_payloads():
    async def go():
        provider = MemoryMessagingProvider()
        c = EntityCache()
        r = RemoteCacheInvalidation(provider, "c0", {"whisks": c})
        r.start()
        prod = provider.get_producer()
        for _ in range(5):
            await prod.send("cacheInvalidation", b"not json")
        await asyncio.sleep(0.1)
        free = r._feed.free_capacity
        await r.stop()
        return free
    assert run(go()) <= 128


def test_tracer_concurrent_spans_same_transid_finish_their_own():
    """finish_span(span=...) must close the given span even when a later
    concurrent span sits above it on the per-transid stack."""
    from openwhisk_tpu.utils.tracing import BufferReporter, Tracer

    rep = BufferReporter()
    tr = Tracer(reporter=rep)
    tid = TransactionId()
    a = tr.start_span("invoke_a", tid)
    b = tr.start_span("invoke_b", tid)  # interleaved concurrent invoke
    tr.finish_span(tid, {"action": "a"}, span=a)  # a finishes FIRST
    tr.finish_span(tid, {"action": "b"}, span=b)
    by_name = {s.name: s for s in rep.spans}
    assert by_name["invoke_a"].tags["action"] == "a"
    assert by_name["invoke_b"].tags["action"] == "b"
    assert not tr._stacks  # fully drained


def test_attachment_conflict_loser_cannot_corrupt_winner_code():
    """Concurrent action updates: the losing writer's attachment bytes must
    never be paired with the winning writer's document (per-put names)."""
    from openwhisk_tpu.core.entity import WhiskAction
    from openwhisk_tpu.core.entity.exec import CodeExec
    from openwhisk_tpu.core.entity.names import EntityName as EN
    from openwhisk_tpu.database import MemoryArtifactStore
    from openwhisk_tpu.database.entities import EntityStore
    from openwhisk_tpu.database.store import DocumentConflict

    big_a = "def main(x): return {'who': 'A'}\n" + "#" * 70_000
    big_b = "def main(x): return {'who': 'B'}\n" + "#" * 70_000

    async def go():
        es = EntityStore(MemoryArtifactStore(), cache=None)
        mk = lambda code: WhiskAction(EntityPath("ns"), EN("act"),
                                      CodeExec(kind="python:3", code=code))
        first = mk(big_a)
        await es.put(first)                       # rev 1, code A
        winner = mk(big_a.replace("'A'", "'A2'"))
        winner.rev = first.rev
        loser = mk(big_b)
        loser.rev = first.rev
        await es.put(winner)                      # rev 2, code A2
        with pytest.raises(DocumentConflict):
            await es.put(loser)                   # stale rev: must lose
        got = await es.get(WhiskAction, "ns/act", use_cache=False)
        return got.exec.code

    code = run(go())
    assert "'A2'" in code and "'B'" not in code  # winner doc ↔ winner code


def test_from_latest_subscriber_keeps_topic_backlog_for_queue_groups():
    """A from_latest consumer (health stream) must not destroy the
    pre-subscription backlog retained for a later queue-semantics group."""
    async def go():
        bus = MemoryMessagingProvider()
        prod = bus.get_producer()
        await prod.send("t", b"retained-1")
        await prod.send("t", b"retained-2")
        bus.get_consumer("t", "stream", from_latest=True)  # must not eat backlog
        queue_consumer = bus.get_consumer("t", "workers")
        got = await queue_consumer.peek(10, timeout=0.05)
        return [payload for _, _, _, payload in got]
    assert run(go()) == [b"retained-1", b"retained-2"]


def test_peek_survives_retention_resize_while_waiting():
    """set_max_messages swaps the group deque; a consumer parked in peek()
    must still see messages appended to the replacement deque."""
    async def go():
        bus = MemoryMessagingProvider()
        consumer = bus.get_consumer("t", "g")
        prod = bus.get_producer()

        async def resize_then_send():
            await asyncio.sleep(0.02)
            bus.bus.topic("t").set_max_messages(16)
            await prod.send("t", b"after-resize")

        task = asyncio.ensure_future(resize_then_send())
        got = await consumer.peek(1, timeout=2.0)
        await task
        return got
    got = run(go())
    assert [p for _, _, _, p in got] == [b"after-resize"]


def test_deploy_limits_keys_normalized_and_validated():
    from openwhisk_tpu.tools.deploy import _config_env
    env = _config_env({"limits": {"invocations_per_minute": 120}})
    assert env == {"CONFIG_whisk_limits_invocationsPerMinute": "120"}
    with pytest.raises(ValueError):
        _config_env({"limits": {"invocationsPerHour": 9}})


def test_actionproxy_reinit_drops_previous_zip_from_sys_path():
    import base64
    import io
    import sys
    import zipfile

    from openwhisk_tpu.containerpool import actionproxy

    def zip_b64(helper_body: str, main_body: str) -> str:
        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w") as z:
            z.writestr("helper.py", helper_body)
            z.writestr("__main__.py", main_body)
        return base64.b64encode(buf.getvalue()).decode()

    main_src = "import helper\ndef main(args):\n    return {'v': helper.VALUE}\n"
    saved = actionproxy._state.get("workdir")
    try:
        fn1 = actionproxy._compile_binary_action(zip_b64("VALUE = 1", main_src), "main")
        assert fn1({}) == {"v": 1}
        first_dir = actionproxy._state["workdir"]
        fn2 = actionproxy._compile_binary_action(zip_b64("VALUE = 2", main_src), "main")
        assert fn2({}) == {"v": 2}  # stale helper module must not shadow
        assert first_dir not in sys.path
    finally:
        wd = actionproxy._state.get("workdir")
        if wd and wd in sys.path:
            sys.path.remove(wd)
        actionproxy._state["workdir"] = saved
        sys.modules.pop("helper", None)


def test_actionproxy_failed_reinit_leaves_previous_action_working():
    """A re-init whose zip does not compile must not break the installed
    action: its modules, path entry, and workdir survive the failure."""
    import base64
    import io
    import os
    import sys
    import zipfile

    from openwhisk_tpu.containerpool import actionproxy

    def zip_b64(files: dict) -> str:
        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w") as z:
            for name, body in files.items():
                z.writestr(name, body)
        return base64.b64encode(buf.getvalue()).decode()

    good = zip_b64({"helper.py": "VALUE = 7",
                    "__main__.py": "import helper\n"
                                   "def main(args):\n"
                                   "    import helper as h\n"
                                   "    return {'v': h.VALUE}\n"})
    bad = zip_b64({"__main__.py": "not_main = 1\n"})  # no callable main
    saved = actionproxy._state.get("workdir")
    try:
        fn = actionproxy._compile_binary_action(good, "main")
        assert fn({}) == {"v": 7}
        good_dir = actionproxy._state["workdir"]
        with pytest.raises(ValueError):
            actionproxy._compile_binary_action(bad, "main")
        assert actionproxy._state["workdir"] == good_dir
        assert good_dir in sys.path and os.path.isdir(good_dir)
        assert fn({}) == {"v": 7}  # helper import still resolves
    finally:
        wd = actionproxy._state.get("workdir")
        if wd and wd in sys.path:
            sys.path.remove(wd)
        actionproxy._state["workdir"] = saved
        sys.modules.pop("helper", None)


def test_invoker_executes_routed_revision_not_stale_cache():
    """An invoker whose EntityStore cache holds rev-1 of an action must reload
    when the ActivationMessage routes rev-2 (ref InvokerReactive.scala:244-258:
    the fetch is revision-keyed; a warm fleet must never keep executing deleted
    code). Before the fix, each standalone invoker had a private cache with no
    invalidation wiring, so updated actions never took effect."""
    from openwhisk_tpu.core.entity import CodeExec, WhiskAction
    from openwhisk_tpu.database.entities import EntityStore

    async def go():
        st = SqliteArtifactStore()
        es_controller = EntityStore(st)
        es_invoker = EntityStore(st)  # separate cache, as in make_standalone
        a = WhiskAction(EntityPath("ns"), EntityName("a"),
                        CodeExec(kind="python:3", code="v1"))
        rev1 = await es_controller.put(a)
        # warm the invoker-side cache at rev 1
        got1 = await es_invoker.get_action("ns/a", rev=rev1.rev)
        assert got1.exec.code == "v1"
        # controller updates the action -> rev 2
        a2 = await es_controller.get_action("ns/a")
        a2.exec = CodeExec(kind="python:3", code="v2")
        a2.version = a2.version.up_patch()
        rev2 = await es_controller.put(a2)
        # a message routing rev2 must not serve the stale cached rev1
        got2 = await es_invoker.get_action("ns/a", rev=rev2.rev)
        assert got2.exec.code == "v2"
        assert got2.rev.rev == rev2.rev
        # and a rev-less fetch still serves the (now fresh) cache
        got3 = await es_invoker.get_action("ns/a")
        assert got3.exec.code == "v2"
    run(go())


def test_rev_guard_does_not_thrash_on_older_routed_rev():
    """A backlog of old-rev activations draining after an update must be
    served from the (newer) cache, not invalidate it per message; only a
    cached generation OLDER than the routed one reloads."""
    from openwhisk_tpu.database.entities import _rev_older_than

    assert _rev_older_than("1-abc", "2-def") is True
    assert _rev_older_than("2-def", "1-abc") is False   # newer cache: serve
    assert _rev_older_than("2-def", "2-def") is False
    assert _rev_older_than(None, "1-abc") is True
    assert _rev_older_than("garbage", "also-garbage") is True  # conservative reload

    from openwhisk_tpu.core.entity import CodeExec, WhiskAction
    from openwhisk_tpu.database.entities import EntityStore

    async def go():
        st = SqliteArtifactStore()
        es = EntityStore(st)
        a = WhiskAction(EntityPath("ns"), EntityName("b"),
                        CodeExec(kind="python:3", code="v1"))
        rev1 = await es.put(a)
        a2 = await es.get_action("ns/b")
        a2.exec = CodeExec(kind="python:3", code="v2")
        rev2 = await es.put(a2)
        # cache holds rev2; an old-rev message must NOT evict it
        loads = 0
        orig_get = st.get

        async def counting_get(doc_id):
            nonlocal loads
            loads += 1
            return await orig_get(doc_id)

        st.get = counting_get
        got = await es.get_action("ns/b", rev=rev1.rev)
        assert got.exec.code == "v2" and loads == 0
        got = await es.get_action("ns/b", rev=rev2.rev)
        assert got.exec.code == "v2" and loads == 0
    run(go())


def test_device_failure_paths_release_conc_slots():
    """Advisor r4: a device dispatch (or readback) failure must release the
    host-side concurrency slots acquired in publish() — otherwise every
    failed batch permanently leaks refcounts and the zero-refcount invariant
    the soak simulation asserts is violated."""
    from openwhisk_tpu.controller.loadbalancer import (LoadBalancerException,
                                                       TpuBalancer)
    from openwhisk_tpu.core.entity import ControllerInstanceId, Identity
    from tests.test_balancers import _fleet, _ping_all, make_action, make_msg

    async def go():
        provider = MemoryMessagingProvider()
        bal = TpuBalancer(provider, ControllerInstanceId("0"),
                          managed_fraction=1.0, blackbox_fraction=0.0,
                          batch_window=0.002, max_batch=8)
        await bal.start()
        invokers, producer = await _fleet(provider, 2)
        await _ping_all(invokers, producer)
        ident = Identity.generate("guest")
        action = make_action("boom", memory=128)

        def explode(*a, **k):
            raise RuntimeError("injected device fault")

        bal._packed_fn = explode
        with pytest.raises(LoadBalancerException):
            await bal.publish(action, make_msg(action, ident, True))
        leaked = sum(bal._slots.refcount.values())
        await bal.close()
        for inv in invokers:
            await inv.stop()
        return leaked

    assert run(go()) == 0


def test_prometheus_label_values_escaped():
    """Advisor r4: label values from user-event bodies (metricName) must not
    corrupt the exposition page — escape backslash, quote, newline."""
    from openwhisk_tpu.utils.logging import MetricEmitter

    m = MetricEmitter()
    m.counter("userevents_total", tags={"metric": 'bad"value\nwith\\stuff'})
    page = m.prometheus_text()
    line = [l for l in page.splitlines() if l.startswith("openwhisk_userevents_total{")][0]
    assert '\n' not in line  # splitlines guarantees it, but the raw value had one
    assert 'bad\\"value\\nwith\\\\stuff' in line


def test_readback_failure_reverses_device_placements():
    """r5 review: when the dispatch succeeds but the host readback fails,
    the batch's placements live on device with no publisher left to release
    them. The balancer must reverse them on device (release fold inverts the
    schedule fold) before freeing the host slots — otherwise a later action
    reusing the slot index inherits phantom concurrency."""
    import numpy as np

    from openwhisk_tpu.controller.loadbalancer import (LoadBalancerException,
                                                       TpuBalancer)
    from openwhisk_tpu.core.entity import ControllerInstanceId, Identity
    from tests.test_balancers import _fleet, _ping_all, make_action, make_msg

    async def go():
        provider = MemoryMessagingProvider()
        bal = TpuBalancer(provider, ControllerInstanceId("0"),
                          managed_fraction=1.0, blackbox_fraction=0.0,
                          batch_window=0.002, max_batch=8)
        await bal.start()
        invokers, producer = await _fleet(provider, 2)
        await _ping_all(invokers, producer)
        free0 = np.asarray(bal.state.free_mb).copy()
        conc0 = np.asarray(bal.state.conc_free).copy()

        def poisoned(out):
            raise RuntimeError("tunnel died mid-readback")

        bal._read_back = poisoned
        ident = Identity.generate("guest")
        action = make_action("phantom", memory=256)
        with pytest.raises(LoadBalancerException):
            await bal.publish(action, make_msg(action, ident, True))
        leaked = sum(bal._slots.refcount.values())
        free1 = np.asarray(bal.state.free_mb).copy()
        conc1 = np.asarray(bal.state.conc_free).copy()
        await bal.close()
        for inv in invokers:
            await inv.stop()
        return leaked, (free0 == free1).all(), (conc0 == conc1).all()

    leaked, free_ok, conc_ok = run(go())
    assert leaked == 0
    assert free_ok and conc_ok


def test_cancelled_publisher_releases_capacity():
    """r5 review: a publish() cancelled while awaiting placement (client
    disconnect) must not leak its host conc slot nor the device capacity the
    schedule fold reserved for it."""
    import numpy as np

    from openwhisk_tpu.controller.loadbalancer import TpuBalancer
    from openwhisk_tpu.core.entity import ControllerInstanceId, Identity
    from tests.test_balancers import _fleet, _ping_all, make_action, make_msg

    async def go():
        provider = MemoryMessagingProvider()
        bal = TpuBalancer(provider, ControllerInstanceId("0"),
                          managed_fraction=1.0, blackbox_fraction=0.0,
                          batch_window=0.002, max_batch=8)
        await bal.start()
        invokers, producer = await _fleet(provider, 2)
        await _ping_all(invokers, producer)
        free0 = np.asarray(bal.state.free_mb).copy()
        conc0 = np.asarray(bal.state.conc_free).copy()
        ident = Identity.generate("guest")
        action = make_action("gone", memory=256)
        task = asyncio.get_event_loop().create_task(
            bal.publish(action, make_msg(action, ident, True)))
        await asyncio.sleep(0)  # let publish enqueue into _pending
        task.cancel()
        with pytest.raises(asyncio.CancelledError):
            await task
        # the batch still dispatches; the abandoned release then drains
        for _ in range(100):
            await asyncio.sleep(0.01)
            if (sum(bal._slots.refcount.values()) == 0
                    and (np.asarray(bal.state.free_mb) == free0).all()):
                break
        leaked = sum(bal._slots.refcount.values())
        free1 = np.asarray(bal.state.free_mb).copy()
        conc1 = np.asarray(bal.state.conc_free).copy()
        await bal.close()
        for inv in invokers:
            await inv.stop()
        return leaked, (free0 == free1).all(), (conc0 == conc1).all()

    leaked, free_ok, conc_ok = run(go())
    assert leaked == 0
    assert free_ok and conc_ok


def test_auto_kernel_outgrow_swaps_to_xla():
    """r5 review: with kernel="auto" (the new default) a balancer whose
    state outgrows the pallas VMEM budget must still swap to the XLA
    kernels — the guard keys on kernel_resolved, not the literal "pallas"
    constructor argument."""
    from openwhisk_tpu.controller.loadbalancer import TpuBalancer
    from openwhisk_tpu.core.entity import ControllerInstanceId

    bal = TpuBalancer(MemoryMessagingProvider(), ControllerInstanceId("0"),
                      action_slots=4096, initial_pad=64)
    assert bal.kernel == "auto"
    # simulate the auto policy having resolved pallas (as on real TPU —
    # on the CPU test backend auto resolves xla, so force the state the
    # guard must handle)
    bal.kernel_resolved = "pallas"
    bal._grow_padding(1024)  # (4096+2)*1024*4 bytes >> the 8 MiB budget
    assert bal.kernel_resolved == "xla"
    # the swap honors the placement-kernel knob: auto resolves the
    # per-bucket scan/repair hybrid on the XLA path (PR 5)
    assert bal.placement_kernel_resolved == "repair"
    assert getattr(bal._sched_fn, "_placement_hybrid", False)
    assert getattr(bal._release_fn, "_placement_hybrid", False)

"""Regression tests for review findings (sqlite :memory: threading, LIKE
wildcard escaping, ack shrink aliasing, feed capacity double-credit)."""
import asyncio

import pytest

from openwhisk_tpu.core.entity import (ActivationId, ActivationResponse,
                                       EntityName, EntityPath, Subject,
                                       WhiskActivation)
from openwhisk_tpu.database import SqliteArtifactStore
from openwhisk_tpu.database.cache import EntityCache, RemoteCacheInvalidation
from openwhisk_tpu.messaging import MemoryMessagingProvider, ResultMessage, parse_ack
from openwhisk_tpu.utils.transaction import TransactionId


def run(coro):
    return asyncio.run(coro)


def test_sqlite_memory_store_works_across_executor_threads():
    async def go():
        st = SqliteArtifactStore()  # :memory:
        await st.put("ns/a", {"entityType": "actions", "namespace": "ns",
                              "name": "a", "updated": 1})
        return await st.get("ns/a")
    assert run(go())["name"] == "a"


def test_sqlite_namespace_underscore_not_wildcard():
    async def go():
        st = SqliteArtifactStore()
        await st.put("my_ns/a", {"entityType": "actions", "namespace": "my_ns",
                                 "name": "a", "updated": 1})
        await st.put("myxns/pkg/b", {"entityType": "actions", "namespace": "myxns/pkg",
                                     "name": "b", "updated": 2})
        docs = await st.query("actions", "my_ns")
        count = await st.count("actions", "my_ns")
        return [d["name"] for d in docs], count
    names, count = run(go())
    assert names == ["a"]
    assert count == 1


def test_ack_shrink_does_not_mutate_stored_activation():
    act = WhiskActivation(EntityPath("guest"), EntityName("big"),
                          Subject("guest-user"), ActivationId.generate(),
                          1.0, 2.0, ActivationResponse.success({"blob": "x" * 100}))
    msg = ResultMessage(TransactionId(), act)
    shrunk = msg.shrink(10)
    assert act.response.result == {"blob": "x" * 100}  # original intact
    parsed = parse_ack(shrunk.serialize())
    assert parsed.activation.response.result is None
    assert parsed.activation.response.size is not None


def test_invalidation_feed_capacity_not_inflated_by_bad_payloads():
    async def go():
        provider = MemoryMessagingProvider()
        c = EntityCache()
        r = RemoteCacheInvalidation(provider, "c0", {"whisks": c})
        r.start()
        prod = provider.get_producer()
        for _ in range(5):
            await prod.send("cacheInvalidation", b"not json")
        await asyncio.sleep(0.1)
        free = r._feed.free_capacity
        await r.stop()
        return free
    assert run(go()) <= 128


def test_tracer_concurrent_spans_same_transid_finish_their_own():
    """finish_span(span=...) must close the given span even when a later
    concurrent span sits above it on the per-transid stack."""
    from openwhisk_tpu.utils.tracing import BufferReporter, Tracer

    rep = BufferReporter()
    tr = Tracer(reporter=rep)
    tid = TransactionId()
    a = tr.start_span("invoke_a", tid)
    b = tr.start_span("invoke_b", tid)  # interleaved concurrent invoke
    tr.finish_span(tid, {"action": "a"}, span=a)  # a finishes FIRST
    tr.finish_span(tid, {"action": "b"}, span=b)
    by_name = {s.name: s for s in rep.spans}
    assert by_name["invoke_a"].tags["action"] == "a"
    assert by_name["invoke_b"].tags["action"] == "b"
    assert not tr._stacks  # fully drained


def test_attachment_conflict_loser_cannot_corrupt_winner_code():
    """Concurrent action updates: the losing writer's attachment bytes must
    never be paired with the winning writer's document (per-put names)."""
    from openwhisk_tpu.core.entity import WhiskAction
    from openwhisk_tpu.core.entity.exec import CodeExec
    from openwhisk_tpu.core.entity.names import EntityName as EN
    from openwhisk_tpu.database import MemoryArtifactStore
    from openwhisk_tpu.database.entities import EntityStore
    from openwhisk_tpu.database.store import DocumentConflict

    big_a = "def main(x): return {'who': 'A'}\n" + "#" * 70_000
    big_b = "def main(x): return {'who': 'B'}\n" + "#" * 70_000

    async def go():
        es = EntityStore(MemoryArtifactStore(), cache=None)
        mk = lambda code: WhiskAction(EntityPath("ns"), EN("act"),
                                      CodeExec(kind="python:3", code=code))
        first = mk(big_a)
        await es.put(first)                       # rev 1, code A
        winner = mk(big_a.replace("'A'", "'A2'"))
        winner.rev = first.rev
        loser = mk(big_b)
        loser.rev = first.rev
        await es.put(winner)                      # rev 2, code A2
        with pytest.raises(DocumentConflict):
            await es.put(loser)                   # stale rev: must lose
        got = await es.get(WhiskAction, "ns/act", use_cache=False)
        return got.exec.code

    code = run(go())
    assert "'A2'" in code and "'B'" not in code  # winner doc ↔ winner code

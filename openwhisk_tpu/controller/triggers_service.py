"""Trigger firing: activate every ACTIVE rule's action.

Rebuild of core/controller/.../controller/Triggers.scala:320-412 — the
reference loops an authenticated HTTP POST back into its own actions API
(a noted TODO in its source); here rule dispatch is direct and in-process.
The trigger's activation record collects per-rule outcomes in its logs.
"""
from __future__ import annotations

import asyncio
import time
from typing import Any, Dict, Optional

from ..core.entity import (ACTIVE, ActivationId, ActivationResponse, Identity,
                           Parameters, WhiskActivation, WhiskTrigger)
from ..database import NoDocumentException
from ..utils.transaction import TransactionId
from .conductors import is_conductor
from .invoke import resolve_action


class TriggerService:
    def __init__(self, entity_store, activation_store, action_invoker,
                 sequencer=None, conductor=None):
        self.entity_store = entity_store
        self.activation_store = activation_store
        self.invoker = action_invoker
        self.sequencer = sequencer
        self.conductor = conductor

    async def fire(self, identity: Identity, trigger: WhiskTrigger,
                   payload: Optional[Dict[str, Any]],
                   transid: Optional[TransactionId] = None
                   ) -> Optional[ActivationId]:
        """Returns the trigger's activation id, or None when no rules are
        active (reference answers 204 NoContent in that case)."""
        transid = transid or TransactionId()
        active_rules = {name: r for name, r in trigger.rules.items()
                        if r.status == ACTIVE}
        if not active_rules:
            return None
        aid = ActivationId.generate()
        start = time.time()
        args = trigger.parameters.merge(
            Parameters.from_arguments(payload or {})).to_arguments()
        results = await asyncio.gather(
            *[self._fire_rule(identity, name, rule, args, aid, transid)
              for name, rule in active_rules.items()])
        activation = WhiskActivation(
            namespace=identity.namespace_path, name=trigger.name,
            subject=identity.subject, activation_id=aid,
            start=start, end=time.time(),
            response=ActivationResponse.success(args),
            logs=[r for r in results],
            version=trigger.version)
        await self.activation_store.store(activation, context=identity)
        return aid

    async def _fire_rule(self, identity, rule_name, rule, args, cause, transid) -> str:
        import json

        try:
            action, pkg_params = await resolve_action(
                self.entity_store, rule.action.resolve(str(identity.namespace.name)),
                identity)
            if action.is_sequence and self.sequencer is not None:
                outcome = await self.sequencer.invoke_sequence(
                    identity, action, args, blocking=False, transid=transid,
                    cause=cause)
            elif self.conductor is not None and is_conductor(action):
                outcome = await self.conductor.invoke_composition(
                    identity, action, args, blocking=False, transid=transid,
                    cause=cause, package_params=pkg_params)
            else:
                outcome = await self.invoker.invoke(
                    identity, action, pkg_params, args, blocking=False,
                    transid=transid, cause=cause)
            return json.dumps({"statusCode": 0, "success": True,
                               "activationId": outcome.activation_id.asString,
                               "rule": rule_name,
                               "action": str(rule.action)})
        except NoDocumentException:
            return json.dumps({"statusCode": 1, "success": False,
                               "error": f"action '{rule.action}' does not exist",
                               "rule": rule_name})
        except Exception as e:  # noqa: BLE001 — a failing rule must not fail the fire
            return json.dumps({"statusCode": 1, "success": False,
                               "error": str(e), "rule": rule_name})

"""ActivationStore SPI: persistence of activation records.

Rebuild of common/scala/.../core/database/ActivationStore.scala:34-159 —
store/get/delete/list activations, with `ArtifactActivationStore` writing
through a Batcher (write coalescing) and `NoopActivationStore` for
deployments that sink records elsewhere. `store_context` gates persistence on
the user's `store_activations` limit exactly as the reference's
UserContext checks.
"""
from __future__ import annotations

from typing import List, Optional

from ..core.entity import ActivationId, Identity, WhiskActivation
from .batcher import Batcher
from .store import ArtifactStore, NoDocumentException


class ActivationStore:
    async def store(self, activation: WhiskActivation,
                    context: Optional[Identity] = None) -> Optional[str]:
        raise NotImplementedError

    async def get(self, namespace: str, activation_id: ActivationId) -> WhiskActivation:
        raise NotImplementedError

    async def delete(self, namespace: str, activation_id: ActivationId) -> bool:
        raise NotImplementedError

    async def list(self, namespace: str, name: Optional[str] = None,
                   skip: int = 0, limit: int = 30,
                   since: Optional[float] = None, upto: Optional[float] = None
                   ) -> List[dict]:
        raise NotImplementedError

    async def count(self, namespace: str, name: Optional[str] = None,
                    since: Optional[float] = None, upto: Optional[float] = None
                    ) -> int:
        raise NotImplementedError


class ArtifactActivationStore(ActivationStore):
    def __init__(self, store: ArtifactStore, batch_size: int = 500):
        self.store_backend = store
        self._batcher: Batcher = Batcher(self._write_batch, batch_size=batch_size)

    async def _write_batch(self, activations: List[WhiskActivation]) -> List[str]:
        # stores with a native bulk write take the whole coalesced batch in
        # one call (one lock/round trip for N records) — without it the
        # batcher still amortizes scheduling but the backend sees N puts
        put_many = getattr(self.store_backend, "put_many", None)
        if put_many is not None:
            return await put_many([(a.docid, a.to_document())
                                   for a in activations])
        out = []
        for a in activations:
            out.append(await self.store_backend.put(a.docid, a.to_document()))
        return out

    async def store(self, activation: WhiskActivation,
                    context: Optional[Identity] = None) -> Optional[str]:
        if context is not None and context.limits.store_activations is False:
            return None
        return await self._batcher.put(activation)

    async def get(self, namespace: str, activation_id: ActivationId) -> WhiskActivation:
        doc = await self.store_backend.get(f"{namespace}/{activation_id}")
        return WhiskActivation.from_json(doc)

    async def delete(self, namespace: str, activation_id: ActivationId) -> bool:
        return await self.store_backend.delete(f"{namespace}/{activation_id}")

    async def list(self, namespace: str, name: Optional[str] = None,
                   skip: int = 0, limit: int = 30,
                   since: Optional[float] = None, upto: Optional[float] = None
                   ) -> List[dict]:
        since_ms = since * 1000 if since else None
        upto_ms = upto * 1000 if upto else None
        return await self.store_backend.query(
            "activations", namespace, name, since_ms, upto_ms, skip, limit)

    async def count(self, namespace: str, name: Optional[str] = None,
                    since: Optional[float] = None, upto: Optional[float] = None
                    ) -> int:
        since_ms = since * 1000 if since else None
        upto_ms = upto * 1000 if upto else None
        return await self.store_backend.count("activations", namespace, name,
                                              since_ms, upto_ms)


class NoopActivationStore(ActivationStore):
    """Discards records (ref NoopActivationStore — used when activations are
    sinked to logs/elsewhere)."""

    async def store(self, activation, context=None):
        return None

    async def get(self, namespace, activation_id):
        raise NoDocumentException(str(activation_id))

    async def delete(self, namespace, activation_id):
        return False

    async def list(self, namespace, name=None, skip=0, limit=30, since=None, upto=None):
        return []

    async def count(self, namespace, name=None, since=None, upto=None):
        return 0


class ArtifactActivationStoreProvider:
    @staticmethod
    def instance(store: ArtifactStore, **kwargs) -> ArtifactActivationStore:
        return ArtifactActivationStore(store, **kwargs)

"""Journal time-travel debugger: step-through replay with breakpoints.

PR 8 made placement deterministic and journaled (`replay_journal`
re-derives the dead active's books bit-exactly); ISSUE 19 turns that
replay into a DEBUGGER. `replay_stepper` (tpu_balancer.py) already yields
one step per applied record — this module drives it interactively:

  * `step(n)` — apply the next n records,
  * `run_to_seq(K)` — apply through seq K and stop,
  * `run_to_activation(aid)` — stop at the batch that placed `aid`
    (batch journal records carry their `aids`),
  * `books()` / `decisions()` — inspect the re-derived capacity books and
    the last batch's derived-vs-recorded decision vectors at ANY stop,
  * `diff_books(captured)` — compare the replayed state against the
    books an incident bundle (utils/blackbox.py) froze at capture time:
    replay divergence is incident evidence (a kernel-knob change across
    a restart, mid-history corruption, a non-deterministic kernel).

The debugger owns an OFFLINE balancer (the test_journal idiom: a fresh
TpuBalancer over a MemoryMessagingProvider that never serves traffic) and
replays onto it, so a live controller is never touched. Construction and
stepping are synchronous; only the balancer teardown is async
(`aclose()`), matching the balancer's own lifecycle. tools/owdebug.py is
the CLI over this API.
"""
from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional

import numpy as np

from ...utils.blackbox import read_bundle


def make_offline_balancer(kernel: Optional[str] = None, logger=None,
                          instance: str = "0"):
    """A traffic-free TpuBalancer to replay onto (owns no topics, serves
    no activations; `managed_fraction=1.0` mirrors the journal writers)."""
    from ...core.entity import ControllerInstanceId
    from ...messaging import MemoryMessagingProvider
    from .tpu_balancer import TpuBalancer
    kw: Dict[str, Any] = {}
    if kernel:
        kw["kernel"] = kernel
    if logger is not None:
        kw["logger"] = logger
    return TpuBalancer(MemoryMessagingProvider(),
                       ControllerInstanceId(instance),
                       managed_fraction=1.0, blackbox_fraction=0.0, **kw)


def _step_summary(step: dict) -> dict:
    """JSON-safe row for step history / CLI printing."""
    detail = step.get("detail") or {}
    out = {"seq": step["seq"], "t": step["t"]}
    if step["t"] == "batch":
        out["b"] = detail.get("b")
        out["aids"] = list(detail.get("aids") or ())
        out["acked"] = detail.get("acked", False)
        out["mismatches"] = detail.get("mismatches", 0)
    return out


class JournalDebugger:
    """Step-through replay session over one journal window (module doc).

    The underlying generator holds `_journal_mute` on the offline
    balancer for the whole session; `close()` (or exhausting the replay)
    runs the stepper's finalization — always close a session you abandon
    early, or the balancer's host books are never refreshed."""

    def __init__(self, records: Iterable[dict], balancer=None,
                 logger=None, from_seq: Optional[int] = None,
                 captured_books: Optional[dict] = None,
                 kernel: Optional[str] = None):
        self.balancer = (balancer if balancer is not None
                         else make_offline_balancer(kernel=kernel,
                                                    logger=logger))
        self._owns_balancer = balancer is None
        self.captured_books = captured_books
        self.stats: Dict[str, Any] = {}
        self.records = list(records)
        self._stepper = self.balancer.replay_stepper(
            self.records, logger=logger, from_seq=from_seq,
            stats=self.stats)
        #: summaries of every applied step, in order
        self.history: List[dict] = []
        #: the last applied step, full detail (numpy vectors included)
        self.current: Optional[dict] = None
        self.done = False

    # -- constructors ------------------------------------------------------
    @classmethod
    def from_directory(cls, path: str, after_seq: int = 0,
                       **kw) -> "JournalDebugger":
        """Replay a journal directory's tail (seq > after_seq)."""
        from .journal import PlacementJournal
        journal = PlacementJournal(path)
        try:
            records = list(journal.records(after_seq))
        finally:
            journal.close()
        return cls(records, from_seq=after_seq or None, **kw)

    @classmethod
    def from_bundle(cls, bundle, **kw) -> "JournalDebugger":
        """Replay an incident bundle's embedded journal window; the
        bundle's captured books become the diff baseline. `bundle` is a
        payload dict or a path to a `.wbb` file."""
        if isinstance(bundle, str):
            payload = read_bundle(bundle)
            if payload is None:
                raise ValueError(f"not a readable incident bundle: "
                                 f"{bundle}")
            bundle = payload
        planes = bundle.get("planes") or {}
        window = planes.get("journal") or {}
        records = window.get("records") or []
        from_seq = window.get("from_seq")
        return cls(records,
                   from_seq=int(from_seq) if from_seq else None,
                   captured_books=planes.get("books"), **kw)

    # -- stepping ----------------------------------------------------------
    @property
    def position(self) -> int:
        """Seq of the last applied record (stats from_seq before any)."""
        if self.current is not None:
            return int(self.current["seq"])
        return int(self.stats.get("from_seq", 0) or 0)

    def _advance(self) -> Optional[dict]:
        if self.done:
            return None
        try:
            step = next(self._stepper)
        except StopIteration:
            self.done = True
            return None
        self.current = step
        self.history.append(_step_summary(step))
        return step

    def step(self, n: int = 1) -> List[dict]:
        """Apply the next `n` records; returns their summaries (empty at
        end of window)."""
        out = []
        for _ in range(max(0, int(n))):
            step = self._advance()
            if step is None:
                break
            out.append(self.history[-1])
        return out

    def run_to_seq(self, seq: int) -> Optional[dict]:
        """Apply records THROUGH seq (state includes seq's mutation);
        returns the stop step's summary, None when the window ends
        first."""
        while True:
            step = self._advance()
            if step is None:
                return None
            if int(step["seq"]) >= int(seq):
                return self.history[-1]

    def run_to_activation(self, activation_id: str) -> Optional[dict]:
        """Break on the batch record that placed `activation_id`; the
        stopped state has that batch applied. None = never placed in this
        window."""
        aid = str(activation_id)
        while True:
            step = self._advance()
            if step is None:
                return None
            detail = step.get("detail") or {}
            if step["t"] == "batch" and aid in (detail.get("aids") or ()):
                return self.history[-1]

    def run_to_end(self) -> dict:
        """Apply everything left; returns the replay stats
        (replayed/batches/parity_mismatches/last_seq)."""
        while self._advance() is not None:
            pass
        return dict(self.stats)

    # -- inspection --------------------------------------------------------
    def books(self) -> List[int]:
        """The re-derived free-capacity books (MB per invoker row) at the
        current stop. Device pull — never call from an event loop."""
        return np.asarray(self.balancer.state.free_mb).tolist()

    def decisions(self) -> Optional[dict]:
        """Derived-vs-recorded decision vectors of the last applied batch
        (None when the last step was structural or nothing applied)."""
        if self.current is None or self.current["t"] != "batch":
            return None
        d = dict(self.current.get("detail") or {})
        for k in ("derived", "recorded", "throttled"):
            if k in d:
                d[k] = np.asarray(d[k]).tolist()
        return d

    def diff_books(self, captured: Optional[dict] = None) -> dict:
        """Replayed books vs a captured snapshot (the bundle's `books`
        plane by default). Rows beyond either side's pad are zero-capacity
        padding and compare as 0."""
        captured = captured if captured is not None else self.captured_books
        if not captured:
            return {"error": "no captured books to diff against"}
        replayed = np.asarray(self.balancer.state.free_mb,
                              np.int64).ravel()
        frozen = np.asarray(captured.get("free_mb") or [],
                            np.int64).ravel()
        n = max(len(replayed), len(frozen))
        r = np.zeros(n, np.int64)
        c = np.zeros(n, np.int64)
        r[:len(replayed)] = replayed
        c[:len(frozen)] = frozen
        bad = np.nonzero(r != c)[0]
        conc = np.asarray(self.balancer.state.conc_free)
        nz = {(int(i), int(j)): int(conc[i, j])
              for i, j in zip(*np.nonzero(conc))}
        frozen_nz = {(int(i), int(j)): int(v)
                     for i, j, v in captured.get("conc_nonzero") or ()}
        conc_mismatches = sum(
            1 for k in set(nz) | set(frozen_nz)
            if nz.get(k, 0) != frozen_nz.get(k, 0))
        return {
            "rows_compared": n,
            "free_mb_mismatches": [[int(i), int(r[i]), int(c[i])]
                                   for i in bad[:64]],
            "free_mb_mismatch_rows": int(len(bad)),
            "conc_mismatches": int(conc_mismatches),
            "parity_mismatches": int(
                self.stats.get("parity_mismatches", 0)),
            "replayed_seq": self.position,
            "captured_seq": captured.get("journal_seq"),
            "match": bool(len(bad) == 0 and conc_mismatches == 0),
        }

    # -- lifecycle ---------------------------------------------------------
    def close(self) -> None:
        """End the session: runs the stepper's finalization (journal
        un-mute + host-books refresh) without applying further records."""
        self._stepper.close()
        self.done = True

    async def aclose(self) -> None:
        """close() plus teardown of a debugger-owned offline balancer."""
        self.close()
        if self._owns_balancer:
            await self.balancer.close()

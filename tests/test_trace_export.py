"""Zipkin trace export (ref OpenTracingProvider.scala:43-160 + the zipkin
config block application.conf:461-476): finished spans batch and POST to
{url}/api/v2/spans as Zipkin v2 JSON; a dead collector drops spans without
disturbing the caller; CONFIG_whisk_tracing_zipkinUrl swaps the reporter in.
"""
import asyncio
import json

import pytest
from aiohttp import web

from openwhisk_tpu.utils.tracing import (Tracer, ZipkinReporter,
                                         maybe_enable_zipkin)
from openwhisk_tpu.utils.transaction import TransactionId


class FakeCollector:
    def __init__(self, delay: float = 0.0):
        self.batches = []
        self.status = 202
        self.delay = delay
        self.runner = None
        self.port = None

    async def start(self):
        app = web.Application()
        app.router.add_post("/api/v2/spans", self.handle)
        self.runner = web.AppRunner(app)
        await self.runner.setup()
        site = web.TCPSite(self.runner, "127.0.0.1", 0)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        return f"http://127.0.0.1:{self.port}"

    async def handle(self, request):
        assert request.content_type == "application/json"
        body = await request.json()
        if self.delay:
            await asyncio.sleep(self.delay)
        self.batches.append(body)
        return web.Response(status=self.status)

    async def stop(self):
        await self.runner.cleanup()

    @property
    def spans(self):
        return [s for b in self.batches for s in b]


class TestZipkinReporter:
    def test_spans_exported_in_zipkin_v2_shape(self):
        async def go():
            collector = FakeCollector()
            url = await collector.start()
            tracer = Tracer(ZipkinReporter(url, service_name="controller0",
                                           flush_interval=0.05))
            transid = TransactionId()
            parent = tracer.start_span("controller_activation", transid)
            child = tracer.start_span("loadbalancer_publish", transid)
            tracer.finish_span(transid, {"invoker": "invoker0"}, span=child)
            tracer.finish_span(transid, {"action": "guest/hello"}, span=parent)
            await asyncio.sleep(0.2)  # flush tick
            await tracer.reporter.close()
            await collector.stop()
            return collector.spans, parent, child

        spans, parent, child = asyncio.run(go())
        assert len(spans) == 2
        by_name = {s["name"]: s for s in spans}
        pub = by_name["loadbalancer_publish"]
        act = by_name["controller_activation"]
        # same trace, correct parentage
        assert pub["traceId"] == act["traceId"] == parent.trace_id
        assert pub["parentId"] == act["id"] == parent.span_id
        assert "parentId" not in act  # root span omits the field
        assert act["localEndpoint"] == {"serviceName": "controller0"}
        # zipkin v2 units: microseconds, string tags
        assert act["duration"] >= 0 and isinstance(act["timestamp"], int)
        assert pub["tags"] == {"invoker": "invoker0"}

    def test_batching_by_size_and_close_flush(self):
        async def go():
            collector = FakeCollector()
            url = await collector.start()
            reporter = ZipkinReporter(url, batch_size=3, flush_interval=30.0)
            tracer = Tracer(reporter)
            for i in range(3):
                t = TransactionId()
                tracer.start_span(f"s{i}", t)
                tracer.finish_span(t)
            await asyncio.sleep(0.1)  # size-triggered flush (3 spans)
            t = TransactionId()
            tracer.start_span("s3", t)
            tracer.finish_span(t)
            mid = [len(b) for b in collector.batches]
            await reporter.close()  # drains the 4th without waiting 30 s
            await collector.stop()
            return mid, [len(b) for b in collector.batches], reporter

        mid, final, reporter = asyncio.run(go())
        assert mid == [3]
        assert final == [3, 1]
        assert reporter.sent_spans == 4 and reporter.dropped_spans == 0

    def test_close_mid_flush_accounts_for_every_span(self):
        """close() while a flush is mid-POST must not vanish the popped
        batch: cancelled batches re-queue and are re-sent (or counted
        dropped) by close's final flush."""
        async def go():
            collector = FakeCollector(delay=0.25)
            url = await collector.start()
            reporter = ZipkinReporter(url, flush_interval=0.01)
            tracer = Tracer(reporter)
            for i in range(2):
                t = TransactionId()
                tracer.start_span(f"s{i}", t)
                tracer.finish_span(t)
            await asyncio.sleep(0.1)  # flush is now awaiting the slow POST
            await reporter.close()
            await collector.stop()
            return reporter

        reporter = asyncio.run(go())
        assert reporter.sent_spans + reporter.dropped_spans == 2, \
            "cancelled mid-POST batch must be re-queued, not lost uncounted"

    def test_dead_collector_drops_without_raising(self):
        async def go():
            reporter = ZipkinReporter("http://127.0.0.1:1",  # nothing listens
                                      flush_interval=0.01)
            tracer = Tracer(reporter)
            t = TransactionId()
            tracer.start_span("doomed", t)
            tracer.finish_span(t)
            await asyncio.sleep(0.1)
            await reporter.close()
            return reporter

        reporter = asyncio.run(go())
        assert reporter.dropped_spans == 1 and reporter.sent_spans == 0

    def test_collector_error_status_counts_dropped(self):
        async def go():
            collector = FakeCollector()
            collector.status = 500
            url = await collector.start()
            reporter = ZipkinReporter(url, flush_interval=0.01)
            tracer = Tracer(reporter)
            t = TransactionId()
            tracer.start_span("rejected", t)
            tracer.finish_span(t)
            await asyncio.sleep(0.15)
            await reporter.close()
            await collector.stop()
            return reporter

        reporter = asyncio.run(go())
        assert reporter.dropped_spans == 1


class TestConfigGate:
    def test_disabled_without_env(self, monkeypatch):
        monkeypatch.delenv("CONFIG_whisk_tracing_zipkinUrl", raising=False)
        tracer = Tracer()
        before = tracer.reporter
        assert maybe_enable_zipkin("controller0", tracer) is None
        assert tracer.reporter is before

    def test_enabled_with_env(self, monkeypatch):
        monkeypatch.setenv("CONFIG_whisk_tracing_zipkinUrl",
                           "http://zipkin:9411")
        monkeypatch.setenv("CONFIG_whisk_tracing_batchSize", "7")
        tracer = Tracer()
        reporter = maybe_enable_zipkin("invoker-a", tracer)
        assert isinstance(reporter, ZipkinReporter)
        assert tracer.reporter is reporter
        assert reporter.url == "http://zipkin:9411/api/v2/spans"
        assert reporter.batch_size == 7
        assert reporter.service_name == "invoker-a"


class TestOrphanFinishes:
    """Satellite: finish_span on a missing/foreign span used to silently
    return None — now it counts, and the tracing gauges expose it."""

    def test_orphan_finish_counts_and_gauges(self):
        from openwhisk_tpu.utils.logging import MetricEmitter
        from openwhisk_tpu.utils.tracing import (Span, export_tracing_gauges)
        import time as _time

        t = Tracer()
        tid = TransactionId()
        # no stack at all for this transid
        assert t.finish_span(tid) is None
        assert t.orphan_finishes == 1
        # a span that is not in the stack (e.g. finished twice)
        live = t.start_span("op", tid)
        foreign = Span("t" * 32, "f" * 16, None, "ghost", _time.time())
        assert t.finish_span(tid, span=foreign) is None
        assert t.orphan_finishes == 2
        # a legitimate finish does not count
        assert t.finish_span(tid, span=live) is live
        assert t.orphan_finishes == 2
        # double-finish of the same span IS an orphan again
        assert t.finish_span(tid, span=live) is None
        assert t.orphan_finishes == 3

        m = MetricEmitter()
        export_tracing_gauges(m, t)
        assert m.gauge_value("tracing_orphan_finishes") == 3
        assert m.gauge_value("tracing_spans_sent") == 1
        assert m.gauge_value("tracing_spans_dropped") == 0
        assert m.gauge_value("tracing_active_transactions") == 0

    def test_trace_id_of_parses_traceparent(self):
        from openwhisk_tpu.utils.tracing import trace_id_of
        assert trace_id_of({"traceparent": f"00-{'ab' * 16}-{'cd' * 8}-01"}) \
            == "ab" * 16
        assert trace_id_of(None) is None
        assert trace_id_of({}) is None
        assert trace_id_of({"traceparent": "garbage"}) is None
